"""Benchmark: flagship (PNA multi-head) training throughput in graphs/sec.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no throughput numbers (BASELINE.md: "none
published"), so ``vs_baseline`` is measured against the first recorded
bench of this build (BENCH_r1.json, written by the driver) when present,
else 1.0.
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> None:
    import jax

    # keep bench on the real device the driver provides (TPU under axon,
    # else whatever the default backend is)
    import numpy as np

    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer

    # Defaults sized to the single-chip sweet spot measured on v5e: the
    # jitted step is dispatch-latency-bound (~0.6 ms) up through batch
    # 1024 (HBM tops out before 2048), so throughput scales with batch
    # until there; batch 1024 both fills the chip and stays inside HBM.
    # 2560 samples -> 2048 train -> two full batches in the timed loop.
    # NOTE: default changes reset comparability with previously recorded
    # BENCH_r*.json baselines — only change them alongside a fresh baseline.
    n_samples = int(os.environ.get("BENCH_SAMPLES", 2560))
    batch_size = int(os.environ.get("BENCH_BATCH", 1024))
    hidden = int(os.environ.get("BENCH_HIDDEN", 128))
    layers = int(os.environ.get("BENCH_LAYERS", 6))
    measure_steps = int(os.environ.get("BENCH_STEPS", 40))
    if int(0.8 * n_samples) < batch_size:
        raise SystemExit(
            f"BENCH_SAMPLES={n_samples} yields {int(0.8 * n_samples)} train "
            f"samples < BENCH_BATCH={batch_size}; raise BENCH_SAMPLES or "
            "lower BENCH_BATCH"
        )

    # BENCH_CACHE=1 keeps every batch resident on device (fixed
    # composition) — useful when the host->device link is slow; measured
    # at parity with the default prefetch pipeline on the v5e tunnel, so
    # the standard path stays the default
    config, model, variables, loader = build_flagship(
        n_samples=n_samples,
        hidden_dim=hidden,
        num_conv_layers=layers,
        batch_size=batch_size,
        cache_device_batches=os.environ.get("BENCH_CACHE", "0") == "1",
    )
    tx = select_optimizer(config["NeuralNetwork"]["Training"])
    state = create_train_state(variables, tx)
    # bf16 forward/backward (f32 master params); BENCH_BF16=0 opts out
    compute_dtype = None
    if os.environ.get("BENCH_BF16", "1") == "1":
        import jax.numpy as jnp

        compute_dtype = jnp.bfloat16
    graphs_per_batch = batch_size

    if os.environ.get("BENCH_SCAN", "0") == "1":
        # whole-epoch lax.scan dispatch (Training.scan_epoch path): one
        # host->device round trip per epoch instead of per step. Off by
        # default: on the tunneled bench chip the scan executable hits a
        # server-side ~0.5s/dispatch pathology (the same step body
        # dispatched per-step is ~0.6 ms), so the per-step path measures
        # reliably there; on directly-attached pods scan amortizes
        # dispatch latency and is the faster mode.
        import jax.numpy as jnp

        from hydragnn_tpu.train import make_scan_epoch

        scan_fn = make_scan_epoch(model, tx, compute_dtype=compute_dtype)
        nb = len(loader)
        if nb == 0:
            raise RuntimeError("empty bench loader")
        stacked = loader.stacked_device_batches()
        order = jnp.arange(nb, dtype=jnp.int32)
        state, losses, _, _ = scan_fn(state, stacked, order)  # compile
        jax.block_until_ready(losses)
        done = 0
        t0 = time.perf_counter()
        while done < measure_steps:
            state, losses, _, _ = scan_fn(state, stacked, order)
            done += nb
        jax.block_until_ready(losses)
        dt = time.perf_counter() - t0
        graphs_per_sec = done * graphs_per_batch / dt
    else:
        step = make_train_step(model, tx, compute_dtype=compute_dtype)

        batches = list(loader)
        if not batches:
            raise RuntimeError("empty bench loader")

        # compile + warmup
        state, loss, _ = step(state, batches[0])
        jax.block_until_ready(loss)

        done = 0
        t0 = time.perf_counter()
        while done < measure_steps:
            for b in batches:
                state, loss, _ = step(state, b)
                done += 1
                if done >= measure_steps:
                    break
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        graphs_per_sec = done * graphs_per_batch / dt

    baseline = None
    for fname in ("BENCH_r1.json", "BENCH_BASELINE.json"):
        p = os.path.join(os.path.dirname(os.path.abspath(__file__)), fname)
        if os.path.exists(p):
            try:
                with open(p) as f:
                    rec = json.load(f)
                if rec.get("unit") == "graphs/sec" and rec.get("value"):
                    baseline = float(rec["value"])
                    break
            except (json.JSONDecodeError, KeyError, TypeError, ValueError):
                pass
    vs_baseline = graphs_per_sec / baseline if baseline else 1.0

    print(
        json.dumps(
            {
                "metric": "flagship_pna_multihead_train_throughput",
                "value": round(graphs_per_sec, 2),
                "unit": "graphs/sec",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
