"""Benchmark: flagship (PNA multi-head) training across graph scales.

Prints ONE JSON line. Headline fields ({"metric", "value", "unit",
"vs_baseline"}) stay comparable across rounds: value = tiny-BCC flagship
training throughput in graphs/sec. Extra fields publish the evidence the
headline alone can't carry:

  - per-config results for three graph scales (tiny-BCC flagship,
    QM9-realistic molecules with edge features, large graphs), each with
    step time, analytic FLOPs/step (XLA cost analysis), achieved
    TFLOP/s, HBM GB/s, and MFU against the chip's bf16 peak;
  - measured dispatch latency (the step-time floor on the tunneled dev
    chip, where dispatch — not compute — often dominates tiny configs).

The reference publishes no throughput numbers (BASELINE.md: "none
published"), so ``vs_baseline`` compares against the EARLIEST recorded
round of this build (``BENCH_r*.json``, written by the driver; the r01
value predates the multi-config bench but measured the same tiny-BCC
config), else 1.0.

Tunnel discipline (see .claude/skills/verify/SKILL.md): the dev chip
throttles after ~100 fast dispatches, so the total dispatch budget here
is kept under ~90 and the headline config is measured first.

TIMING CORRECTNESS: on the tunneled dev chip ``jax.block_until_ready``
returns at dispatch-ack, NOT device completion (calibrated: chained
8192^3 bf16 matmuls "finish" at 35 PFLOP/s — 180x over the chip's
peak). Every timed loop here therefore ends with an actual D2H readback
(np.asarray of the final loss), which cannot be acknowledged without
executing the full dependency chain; the same calibration then lands at
~94 TFLOP/s (48% MFU) — physical. Round-1's recorded 1.31M graphs/sec
predates this fix and measured dispatch rate, not device throughput;
``vs_baseline`` against it is meaningful only from r02 onward.
"""

from __future__ import annotations

import json
import os
import re
import statistics
import sys
import time


# The chip-peak table and the XLA cost-model reader now live in
# hydragnn_tpu/obs/introspect.py: the training loop's per-run
# hardware-efficiency ledger and this bench must price FLOPs/MFU from
# the SAME source or their numbers silently diverge.
from hydragnn_tpu.obs.introspect import (  # noqa: E402
    cost_analysis as _cost_analysis,
    peak_flops as _peak_flops,
    peak_hbm_bw as _peak_hbm_bw,
)


def _measure_dispatch_ms() -> float:
    """Median latency of a trivial jitted dispatch + D2H readback: the
    per-step floor (on the tunneled chip this is the RPC round trip;
    block_until_ready alone returns at dispatch-ack and measures
    nothing — see module docstring)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    tiny = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros(())
    np.asarray(tiny(x))  # compile + real sync
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(tiny(x))
        ts.append((time.perf_counter() - t0) * 1e3)
    return statistics.median(ts)


def _kernel_roofline(cols, rows, tot_us, n_steps=2, top=10,
                     edge_occ_frac=None) -> list:
    """Per-kernel roofline attribution from the hlo_stats trace rows:
    for each of the ``top`` ops by device self time, report its time
    share, its bytes — MEASURED (self time x xprof's measured BW) for
    regular HLO ops, operand-shape COST-MODEL bytes for custom-calls
    (Pallas kernels, which xprof reports no BW for) — its achieved
    GB/s, and its fraction of the chip's HBM roofline. This is how a
    fusion's win is ATTRIBUTED rather than inferred: the op it removed
    disappears from the table, and the kernel that replaced it shows
    its own bytes/time against the roofline (ISSUE 6 satellite;
    docs/PERF.md "Per-kernel roofline")."""
    import jax

    try:
        from tools.analyze_hlo_stats import _customcall_bytes
    except ImportError:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from tools.analyze_hlo_stats import _customcall_bytes

    peak_bw = _peak_hbm_bw(jax.devices()[0])
    i_t = cols.index("total_self_time")
    i_bw = cols.index("measured_memory_bw")
    i_cat = cols.index("category")
    i_expr = cols.index("hlo_op_expression")
    ops = []
    for row in rows:
        cells = row["c"]
        t_us = float((cells[i_t] or {}).get("v") or 0.0)
        if t_us <= 0:
            continue
        cat = str((cells[i_cat] or {}).get("v") or "")
        expr = str((cells[i_expr] or {}).get("v") or "")
        bw = float((cells[i_bw] or {}).get("v") or 0.0)  # GiB/s, 0 for kernels
        if cat == "custom-call":
            nbytes = _customcall_bytes(expr) * (
                float((cells[cols.index("occurrences")] or {}).get("v") or 1.0)
                if "occurrences" in cols
                else 1.0
            )
            src = "costmodel"
        else:
            nbytes = bw * (2**30) * (t_us / 1e6)
            src = "measured"
        # a short, stable op label: the assignment target of the HLO
        # expression (e.g. "%fusion.123"), else the category
        label = expr.split("=", 1)[0].strip() if "=" in expr else cat
        ops.append((t_us, cat, label[:60], nbytes, src))
    ops.sort(reverse=True)
    out = []
    for t_us, cat, label, nbytes, src in ops[:top]:
        gbps = nbytes / (t_us / 1e6) / 1e9 if t_us > 0 else 0.0
        entry = {
            "op": label,
            "category": cat,
            "time_ms_per_step": round(t_us / 1e3 / n_steps, 3),
            "pct_device_time": round(100.0 * t_us / max(tot_us, 1e-9), 1),
            "bytes_per_step": round(nbytes / n_steps),
            "bytes_source": src,
            "gbps": round(gbps, 1),
        }
        # cost-model entries price PADDED operand shapes; the batch's
        # real-edge occupancy says how much of that a kernel bounding
        # its chunk loop at the occupancy actually moves (ISSUE 10)
        if src == "costmodel" and edge_occ_frac is not None:
            entry["bytes_per_step_useful"] = round(
                nbytes / n_steps * edge_occ_frac
            )
            entry["pad_waste_frac"] = round(1.0 - edge_occ_frac, 4)
        if peak_bw:
            entry["pct_hbm_roofline"] = round(100.0 * gbps * 1e9 / peak_bw, 1)
        out.append(entry)
    return out


def _measured_traffic(compiled, state, batches, edge_occ_frac=None) -> dict:
    """Trace 2 executions and sum per-op device self time and
    self_time x measured-BW bytes from xprof's hlo_stats — the
    MEASURED counterpart of the cost model's 'bytes accessed', which
    ignores fusion and has printed >chip-peak GB/s as achieved
    (VERDICT r03 Weak #2). Returns {} when the profiler/converter is
    unavailable (e.g. CPU smoke)."""
    import glob
    import shutil
    import tempfile

    import jax
    import numpy as np

    tdir = tempfile.mkdtemp(prefix="bench_trace_")
    try:
        try:
            with jax.profiler.trace(tdir):
                st = state
                for i in range(2):
                    st, loss, _ = compiled(st, batches[i % len(batches)])
                np.asarray(loss)
            planes = glob.glob(f"{tdir}/**/*.xplane.pb", recursive=True)
            if not planes:
                return {}
            from xprof.convert import raw_to_tool_data as rd

            data, _ = rd.xspace_to_tool_data(planes, "hlo_stats", {"tqx": "out:csv;"})
            if isinstance(data, bytes):
                data = data.decode("utf-8", "replace")
            import json as _json

            tab = _json.loads(data)
            cols = [c["id"] for c in tab["cols"]]
            i_t = cols.index("total_self_time")
            i_bw = cols.index("measured_memory_bw")
            tot_us = 0.0
            tot_bytes = 0.0
            for row in tab["rows"]:
                cells = row["c"]
                t_us = float((cells[i_t] or {}).get("v") or 0.0)
                bw = float((cells[i_bw] or {}).get("v") or 0.0)  # GiB/s
                tot_us += t_us
                tot_bytes += bw * (2**30) * (t_us / 1e6)
            if tot_us <= 0:
                return {}
            out = {
                "device_step_ms_traced": round(tot_us / 1e3 / 2, 3),
                "bytes_per_step_measured": round(tot_bytes / 2),
                "hbm_gbps_measured": round(tot_bytes / (tot_us / 1e6) / 1e9, 1),
            }
            # per-kernel roofline attribution (fused-kernel wins show up
            # as the replaced ops VANISHING from this table; guarded —
            # an hlo_stats dialect without the columns must not cost the
            # measurement above)
            try:
                out["roofline"] = _kernel_roofline(
                    cols, tab["rows"], tot_us, edge_occ_frac=edge_occ_frac
                )
            except Exception:
                pass
            # xprof reports no memory BW for custom-calls (Pallas
            # kernels), so their DMA traffic is invisible to the
            # measured sum; the CSR kernels stream each operand once by
            # construction, so operand+result shape bytes are a sound
            # per-op estimate (tools/analyze_hlo_stats.py, r05).
            # Guarded separately: a converter without these columns must
            # only cost the NEW fields, not the measurement above.
            try:
                try:
                    from tools.analyze_hlo_stats import _customcall_bytes
                except ImportError:  # invoked from outside the repo root
                    sys.path.insert(
                        0, os.path.dirname(os.path.abspath(__file__))
                    )
                    from tools.analyze_hlo_stats import _customcall_bytes

                i_cat = cols.index("category")
                i_expr = cols.index("hlo_op_expression")
                i_n = cols.index("occurrences")
                kernel_bytes = 0.0
                for row in tab["rows"]:
                    cells = row["c"]
                    if ((cells[i_cat] or {}).get("v") or "") == "custom-call":
                        occ = float((cells[i_n] or {}).get("v") or 1.0)
                        kernel_bytes += occ * _customcall_bytes(
                            str((cells[i_expr] or {}).get("v") or "")
                        )
                out["kernel_bytes_per_step_est"] = round(kernel_bytes / 2)
                out["hbm_gbps_combined_est"] = round(
                    (tot_bytes + kernel_bytes) / (tot_us / 1e6) / 1e9, 1
                )
                if edge_occ_frac is not None:
                    # shape-priced kernel bytes scaled by the batch's
                    # real-edge occupancy: the USEFUL fraction of that
                    # estimate (occupancy skipping makes the rest free)
                    out["kernel_bytes_per_step_useful_est"] = round(
                        kernel_bytes / 2 * edge_occ_frac
                    )
                    out["kernel_pad_waste_frac"] = round(
                        1.0 - edge_occ_frac, 4
                    )
            except Exception:
                pass
            return out
        except Exception:
            return {}
    finally:
        shutil.rmtree(tdir, ignore_errors=True)


def _bench_one(
    name: str,
    *,
    n_samples: int,
    batch_size: int,
    hidden: int,
    layers: int,
    unit_cells,
    measure_steps: int,
    edge_lengths: bool = False,
    cache: bool = False,
    bf16: bool = True,
    peak: float | None = None,
    scan: bool = False,
    scan_also: bool = False,
    measure_bytes: bool = False,
    dispatch_ms: float | None = None,
) -> dict:
    """Build one config, run ``measure_steps`` train steps, report.

    ``scan=True`` (BENCH_SCAN=1) measures the Training.scan_epoch
    whole-epoch lax.scan dispatch instead of the per-step path. Off by
    default: on the tunneled dev chip the scan executable hits a
    server-side ~0.5s/dispatch pathology (the same step body dispatched
    per-step is ~0.6 ms); on directly-attached pods scan amortizes
    dispatch latency and is the faster mode.
    """
    import jax

    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer

    config, model, variables, loader = build_flagship(
        n_samples=n_samples,
        hidden_dim=hidden,
        num_conv_layers=layers,
        batch_size=batch_size,
        unit_cells=unit_cells,
        cache_device_batches=cache,
        edge_lengths=edge_lengths,
    )
    tx = select_optimizer(config["NeuralNetwork"]["Training"])
    state = create_train_state(variables, tx)
    compute_dtype = None
    if bf16:
        import jax.numpy as jnp

        compute_dtype = jnp.bfloat16

    step = make_train_step(model, tx, compute_dtype=compute_dtype)
    batches = list(loader)
    if not batches:
        raise RuntimeError(f"empty bench loader for config {name}")

    # AOT-compile once: the same executable serves the cost analysis and
    # the timed loop (no double jit-cache compilation). With
    # HYDRAGNN_EXEC_CACHE set, the persistent executable cache
    # (utils/exec_cache.py) replaces a repeated round's lowering+compile
    # with a disk deserialize; without the env var this is byte-for-byte
    # the old path.
    from hydragnn_tpu.utils.exec_cache import (
        ExecCache,
        abstract_fingerprint,
        compat_manifest,
        fingerprint,
    )

    ecache = ExecCache.from_env(consumer="bench")
    exec_cache_hit = False
    if ecache.enabled:
        # cache the donation-free twin of the step — a deserialized
        # DONATED executable is unsound (utils/exec_cache.py docstring)
        import jax

        body = getattr(step, "__wrapped__", None)
        cache_step = jax.jit(body) if body is not None else step
        compiled, exec_cache_hit, _ = ecache.get_or_compile(
            fingerprint(
                "bench_step",
                name,
                abstract_fingerprint((state, batches[0])),
                body is None,
            ),
            cache_step,
            (state, batches[0]),
            compat_manifest(compute_dtype=compute_dtype),
            donated=body is None,
            label=name,
        )
    else:
        compiled = step.lower(state, batches[0]).compile()
    flops, nbytes = _cost_analysis(compiled)

    import numpy as np

    # NOTE: every timed region ends with np.asarray(loss) — a real D2H
    # readback of a value depending on the whole step chain. On the
    # tunneled chip block_until_ready returns at dispatch-ack, so it
    # must NOT be the timing fence (module docstring calibration).
    if scan:
        import jax.numpy as jnp

        from hydragnn_tpu.train import make_scan_epoch

        scan_fn = make_scan_epoch(model, tx, compute_dtype=compute_dtype)
        nb = len(loader)
        stacked = loader.stacked_device_batches()
        order = jnp.arange(nb, dtype=jnp.int32)
        state, losses, _, _ = scan_fn(state, stacked, order)  # compile
        np.asarray(losses)
        done = 0
        t0 = time.perf_counter()
        while done < measure_steps:
            state, losses, _, _ = scan_fn(state, stacked, order)
            done += nb
        np.asarray(losses)
        dt = time.perf_counter() - t0
    else:
        state, loss, _ = compiled(state, batches[0])  # warmup execution
        np.asarray(loss)

        # SEGMENTED timing (VERDICT r03 item 8): >= 3 D2H-fenced
        # segments give a median + spread instead of one number with
        # unbounded tunnel noise
        n_seg = max(3, min(5, measure_steps))
        per_seg = max(1, measure_steps // n_seg)
        seg_ms = []
        done = 0
        t0 = time.perf_counter()
        for _ in range(n_seg):
            t1 = time.perf_counter()
            for _ in range(per_seg):
                state, loss, _ = compiled(state, batches[done % len(batches)])
                done += 1
            np.asarray(loss)
            seg_ms.append((time.perf_counter() - t1) / per_seg * 1e3)
        dt = time.perf_counter() - t0

    step_s = dt / done
    if not scan:
        med = statistics.median(seg_ms)
        # the median segment is the robust step time; the mean (step_s)
        # keeps r02/r03 comparability
        step_s = med / 1e3

    # scan-slope step time (VERDICT r02 item 4): chain the step K times
    # inside one lax.scan dispatch and take the slope between two K
    # values — cancels the tunnel's per-dispatch RTT + server overhead
    # (10-120 ms depending on burst history), which otherwise pollutes
    # small configs whose step is cheaper than the dispatch floor. Costs
    # 2 compiles + 2 dispatches per config. (The full-epoch scan_epoch
    # path is a different executable with its own tunnel pathology —
    # docs/PERF.md; this is the same per-step body, chained.)
    scan_step_ms = None
    smoke_default = "0" if os.environ.get("BENCH_SMOKE", "0") == "1" else "1"
    if os.environ.get("BENCH_SCAN_SLOPE", smoke_default) == "1":
        from hydragnn_tpu.train.state import _train_step_body
        from hydragnn_tpu.utils.profile import scan_slope_ms

        body = _train_step_body(model, tx, compute_dtype=compute_dtype)
        batch0 = batches[0]

        def make_chain(k: int):
            def f(st, _):
                st, loss, _ = body(st, batch0)
                return st, loss

            fn = jax.jit(lambda st: jax.lax.scan(f, st, None, length=k))

            def run():
                _, losses = fn(state)
                np.asarray(losses[-1])  # real D2H sync

            return run

        k1, k2 = (2, 4) if measure_steps <= 4 else (4, 12)
        scan_step_ms = scan_slope_ms(make_chain, k1, k2)
        if scan_step_ms <= 0:
            # two timed dispatches under burst-varying RTT can invert;
            # a non-positive slope is noise — don't record garbage
            scan_step_ms = None

    # scan_epoch wall measurement (VERDICT r04 item 5): the whole-epoch
    # lax.scan dispatch over DEVICE-RESIDENT stacked batches, with the
    # order tiled across epochs so one dispatch covers >= 64 steps —
    # this amortizes the tunnel's per-dispatch floor (~60-70 ms) into
    # noise and yields a WALL number commensurate with traced device
    # time (r05 qm9: 7.06 ms/step wall at 128 steps/dispatch vs 6.28 ms
    # traced = 1.12x; a 1-step dispatch reads 71 ms). This is also the
    # honest production mode for datasets that fit in HBM.
    scan_epoch_ms = None
    if scan_also:
        import jax.numpy as jnp

        from hydragnn_tpu.train import make_scan_epoch

        scan_fn = make_scan_epoch(model, tx, compute_dtype=compute_dtype)
        nb = len(loader)
        stacked = loader.stacked_device_batches()
        reps = max(1, -(-max(measure_steps, 64) // nb))
        order = jnp.tile(jnp.arange(nb, dtype=jnp.int32), reps)
        # scan_fn DONATES its state argument (train/state.py); hand it a
        # copy so `state` stays alive for _measured_traffic below
        s_state = jax.tree_util.tree_map(jnp.array, state)
        s_state, losses, _, _ = scan_fn(s_state, stacked, order)  # compile+warm
        np.asarray(losses)
        t0 = time.perf_counter()
        s_state, losses, _, _ = scan_fn(s_state, stacked, order)
        np.asarray(losses)
        scan_epoch_ms = (time.perf_counter() - t0) * 1e3 / (nb * reps)

    real_nodes = float(
        sum(s.num_nodes for s in loader.samples) / max(len(loader.samples), 1)
    )
    # per-config pad-occupancy + the analytic conv-traffic model
    # (useful vs padded bytes across kernel modes — the numbers the
    # cost model can't see because it prices padded operand shapes)
    from hydragnn_tpu.obs.introspect import (
        conv_traffic_model,
        pad_waste_from_batch,
    )

    pad_waste = pad_waste_from_batch(batches[0])
    conv_traffic = conv_traffic_model(
        pad_waste["node_pad"], pad_waste["edge_pad"], hidden, layers,
        real_edges=pad_waste["real_edges_mean"],
    )
    edge_occ_frac = 1.0 - pad_waste["edge_waste_frac"]
    out = {
        "graphs_per_sec": round(batch_size / step_s, 2),
        "step_ms": round(step_s * 1e3, 3),
        "batch_size": batch_size,
        "steps": done,
        "nodes_per_graph_mean": round(real_nodes, 1),
        "node_pad": int(batches[0].nodes.shape[0]),
        "edge_pad": int(batches[0].senders.shape[0]),
        "edge_features": bool(edge_lengths),
        "hidden_dim": hidden,
        "num_conv_layers": layers,
        "pad_waste": pad_waste,
        "conv_traffic_model": conv_traffic,
    }
    if ecache.enabled:
        out["exec_cache_hit"] = bool(exec_cache_hit)
    if not scan:
        out["step_ms_median"] = round(statistics.median(seg_ms), 3)
        out["step_ms_segments"] = [round(t, 2) for t in seg_ms]
        out["step_ms_spread"] = round(max(seg_ms) - min(seg_ms), 3)
    if measure_bytes:
        out.update(
            _measured_traffic(
                compiled, state, batches, edge_occ_frac=edge_occ_frac
            )
        )
    if scan_step_ms is not None:
        out["scan_step_ms"] = round(scan_step_ms, 3)
        out["graphs_per_sec_scan"] = round(batch_size / max(scan_step_ms, 1e-9) * 1e3, 2)
    if scan_epoch_ms is not None:
        out["scan_epoch_step_ms"] = round(scan_epoch_ms, 3)
        out["scan_epoch_steps_per_dispatch"] = nb * reps
        out["graphs_per_sec_scan_epoch"] = round(
            batch_size / max(scan_epoch_ms, 1e-9) * 1e3, 2
        )
    # Dispatch-dominated configs (step < ~2x the tunnel's per-dispatch
    # floor) understate DEVICE throughput by up to 3x; the scan-slope
    # number (same step body, K chained per dispatch) is the honest
    # headline there (VERDICT r03 item 6). Scan-slope itself is noisy
    # for small steps (two timed dispatches under burst-varying RTT —
    # adjacent identical runs have measured 1.4 vs 9.3 ms), so it is
    # clamped from below by the traced device self time: a slope under
    # what the device physically spends is noise, not throughput.
    traced = out.get("device_step_ms_traced")
    if (
        scan_epoch_ms is not None
        and dispatch_ms is not None
        and step_s * 1e3 < 2.0 * dispatch_ms
    ):
        # the scan_epoch number is a genuine WALL measurement (>= 64
        # steps per D2H-fenced dispatch) — it cannot under-run device
        # time, so no clamp is needed; it supersedes the noisier
        # scan-slope estimate as the dispatch-dominated headline
        out["headline_graphs_per_sec"] = round(
            batch_size / scan_epoch_ms * 1e3, 2
        )
        out["headline_protocol"] = "scan_epoch wall (per-step d2h is dispatch-dominated)"
    elif (
        scan_step_ms is not None
        and dispatch_ms is not None
        and step_s * 1e3 < 2.0 * dispatch_ms
    ):
        headline_ms = scan_step_ms
        if traced is None:
            proto = "scan-slope (per-step d2h is dispatch-dominated; UNCLAMPED: no trace)"
        elif traced > headline_ms:
            headline_ms = traced
            proto = "traced device self time (scan-slope under-ran it: noise)"
        else:
            proto = "scan-slope (per-step d2h is dispatch-dominated)"
        out["headline_graphs_per_sec"] = round(batch_size / headline_ms * 1e3, 2)
        out["headline_protocol"] = proto
    else:
        out["headline_graphs_per_sec"] = out["graphs_per_sec"]
        out["headline_protocol"] = "per-step d2h"
    # the same noise clamp applies to every scan-slope-derived rate
    # (mfu_scan once reported >1.0 from a noise slope)
    scan_clamped_ms = scan_step_ms
    if scan_clamped_ms is not None and traced is not None:
        scan_clamped_ms = max(scan_clamped_ms, traced)
    scan_s = (scan_clamped_ms or 0.0) / 1e3
    if flops:
        out["flops_per_step"] = flops
        out["achieved_tflops"] = round(flops / step_s / 1e12, 3)
        if peak:
            out["mfu"] = round(flops / step_s / peak, 4)
            if scan_s > 0:
                out["mfu_scan"] = round(flops / scan_s / peak, 4)
    if nbytes:
        # COST-MODEL bytes ignore fusion — an UPPER BOUND on traffic,
        # not a measurement (r03 printed 1920 GB/s "achieved" on a
        # ~820 GB/s chip from these; VERDICT r03 Weak #2). The measured
        # numbers (bytes_per_step_measured / hbm_gbps_measured, from
        # the xprof trace) are the achieved-traffic fields.
        out["bytes_per_step_costmodel"] = nbytes
        out["hbm_gbps_costmodel_upper_bound"] = round(nbytes / step_s / 1e9, 1)
        if flops:
            out["arithmetic_intensity_costmodel"] = round(flops / nbytes, 2)
    return out


def _load_baseline(here: str) -> float | None:
    """Earliest recorded round's headline graphs/sec (driver-written
    BENCH_r*.json wrap the printed line under "parsed"), else
    BENCH_BASELINE.json, else None. Records WITHOUT the
    ``"timing": "d2h-sync"`` marker are skipped: they predate the timing
    fix (r01 measured dispatch-ack rate, ~1000x off device throughput)
    and comparing against them would report a permanent fake regression."""
    rounds = []
    for fname in os.listdir(here):
        m = re.fullmatch(r"BENCH_r(\d+)\.json", fname)
        if m:
            rounds.append((int(m.group(1)), fname))
    candidates = [f for _, f in sorted(rounds)] + ["BENCH_BASELINE.json"]
    for fname in candidates:
        p = os.path.join(here, fname)
        if not os.path.exists(p):
            continue
        try:
            with open(p) as f:
                rec = json.load(f)
            rec = rec.get("parsed", rec)
            if (
                rec.get("unit") == "graphs/sec"
                and rec.get("value")
                and rec.get("timing") == "d2h-sync"
                # partial rounds (BENCH_CONFIGS=qm9 etc.) publish under
                # their own metric name — never the flagship baseline
                and rec.get("metric") == "flagship_pna_multihead_train_throughput"
            ):
                return float(rec["value"])
        except (json.JSONDecodeError, KeyError, TypeError, ValueError, AttributeError):
            continue
    return None


def emit_backend_failure(metric: str, exc) -> "SystemExit":
    """Print ONE structured JSON failure line (the record a
    ``hydragnn_tpu.utils.platform.BackendInitError`` carries, or a
    synthesized one) and return a clean SystemExit — drivers capture a
    parseable record instead of a raw traceback (ISSUE r05 Weak #1).
    The record carries ``retries`` (attempts beyond the first that
    ``init_backend_with_retry`` burned before giving up)."""
    record = getattr(
        exc,
        "record",
        {
            "failure": "backend_init",
            "stage": "device_query",
            "jax_platforms": os.environ.get("JAX_PLATFORMS"),
            "error": str(exc).strip()[-400:],
            "error_type": type(exc).__name__,
        },
    )
    record.setdefault("retries", 0)
    print(json.dumps({"metric": metric, "value": None, "unit": None, **record}))
    return SystemExit(1)


def open_bench_flight(default_name: str) -> "object":
    """Fresh flight recorder for a bench run — the self-contained JSONL
    evidence artifact committed next to the BENCH_*.json records
    (docs/OBSERVABILITY.md). ``BENCH_FLIGHT`` overrides the path; the
    file is truncated per run (each bench run is one flight)."""
    from hydragnn_tpu.obs import FlightRecorder

    here = os.path.dirname(os.path.abspath(__file__))
    path = os.environ.get("BENCH_FLIGHT", os.path.join(here, default_name))
    try:
        os.remove(path)
    except OSError:
        pass
    return FlightRecorder(path)


def init_device_with_flight(metric: str, flight):
    """Backend init with bounded retry-with-backoff (~5 attempts over
    ~2 min for transient UNAVAILABLE-class failures; config errors fail
    fast), every retry and the terminal failure recorded into the
    flight record. Returns (device, retries)."""
    from hydragnn_tpu.utils.platform import (
        BackendInitError,
        init_backend_with_retry,
    )

    def _on_retry(attempt, exc, delay):
        flight.retry(
            attempt, str(exc), stage="backend_init", next_delay_s=delay
        )
        print(
            f"backend init attempt {attempt} failed ({str(exc).strip()[-200:]});"
            f" retrying in {delay:.0f}s",
            file=sys.stderr,
        )

    try:
        devices, retries = init_backend_with_retry(on_retry=_on_retry)
    except (BackendInitError, RuntimeError, AssertionError) as exc:
        flight.error(exc, stage="backend_init")
        flight.end_run(status="failed")
        flight.close()
        raise emit_backend_failure(metric, exc) from exc
    return devices[0], retries


def main() -> None:
    # honor an explicit JAX_PLATFORMS (e.g. cpu for CI smoke) — the axon
    # plugin image overrides the env unless pinned through jax.config
    # BEFORE backend init (hydragnn_tpu/utils/platform.py); without a
    # pin the bench stays on the real device the driver provides.
    # Transient init failures retry with backoff; the flight record is
    # the evidence artifact either way.
    _metric = "flagship_pna_multihead_train_throughput"
    flight = open_bench_flight("BENCH_FLIGHT.jsonl")
    device, init_retries = init_device_with_flight(_metric, flight)
    peak = _peak_flops(device)
    bf16 = os.environ.get("BENCH_BF16", "1") == "1"
    cache = os.environ.get("BENCH_CACHE", "0") == "1"

    # BENCH_SMOKE=1: shrink every config so the whole bench runs in
    # seconds on a CPU (CI smoke); real numbers come from the full sizes
    # on the TPU. Explicit BENCH_* env knobs still win.
    smoke = os.environ.get("BENCH_SMOKE", "0") == "1"

    # Headline config knobs (tiny-BCC flagship), sized to the single-chip
    # sweet spot measured on v5e: batch 1024 fills the chip, HBM tops
    # out before 2048. NOTE: default changes reset comparability with
    # recorded BENCH_r*.json baselines.
    # (n_samples dropped 2560 -> 1280 in r02: with honest D2H timing the
    # steps cost real seconds and host-side data generation dominated the
    # bench budget; comparability was already reset by the timing fix)
    n_samples = int(os.environ.get("BENCH_SAMPLES", 80 if smoke else 1280))
    batch_size = int(os.environ.get("BENCH_BATCH", 16 if smoke else 1024))
    hidden = int(os.environ.get("BENCH_HIDDEN", 16 if smoke else 128))
    layers = int(os.environ.get("BENCH_LAYERS", 2 if smoke else 6))
    measure_steps = int(os.environ.get("BENCH_STEPS", 4 if smoke else 20))
    if int(0.8 * n_samples) < batch_size:
        raise SystemExit(
            f"BENCH_SAMPLES={n_samples} yields {int(0.8 * n_samples)} train "
            f"samples < BENCH_BATCH={batch_size}; raise BENCH_SAMPLES or "
            "lower BENCH_BATCH"
        )

    # dispatch floor measured FIRST: after the timed configs the tunnel's
    # post-burst throttle inflates it ~10x, making it useless as the
    # step-time decomposition floor it exists to be
    dispatch_ms = round(_measure_dispatch_ms(), 3)
    # measured HBM traffic via a 2-step xprof trace per config (adds ~2
    # dispatches + converter time; skipped on smoke/CPU where the
    # device trace has no HBM counters)
    measure_bytes = (
        os.environ.get("BENCH_MEASURE_BYTES", "0" if smoke else "1") == "1"
    )

    raw = os.environ.get("BENCH_CONFIGS", "flagship,qm9,large")
    which = [t.strip() for t in raw.split(",") if t.strip()]
    known = {"flagship", "qm9", "large"}
    unknown = [t for t in which if t not in known]
    if unknown or not which:
        raise SystemExit(
            f"BENCH_CONFIGS={raw!r}: unknown config(s) {unknown or '(empty)'}; "
            f"valid names: {sorted(known)}"
        )
    scan = os.environ.get("BENCH_SCAN", "0") == "1"
    configs: dict = {}

    # the bench measures the single-chip hot path; saying so through the
    # Partitioner keeps bench/train/serve on one sharding vocabulary
    # (docs/PARALLELISM.md — multi-width runs live in bench_scaling.py)
    from hydragnn_tpu.parallel import Partitioner

    flight.start_run(
        {
            "mode": "bench",
            "metric": _metric,
            "device_kind": getattr(device, "device_kind", str(device)),
            "configs": which,
            "bf16": bf16,
            "smoke": smoke,
            "dispatch_ms": dispatch_ms,
            "init_retries": init_retries,
            "parallel": Partitioner().manifest(),
            "knobs": {
                "samples": n_samples,
                "batch": batch_size,
                "hidden": hidden,
                "layers": layers,
                "steps": measure_steps,
            },
        }
    )

    def _run_config(name: str, **kw) -> dict:
        """One bench config, flight-recorded: the result event lands as
        soon as the config finishes, so a later config dying (the r05
        artifact failure mode) cannot erase the evidence of the ones
        that ran."""
        try:
            out = _bench_one(name, **kw)
        except BaseException as exc:
            flight.error(exc, stage=f"config:{name}")
            flight.end_run(status="failed")
            flight.close()
            raise
        flight.record("bench_config", name=name, result=out)
        return out

    # headline first: the tunnel throttles after a dispatch burst, so the
    # round-over-round comparable number gets the fresh budget
    if "flagship" in which:
        configs["flagship_tiny_bcc"] = _run_config(
            "flagship_tiny_bcc",
            n_samples=n_samples,
            batch_size=batch_size,
            hidden=hidden,
            layers=layers,
            unit_cells=(2, 4),  # build_flagship default: r01 comparability
            measure_steps=measure_steps,
            cache=cache,
            bf16=bf16,
            peak=peak,
            scan=scan,
            measure_bytes=measure_bytes,
            dispatch_ms=dispatch_ms,
        )
    if "qm9" in which:
        # QM9-realistic: molecule-sized graphs (QM9 mean ~18 heavy+H
        # atoms), length edge features through the PNA stack, the
        # examples/qm9 architecture shape
        configs["qm9_scale"] = _run_config(
            "qm9_scale",
            n_samples=48 if smoke else 384,
            batch_size=16 if smoke else 256,
            hidden=16 if smoke else 64,
            layers=2 if smoke else 6,
            unit_cells=(2, 3),
            measure_steps=2 if smoke else min(measure_steps, 15),
            edge_lengths=True,
            cache=cache,
            bf16=bf16,
            peak=peak,
            # qm9's per-step wall is dispatch-floor-dominated (43.5 ms
            # recorded at r04 against 6.28 ms device); the scan_epoch
            # wall is the figure that amortizes it
            scan_also=not smoke,
            measure_bytes=measure_bytes,
            dispatch_ms=dispatch_ms,
        )
    if "large" in which:
        # large graphs (hundreds of nodes: OC-supercell scale per graph)
        configs["large_graph"] = _run_config(
            "large_graph",
            n_samples=12 if smoke else 48,
            batch_size=4 if smoke else 32,
            hidden=16 if smoke else hidden,
            layers=2 if smoke else layers,
            unit_cells=(4, 5) if smoke else (6, 8),
            measure_steps=2 if smoke else min(measure_steps, 10),
            cache=cache,
            bf16=bf16,
            peak=peak,
            measure_bytes=measure_bytes,
            dispatch_ms=dispatch_ms,
        )

    if "flagship_tiny_bcc" in configs:
        headline_name, metric = (
            "flagship_tiny_bcc",
            "flagship_pna_multihead_train_throughput",
        )
    else:
        # partial run: publish under the actual config's name and skip
        # the flagship baseline comparison (apples-to-oranges otherwise)
        headline_name = next(iter(configs))
        metric = f"{headline_name}_train_throughput"
    graphs_per_sec = configs[headline_name]["graphs_per_sec"]

    baseline = None
    if headline_name == "flagship_tiny_bcc":
        here = os.path.dirname(os.path.abspath(__file__))
        baseline = _load_baseline(here)
    vs_baseline = graphs_per_sec / baseline if baseline else 1.0

    record = {
        "metric": metric,
        "value": graphs_per_sec,
        "unit": "graphs/sec",
        "vs_baseline": round(vs_baseline, 3),
        "timing": "d2h-sync",
        "init_retries": init_retries,
        "vs_baseline_note": (
            "r01 measured dispatch-ack timing (no device sync; see module "
            "docstring) — comparable baselines start at r02"
        ),
        "device": getattr(device, "device_kind", str(device)),
        "bf16": bf16,
        "dispatch_ms": dispatch_ms,
        "peak_bf16_tflops": peak / 1e12 if peak else None,
        "configs": configs,
    }
    # The driver captures only a ~2000-char stdout TAIL; the full
    # per-config blob (several KB) once truncated an entire round's
    # record mid-object (BENCH_r04 "parsed": null). The full record goes
    # to a file in-tree; stdout gets a compact single line that always
    # fits, carrying the headline plus the per-config numbers the
    # round-over-round tables are built from.
    here = os.path.dirname(os.path.abspath(__file__))
    local_path = os.path.join(here, "BENCH_LOCAL.json")
    try:
        with open(local_path, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
    except OSError as e:
        print(f"warning: could not write {local_path}: {e}", file=sys.stderr)

    def _brief(c: dict) -> dict:
        out = {}
        for src, dst in (
            ("graphs_per_sec", "gps"),
            ("headline_graphs_per_sec", "gps_headline"),
            ("step_ms", "step_ms"),
            ("scan_step_ms", "scan_ms"),
            ("scan_epoch_step_ms", "scan_ep_ms"),
            ("device_step_ms_traced", "dev_ms"),
            ("hbm_gbps_measured", "gbps"),
        ):
            v = c.get(src)
            if isinstance(v, (int, float)):
                out[dst] = round(v, 2)
        return out

    compact = {
        "metric": metric,
        "value": graphs_per_sec,
        "unit": "graphs/sec",
        "vs_baseline": round(vs_baseline, 3),
        "timing": "d2h-sync",
        "device": record["device"],
        "dispatch_ms": dispatch_ms,
        "full_record": "BENCH_LOCAL.json",
        "summary": {name: _brief(c) for name, c in configs.items()},
    }
    line = json.dumps(compact)
    # belt-and-braces: shed per-config summaries one at a time (last
    # config first — the flagship headline survives longest) until the
    # line fits the driver's ~2000-char stdout tail
    while len(line) > 1800 and compact["summary"]:
        compact["summary"].pop(next(reversed(compact["summary"])))
        compact["summary_truncated"] = True
        line = json.dumps(compact)
    flight.end_run(
        status="completed",
        metric=metric,
        value=graphs_per_sec,
        vs_baseline=round(vs_baseline, 3),
        init_retries=init_retries,
    )
    flight.close()
    print(line)


if __name__ == "__main__":
    main()
