"""HydraGNN-TPU: a TPU-native multi-task graph neural network framework.

A ground-up JAX/XLA/pjit re-design with the capabilities of HydraGNN
(reference: /root/reference — ORNL HydraGNN, mirrored by
Utah-Math-Data-Science/HydraGNN): one shared message-passing encoder,
N decoder heads predicting graph-level and/or node-level properties,
trained data-parallel over a TPU device mesh.

Key design departures from the torch/CUDA reference (see SURVEY.md §7):
  - ragged PyG ``Data``/``Batch``  ->  statically-padded ``GraphBatch`` pytrees
  - torch-scatter aggregation      ->  XLA segment ops on sorted edge ids
  - DDP/NCCL data parallelism      ->  ``jit`` over a ``jax.sharding.Mesh``
  - torch BatchNorm                ->  mask-aware BatchNorm with optional
                                       cross-device ``psum`` (SyncBN parity)

Public entry points mirror the reference API surface
(reference: hydragnn/__init__.py:1-3, run_training.py:42, run_prediction.py:27):

    import hydragnn_tpu
    hydragnn_tpu.run_training("config.json")
    hydragnn_tpu.run_prediction("config.json")
"""

from hydragnn_tpu import graph  # noqa: F401
from hydragnn_tpu import models  # noqa: F401
from hydragnn_tpu import obs  # noqa: F401
from hydragnn_tpu import utils  # noqa: F401

__version__ = "0.1.0"


# Entry points live in hydragnn_tpu.api (a distinct module name, so the
# lazy import cannot rebind these wrapper attributes to a submodule).
def run_training(config, **kwargs):
    from hydragnn_tpu.api import run_training as _rt

    return _rt(config, **kwargs)


def run_prediction(config, **kwargs):
    from hydragnn_tpu.api import run_prediction as _rp

    return _rp(config, **kwargs)


def serve_model(config, **kwargs):
    from hydragnn_tpu.api import serve_model as _sm

    return _sm(config, **kwargs)
