"""One fleet replica: a supervised :class:`ModelServer` lifecycle wrapper.

A replica IS a ModelServer — same bucket ladder, same dispatch
supervisor, same health surface — plus the lifecycle the fleet layer
needs around it:

  - **warm spawn**: every replica is built against the fleet's shared
    ``exec_cache_dir``, so the first replica pays the AOT compiles and
    every later one deserializes the whole ladder from disk
    (0 compiles, ``exec_cache_hits == len(buckets)`` — the ~0.14s
    cold-start the exec-cache PR measured);
  - **in-flight accounting**: the router routes on
    :meth:`load` (queued + executing requests) and retirement waits on
    it — a drained replica has zero unresolved futures by definition;
  - **drain-then-stop retirement**: :meth:`drain_stop` stops admitting
    (the router un-targets it first), waits for in-flight work, then
    stops the server — scale-down never fails a request;
  - **probe export**: :meth:`export_probe` writes a per-replica
    Prometheus textfile with the STANDARD ``hydragnn_serve_ready`` /
    ``hydragnn_serve_live`` gauge names, so ``tools/serve_probe.py``
    (and its ``--fleet`` aggregate mode) probes a replica exactly like
    a standalone server. (The replica's registry metrics are prefixed
    ``fleet.<name>.*`` to avoid aliasing in the shared fleet registry,
    which would render as ``hydragnn_fleet_<name>_ready`` — not the
    probe contract — hence this dedicated writer.)

Health verdicts come from ``ModelServer.health()`` unchanged: a replica
whose dispatch supervisor gave up reports ``live=False`` and the fleet
controller reaps and replaces it.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, Optional

from hydragnn_tpu.serve.batcher import ServerClosed
from hydragnn_tpu.serve.server import ModelServer
from hydragnn_tpu.utils import syncdebug


class ReplicaFailed(RuntimeError):
    """Spawning or retiring a replica failed; the fleet itself survives
    (the controller records the failure and keeps its bounds)."""


class FleetReplica:
    """Lifecycle wrapper around one started-or-starting ModelServer.

    States: ``starting`` (built, ladder warming) -> ``ready``
    (serving) -> ``draining`` (no new admissions, in-flight work
    finishing) -> ``stopped``. A replica that died under its server's
    restart budget shows ``live=False`` in any state — state tracks
    intent, health tracks reality.
    """

    def __init__(self, name: str, model: str, server: ModelServer):
        self.name = name
        self.model = model
        self.server = server
        self._lock = syncdebug.maybe_wrap(
            threading.Condition(), "fleet.FleetReplica._lock"
        )
        self._inflight = 0  # graftsync: guarded-by=fleet.FleetReplica._lock
        self._draining = False  # graftsync: guarded-by=fleet.FleetReplica._lock
        self._stopped = False  # graftsync: guarded-by=fleet.FleetReplica._lock
        self.spawned_t = time.monotonic()

    # -- request path (router only) ----------------------------------------

    def submit(self, sample: Any, seq: int = -1, tenant: str = "default") -> Future:
        """Admit one request on this replica's server, counting it
        in-flight until its future resolves (the drain barrier).
        ``tenant`` flows through to the server's request spool so
        per-tenant traffic stays attributable in the spooled shards."""
        with self._lock:
            if self._draining or self._stopped:
                raise ServerClosed(
                    f"replica {self.name} is "
                    f"{'draining' if self._draining else 'stopped'}"
                )
            self._inflight += 1
        try:
            fut = self.server.submit(sample, tenant=tenant)
        except BaseException:
            self._dec_inflight()
            raise
        fut.add_done_callback(lambda _f: self._dec_inflight())
        return fut

    def _dec_inflight(self) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            if self._inflight == 0:
                self._lock.notify_all()

    def load(self) -> int:
        """Unresolved requests on this replica (queued + executing) —
        the router's least-loaded placement signal; a superset of the
        server's queue depth that also covers batches in flight."""
        with self._lock:
            return self._inflight

    def queue_depth(self) -> int:
        return self.server.queue_depth()

    # -- health -------------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        h = self.server.health()
        h["replica"] = self.name
        h["model"] = self.model
        h["state"] = self.state
        h["inflight"] = self.load()
        return h

    @property
    def live(self) -> bool:
        return bool(self.server.health()["live"])

    @property
    def ready(self) -> bool:
        """Routable: the server says READY and the fleet has not begun
        retiring or pausing this replica."""
        with self._lock:
            if self._draining or self._stopped:
                return False
        return bool(self.server.health()["ready"])

    @property
    def state(self) -> str:
        with self._lock:
            if self._stopped:
                return "stopped"
            if self._draining:
                return "draining"
        return "ready" if self.server.health()["ready"] else "starting"

    # -- retirement ---------------------------------------------------------

    def drain(self, timeout: Optional[float] = 30.0) -> bool:
        """Stop admitting and wait until every in-flight request has
        resolved; returns False on timeout (requests still pending —
        the caller decides whether to stop anyway)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            self._draining = True
            while self._inflight > 0:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._lock.wait(timeout=remaining)
        return True

    def undrain(self) -> None:
        """Re-open admissions (rolling reload resumes a paused replica;
        a stopped replica stays stopped)."""
        with self._lock:
            if not self._stopped:
                self._draining = False

    def drain_stop(self, timeout: Optional[float] = 30.0) -> bool:
        """Graceful retirement: drain, then stop the server (which
        flushes its own queue and finalizes its flight record). Returns
        whether the drain completed before the timeout."""
        drained = self.drain(timeout)
        with self._lock:
            self._stopped = True
        self.server.stop()
        return drained

    def kill(self) -> None:
        """Simulated abrupt replica death (chaos/test hook): the
        dispatch restart budget is marked exhausted and every queued
        request fails with the typed dispatch error — exactly the
        observable state of a replica whose supervisor gave up, which
        is what the controller's reap path keys on."""
        sup = self.server._supervisor
        if sup is not None:
            sup.failed = True
        self.server._on_dispatch_giveup(ReplicaFailed(f"replica {self.name} killed"))

    # -- probe export --------------------------------------------------------

    def export_probe(self, path: str) -> None:
        """Write this replica's probe textfile with the standard
        ``hydragnn_serve_{live,ready}`` gauge names (the
        ``tools/serve_probe.py`` contract), atomically."""
        h = self.server.health()
        ready = h["ready"]
        with self._lock:
            ready = ready and not (self._draining or self._stopped)
        write_probe_textfile(path, live=h["live"], ready=ready)


def write_probe_textfile(path: str, *, live: bool, ready: bool) -> None:
    """Minimal probe exposition: the two gauges ``serve_probe`` parses,
    under the standard names regardless of the writer's registry
    prefix. Atomic rename so a probe never reads a half-written file."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    body = (
        "# TYPE hydragnn_serve_live gauge\n"
        f"hydragnn_serve_live {1 if live else 0}\n"
        "# TYPE hydragnn_serve_ready gauge\n"
        f"hydragnn_serve_ready {1 if ready else 0}\n"
    )
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    with open(tmp, "w") as f:
        f.write(body)
    os.replace(tmp, path)
