"""Autoscaling multi-tenant serving fleet (docs/FLEET.md).

N supervised :class:`~hydragnn_tpu.serve.server.ModelServer` replicas
behind one admission router with per-tenant quotas and priority
classes, scaled by a trigger-driven controller and reloaded fleet-wide
one replica at a time. Composition layer only: batching, buckets,
canary reloads, SLO triggers, and tracing all come from ``serve/`` and
``obs/`` unchanged.
"""

from hydragnn_tpu.fleet.controller import ControllerConfig, FleetController
from hydragnn_tpu.fleet.fleet import Fleet
from hydragnn_tpu.fleet.replica import FleetReplica, ReplicaFailed, write_probe_textfile
from hydragnn_tpu.fleet.router import (
    FleetRouter,
    RouterConfig,
    TenantOverloaded,
    TenantQuota,
)

__all__ = [
    "ControllerConfig",
    "Fleet",
    "FleetController",
    "FleetReplica",
    "FleetRouter",
    "ReplicaFailed",
    "RouterConfig",
    "TenantOverloaded",
    "TenantQuota",
    "write_probe_textfile",
]
