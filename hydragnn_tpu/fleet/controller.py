"""Trigger-driven fleet autoscaler: breach -> replica, quiet -> retire.

The controller owns NO load math of its own — the breach signal is the
r12 :class:`~hydragnn_tpu.obs.triggers.TriggerEngine` evaluating the
fleet registry's aggregates (``fleet.queue_depth`` gauge,
``fleet.latency_s`` histogram p99), the same rule kinds, the same
injectable clock, the same evidence discipline. What the controller
adds is the decision policy around the verdicts:

  - **sustained breach** -> scale up: a verdict must repeat for
    ``breach_evals`` consecutive evaluation steps before a replica is
    spawned (one latency blip is not a capacity problem);
  - **cooldown**: at most one scale decision per ``cooldown_s`` — the
    fleet must see the effect of the last decision before making
    another (a fresh replica needs a moment to absorb queue);
  - **bounds**: never below ``min_replicas`` (scale-down) or above
    ``max_replicas`` (a breach at the cap records a ``hold`` —
    suppressed-and-counted, never silent);
  - **quiet scale-down**: fleet load continuously at/below
    ``quiet_load`` for ``quiet_for_s`` retires the least-loaded
    replica (drain-then-stop — zero dropped requests);
  - **reap**: a replica whose server is no longer live (dispatch
    restart budget exhausted, killed) is detached and replaced
    immediately, outside the cooldown — restoring capacity is never
    rate-limited.

Every decision — up, down, replace, hold, up_failed — is one
``fleet_scale`` flight event with the action, the reason (trigger rule
name, ``quiet``, ``dead_replica``...), and the resulting replica
count. Tests drive :meth:`FleetController.step` directly under a fake
clock; production runs the same step from the background loop thread.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional

from hydragnn_tpu.obs.triggers import TriggerEngine, TriggerRule
from hydragnn_tpu.utils import knobs, syncdebug


@dataclasses.dataclass(frozen=True)
class ControllerConfig:
    """Scaling policy. ``None`` fields resolve from the
    ``HYDRAGNN_FLEET_*`` knobs at controller construction, so an
    explicit argument always wins over the environment.

    ``slo_queue_depth``/``slo_p99_ms`` parameterize the trigger rules
    the controller builds when no engine is injected; ``quiet_load`` is
    the fleet in-flight count at/below which the fleet counts as quiet.
    """

    min_replicas: Optional[int] = None
    max_replicas: Optional[int] = None
    cooldown_s: Optional[float] = None
    quiet_for_s: Optional[float] = None
    eval_every_s: Optional[float] = None
    quiet_load: int = 0
    breach_evals: int = 2
    slo_queue_depth: Optional[float] = None
    slo_p99_ms: Optional[float] = None
    drain_timeout_s: float = 30.0


class FleetController:
    """Autoscaler over a fleet.

    ``fleet`` is duck-typed (the real :class:`~hydragnn_tpu.fleet.fleet.
    Fleet`, or a test stub): it must expose ``replica_count()``,
    ``live_replicas()`` / ``dead_replicas()``, ``scale_up(reason)``,
    ``scale_down(reason, timeout)`` and ``replace(name, reason)``.
    ``engine`` defaults to a TriggerEngine over ``registry`` built from
    the config's SLO fields (no cooldown of its own — the controller
    owns rate limiting). ``clock`` is injectable for fake-clock tests.
    """

    def __init__(
        self,
        fleet,
        registry=None,
        config: Optional[ControllerConfig] = None,
        engine: Optional[TriggerEngine] = None,
        flight=None,
        clock=time.monotonic,
    ):
        cfg = config or ControllerConfig()
        self.fleet = fleet
        self.flight = flight
        self._clock = clock
        self.min_replicas = (
            cfg.min_replicas
            if cfg.min_replicas is not None
            else knobs.get_int("HYDRAGNN_FLEET_MIN_REPLICAS", 1)
        )
        self.max_replicas = (
            cfg.max_replicas
            if cfg.max_replicas is not None
            else knobs.get_int("HYDRAGNN_FLEET_MAX_REPLICAS", 4)
        )
        self.cooldown_s = (
            cfg.cooldown_s
            if cfg.cooldown_s is not None
            else knobs.get_float("HYDRAGNN_FLEET_COOLDOWN_S", 30.0)
        )
        self.quiet_for_s = (
            cfg.quiet_for_s
            if cfg.quiet_for_s is not None
            else knobs.get_float("HYDRAGNN_FLEET_QUIET_S", 60.0)
        )
        self.eval_every_s = (
            cfg.eval_every_s
            if cfg.eval_every_s is not None
            else knobs.get_float("HYDRAGNN_FLEET_EVAL_EVERY_S", 1.0)
        )
        self.quiet_load = int(cfg.quiet_load)
        self.breach_evals = max(1, int(cfg.breach_evals))
        self.drain_timeout_s = float(cfg.drain_timeout_s)
        if engine is None:
            rules = []
            if cfg.slo_queue_depth is not None:
                rules.append(
                    TriggerRule(
                        "fleet_queue_depth", "queue_depth",
                        "fleet.queue_depth", float(cfg.slo_queue_depth),
                    )
                )
            if cfg.slo_p99_ms is not None:
                rules.append(
                    TriggerRule(
                        "fleet_p99", "latency_p99",
                        "fleet.latency_s", cfg.slo_p99_ms / 1e3,
                    )
                )
            # the CONTROLLER owns rate limiting (cooldown_s above); the
            # engine must report every breach it sees, unlimited
            engine = TriggerEngine(
                rules, registry=registry, cooldown_s=0.0,
                max_incidents=1_000_000_000, clock=clock,
            )
        self.engine = engine
        # decision state — only step() (one caller at a time: the loop
        # thread or a test driving it directly) mutates these
        # graftsync: thread-safe=only the single step() caller mutates (loop thread or test)
        self._last_scale_t: Optional[float] = None
        # graftsync: thread-safe=only the single step() caller mutates
        self._breach_streak = 0
        # graftsync: thread-safe=only the single step() caller mutates
        self._quiet_since: Optional[float] = None
        self.decisions: List[Dict[str, Any]] = []  # graftsync: guarded-by=fleet.FleetController._lock
        self._lock = syncdebug.maybe_wrap(
            threading.Lock(), "fleet.FleetController._lock"
        )
        # graftsync: thread-safe=written before the loop thread starts; the loop reads it
        self._loop: Optional[threading.Thread] = None
        # graftsync: thread-safe=threading.Event is internally synchronized
        self._stop = threading.Event()

    # -- decisions -----------------------------------------------------------

    def _decide(self, action: str, reason: str, **detail) -> Dict[str, Any]:
        d = {
            "action": action,
            "reason": reason,
            "replicas": self.fleet.replica_count(),
            **detail,
        }
        with self._lock:
            self.decisions.append(d)
        if self.flight is not None:
            self.flight.record("fleet_scale", **d)
        return d

    def _cooling(self, now: float) -> bool:
        return (
            self._last_scale_t is not None
            and now - self._last_scale_t < self.cooldown_s
        )

    def step(self) -> List[Dict[str, Any]]:
        """One evaluation pass; returns the decisions made (possibly
        empty). Order matters: reap first (capacity restoration is
        never rate-limited), then breach scale-up, then quiet
        scale-down."""
        now = self._clock()
        out: List[Dict[str, Any]] = []

        # 1. reap dead replicas — replace immediately, outside cooldown
        for name in list(self.fleet.dead_replicas()):
            try:
                self.fleet.replace(name, reason="dead_replica")
                out.append(self._decide("replace", "dead_replica", dead=name))
            except Exception as exc:
                out.append(
                    self._decide(
                        "replace_failed", "dead_replica",
                        dead=name, error=repr(exc)[-200:],
                    )
                )
            self._last_scale_t = now

        # 2. breach -> scale up (sustained verdicts only)
        verdicts = self.engine.evaluate()
        if verdicts:
            self._breach_streak += 1
            self._quiet_since = None
        else:
            self._breach_streak = 0
        if verdicts and self._breach_streak >= self.breach_evals:
            rule = verdicts[0].rule
            if self._cooling(now):
                pass  # not a decision yet: the last one is still settling
            elif self.fleet.replica_count() >= self.max_replicas:
                out.append(
                    self._decide("hold", rule, bound="max_replicas")
                )
                self._last_scale_t = now
            else:
                try:
                    name = self.fleet.scale_up(reason=rule)
                    out.append(self._decide("up", rule, spawned=name))
                except Exception as exc:
                    out.append(
                        self._decide("up_failed", rule, error=repr(exc)[-200:])
                    )
                self._last_scale_t = now
                self._breach_streak = 0
            return out

        # 3. quiet fleet -> scale down
        if self.fleet.total_load() <= self.quiet_load:
            if self._quiet_since is None:
                self._quiet_since = now
            quiet_for = now - self._quiet_since
            if (
                quiet_for >= self.quiet_for_s
                and self.fleet.replica_count() > self.min_replicas
                and not self._cooling(now)
            ):
                try:
                    name = self.fleet.scale_down(
                        reason="quiet", timeout=self.drain_timeout_s
                    )
                    out.append(self._decide("down", "quiet", retired=name))
                except Exception as exc:
                    out.append(
                        self._decide(
                            "down_failed", "quiet", error=repr(exc)[-200:]
                        )
                    )
                self._last_scale_t = now
                self._quiet_since = now
        else:
            self._quiet_since = None
        return out

    # -- background loop ----------------------------------------------------

    def start(self) -> "FleetController":
        if self._loop is not None:
            return self
        self._stop.clear()
        self._loop = threading.Thread(
            target=self._run, name="hydragnn-fleet-controller", daemon=True
        )
        self._loop.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._loop is not None:
            self._loop.join(timeout)
            self._loop = None

    # graftsync: thread-root
    def _run(self) -> None:
        while not self._stop.wait(self.eval_every_s):
            try:
                self.step()
            except Exception as exc:
                # the controller must outlive any single bad step; the
                # failure is evidence, not a death
                if self.flight is not None:
                    self.flight.error(exc, where="fleet_controller")

    def decision_log(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self.decisions)
