"""The fleet: N supervised replicas, one router, one metrics registry.

One :class:`Fleet` serves several models (the multi-model registry —
the exec-cache key already fingerprints architecture, so mixed ladders
share one cache directory safely) behind one admission front door.
Everything the subsystem promises composes from pieces that already
exist:

  - replicas are plain :class:`~hydragnn_tpu.serve.server.ModelServer`
    instances wrapped by :class:`~hydragnn_tpu.fleet.replica.
    FleetReplica`, every one built against the SHARED ``exec_cache_dir``
    so only the first pays AOT compiles;
  - per-replica metrics live on the shared fleet registry under
    ``fleet.<replica>.*`` (the :class:`~hydragnn_tpu.serve.metrics.
    ServeMetrics` prefix seam), next to the router's fleet aggregates
    the autoscaler triggers read;
  - scale-up picks the busiest model group, scale-down drains the
    least-loaded replica (never orphaning a model);
  - :meth:`rolling_reload` walks a model's replicas one at a time —
    router pause -> drain -> the server's own canary/rollback
    ``reload()`` -> resume — so the fleet never has fewer than N-1
    replicas serving and a bad candidate rolls back with the fleet
    untouched (one ``fleet_reload`` flight event per replica).

All replica servers share the fleet's flight recorder: one JSONL
carries every replica's ``run_start`` manifest, exec-cache events,
scale decisions, and reload outcomes — the merged timeline ci.sh
validates.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Dict, List, Optional, Sequence

from hydragnn_tpu.fleet.replica import FleetReplica, ReplicaFailed, write_probe_textfile
from hydragnn_tpu.fleet.router import FleetRouter, RouterConfig, TenantQuota
from hydragnn_tpu.obs.registry import MetricsRegistry
from hydragnn_tpu.serve.buckets import build_bucket_ladder
from hydragnn_tpu.serve.metrics import ServeMetrics
from hydragnn_tpu.serve.server import ModelServer, ReloadFailed, ServeConfig
from hydragnn_tpu.utils import syncdebug


@dataclasses.dataclass
class _ModelGroup:
    """One registered model: what a spawn needs to build its server."""

    name: str
    served: Any  # serve/registry.py ServedModel
    reference_samples: Sequence
    serve_config: ServeConfig


class Fleet:
    """Replica orchestration over one shared router and registry.

    ``exec_cache_dir`` is the warm-start seam: every replica's
    ServeConfig is rebuilt to point at it (an explicit per-model
    ``exec_cache_dir`` wins). ``registry`` defaults to a private
    :class:`MetricsRegistry`; pass a shared one to co-locate fleet
    metrics with a larger process.
    """

    def __init__(
        self,
        exec_cache_dir: Optional[str] = None,
        registry: Optional[MetricsRegistry] = None,
        flight=None,
        router_config: Optional[RouterConfig] = None,
        quotas: Optional[Dict[str, TenantQuota]] = None,
    ):
        self.exec_cache_dir = exec_cache_dir
        self.registry = registry if registry is not None else MetricsRegistry()
        if flight is None:
            from hydragnn_tpu.obs import FlightRecorder

            flight = FlightRecorder(None, enabled=False)
        self.flight = flight
        self.router = FleetRouter(
            self.registry, flight=flight, quotas=quotas, config=router_config
        )
        self._lock = syncdebug.maybe_wrap(
            threading.Lock(), "fleet.Fleet._lock"
        )
        # graftsync: guarded-by=fleet.Fleet._lock
        self._models: Dict[str, _ModelGroup] = {}
        self._next_replica = 0  # graftsync: guarded-by=fleet.Fleet._lock

    # -- model registry -----------------------------------------------------

    def add_model(
        self,
        name: str,
        served,
        reference_samples: Sequence,
        serve_config: Optional[ServeConfig] = None,
        replicas: int = 1,
    ) -> List[FleetReplica]:
        """Register one model and spawn its initial replicas."""
        cfg = serve_config or ServeConfig()
        cfg = dataclasses.replace(
            cfg,
            exec_cache_dir=cfg.exec_cache_dir or self.exec_cache_dir,
            # per-replica registries share the fleet one; the registry-
            # wide textfile would not speak the probe contract, so probe
            # export goes through export_probes() instead
            prometheus_path=None,
        )
        with self._lock:
            if name in self._models:
                raise ValueError(f"model {name!r} already registered")
            self._models[name] = _ModelGroup(name, served, reference_samples, cfg)
        return [self._spawn(name) for _ in range(max(1, int(replicas)))]

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    # -- replica lifecycle --------------------------------------------------

    def _spawn(self, model: str) -> FleetReplica:
        """Build + start one replica for ``model`` and attach it to the
        router. Any failure is wrapped in :class:`ReplicaFailed` — the
        fleet (and controller) survive a bad spawn."""
        with self._lock:
            group = self._models.get(model)
            rname = f"r{self._next_replica}"
            self._next_replica += 1
        if group is None:
            raise ReplicaFailed(f"unknown model {model!r}")
        try:
            cfg = group.serve_config
            # the ladder is deterministic in (samples, config), so the
            # prefixed metrics facade can be sized before the server
            # builds its own identical ladder
            n_buckets = len(
                build_bucket_ladder(
                    group.reference_samples,
                    cfg.max_batch,
                    num_buckets=cfg.num_buckets,
                    node_multiple=cfg.node_multiple,
                    edge_multiple=cfg.edge_multiple,
                )
            )
            metrics = ServeMetrics(
                n_buckets,
                latency_window=cfg.latency_window,
                registry=self.registry,
                prefix=f"fleet.{rname}",
            )
            server = ModelServer(
                group.served,
                group.reference_samples,
                cfg,
                metrics=metrics,
                flight=self.flight,
            )
            server.start()
        except Exception as exc:
            raise ReplicaFailed(
                f"spawning replica {rname} for model {model!r} failed: {exc!r}"
            ) from exc
        replica = FleetReplica(rname, model, server)
        self.router.attach(replica)
        return replica

    def replica_count(self) -> int:
        return len(self.router.replicas())

    def replicas(self) -> List[FleetReplica]:
        return self.router.replicas()

    def get_replica(self, name: str) -> Optional[FleetReplica]:
        for r in self.router.replicas():
            if r.name == name:
                return r
        return None

    def dead_replicas(self) -> List[str]:
        """Names of attached replicas that are no longer live (the
        controller's reap input)."""
        return [r.name for r in self.router.replicas() if not r.live]

    def total_load(self) -> int:
        return self.router.total_load()

    # -- scaling primitives (the controller's verbs) ------------------------

    def scale_up(self, reason: str = "manual") -> str:
        """Spawn one replica for the busiest model group; returns the
        new replica's name."""
        with self._lock:
            names = sorted(self._models)
        if not names:
            raise ReplicaFailed("no model registered")
        loads = {n: 0 for n in names}
        for r in self.router.replicas():
            if r.model in loads:
                loads[r.model] += r.load()
        busiest = max(names, key=lambda n: loads[n])
        return self._spawn(busiest).name

    def scale_down(
        self, reason: str = "manual", timeout: Optional[float] = 30.0
    ) -> str:
        """Retire the least-loaded replica whose model keeps at least
        one other replica; drain-then-stop so nothing in flight is
        lost. Returns the retired replica's name."""
        replicas = self.router.replicas()
        per_model: Dict[str, int] = {}
        for r in replicas:
            per_model[r.model] = per_model.get(r.model, 0) + 1
        candidates = [r for r in replicas if per_model[r.model] > 1]
        if not candidates and len(per_model) == 1:
            candidates = replicas  # single model: the controller's
            # min_replicas bound is the floor, not model coverage
        if not candidates:
            raise ReplicaFailed("no replica can be retired without orphaning a model")
        victim = min(candidates, key=lambda r: r.load())
        self.router.detach(victim.name)
        victim.drain_stop(timeout)
        return victim.name

    def replace(self, name: str, reason: str = "dead_replica") -> str:
        """Reap one dead replica and spawn its replacement (same
        model). The dead server is stopped for finalization only — its
        queue already failed everything typed when it died."""
        dead = self.router.detach(name)
        if dead is None:
            raise ReplicaFailed(f"no attached replica named {name!r}")
        try:
            dead.server.stop(timeout=1.0)
        except Exception:
            pass  # already loudly dead; finalization is best-effort
        return self._spawn(dead.model).name

    # -- fleet-wide rolling reload ------------------------------------------

    def rolling_reload(
        self,
        model: str,
        checkpoint: Optional[str] = None,
        *,
        variables: Optional[Dict[str, Any]] = None,
        log_dir: Optional[str] = None,
        drain_timeout_s: float = 30.0,
    ) -> List[Dict[str, Any]]:
        """Reload every replica of ``model`` one at a time: the router
        stops placing on a replica, its in-flight work drains, the
        server's own canary-gated ``reload()`` swaps weights (rollback
        built in), and the replica rejoins placement — N-1 replicas
        serve throughout. A failed canary aborts the roll with the
        remaining replicas untouched on the old weights and raises
        :class:`~hydragnn_tpu.serve.server.ReloadFailed`."""
        targets = [r for r in self.router.replicas() if r.model == model]
        if not targets:
            raise ReplicaFailed(f"no replicas serving model {model!r}")
        outcomes: List[Dict[str, Any]] = []
        for r in sorted(targets, key=lambda x: x.name):
            self.router.pause(r.name)
            r.drain(drain_timeout_s)
            if not r.live:
                # replica died mid-roll (its queued futures already
                # failed with the typed dispatch error when it died —
                # nothing is silently lost): abort the roll with every
                # remaining replica serving the OLD weights; the
                # controller's reap path owns the corpse
                r.undrain()
                self.router.resume(r.name)
                self.flight.record(
                    "fleet_reload",
                    model=model,
                    replica=r.name,
                    ok=False,
                    error="replica died mid-roll",
                    aborted_roll=True,
                )
                raise ReloadFailed(
                    f"rolling reload of {model!r} aborted: replica "
                    f"{r.name} died mid-roll; remaining replicas still "
                    "serve the previous weights"
                )
            try:
                info = r.server.reload(
                    checkpoint, variables=variables, log_dir=log_dir
                )
            except ReloadFailed as exc:
                # old weights still serving on THIS replica too — put it
                # back in rotation before surfacing the abort
                r.undrain()
                self.router.resume(r.name)
                self.flight.record(
                    "fleet_reload",
                    model=model,
                    replica=r.name,
                    ok=False,
                    error=repr(exc)[-200:],
                    aborted_roll=True,
                )
                raise
            r.undrain()
            self.router.resume(r.name)
            outcome = {"replica": r.name, "ok": True, **info}
            outcomes.append(outcome)
            self.flight.record(
                "fleet_reload", model=model, replica=r.name, ok=True,
                swap_s=info.get("swap_s"),
            )
        return outcomes

    # -- request path -------------------------------------------------------

    def submit(self, sample, tenant: str = "default", model: Optional[str] = None):
        return self.router.submit(sample, tenant=tenant, model=model)

    def predict(
        self,
        sample,
        tenant: str = "default",
        model: Optional[str] = None,
        timeout: Optional[float] = None,
    ):
        return self.router.predict(sample, tenant=tenant, model=model, timeout=timeout)

    # -- health / probes ----------------------------------------------------

    def health(self) -> Dict[str, Any]:
        replicas = {r.name: r.health() for r in self.router.replicas()}
        ready = sum(1 for h in replicas.values() if h["ready"])
        live = sum(1 for h in replicas.values() if h["live"])
        return {
            "replicas": replicas,
            "replica_count": len(replicas),
            "ready_count": ready,
            "live_count": live,
            "total_load": self.total_load(),
            "models": self.models(),
        }

    def export_probes(self, directory: str) -> List[str]:
        """One probe textfile per replica (``<name>.prom``) plus the
        router's own ``router.prom`` (ready = at least one replica
        routable), all under the standard ``hydragnn_serve_*`` gauge
        names — the files ``tools/serve_probe.py --fleet`` aggregates."""
        os.makedirs(directory, exist_ok=True)
        paths: List[str] = []
        replicas = self.router.replicas()
        for r in replicas:
            p = os.path.join(directory, f"{r.name}.prom")
            r.export_probe(p)
            paths.append(p)
        router_path = os.path.join(directory, "router.prom")
        write_probe_textfile(
            router_path,
            live=any(r.live for r in replicas),
            ready=any(r.ready for r in replicas),
        )
        paths.append(router_path)
        return paths

    # -- teardown -----------------------------------------------------------

    def stop(self, timeout: Optional[float] = 10.0) -> None:
        """Drain-stop every replica (detaching each from the router
        first so nothing new lands while it drains)."""
        for r in self.router.replicas():
            self.router.detach(r.name)
            try:
                r.drain_stop(timeout)
            except Exception:
                pass  # teardown is best-effort; servers finalize themselves

    def __enter__(self) -> "Fleet":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
