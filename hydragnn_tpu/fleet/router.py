"""Fleet admission front door: tenancy, quotas, priorities, placement.

Every request enters the fleet here, not at a replica. Admission runs
four gates in order, each with its own typed rejection and counter:

  1. **tenant quota** — a token bucket per tenant (``rate`` tokens/s
     refill up to ``burst``; one admission costs one token). An empty
     bucket raises :class:`TenantOverloaded` — an
     :class:`~hydragnn_tpu.serve.batcher.Overloaded` subclass carrying
     the tenant and the admission trace ID, so a 429 can name who was
     throttled and the flight timeline can show why.
  2. **priority shedding** — quotas carry a priority class
     (``premium`` / ``standard`` / ``batch``). When the fleet-wide
     in-flight load reaches ``RouterConfig.shed_load``, ``batch``
     traffic is shed first (typed Overloaded), keeping headroom for the
     interactive classes. Disabled when ``shed_load`` is None.
  3. **placement** — least-loaded routing: among READY replicas serving
     the requested model (excluding paused/draining ones), the replica
     with the fewest unresolved requests wins. No READY replica ->
     Overloaded (the caller's retry/shed decision, exactly as for a
     single overloaded server).
  4. **replica-death retry** — a future that fails with the dispatch
     death signature (``RequestFailed(reason="dispatch")`` /
     ``ServerClosed``) is resubmitted once to a DIFFERENT replica:
     a replica killed mid-traffic costs latency, not answers.

Per-tenant metrics land on the shared fleet registry
(``fleet.tenant.<tenant>.{requests,rejected,latency_s}``) next to the
fleet aggregates (``fleet.queue_depth``, ``fleet.latency_s``) the
autoscaler's trigger rules watch. A trace is begun AT ADMISSION with
the tenant and model stamped in its attrs, so per-tenant debugging
rides the same r12 timeline as everything else.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

from hydragnn_tpu.fleet.replica import FleetReplica
from hydragnn_tpu.obs.trace import Tracer
from hydragnn_tpu.serve.batcher import Overloaded, ServerClosed
from hydragnn_tpu.serve.server import RequestFailed
from hydragnn_tpu.utils import knobs, syncdebug

PRIORITIES = ("premium", "standard", "batch")


class TenantOverloaded(Overloaded):
    """A tenant's admission quota (or the shed gate) rejected the
    request. Carries ``tenant`` and the admission ``trace_id`` so the
    rejection is attributable end to end."""

    def __init__(self, message: str, tenant: str, trace_id: Optional[str] = None):
        super().__init__(message)
        self.tenant = tenant
        self.trace_id = trace_id


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """One tenant's admission contract: ``rate`` requests/s refill up
    to ``burst`` tokens (0 rate = unlimited), plus the priority class
    the shed gate orders by."""

    rate: float = 0.0
    burst: float = 32.0
    priority: str = "standard"

    def __post_init__(self):
        if self.priority not in PRIORITIES:
            raise ValueError(
                f"unknown priority {self.priority!r} (one of {PRIORITIES})"
            )


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Router policy. ``default_rate``/``default_burst`` default from
    the ``HYDRAGNN_FLEET_TENANT_*`` knobs and apply to tenants without
    an explicit quota; ``shed_load`` is the fleet-wide in-flight count
    at which ``batch``-priority traffic sheds (None = never);
    ``max_death_retries`` bounds per-request replica-death retries."""

    default_rate: Optional[float] = None
    default_burst: Optional[float] = None
    shed_load: Optional[int] = None
    max_death_retries: int = 1


class _TokenBucket:
    """Classic token bucket; not thread-safe on its own (the router's
    lock serializes access)."""

    def __init__(self, rate: float, burst: float, clock):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()

    def try_take(self) -> bool:
        if self.rate <= 0:
            return True  # unlimited tenant
        now = self._clock()
        self._tokens = min(self.burst, self._tokens + (now - self._last) * self.rate)
        self._last = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


class FleetRouter:
    """Shared admission front door over a set of :class:`FleetReplica`.

    The fleet attaches/detaches replicas as the controller scales;
    ``pause``/``resume`` take a replica out of placement without
    draining it (the rolling-reload primitive). ``clock`` is injectable
    for deterministic quota tests.
    """

    def __init__(
        self,
        registry,
        flight=None,
        quotas: Optional[Dict[str, TenantQuota]] = None,
        config: Optional[RouterConfig] = None,
        clock=time.monotonic,
    ):
        cfg = config or RouterConfig()
        self.config = cfg
        self.registry = registry
        self.flight = flight
        self._clock = clock
        self._default_rate = (
            cfg.default_rate
            if cfg.default_rate is not None
            else knobs.get_float("HYDRAGNN_FLEET_TENANT_RATE", 0.0)
        )
        self._default_burst = (
            cfg.default_burst
            if cfg.default_burst is not None
            else knobs.get_float("HYDRAGNN_FLEET_TENANT_BURST", 32.0)
        )
        self._tracer = Tracer(flight=flight)
        self._lock = syncdebug.maybe_wrap(
            threading.Lock(), "fleet.FleetRouter._lock"
        )
        # graftsync: guarded-by=fleet.FleetRouter._lock
        self._replicas: Dict[str, FleetReplica] = {}
        self._paused: set = set()  # graftsync: guarded-by=fleet.FleetRouter._lock
        self._quotas: Dict[str, TenantQuota] = dict(quotas or {})  # graftsync: guarded-by=fleet.FleetRouter._lock
        self._buckets: Dict[str, _TokenBucket] = {}  # graftsync: guarded-by=fleet.FleetRouter._lock
        self._tenant_metrics: Dict[str, dict] = {}  # graftsync: guarded-by=fleet.FleetRouter._lock
        r = registry
        self._requests = r.counter("fleet.requests_total")
        self._results = r.counter("fleet.results_total")
        self._rejected_quota = r.counter("fleet.rejected_quota")
        self._rejected_shed = r.counter("fleet.rejected_shed")
        self._rejected_no_replica = r.counter("fleet.rejected_no_replica")
        self._death_retries = r.counter("fleet.death_retries")
        self._failed = r.counter("fleet.failed")
        self._queue_depth = r.gauge("fleet.queue_depth")
        self._latency = r.histogram("fleet.latency_s")

    # -- replica set --------------------------------------------------------

    def attach(self, replica: FleetReplica) -> None:
        with self._lock:
            self._replicas[replica.name] = replica
            self._paused.discard(replica.name)

    def detach(self, name: str) -> Optional[FleetReplica]:
        with self._lock:
            self._paused.discard(name)
            return self._replicas.pop(name, None)

    def pause(self, name: str) -> None:
        """Take a replica out of placement (it keeps serving what it
        already holds) — the rolling-reload drain step."""
        with self._lock:
            self._paused.add(name)

    def resume(self, name: str) -> None:
        with self._lock:
            self._paused.discard(name)

    def replicas(self) -> List[FleetReplica]:
        with self._lock:
            return list(self._replicas.values())

    def set_quota(self, tenant: str, quota: TenantQuota) -> None:
        with self._lock:
            self._quotas[tenant] = quota
            self._buckets.pop(tenant, None)  # rebuilt from the new quota

    # -- metrics helpers ----------------------------------------------------

    # graftsync: holds=fleet.FleetRouter._lock
    def _tenant(self, tenant: str) -> dict:
        """Per-tenant metric bundle, created lazily under the lock —
        every caller already holds it."""
        m = self._tenant_metrics.get(tenant)
        if m is None:
            p = f"fleet.tenant.{tenant.replace('.', '_')}"
            m = {
                "requests": self.registry.counter(f"{p}.requests"),
                "rejected": self.registry.counter(f"{p}.rejected"),
                "latency": self.registry.histogram(f"{p}.latency_s"),
            }
            self._tenant_metrics[tenant] = m
        return m

    def total_load(self) -> int:
        """Unresolved requests across the whole fleet — the aggregate
        the shed gate and the autoscaler's queue_depth rule read."""
        return sum(r.load() for r in self.replicas())

    def _set_queue_gauge(self) -> None:
        self._queue_depth.set(self.total_load())

    # -- admission ----------------------------------------------------------

    def submit(
        self, sample: Any, tenant: str = "default", model: Optional[str] = None
    ) -> Future:
        """Admit one request for ``tenant``; returns a router-owned
        Future resolving to the model's result dict. Raises
        :class:`TenantOverloaded` (quota/shed) or
        :class:`~hydragnn_tpu.serve.batcher.Overloaded` (no READY
        replica) — typed and immediate."""
        self._requests.inc()
        trace = self._tracer.begin(tenant=tenant, model=model or "default")
        trace_id = trace.trace_id if trace is not None else None
        with self._lock:
            tm = self._tenant(tenant)
            tm["requests"].inc()
            quota = self._quotas.get(tenant)
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = _TokenBucket(
                    quota.rate if quota else self._default_rate,
                    quota.burst if quota else self._default_burst,
                    self._clock,
                )
                self._buckets[tenant] = bucket
            admitted = bucket.try_take()
        if not admitted:
            tm["rejected"].inc()
            self._rejected_quota.inc()
            self._finish_reject(trace, "quota", tenant)
            raise TenantOverloaded(
                f"tenant {tenant!r} over admission quota "
                f"(rate {bucket.rate:g}/s, burst {bucket.burst:g})",
                tenant=tenant,
                trace_id=trace_id,
            )
        priority = quota.priority if quota else "standard"
        shed = self.config.shed_load
        if shed is not None and priority == "batch" and self.total_load() >= shed:
            tm["rejected"].inc()
            self._rejected_shed.inc()
            self._finish_reject(trace, "shed", tenant)
            raise TenantOverloaded(
                f"batch-priority tenant {tenant!r} shed (fleet load >= {shed})",
                tenant=tenant,
                trace_id=trace_id,
            )
        outer: Future = Future()
        t0 = time.monotonic()
        self._dispatch(
            sample, tenant, model, outer, trace, t0,
            tried=[], retries_left=self.config.max_death_retries,
        )
        self._set_queue_gauge()
        return outer

    def _pick(self, model: Optional[str], exclude) -> Optional[FleetReplica]:
        """Least-loaded READY replica serving ``model`` (any model when
        None), skipping paused and excluded names."""
        with self._lock:
            candidates = [
                r
                for name, r in self._replicas.items()
                if name not in self._paused
                and name not in exclude
                and (model is None or r.model == model)
            ]
        ready = [r for r in candidates if r.ready]
        if not ready:
            return None
        return min(ready, key=lambda r: r.load())

    def _dispatch(
        self, sample, tenant, model, outer: Future, trace, t0: float,
        tried: List[str], retries_left: int,
    ) -> None:
        replica = self._pick(model, exclude=set(tried))
        if replica is None and tried:
            # retry path: every untried replica is unready — fall back
            # to ANY ready replica (a restarted replacement may reuse a
            # tried name's slot) before giving up
            replica = self._pick(model, exclude=set())
        if replica is None:
            self._rejected_no_replica.inc()
            self._finish_reject(trace, "no_replica", tenant)
            outer.set_exception(
                Overloaded(
                    f"no READY replica for model {model or 'default'!r} "
                    f"(fleet of {len(self.replicas())})"
                )
            )
            return
        if trace is not None:
            trace.mark("fleet.admit", replica=replica.name)
        try:
            # tenant rides to the replica's server so spooled requests
            # stay attributable per tenant (obs/spool.py)
            inner = replica.submit(sample, tenant=tenant)
        except (Overloaded, ServerClosed) as exc:
            if retries_left > 0:
                self._death_retries.inc()
                self._dispatch(
                    sample, tenant, model, outer, trace, t0,
                    tried=tried + [replica.name], retries_left=retries_left - 1,
                )
                return
            self._finish_reject(trace, "replica_rejected", tenant)
            outer.set_exception(exc)
            return
        inner.add_done_callback(
            lambda f: self._on_result(
                f, sample, tenant, model, outer, trace, t0,
                tried + [replica.name], retries_left, replica.name,
            )
        )

    def _on_result(
        self, inner: Future, sample, tenant, model, outer: Future, trace,
        t0: float, tried: List[str], retries_left: int, replica_name: str,
    ) -> None:
        exc = inner.exception()
        if exc is None:
            latency = time.monotonic() - t0
            self._latency.observe(latency)
            with self._lock:
                self._tenant(tenant)["latency"].observe(latency)
            self._results.inc()
            if trace is not None:
                trace.mark("fleet.complete", replica=replica_name)
                self._tracer.finish(trace)
            outer.set_result(inner.result())
            self._set_queue_gauge()
            return
        died = isinstance(exc, ServerClosed) or (
            isinstance(exc, RequestFailed) and exc.reason == "dispatch"
        )
        if died and retries_left > 0:
            self._death_retries.inc()
            if trace is not None:
                trace.mark(
                    "fleet.retry", replica=replica_name, error=type(exc).__name__
                )
            self._dispatch(
                sample, tenant, model, outer, trace, t0,
                tried=tried, retries_left=retries_left - 1,
            )
            return
        self._failed.inc()
        if trace is not None:
            trace.mark(
                "fleet.failed", replica=replica_name, error=type(exc).__name__
            )
            self._tracer.finish(trace)
        outer.set_exception(exc)
        self._set_queue_gauge()

    def _finish_reject(self, trace, reason: str, tenant: str) -> None:
        if trace is not None:
            trace.mark("fleet.reject", reason=reason, tenant=tenant)
            self._tracer.finish(trace)

    # -- convenience --------------------------------------------------------

    def predict(
        self,
        sample: Any,
        tenant: str = "default",
        model: Optional[str] = None,
        timeout: Optional[float] = None,
    ):
        return self.submit(sample, tenant=tenant, model=model).result(timeout)

    def traces(self):
        """The admission tracer's finished-trace ring (tests assert the
        tenant rode the trace)."""
        return self._tracer.traces()
