"""Top-level entry points: ``run_training``, ``run_prediction``, ``serve_model``.

Mirrors the reference pipelines (reference: hydragnn/run_training.py:42-133
and hydragnn/run_prediction.py:27-83): log setup -> distributed init ->
data load/split -> config inference -> model factory -> optimizer ->
optional checkpoint-continue -> epoch loop -> save model -> timers.
Differences by design: the "DDP wrap" disappears (data parallelism is a
sharding annotation in the train step, not a model wrapper), and H2D
movement happens in the loader (fixed-shape batches).

Both accept a config file path or dict (the reference uses singledispatch,
run_training.py:42-57); the dataset comes either from
``Dataset.path["total"]`` raw files or from an in-memory ``samples`` list
(the synthetic/test path).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from hydragnn_tpu.data.ingest import load_raw_samples, prepare_dataset
from hydragnn_tpu.data.loader import GraphLoader
from hydragnn_tpu.models.create import create_model_config
from hydragnn_tpu.train import (
    create_train_state,
    make_eval_step,
    select_optimizer,
    test_epoch,
    train_validate_test,
)
from hydragnn_tpu.utils.checkpoint import (
    load_existing_model,
    load_existing_model_config,
    save_model,
)
from hydragnn_tpu.utils.config import (
    get_log_name_config,
    load_config,
    save_config,
    update_config,
)
from hydragnn_tpu.utils.print_utils import setup_log
from hydragnn_tpu.utils.time_utils import Timer, print_timers


def prepare_loaders_and_config(
    config: Dict[str, Any],
    samples: Optional[List] = None,
    device_stack: int = 1,
) -> Tuple[GraphLoader, GraphLoader, GraphLoader, Dict[str, Any]]:
    """Data load + split + config inference (reference:
    dataset_loading_and_splitting + update_config, run_training.py:67-78).

    ``device_stack`` > 1 makes every loader yield batches with a leading
    device axis for the sharded (data-parallel) step functions."""
    if samples is None:
        path = config["Dataset"]["path"]
        if "total" in path:
            samples = load_raw_samples(config, path["total"])
            train, val, test, mm_g, mm_n = prepare_dataset(samples, config)
        else:
            # per-split raw paths (reference: Dataset.path train/validate/
            # test layout, load_data.py:352-393); split membership is
            # pre-defined, normalization spans all splits
            from hydragnn_tpu.data.ingest import prepare_presplit_dataset

            splits = {}
            for key in ("train", "validate", "test"):
                if key not in path:
                    raise ValueError(
                        f"Dataset.path needs 'total' or 'train'/'validate'/'test'; missing {key!r}"
                    )
                splits[key] = load_raw_samples(config, path[key])
            train, val, test, mm_g, mm_n = prepare_presplit_dataset(
                splits["train"], splits["validate"], splits["test"], config
            )
    else:
        train, val, test, mm_g, mm_n = prepare_dataset(samples, config)

    voi = config["NeuralNetwork"]["Variables_of_interest"]
    voi["minmax_graph_feature"] = mm_g.tolist()
    voi["minmax_node_feature"] = mm_n.tolist()
    config = update_config(config, train, val, test)

    train_loader, val_loader, test_loader = create_dataloaders(
        train, val, test, config, device_stack=device_stack
    )
    return train_loader, val_loader, test_loader, config


def create_dataloaders(
    train: List,
    val: List,
    test: List,
    config: Dict[str, Any],
    device_stack: int = 1,
) -> Tuple[GraphLoader, GraphLoader, GraphLoader]:
    """Per-split loaders over prepared sample lists (the reference's
    ``create_dataloaders``, hydragnn/preprocess/load_data.py:226-283; the
    DistributedSampler role is played by num_shards/shard_rank)."""
    training = config["NeuralNetwork"]["Training"]
    bs = int(training["batch_size"])
    nproc, rank = jax.process_count(), jax.process_index()
    kw = dict(
        num_shards=nproc,
        shard_rank=rank,
        device_stack=device_stack,
        cache_device_batches=bool(training.get("cache_device_batches", False)),
        scan_reshuffle_every=int(training.get("scan_reshuffle_every", 0)),
    )
    train_loader = GraphLoader(train, bs, shuffle=True, **kw)
    val_loader = GraphLoader(val, bs, **kw)
    test_loader = GraphLoader(test, bs, **kw)
    return train_loader, val_loader, test_loader


def _example_for_init(example, device_stack: int):
    """Strip the leading device axis off a loader example when the loader
    stacks sub-batches, so model init sees one sub-batch's shapes."""
    if device_stack > 1:
        return jax.tree_util.tree_map(lambda x: x[0], example)
    return example


def _choose_device_stack(config: Dict[str, Any]) -> int:
    """Batch device-axis width for this process: all local devices
    (divided by ``Parallel.edge``, which shards WITHIN each sub-batch)
    when the per-process batch size divides evenly, else single-device.
    Multi-host runs combine this with a global mesh over every process's
    devices (each process feeds its own shard; ``globalize_batch``
    assembles the logical batch), so the reference's DDP-over-mpirun
    launch shape maps to one process per host here. The width feeds
    ``Partitioner.from_config``, which splits it into ``data × fsdp``."""
    n_local = jax.local_device_count()
    nn = config["NeuralNetwork"]
    par = nn.get("Parallel") or {}
    fsdp = int(par.get("fsdp", 1) or 1)
    edge = int(par.get("edge", 1) or 1)
    if n_local % edge:
        raise ValueError(
            f"Parallel.edge={edge} must divide local_device_count={n_local}"
        )
    usable = n_local // edge
    bs = int(nn["Training"]["batch_size"])
    if usable > 1 and bs % usable != 0:
        if fsdp > 1:
            # an explicit fsdp request must not silently degrade to a
            # replicated single-device run that may not even fit HBM
            raise ValueError(
                f"Parallel.fsdp={fsdp} is set but batch_size={bs} is not "
                f"divisible by the usable device width {usable}; pick a "
                "batch size the device width divides"
            )
        import warnings

        warnings.warn(
            f"batch_size={bs} is not divisible by the usable device "
            f"width {usable}; falling back to SINGLE-DEVICE execution "
            f"(~{usable}x throughput loss). Use a batch_size divisible "
            f"by {usable} to engage all local devices.",
            RuntimeWarning,
            stacklevel=2,
        )
        return 1
    if fsdp > 1 and (usable < fsdp or usable % fsdp):
        raise ValueError(
            f"Parallel.fsdp={fsdp} must divide the usable device width "
            f"{usable} (local_device_count={n_local}, edge={edge})"
        )
    return usable


def train_with_loaders(
    config: Dict[str, Any],
    train_loader: GraphLoader,
    val_loader: GraphLoader,
    test_loader: GraphLoader,
    log_dir: str = "./logs/",
    device_stack: int = 1,
):
    """Model creation + optimizer + epoch loop + checkpoint save, on
    already-built loaders whose config has been through ``update_config``
    — the manual-wiring tail every reference example driver repeats
    (e.g. examples/qm9/qm9.py:66-95). Returns (model, state, history)."""
    verbosity = config.get("Verbosity", {}).get("level", 0)
    log_name = get_log_name_config(config)
    setup_log(log_name, log_dir)
    save_config(config, log_name, log_dir)

    nn_config = config["NeuralNetwork"]
    # Taken BEFORE any mesh is attached to the loaders, so the example is
    # a host-local batch regardless of the distribution mode.
    example = next(iter(train_loader))
    multihost = jax.process_count() > 1
    example_one = _example_for_init(example, device_stack)

    training = nn_config["Training"]
    # Restart-supervisor resume (hydragnn_tpu/resilience/supervisor.py):
    # a restarted child runs with HYDRAGNN_AUTO_RESUME=1 and picks up
    # its own checkpoint via the ordinary continue/startfrom machinery.
    from hydragnn_tpu.resilience import auto_resume_config

    auto_resume_config(training, log_name, log_dir)
    freeze = bool(nn_config["Architecture"].get("freeze_conv_layers"))
    tx = select_optimizer(training, freeze_conv=freeze)

    train_step = eval_step = eval_step_out = stats_step = None
    # ONE sharding story (docs/PARALLELISM.md): the Partitioner owns the
    # composed (data, fsdp, edge) mesh, the loader placement, the state
    # layout (replicated / ZeRO-1 / FSDP), and every partitioned step.
    from hydragnn_tpu.parallel import Partitioner

    if multihost:
        # Global mesh over every process's devices; each process feeds
        # its shard of the logical batch (the reference's one-DDP-rank-
        # per-GPU launch becomes one-process-per-host + a data mesh).
        # Heterogeneous hosts can locally derive different widths
        # (device_stack falls back to 1 when batch_size doesn't divide
        # its local device count); meshes/batch shapes must agree
        # everywhere or the collectives fail opaquely downstream, so the
        # widths are validated BEFORE the partitioner builds its global
        # mesh from them. Gather every process's (validity, width)
        # BEFORE raising: if only some processes raised, the rest would
        # block forever inside this collective.
        from jax.experimental import multihost_utils

        ok = device_stack in (1, jax.local_device_count())
        info = np.asarray(
            multihost_utils.process_allgather(
                np.asarray([int(ok), device_stack], dtype=np.int64)
            )
        ).reshape(-1, 2)
        if not info[:, 0].all():
            bad = [int(s) for o, s in info.tolist() if not o]
            raise ValueError(
                "multi-host device_stack must be 1 or local_device_count; "
                f"invalid widths across processes: {bad}"
            )
        stacks = info[:, 1]
        if not (stacks == device_stack).all():
            raise ValueError(
                f"device_stack must agree across processes, got {stacks.tolist()}"
            )
    partitioner = Partitioner.from_config(
        nn_config, device_stack=device_stack, multihost=multihost
    )
    if not partitioner.single_device or multihost:
        model, variables = create_model_config(
            nn_config, example_one, bn_axis_name=partitioner.bn_axis_name
        )
        for loader in (train_loader, val_loader, test_loader):
            partitioner.attach_loader(loader)
        state = create_train_state(variables, tx)
        # place BEFORE restoring: the restore target then carries the run's
        # real (FSDP/ZeRO-1) shardings, so orbax places shards directly and
        # the msgpack path re-places onto them
        state = partitioner.shard_init(state)
        state = load_existing_model_config(state, training, log_dir)
        compute_dtype = jax.numpy.bfloat16 if training.get("mixed_precision") else None
        train_step = partitioner.shard_train_step(
            model,
            tx,
            compute_dtype=compute_dtype,
            remat=bool(training.get("remat", False)),
        )
        eval_step = partitioner.shard_eval_step(model)
        eval_step_out = partitioner.shard_eval_step(model, with_outputs=True)
        stats_step = partitioner.shard_stats_step(model)
    else:
        model, variables = create_model_config(nn_config, example_one)
        state = create_train_state(variables, tx)
        state = load_existing_model_config(state, training, log_dir)

    if jax.process_index() == 0:
        from hydragnn_tpu.utils.print_utils import print_model

        print_model(state.params, verbosity)

    viz = config.get("Visualization", {})
    state, history = train_validate_test(
        model,
        tx,
        state,
        train_loader,
        val_loader,
        test_loader,
        nn_config,
        log_name=log_name,
        verbosity=verbosity,
        create_plots=bool(viz.get("create_plots", False)),
        plot_init_solution=bool(viz.get("plot_init_solution", False)),
        plot_hist_solution=bool(viz.get("plot_hist_solution", False)),
        log_dir=log_dir,
        train_step=train_step,
        eval_step=eval_step,
        eval_step_out=eval_step_out,
        stats_step=stats_step,
        # the FULL resolved config goes into the flight-record manifest
        # (the NeuralNetwork section alone loses Dataset/Verbosity —
        # docs/OBSERVABILITY.md documents the manifest contract)
        run_config=config,
        partitioner=partitioner,
    )

    save_model(state, log_name, log_dir, verbosity)
    return model, state, history


def run_training(
    config_file_or_dict,
    samples: Optional[List] = None,
    log_dir: str = "./logs/",
):
    """Full training pipeline; returns (model, state, history, config).

    Telemetry knobs (``NeuralNetwork.Training``, docs/OBSERVABILITY.md):
    ``diagnostics`` (default true) samples per-head gradient norms, the
    inter-task conflict matrix, per-head eval MAE/RMSE and the
    hardware-efficiency ledger (MFU + memory watermark) into the run's
    flight record every ``diag_every`` steps (0 = once per epoch);
    ``prometheus_dir`` additionally writes an atomic ``train.prom``
    textfile snapshot per epoch for a node-exporter textfile collector.
    All of it is inert under ``HYDRAGNN_TELEMETRY=0``."""
    config = load_config(config_file_or_dict)
    verbosity = config.get("Verbosity", {}).get("level", 0)

    timer = Timer("total_training")
    timer.start()
    # stop on ANY exit: the registry timer is process-global, and a run
    # that raised mid-training would otherwise poison every later
    # run_training in the process with "Timer already running"
    try:
        device_stack = _choose_device_stack(config)
        train_loader, val_loader, test_loader, config = prepare_loaders_and_config(
            config, samples, device_stack=device_stack
        )
        model, state, history = train_with_loaders(
            config,
            train_loader,
            val_loader,
            test_loader,
            log_dir=log_dir,
            device_stack=device_stack,
        )
    finally:
        timer.stop()
    print_timers(verbosity)
    return model, state, history, config


def serve_model(
    config_file_or_dict,
    samples: Optional[List] = None,
    log_dir: str = "./logs/",
    serve_config=None,
    start: bool = True,
    flight=None,
):
    """Stand up a batched online-inference server over a trained run.

    Where :func:`run_prediction` re-pads and re-dispatches the whole test
    set offline, this loads the checkpoint ONCE (same restore machinery),
    AOT-compiles a ladder of padded batch shapes, and returns a
    :class:`hydragnn_tpu.serve.ModelServer` answering single-graph
    requests with deadline micro-batching — the online counterpart for
    the paper's one-encoder/N-heads design, where one warm model serves
    every property endpoint concurrently.

    The dataset pipeline runs exactly as in prediction (normalization,
    radius edges, config inference) — its prepared samples size the
    bucket ladder and fix the request field spec; requests must be
    prepared the same way. Predictions are returned in MODEL space
    (normalized targets) — apply ``postprocess.output_denormalize`` for
    physical units.

    Returns the server (started unless ``start=False``); callers own its
    lifecycle (``server.stop()``, or use it as a context manager).
    """
    config = load_config(config_file_or_dict)
    train_loader, val_loader, test_loader, config = prepare_loaders_and_config(
        config, samples
    )
    log_name = get_log_name_config(config)
    reference = (
        list(train_loader.all_samples)
        + list(val_loader.all_samples)
        + list(test_loader.all_samples)
    )

    from hydragnn_tpu.serve import ModelRegistry, ModelServer, ServeConfig

    # Serving under the SAME sharding story as training: Parallel.fsdp
    # shards the served parameters over the fsdp axis (a model beyond one
    # chip's HBM serves from N chips); the bucket-ladder AOT compiles run
    # under the partitioner's mesh instead of an implicit single device.
    from hydragnn_tpu.parallel import Partitioner

    par = config["NeuralNetwork"].get("Parallel") or {}
    fsdp = int(par.get("fsdp", 1) or 1)
    if fsdp > jax.local_device_count():
        import warnings

        warnings.warn(
            f"Parallel.fsdp={fsdp} exceeds local_device_count="
            f"{jax.local_device_count()}; serving single-device "
            "(replicated parameters)",
            RuntimeWarning,
            stacklevel=2,
        )
        fsdp = 1
    partitioner = Partitioner(fsdp=fsdp)

    registry = ModelRegistry(log_dir)
    served = registry.load(
        log_name,
        config["NeuralNetwork"],
        example_graph=reference[0],
        partitioner=partitioner,
    )
    server = ModelServer(served, reference, serve_config or ServeConfig(), flight=flight)
    # reload("run_name") without an explicit log_dir restores from the
    # same checkpoint root this server was stood up from
    server.log_dir = log_dir
    if start:
        server.start()
    return server


def run_prediction(
    config_file_or_dict,
    samples: Optional[List] = None,
    log_dir: str = "./logs/",
) -> Tuple[float, np.ndarray, List[np.ndarray], List[np.ndarray]]:
    """Load data + trained weights, run the full test pass, optionally
    denormalize; returns (error, error_rmse_task, true_values,
    predicted_values) (reference: run_prediction.py:27-83). Single-host
    multi-device runs shard the eval over the local data mesh, like
    training."""
    config = load_config(config_file_or_dict)
    verbosity = config.get("Verbosity", {}).get("level", 0)

    device_stack = _choose_device_stack(config) if jax.process_count() == 1 else 1
    _, _, test_loader, config = prepare_loaders_and_config(
        config, samples, device_stack=device_stack
    )
    log_name = get_log_name_config(config)

    nn_config = config["NeuralNetwork"]
    example = next(iter(test_loader))
    example_one = _example_for_init(example, device_stack)
    model, variables = create_model_config(nn_config, example_one)
    # Same optimizer chain as training: freeze_conv changes the opt_state
    # pytree structure, and the checkpoint schema must match to deserialize.
    tx = select_optimizer(
        nn_config["Training"],
        freeze_conv=bool(nn_config["Architecture"].get("freeze_conv_layers")),
    )
    # Eval never reads the optimizer state; the restore target carries it
    # as HOST arrays only (create_eval_state), so a ZeRO-1-trained
    # checkpoint whose optimizer state cannot fit un-sharded on a device
    # restores fine, and the drop below keeps it off the mesh entirely.
    from hydragnn_tpu.train import create_eval_state

    state = create_eval_state(variables, tx)
    state = load_existing_model(state, log_name, log_dir)
    state = state.replace(opt_state=())

    from hydragnn_tpu.parallel import Partitioner

    partitioner = Partitioner.from_config(nn_config, device_stack=device_stack)
    if not partitioner.single_device:
        partitioner.attach_loader(test_loader)
        state = partitioner.shard_init(state)
        eval_step = partitioner.shard_eval_step(model, with_outputs=True)
    else:
        eval_step = make_eval_step(model, with_outputs=True)
    error, error_rmse_task, true_values, predicted_values = test_epoch(
        test_loader, state, eval_step, model.cfg, verbosity, return_samples=True
    )

    voi = nn_config["Variables_of_interest"]
    if voi.get("denormalize_output"):
        from hydragnn_tpu.postprocess.postprocess import output_denormalize

        true_values, predicted_values = output_denormalize(
            voi["y_minmax"], true_values, predicted_values
        )

    return error, error_rmse_task, true_values, predicted_values
