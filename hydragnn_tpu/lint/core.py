"""graftlint core: the rule registry, file walker, suppression and
baseline machinery behind ``tools/graftlint.py``.

The linter is a plain-AST static analyzer (no imports of the code
under analysis, no jax, no backend init — it must run in milliseconds
on any container), shipping the repo's hard-won JAX/TPU invariants as
enforced rules (docs/LINT.md catalogs them; ``rules.py`` implements
them). Design points:

  - **Findings** carry (rule id, path, line, col, message, severity)
    and a content fingerprint (rule + path + stripped source line) so
    baseline entries survive unrelated line-number churn.
  - **Suppressions** are inline comments — ``# graftlint:
    disable=HG001`` (comma-separate for several, ``all`` for every
    rule) on the offending line or the line directly above it. A
    suppression is an explicit, reviewable decision; docs/LINT.md sets
    the policy (always append a reason).
  - **Baseline**: a committed JSON file of grandfathered fingerprints
    (``tools/graftlint_baseline.json``). The shipped tree is
    lint-clean, so the committed baseline is EMPTY — the machinery
    exists so a future rule can land before its last true positive is
    burned down, without blocking CI.
  - **--changed mode** lints only files git reports modified — the
    fast pre-commit loop. Whole-tree aggregate checks (HG006's
    stale-registry arm) only run on full-tree scans, where the absence
    of a reference is meaningful.

This module must stay stdlib-only and must not import the rest of
``hydragnn_tpu`` (``tools/graftlint.py`` loads the ``lint`` package
standalone, without triggering the package root's jax imports).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
import subprocess
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

SEVERITIES = ("error", "warning")

#: default scan roots, relative to the repo root — everything the CI
#: gate covers (tests included; rules opt out per-path where tests are
#: deliberately adversarial)
DEFAULT_ROOTS = (
    "hydragnn_tpu",
    "tools",
    "examples",
    "tests",
    "bench.py",
    "bench_scaling.py",
    "bench_serve.py",
    "__graft_entry__.py",
)

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules", ".venv"}

# one suppression grammar for both linters: graftlint (HG rules) and
# graftsync (HS rules, lint/concurrency.py) — rule ids are disjoint, so
# either spelling may carry either family
_SUPPRESS_RE = re.compile(
    r"#\s*graft(?:lint|sync):\s*disable(?:-file)?=([A-Za-z0-9_,\s]+)"
)
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*graft(?:lint|sync):\s*disable-file=([A-Za-z0-9_,\s]+)"
)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str
    severity: str = "error"
    snippet: str = ""

    def fingerprint(self) -> str:
        """Stable identity for baseline matching: content-addressed so
        entries survive line renumbering from unrelated edits."""
        h = hashlib.sha256(
            f"{self.rule}|{self.path}|{self.snippet.strip()}".encode()
        )
        return h.hexdigest()[:20]

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule} [{self.severity}] {self.message}"
        )

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "severity": self.severity,
            "message": self.message,
            "fingerprint": self.fingerprint(),
        }


class ParsedModule:
    """One parsed source file plus everything rules need from it."""

    def __init__(self, path: str, source: str):
        self.path = path  # repo-relative, forward slashes
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        # line -> set of suppressed rule ids ("ALL" suppresses any)
        self.suppressions: Dict[int, Set[str]] = {}
        self.file_suppressions: Set[str] = set()
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_FILE_RE.search(line)
            if m:
                self.file_suppressions.update(
                    r.strip().upper() for r in m.group(1).split(",") if r.strip()
                )
                continue
            m = _SUPPRESS_RE.search(line)
            if m:
                self.suppressions[i] = {
                    r.strip().upper() for r in m.group(1).split(",") if r.strip()
                }

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""

    def suppressed(self, rule_id: str, line: int) -> bool:
        rid = rule_id.upper()
        if rid in self.file_suppressions or "ALL" in self.file_suppressions:
            return True
        for at in (line, line - 1):
            ids = self.suppressions.get(at)
            if ids and (rid in ids or "ALL" in ids):
                return True
        return False


class Rule:
    """One invariant. Subclasses set the class attributes and implement
    :meth:`check`; aggregate rules may also implement :meth:`finalize`
    (called once after every module has been checked, full-tree scans
    only)."""

    id: str = "HG000"
    name: str = "unnamed"
    severity: str = "error"
    description: str = ""
    #: path substrings (repo-relative, forward slashes) this rule skips
    exclude: Tuple[str, ...] = ()
    #: when non-empty, the rule ONLY runs on paths containing one of these
    include: Tuple[str, ...] = ()

    def applies_to(self, path: str) -> bool:
        if any(part in path for part in self.exclude):
            return False
        if self.include and not any(part in path for part in self.include):
            return False
        return True

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        raise NotImplementedError

    def finalize(self) -> Iterator[Finding]:
        return iter(())

    def finding(
        self, module: ParsedModule, node: ast.AST, message: str
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=self.id,
            path=module.path,
            line=line,
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
            severity=self.severity,
            snippet=module.snippet(line),
        )


# -- repo-table loaders (AST, never import) --------------------------------


def load_flight_kinds(repo_root: str) -> Set[str]:
    """Every event kind ``obs/flight.py`` registers: the keys of its
    ``_REQUIRED`` dict plus the ``FAULT_KINDS`` tuple, read by AST so
    the linter never imports the package."""
    path = os.path.join(repo_root, "hydragnn_tpu", "obs", "flight.py")
    kinds: Set[str] = set()
    with open(path) as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        names = {t.id for t in targets if isinstance(t, ast.Name)}
        value = node.value
        if "_REQUIRED" in names and isinstance(value, ast.Dict):
            for key in value.keys:
                if isinstance(key, ast.Constant) and isinstance(key.value, str):
                    kinds.add(key.value)
        if "FAULT_KINDS" in names and isinstance(value, (ast.Tuple, ast.List)):
            for elt in value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    kinds.add(elt.value)
    return kinds


def load_knob_registry(repo_root: str) -> Dict[str, int]:
    """``{knob name: declaration line}`` from ``utils/knobs.py``,
    keyed on its ``_K("NAME", ...)`` entry calls — again AST-only."""
    path = os.path.join(repo_root, "hydragnn_tpu", "utils", "knobs.py")
    out: Dict[str, int] = {}
    with open(path) as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "_K"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            out[node.args[0].value] = node.lineno
    return out


# -- baseline ---------------------------------------------------------------


def load_baseline(path: Optional[str]) -> Set[str]:
    if not path or not os.path.exists(path):
        return set()
    with open(path) as f:
        data = json.load(f)
    return {e["fingerprint"] for e in data.get("findings", [])}


def write_baseline(
    path: str, findings: Sequence[Finding], tool: str = "graftlint"
) -> None:
    data = {
        "comment": (
            f"{tool} grandfathered findings (docs/LINT.md). The shipped "
            "tree is lint-clean: keep this EMPTY; a non-empty baseline is "
            "temporary debt for landing a new rule ahead of its fixes."
        ),
        "findings": [
            {
                "rule": f.rule,
                "path": f.path,
                "fingerprint": f.fingerprint(),
                "message": f.message,
            }
            for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
        ],
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


# -- discovery --------------------------------------------------------------


def discover_files(repo_root: str, paths: Sequence[str]) -> List[str]:
    """Repo-relative .py files under the given paths (files or
    directories; absolute or repo-relative)."""
    out: List[str] = []
    seen: Set[str] = set()
    for p in paths:
        absolute = p if os.path.isabs(p) else os.path.join(repo_root, p)
        if os.path.isfile(absolute):
            candidates = [absolute]
        elif os.path.isdir(absolute):
            candidates = []
            for dirpath, dirnames, filenames in os.walk(absolute):
                dirnames[:] = [
                    d for d in dirnames
                    if d not in _SKIP_DIRS and not d.startswith(".")
                ]
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        candidates.append(os.path.join(dirpath, name))
        else:
            continue
        for c in candidates:
            rel = os.path.relpath(c, repo_root).replace(os.sep, "/")
            if rel.startswith(".."):
                rel = c.replace(os.sep, "/")  # outside the repo: keep absolute
            if rel not in seen:
                seen.add(rel)
                out.append(rel)
    return out


def changed_paths(repo_root: str) -> List[str]:
    """Python files git reports as modified/added/untracked vs HEAD —
    the --changed pre-commit scan set."""
    files: Set[str] = set()
    for args in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "diff", "--name-only", "--cached"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            res = subprocess.run(
                args, cwd=repo_root, capture_output=True, text=True, check=False
            )
        except OSError:
            continue
        for line in res.stdout.splitlines():
            line = line.strip()
            if line.endswith(".py"):
                files.add(line)
    return sorted(f for f in files if os.path.exists(os.path.join(repo_root, f)))


# -- runner -----------------------------------------------------------------


def run_lint(
    repo_root: str,
    rules: Sequence[Rule],
    paths: Optional[Sequence[str]] = None,
    baseline: Optional[str] = None,
    full_tree: Optional[bool] = None,
) -> List[Finding]:
    """Lint ``paths`` (default: the whole tree) with ``rules``; returns
    surviving findings (suppressions and baseline already applied).
    ``full_tree`` controls whether aggregate ``finalize`` checks run;
    by default they run exactly when no path restriction was given."""
    if full_tree is None:
        full_tree = paths is None
    scan = discover_files(repo_root, list(paths) if paths else DEFAULT_ROOTS)
    baseline_fps = load_baseline(baseline)
    findings: List[Finding] = []
    for rel in scan:
        absolute = (
            rel if os.path.isabs(rel) else os.path.join(repo_root, rel)
        )
        try:
            with open(absolute, encoding="utf-8") as f:
                source = f.read()
            module = ParsedModule(rel, source)
        except (OSError, SyntaxError, ValueError) as exc:
            findings.append(
                Finding(
                    rule="HG000",
                    path=rel,
                    line=getattr(exc, "lineno", 1) or 1,
                    col=1,
                    message=f"file does not parse: {exc}",
                )
            )
            continue
        for rule in rules:
            if not rule.applies_to(rel):
                continue
            for finding in rule.check(module):
                if module.suppressed(finding.rule, finding.line):
                    continue
                findings.append(finding)
    if full_tree:
        for rule in rules:
            findings.extend(rule.finalize())
    if baseline_fps:
        findings = [f for f in findings if f.fingerprint() not in baseline_fps]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# -- shared AST helpers -----------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_calls(root: ast.AST) -> Iterator[ast.Call]:
    for node in ast.walk(root):
        if isinstance(node, ast.Call):
            yield node


def string_arg(call: ast.Call, index: int, keyword: str) -> Optional[str]:
    """The string constant at positional ``index`` or keyword
    ``keyword`` of a call, else None."""
    if len(call.args) > index and isinstance(call.args[index], ast.Constant):
        v = call.args[index].value
        if isinstance(v, str):
            return v
    for kw in call.keywords:
        if kw.arg == keyword and isinstance(kw.value, ast.Constant):
            v = kw.value.value
            if isinstance(v, str):
                return v
    return None
