"""``graftlint --artifacts``: schema-validate committed flight records.

The repo commits bench evidence as flight JSONL artifacts
(``BENCH_FLIGHT.jsonl``, ``BENCH_SERVE_WARM_FLIGHT.jsonl``). Their
schema lives in ``obs/flight.py`` (``_REQUIRED``), so drift between
the tables and the checked-in records is exactly the static-vs-runtime
gap the linter exists to close: this mode runs the real
``validate_flight_record`` over each artifact and reports problems as
findings. ``flight.py`` is stdlib-only by design, so it is loaded
standalone (``importlib``, no package import, no jax init).
"""

from __future__ import annotations

import importlib.util
import os
from typing import List, Optional, Sequence

from .core import Finding

#: committed flight artifacts validated by the CI stage, repo-relative
DEFAULT_ARTIFACTS = (
    "BENCH_FLIGHT.jsonl",
    "BENCH_SERVE_WARM_FLIGHT.jsonl",
)


def _load_flight_module(repo_root: str):
    path = os.path.join(repo_root, "hydragnn_tpu", "obs", "flight.py")
    spec = importlib.util.spec_from_file_location("_graftlint_flight", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def validate_artifacts(
    repo_root: str, paths: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Validate each artifact; returns findings (empty = all valid).

    ``require_complete`` stays False: serve artifacts legitimately hold
    several run_start/run_end pairs (cold + warm passes) and no epoch
    events — every event must still be individually well-formed, and a
    kind absent from ``_REQUIRED`` has no required-field coverage at
    all, so unregistered kinds in a committed artifact are reported
    here too.
    """
    flight = _load_flight_module(repo_root)
    registered = set(flight._REQUIRED) | set(flight.FAULT_KINDS)
    findings: List[Finding] = []
    for rel in paths or DEFAULT_ARTIFACTS:
        path = rel if os.path.isabs(rel) else os.path.join(repo_root, rel)
        rel_display = rel.replace(os.sep, "/")
        if not os.path.exists(path):
            findings.append(
                Finding(
                    rule="HGART",
                    path=rel_display,
                    line=1,
                    col=1,
                    message="flight artifact missing",
                )
            )
            continue
        for problem in flight.validate_flight_record(path):
            findings.append(
                Finding(
                    rule="HGART",
                    path=rel_display,
                    line=1,
                    col=1,
                    message=problem,
                    snippet=problem,
                )
            )
        for i, ev in enumerate(flight.read_flight_record(path)):
            kind = ev.get("kind")
            if kind and kind != "_unparseable" and kind not in registered:
                findings.append(
                    Finding(
                        rule="HGART",
                        path=rel_display,
                        line=i + 1,
                        col=1,
                        message=(
                            f"event[{i}] kind '{kind}' is not registered "
                            "in obs/flight.py _REQUIRED/FAULT_KINDS"
                        ),
                        snippet=str(kind),
                    )
                )
    return findings
