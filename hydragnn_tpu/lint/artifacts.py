"""``graftlint --artifacts``: schema-validate committed machine artifacts.

The repo commits bench evidence in two shapes, and both are validated
here so a malformed committed artifact fails CI instead of a later tool
run:

* **Flight JSONL records** (``BENCH_FLIGHT.jsonl``,
  ``BENCH_SERVE_WARM_FLIGHT.jsonl``) — their schema lives in
  ``obs/flight.py`` (``_REQUIRED``), so drift between the tables and
  the checked-in records is exactly the static-vs-runtime gap the
  linter exists to close: this mode runs the real
  ``validate_flight_record`` over each and reports problems as
  findings. ``flight.py`` is stdlib-only by design, so it is loaded
  standalone (``importlib``, no package import, no jax init).

* **Machine JSON artifacts** (``BENCH_r*.json``, ``SCALING_*.json``,
  ``MULTICHIP_*.json``, ``TUNE_TILES.json``,
  ``BENCH_CI_BASELINE.json``) — per-kind schemas below
  (``MACHINE_SCHEMAS``), derived from the writers (bench.py,
  tools/estimate_scaling.py, tools/tune_tiles.py, tools/bench_ci.py).
  The checks pin the fields downstream tools actually read; extra keys
  stay legal so a writer can grow its record without a lint dance.
"""

from __future__ import annotations

import fnmatch
import importlib.util
import json
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .core import Finding

#: committed flight artifacts validated by the CI stage, repo-relative.
#: BENCH_FLIGHT.jsonl (the device bench's flight) is deliberately NOT
#: listed: it is rewritten per driver round on the TPU host and is not
#: a committed artifact — listing it made the gate fail on every
#: checkout without device-round evidence.
DEFAULT_ARTIFACTS = (
    "BENCH_SERVE_WARM_FLIGHT.jsonl",
    "BENCH_FLEET_FLIGHT.jsonl",
)


def _require(data: Any, fields: Dict[str, tuple]) -> List[str]:
    """Missing/mistyped required top-level fields of a dict artifact."""
    if not isinstance(data, dict):
        return [f"expected a JSON object, got {type(data).__name__}"]
    problems = []
    for name, types in fields.items():
        if name not in data:
            problems.append(f"missing required field '{name}'")
        elif not isinstance(data[name], types):
            want = "/".join(t.__name__ for t in types)
            problems.append(
                f"field '{name}' is {type(data[name]).__name__}, expected {want}"
            )
    return problems


_NUM = (int, float)


def _check_bench(data: Any) -> List[str]:
    """BENCH_r*.json: one TPU-attempt record (bench driver wrapper).
    ``parsed`` is the bench.py metric block when the run got far enough
    to print one, else null. A failed attempt (rc != 0) must be a
    STRUCTURED failed-run record — ``status: "failed"``, the retry
    count ``init_backend_with_retry`` burned, and a ``failure`` block
    naming the stage and error type — not just a raw traceback tail
    (BENCH_r05 is the committed example)."""
    problems = _require(
        data, {"n": (int,), "cmd": (str,), "rc": (int,), "tail": (str,)}
    )
    if problems:
        return problems
    parsed = data.get("parsed")
    if parsed is not None:
        if not isinstance(parsed, dict):
            return [f"'parsed' is {type(parsed).__name__}, expected object or null"]
        problems += [
            f"parsed.{p}" for p in _require(
                parsed, {"metric": (str,), "value": _NUM, "unit": (str,)}
            )
        ]
    if data["rc"] != 0:
        problems += _require(
            data, {"status": (str,), "retries": (int,), "failure": (dict,)}
        )
        if isinstance(data.get("status"), str) and data["status"] != "failed":
            problems.append(
                f"rc={data['rc']} but status is {data['status']!r},"
                " expected 'failed'"
            )
        if isinstance(data.get("failure"), dict):
            problems += [
                f"failure.{p}" for p in _require(
                    data["failure"],
                    {"stage": (str,), "error_type": (str,), "error": (str,)},
                )
            ]
    return problems


#: the five scenario rows bench_serve.py --fleet always records — a
#: missing one means a chaos scenario silently did not run.
_FLEET_SCENARIOS = (
    "baseline_n1",
    "sustained_n2",
    "replica_kill",
    "scale_up_under_load",
    "rolling_reload",
)


def _check_fleet(data: Any) -> List[str]:
    """BENCH_FLEET.json: the fleet chaos acceptance record
    (bench_serve.py --fleet, docs/FLEET.md): scale-out efficiency
    headline plus one row per chaos scenario."""
    problems = _require(
        data,
        {
            "metric": (str,),
            "value": _NUM,
            "unit": (str,),
            "replicas": (int,),
            "qps_n1": _NUM,
            "qps_n2": _NUM,
            "scaleout_efficiency": _NUM,
            "warm_replica_aot_compiles": (int,),
            "lost_futures": (int,),
            "slo_p99_ms": _NUM,
            "scenarios": (dict,),
            "failures": (list,),
        },
    )
    if problems:
        return problems
    for name in _FLEET_SCENARIOS:
        row = data["scenarios"].get(name)
        if not isinstance(row, dict):
            problems.append(f"scenarios.{name} missing (chaos scenario not run)")
    return problems


def _check_multichip(data: Any) -> List[str]:
    """MULTICHIP_r*.json: one multi-chip attempt record."""
    return _require(
        data,
        {
            "n_devices": (int,),
            "rc": (int,),
            "ok": (bool,),
            "skipped": (bool,),
            "tail": (str,),
        },
    )


def _check_scaling(data: Any) -> List[str]:
    """SCALING_*.json: either a measured sweep (``sizes`` per device
    count, SCALING_cpu8) or an analytic estimate (``mesh`` +
    per-step collective model, SCALING_est_*)."""
    if not isinstance(data, dict):
        return [f"expected a JSON object, got {type(data).__name__}"]
    if "sizes" in data:  # measured sweep
        problems = _require(
            data, {"metric": (str,), "unit": (str,), "steps": (int,), "sizes": (dict,)}
        )
        if problems:
            return problems
        if not data["sizes"]:
            return ["'sizes' sweep is empty"]
        for n, row in data["sizes"].items():
            problems += [
                f"sizes[{n}].{p}" for p in _require(
                    row, {"step_ms": _NUM, "graphs_per_sec": _NUM}
                )
            ]
        return problems
    if "mesh" in data:  # analytic estimate
        problems = _require(
            data, {"mesh": (str,), "step_ms_device_single_chip": _NUM}
        )
        widths = data.get("widths")
        if widths is not None:
            if not isinstance(widths, dict) or not widths:
                problems.append("'widths' must be a non-empty object")
            else:
                for w, row in widths.items():
                    problems += [
                        f"widths[{w}].{p}"
                        for p in _require(row, {"n_devices": (int,)})
                    ]
        return problems
    return ["neither 'sizes' (measured sweep) nor 'mesh' (estimate) present"]


def _check_tune_tiles(data: Any) -> List[str]:
    """TUNE_TILES.json: {shape_tag: {device_kind: {BN, CE, BCAST_CE}}}
    — the committed tile sweep ops/segment_pallas.py reads its
    import-time defaults from."""
    problems = _require(data, {"_doc": (str,)})
    if problems:
        return problems
    tags = {k: v for k, v in data.items() if k != "_doc"}
    if not tags:
        return ["no shape-tag entries (only _doc)"]
    for tag, kinds in tags.items():
        if not isinstance(kinds, dict) or not kinds:
            problems.append(f"'{tag}' must be a non-empty object of device kinds")
            continue
        for kind, tiles in kinds.items():
            problems += [
                f"{tag}.{kind}.{p}" for p in _require(
                    tiles, {"BN": (int,), "CE": (int,), "BCAST_CE": (int,)}
                )
            ]
    return problems


def _check_ci_baseline(data: Any) -> List[str]:
    """BENCH_CI_BASELINE.json: {"backend:device_kind": perf row} — the
    regression reference tools/bench_ci.py compares against."""
    if not isinstance(data, dict):
        return [f"expected a JSON object, got {type(data).__name__}"]
    if not data:
        return ["no 'backend:device_kind' entries"]
    problems = []
    for key, row in data.items():
        if ":" not in key:
            problems.append(f"key '{key}' is not 'backend:device_kind'")
        problems += [
            f"{key}.{p}" for p in _require(
                row,
                {"step_ms_median": _NUM, "graphs_per_sec": _NUM, "steps": (int,)},
            )
        ]
    return problems


#: rule kinds obs/triggers.py:RULE_KINDS declares — duplicated here
#: because this module must stay loadable without the package (and
#: without jax); tests/test_triggers.py pins the two tuples equal.
_INCIDENT_RULE_KINDS = (
    "latency_p99",
    "queue_depth",
    "queue_age",
    "feature_drift",
    "pred_drift",
    "error_drift",
    "mfu_drop",
    "loss_spike",
    "nonfinite_burst",
    "pilot_stuck",
    "step_skew",
    "host_stall",
    "host_lost",
)


def _check_incident_manifest(data: Any) -> List[str]:
    """incident_manifest.json: one incident bundle's closing manifest
    (obs/triggers.py:Incident.close — the runtime validator there is
    validate_incident_manifest; this mirrors it for jax-free lint)."""
    problems = _require(
        data,
        {
            "schema_version": (int,),
            "id": (str,),
            "rule": (str,),
            "kind": (str,),
            "status": (str,),
            "trigger": (dict,),
            "files": (dict,),
            "profile": (dict,),
        },
    )
    if problems:
        return problems
    problems += [
        f"trigger.{p}" for p in _require(
            data["trigger"],
            {"rule": (str,), "kind": (str,), "observed": _NUM, "threshold": _NUM},
        )
    ]
    problems += [
        f"profile.{p}" for p in _require(
            data["profile"],
            {"captured": (bool,), "steps": (int,), "duration_s": _NUM,
             "nonempty": (bool,)},
        )
    ]
    if data["kind"] not in _INCIDENT_RULE_KINDS:
        problems.append(f"unknown rule kind {data['kind']!r}")
    return problems


#: machine-JSON artifact kinds: glob pattern -> (label, validator).
#: Patterns with ZERO committed matches are themselves findings — these
#: artifacts are evidence, and losing one silently is the failure mode.
MACHINE_SCHEMAS: Dict[str, Tuple[str, Callable[[Any], List[str]]]] = {
    "BENCH_r*.json": ("bench attempt record", _check_bench),
    "MULTICHIP_r*.json": ("multi-chip attempt record", _check_multichip),
    "SCALING_*.json": ("scaling sweep/estimate", _check_scaling),
    "TUNE_TILES.json": ("kernel tile sweep", _check_tune_tiles),
    "BENCH_CI_BASELINE.json": ("CI perf baseline", _check_ci_baseline),
    "BENCH_FLEET.json": ("fleet chaos acceptance record", _check_fleet),
}

def _check_drift_report(data: Any) -> List[str]:
    """Drift report sidecar an incident bundle carries for the drift
    rule kinds (obs/drift.py:DriftMonitor.report()); the richer
    ``validate_drift_report`` lives there — this duplicates the fields
    downstream tools read so the linter stays package-free."""
    problems = _require(
        data,
        {"schema": (int,), "counts": (dict,), "feature": (dict,),
         "heads": (dict,), "error": (dict,)},
    )
    if problems:
        return problems
    if data["schema"] != 1:
        problems.append(f"unsupported drift report schema {data['schema']!r}")
    problems += [
        f"counts.{p}" for p in _require(
            data["counts"],
            {"feature_rows": _NUM, "pred_rows": _NUM, "labeled_rows": _NUM},
        )
    ]
    problems += [
        f"feature.{p}" for p in _require(
            data["feature"],
            {"psi_max": _NUM, "qshift_max": _NUM, "channels": (list,)},
        )
    ]
    return problems


def _check_podview_report(data: Any) -> List[str]:
    """Podview skew report sidecar a ``step_skew`` / ``host_stall``
    incident bundle carries (obs/podview.py:SkewMonitor.report()); the
    runtime validator there is ``validate_podview_report`` — this
    mirrors the fields downstream tools read so the linter stays
    package-free."""
    problems = _require(
        data,
        {"schema": (int,), "host": (int,), "hosts": (int,),
         "threshold": _NUM, "history": (list,), "attribution": (dict,)},
    )
    if problems:
        return problems
    if data["schema"] != 1:
        problems.append(f"unsupported podview report schema {data['schema']!r}")
    sh = data.get("slowest_host")
    if sh is not None and not isinstance(sh, int):
        problems.append("field 'slowest_host' must be an int or null")
    return problems


def _check_spool_manifest(data: Any) -> List[str]:
    """Per-shard manifest the request spool writes next to each HGC
    shard (obs/spool.py); pins the fields drift_report / retraining
    tooling read to pick a spool window."""
    problems = _require(
        data,
        {"schema": (int,), "shard": (str,), "num_samples": (int,),
         "model_fingerprint": (str,), "sample_every": (int,),
         "tenants": (list,), "seq_range": (list,), "t_range": (list,)},
    )
    if problems:
        return problems
    if data["schema"] != 1:
        problems.append(f"unsupported spool manifest schema {data['schema']!r}")
    if data["num_samples"] < 1:
        problems.append("spool shard manifest with num_samples < 1")
    if len(data["seq_range"]) != 2:
        problems.append("seq_range must be a [first, last] pair")
    return problems


def _check_pod_shard_manifest(data: Any) -> List[str]:
    """Per-host pod checkpoint shard manifest
    (resilience/podckpt.py:save_pod_shard) — the restore side trusts
    exactly these fields to reassemble leaves across layouts, so the
    linter holds them to the same bar as committed artifacts."""
    problems = _require(
        data,
        {"format_version": (int,), "gen": (int,), "host": (int,),
         "hosts": (int,), "shard": (str,), "sha256": (str,),
         "leaves": (list,)},
    )
    if problems:
        return problems
    if not (0 <= data["host"] < data["hosts"]):
        problems.append(
            f"host {data['host']} outside [0, hosts={data['hosts']})"
        )
    for i, leaf in enumerate(data["leaves"]):
        problems += [
            f"leaves[{i}].{p}" for p in _require(
                leaf, {"path": (str,), "key": (str,), "shape": (list,),
                       "dtype": (str,)},
            )
        ]
    return problems


def _check_pod_commit(data: Any) -> List[str]:
    """Generation COMMIT marker (resilience/podckpt.py) — written LAST
    by rank 0; a reader treats its presence as "this generation is
    complete", so its few fields must always be whole."""
    return _require(
        data,
        {"format_version": (int,), "gen": (int,), "hosts": (int,)},
    )


#: runtime-artifact kinds: produced by RUNS (never committed at the
#: repo root), so they dispatch by name for explicit paths but are
#: exempt from the zero-committed-matches scan above.
RUNTIME_SCHEMAS: Dict[str, Tuple[str, Callable[[Any], List[str]]]] = {
    "incident_manifest.json": (
        "incident bundle manifest", _check_incident_manifest,
    ),
    "drift_report.json": (
        "drift incident report", _check_drift_report,
    ),
    "spool_manifest.json": (
        "request spool shard manifest", _check_spool_manifest,
    ),
    "podview_report.json": (
        "podview skew report", _check_podview_report,
    ),
    "ckpt.gen*.host*.manifest.json": (
        "pod checkpoint shard manifest", _check_pod_shard_manifest,
    ),
    "gen*.COMMIT": (
        "pod checkpoint generation commit marker", _check_pod_commit,
    ),
}


def _machine_kind(name: str) -> Optional[Tuple[str, Callable[[Any], List[str]]]]:
    for pattern, spec in MACHINE_SCHEMAS.items():
        if fnmatch.fnmatch(name, pattern):
            return spec
    for pattern, spec in RUNTIME_SCHEMAS.items():
        if fnmatch.fnmatch(name, pattern):
            return spec
    return None


def validate_machine_artifact(path: str, rel_display: str) -> List[Finding]:
    """Validate ONE committed machine JSON artifact against its kind's
    schema (kind resolved from the file name)."""
    spec = _machine_kind(os.path.basename(path))
    if spec is None:
        return [
            Finding(
                rule="HGART",
                path=rel_display,
                line=1,
                col=1,
                message=(
                    "no schema registered for this artifact name "
                    "(known kinds: "
                    f"{', '.join(sorted({**MACHINE_SCHEMAS, **RUNTIME_SCHEMAS}))})"
                ),
            )
        ]
    label, check = spec
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError) as exc:
        return [
            Finding(
                rule="HGART",
                path=rel_display,
                line=1,
                col=1,
                message=f"unreadable {label}: {exc}",
            )
        ]
    return [
        Finding(
            rule="HGART",
            path=rel_display,
            line=1,
            col=1,
            message=f"invalid {label}: {problem}",
            snippet=problem,
        )
        for problem in check(data)
    ]


def validate_machine_artifacts(repo_root: str) -> List[Finding]:
    """Validate every committed machine JSON artifact in the repo root;
    a kind with no matches at all is reported (lost evidence)."""
    findings: List[Finding] = []
    names = sorted(os.listdir(repo_root))
    for pattern, (label, _) in MACHINE_SCHEMAS.items():
        matches = [n for n in names if fnmatch.fnmatch(n, pattern)]
        if not matches:
            findings.append(
                Finding(
                    rule="HGART",
                    path=pattern,
                    line=1,
                    col=1,
                    message=f"no committed {label} matches '{pattern}'",
                )
            )
        for name in matches:
            findings.extend(
                validate_machine_artifact(os.path.join(repo_root, name), name)
            )
    return findings


def _load_flight_module(repo_root: str):
    path = os.path.join(repo_root, "hydragnn_tpu", "obs", "flight.py")
    spec = importlib.util.spec_from_file_location("_graftlint_flight", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def validate_artifacts(
    repo_root: str, paths: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Validate each artifact; returns findings (empty = all valid).

    ``require_complete`` stays False: serve artifacts legitimately hold
    several run_start/run_end pairs (cold + warm passes) and no epoch
    events — every event must still be individually well-formed, and a
    kind absent from ``_REQUIRED`` has no required-field coverage at
    all, so unregistered kinds in a committed artifact are reported
    here too.

    With no explicit ``paths``, the committed machine JSON artifacts
    (``MACHINE_SCHEMAS``) are validated too; an explicit ``.json`` path
    is dispatched to its kind's schema by file name.
    """
    flight = _load_flight_module(repo_root)
    registered = set(flight._REQUIRED) | set(flight.FAULT_KINDS)
    findings: List[Finding] = []
    if paths is None:
        findings.extend(validate_machine_artifacts(repo_root))
    for rel in paths or DEFAULT_ARTIFACTS:
        path = rel if os.path.isabs(rel) else os.path.join(repo_root, rel)
        rel_display = rel.replace(os.sep, "/")
        if rel_display.endswith(".json"):
            findings.extend(validate_machine_artifact(path, rel_display))
            continue
        if not os.path.exists(path):
            findings.append(
                Finding(
                    rule="HGART",
                    path=rel_display,
                    line=1,
                    col=1,
                    message="flight artifact missing",
                )
            )
            continue
        for problem in flight.validate_flight_record(path):
            findings.append(
                Finding(
                    rule="HGART",
                    path=rel_display,
                    line=1,
                    col=1,
                    message=problem,
                    snippet=problem,
                )
            )
        for i, ev in enumerate(flight.read_flight_record(path)):
            kind = ev.get("kind")
            if kind and kind != "_unparseable" and kind not in registered:
                findings.append(
                    Finding(
                        rule="HGART",
                        path=rel_display,
                        line=i + 1,
                        col=1,
                        message=(
                            f"event[{i}] kind '{kind}' is not registered "
                            "in obs/flight.py _REQUIRED/FAULT_KINDS"
                        ),
                        snippet=str(kind),
                    )
                )
    return findings
