"""graftcheck — compiled-IR contract checker (CC001–CC006).

graftlint (``core``/``rules``) proves invariants of the SOURCE; this
module proves invariants of the EXECUTABLE. Every speed claim since r07
rests on properties of the compiled program — no host syncs in the hot
path, bf16 edge streams with f32 accumulation, the exact FSDP
all-gather/reduce-scatter pattern, donated buffers actually aliased,
one executable per serve bucket — and none of those survive a Python
AST walk: they only exist after ``jax.jit(...).lower()`` (and, for the
collective layout, after XLA's SPMD partitioner runs at ``.compile()``).

The checker lowers the registered hot entry points (train step, scan
epoch, eval/stats steps, serve bucket ladder, bf16 conv forward) under
a given :class:`~hydragnn_tpu.parallel.partitioner.Partitioner` layout
and walks the StableHLO / post-SPMD HLO text for six contracts
(docs/LINT.md catalogs them with their motivating incidents):

  CC001  host-transfer freedom — no infeed/outfeed/host callbacks in
         any lowered hot-path module.
  CC002  dtype discipline — with ``Architecture.conv_bf16`` set, the
         edge-stream dots run in bf16 (f32 accumulation allowed); a
         silent f32 upcast refunds the ISSUE-10 bandwidth win.
  CC003  collective audit — the compiled step's collectives must match
         the set the ``(data, fsdp, edge)`` layout implies; an
         unexpected all-gather refunds FSDP's memory win.
  CC004  bucket-stable compiles — exactly one executable signature per
         serve-ladder bucket, no shape-polymorphic leaks.
  CC005  donation landing — donated entry points carry buffer-donation
         markers in the lowered module and a non-empty
         ``input_output_alias`` map in the executable (the static face
         of the r09 ``donation_check_failed`` gate).
  CC006  static VMEM budgeting — ``ops/fused_conv.py`` residency math
         for every hot-path (nodes, width) shape fits
         ``HYDRAGNN_RESIDENCY_VMEM_MB``, proven from shapes alone.

Findings flow through the graftlint framework (:class:`Finding`,
fingerprints, JSON, baseline); ``tools/graftcheck.py`` is the CLI and
``contract_block`` the cheap in-run variant train/bench manifests stamp
into the flight record.

The text walkers at the top are pure string functions (golden-fixture
testable, no jax); everything that traces or lowers imports jax lazily
so importing this module stays cheap.

Self-test injections: ``HYDRAGNN_INJECT_GRAFTCHECK=cc001..cc006``
(comma-separated) plants one real violation per contract — a host
callback in the eval step, a dropped bf16 cast, a layout-mismatched
collective permute, a colliding bucket plan, a de-donated step, a
starved VMEM budget — so CI can prove each contract actually rejects.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from hydragnn_tpu.lint.core import Finding

SCHEMA_VERSION = 1

#: contract id -> (name, one-line description)
CONTRACTS: Dict[str, Tuple[str, str]] = {
    "CC001": (
        "host-transfer freedom",
        "no infeed/outfeed/host-callback ops in a lowered hot-path module",
    ),
    "CC002": (
        "dtype discipline",
        "conv_bf16 edge-stream dots run in bf16 (f32 accumulation only)",
    ),
    "CC003": (
        "collective audit",
        "compiled collectives match the (data, fsdp, edge) layout",
    ),
    "CC004": (
        "bucket-stable compiles",
        "one executable signature per serve bucket, no dynamic shapes",
    ),
    "CC005": (
        "donation landing",
        "donated args carry aliasing markers in the lowered executable",
    ),
    "CC006": (
        "static VMEM budgeting",
        "fused-conv residency math fits HYDRAGNN_RESIDENCY_VMEM_MB",
    ),
}

#: the injection spec values HYDRAGNN_INJECT_GRAFTCHECK accepts
INJECTABLE = tuple(c.lower() for c in CONTRACTS)

# -- pure text walkers (no jax; golden-fixture testable) --------------------

#: substrings whose presence in a lowered module means the executable
#: round-trips through the host mid-step. ``stablehlo.custom_call``
#: callback targets cover jax.pure_callback / io_callback /
#: debug.callback on every backend spelling jax 0.4-0.6 emits.
HOST_TRANSFER_MARKERS = (
    "stablehlo.infeed",
    "stablehlo.outfeed",
    "stablehlo.send",
    "stablehlo.recv",
    "xla_python_cpu_callback",
    "xla_python_gpu_callback",
    "xla_ffi_python_cpu_callback",
    "xla_ffi_python_gpu_callback",
    "xla_python_callback",
)

#: how buffer donation shows in lowered StableHLO: plain jit emits
#: ``tf.aliasing_output``; jit-with-shardings (the partitioned steps)
#: emits ``jax.buffer_donor`` and resolves aliases at compile time.
DONATION_MARKERS = ("tf.aliasing_output", "jax.buffer_donor")

_ALIAS_RE = re.compile(r"input_output_alias=\{\s*\{")
_DYNAMIC_DIM_RE = re.compile(r"tensor<\?|tensor<\d*x\?")

_COLLECTIVE_RE = re.compile(
    r"=\s*\S+\s+"
    r"(all-gather|all-reduce|reduce-scatter|collective-permute|all-to-all)"
    r"(?:-start)?\("
)
_IOTA_GROUPS_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
_EXPLICIT_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}")

_DOT_RE = re.compile(
    r"stablehlo\.(dot_general|convolution)\s.*?:\s*\(([^)]*)\)\s*->\s*(tensor<[^>]+>)"
)
_TENSOR_RE = re.compile(r"tensor<([0-9x]+)x(f64|f32|f16|bf16)>")


def scan_host_transfers(lowered_text: str) -> List[str]:
    """Host-transfer markers present in a lowered module (CC001)."""
    return sorted(m for m in HOST_TRANSFER_MARKERS if m in lowered_text)


def scan_donation_markers(lowered_text: str) -> bool:
    """Whether the lowered module carries buffer-donation attributes
    on any argument (CC005)."""
    return any(m in lowered_text for m in DONATION_MARKERS)


def scan_compiled_aliasing(compiled_text: str) -> bool:
    """Whether the post-compile HLO module header declares a non-empty
    ``input_output_alias`` map — donation actually landed (CC005)."""
    return bool(_ALIAS_RE.search(compiled_text))


def scan_dynamic_dims(lowered_text: str) -> bool:
    """Whether any tensor type in the module has a dynamic (``?``)
    dimension — a shape-polymorphic leak (CC004)."""
    return bool(_DYNAMIC_DIM_RE.search(lowered_text))


@dataclasses.dataclass(frozen=True)
class Collective:
    """One collective op parsed out of post-SPMD compiled HLO."""

    kind: str  # all-gather | all-reduce | reduce-scatter | ...
    group_count: Optional[int]  # None when the op carries no groups
    group_size: Optional[int]  # None when groups are absent/ragged


def parse_collectives(compiled_text: str) -> List[Collective]:
    """Every cross-device collective in a compiled HLO module, with its
    replica-group geometry. Handles both textual forms XLA emits: the
    iota form ``replica_groups=[G,S]<=[N]`` (G groups of S devices) and
    the explicit form ``replica_groups={{0,1},{2,3}}``."""
    out: List[Collective] = []
    for line in compiled_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if not m:
            continue
        kind = m.group(1)
        count: Optional[int] = None
        size: Optional[int] = None
        mi = _IOTA_GROUPS_RE.search(line)
        if mi:
            count, size = int(mi.group(1)), int(mi.group(2))
        else:
            me = _EXPLICIT_GROUPS_RE.search(line)
            if me:
                groups = re.findall(r"\{([^{}]*)\}", me.group(1))
                sizes = {
                    len([t for t in g.split(",") if t.strip()]) for g in groups
                }
                count = len(groups)
                size = sizes.pop() if len(sizes) == 1 else None
        out.append(Collective(kind=kind, group_count=count, group_size=size))
    return out


def audit_collectives(
    collectives: Sequence[Collective],
    data: int,
    fsdp: int,
    zero1: bool = False,
) -> List[str]:
    """CC003: violation messages for collectives the ``(data, fsdp)``
    layout does not predict.

    The expected set, derived from how the Partitioner builds its
    steps (``parallel/sharded.py`` psum/pmean over the lead axes,
    FSDP parameter all-gathers, ZeRO-1/FSDP grad reduce-scatters):

      - ``all-reduce``  — always allowed; group size must span the
        batch axes (``data`` or ``data*fsdp``).
      - ``all-gather``  — FSDP only; group size must equal ``fsdp``
        (an all-gather elsewhere silently refunds FSDP's memory win).
      - ``reduce-scatter`` — FSDP/ZeRO-1 only, same group rule.
      - ``collective-permute`` / ``all-to-all`` — never expected in
        these programs; halo exchanges have no place in this model.

    Ops whose groups could not be parsed (``None``) are audited by
    kind only."""
    total = data * max(fsdp, 1)
    problems: List[str] = []
    for c in collectives:
        geom = (
            f"{c.group_count}x{c.group_size}"
            if c.group_count is not None
            else "?"
        )
        if c.kind in ("collective-permute", "all-to-all"):
            problems.append(
                f"unexpected {c.kind} (groups {geom}): the (data={data}, "
                f"fsdp={fsdp}) layout implies no permutation collectives"
            )
        elif c.kind == "all-gather":
            if fsdp <= 1:
                problems.append(
                    f"all-gather (groups {geom}) in a pure-DP program: "
                    "parameters are replicated, nothing should gather"
                )
            elif c.group_size is not None and c.group_size != fsdp:
                problems.append(
                    f"all-gather group size {c.group_size} != fsdp={fsdp}: "
                    "a gather over the wrong axis refunds FSDP's memory win"
                )
        elif c.kind == "reduce-scatter":
            if fsdp <= 1 and not zero1:
                problems.append(
                    f"reduce-scatter (groups {geom}) without fsdp/zero1: "
                    "no state shard exists to scatter into"
                )
            elif c.group_size is not None and c.group_size not in (fsdp, data):
                problems.append(
                    f"reduce-scatter group size {c.group_size} matches "
                    f"neither fsdp={fsdp} nor data={data}"
                )
        elif c.kind == "all-reduce":
            if c.group_size is not None and c.group_size not in (1, data, total):
                problems.append(
                    f"all-reduce group size {c.group_size} spans neither "
                    f"data={data} nor the full mesh ({total}): a reduction "
                    "over a partial axis is a layout mismatch"
                )
    return problems


def scan_edge_f32_dots(lowered_text: str, edge_pad: int) -> List[str]:
    """CC002: f32xf32 dot/convolution ops on the edge stream — ops whose
    operands are all f32 and whose leading dimension equals the batch's
    padded edge count. Node-level and head dots legitimately stay f32;
    the contract is about the [E, *] streams whose bytes dominate."""
    bad: List[str] = []
    for m in _DOT_RE.finditer(lowered_text):
        operands = _TENSOR_RE.findall(m.group(2))
        if not operands or any(dt != "f32" for _, dt in operands):
            continue
        lead = operands[0][0].split("x")[0]
        if lead == str(edge_pad):
            bad.append(
                f"f32 {m.group(1)} over the edge stream "
                f"({operands[0][0]}): conv_bf16 promised bf16 operands"
            )
    return bad


def count_bf16_values(lowered_text: str) -> int:
    """Number of bf16 tensor types in a lowered module — zero under a
    conv_bf16 config means the casts were dropped entirely (CC002)."""
    return lowered_text.count("xbf16>")


# -- findings ---------------------------------------------------------------


def _finding(rule: str, entry: str, message: str, severity: str = "error") -> Finding:
    """A graftcheck finding. ``path`` is the synthetic entry-point
    coordinate (``graftcheck/<layout>/<entry>``); the snippet carries
    the message head so fingerprints are content-stable across
    line-number-free findings."""
    return Finding(
        rule=rule,
        path=entry,
        line=0,
        col=0,
        message=message,
        severity=severity,
        snippet=message.split(":")[0],
    )


# -- lowered entry points ---------------------------------------------------


@dataclasses.dataclass
class LoweredEntry:
    """One hot entry point, lowered (and maybe compiled) for checking.

    ``donated``: this entry's contract includes buffer donation (train
    steps donate the state; serve forwards only donate off-CPU).
    ``bf16_expected``: the entry was built under conv_bf16=True, so
    CC002 applies. ``edge_pad`` is the padded edge count of the example
    batch (the CC002 edge-stream scope)."""

    name: str
    lowered_text: str
    compiled_text: Optional[str] = None
    donated: bool = False
    bf16_expected: bool = False
    edge_pad: Optional[int] = None


@dataclasses.dataclass
class CheckSetup:
    """Everything one graftcheck pass operates on."""

    layout: str
    data: int
    fsdp: int
    zero1: bool
    entries: List[LoweredEntry]
    #: (bucket_name, flattened (shape, dtype) signature) per serve bucket
    bucket_signatures: List[Tuple[str, Tuple]]
    #: (num_nodes, width) shapes the hot paths run the fused conv at
    residency_shapes: List[Tuple[int, int]]
    #: CC006 budget override in bytes (injection); None = the knob
    vmem_budget_override: Optional[int] = None


def parse_inject_spec(spec: Optional[str]) -> Set[str]:
    """``cc001,cc004`` -> {"cc001", "cc004"}; unknown tokens raise so a
    typo'd self-test fails loudly instead of silently passing."""
    if not spec:
        return set()
    toks = {t.strip().lower() for t in spec.split(",") if t.strip()}
    unknown = toks - set(INJECTABLE)
    if unknown:
        raise ValueError(
            f"HYDRAGNN_INJECT_GRAFTCHECK: unknown contract(s) {sorted(unknown)}; "
            f"valid: {', '.join(INJECTABLE)}"
        )
    return toks


def active_injections() -> Set[str]:
    from hydragnn_tpu.utils import knobs

    return parse_inject_spec(knobs.get_str("HYDRAGNN_INJECT_GRAFTCHECK"))


def _tiny_flagship(device_stack: int, conv_bf16: bool = False,
                   model_type: Optional[str] = None):
    """The ci.sh graftcheck-stage miniature: flagship config + deterministic
    graphs, small enough that lowering stays in the seconds range.
    Returns (loader, nn_config, batch, model, variables)."""
    from hydragnn_tpu.api import prepare_loaders_and_config
    from hydragnn_tpu.data.synthetic import deterministic_graph_data
    from hydragnn_tpu.flagship import flagship_config
    from hydragnn_tpu.models.create import create_model_config
    import jax

    hidden = 1 if model_type == "CGCNN" else 8
    cfg = flagship_config(
        hidden_dim=hidden, num_conv_layers=2, batch_size=8, num_epoch=1
    )
    arch = cfg["NeuralNetwork"]["Architecture"]
    if conv_bf16:
        arch["conv_bf16"] = True
    if model_type:
        arch["model_type"] = model_type
    if hidden < 2:
        # flagship head widths scale off hidden_dim and hit zero at the
        # width-1 CGCNN miniature; any small positive dims lower fine
        for head in arch["output_heads"].values():
            head["dim_headlayers"] = [4, 2]
            if "dim_sharedlayers" in head:
                head["dim_sharedlayers"] = 4
    samples = deterministic_graph_data(
        number_configurations=24,
        unit_cell_x_range=(2, 3),
        unit_cell_y_range=(2, 3),
        unit_cell_z_range=(2, 3),
        seed=0,
    )
    loader, _, _, config = prepare_loaders_and_config(
        cfg, samples, device_stack=device_stack
    )
    nn = config["NeuralNetwork"]
    batch = next(iter(loader))
    example = batch
    if device_stack > 1:
        example = jax.tree_util.tree_map(lambda x: x[0], batch)
    model, variables = create_model_config(nn, example)
    return loader, nn, batch, model, variables


def _layout_config(layout: str):
    """Named CI layouts on the forced host mesh: ``dp`` = pure data
    parallel over every device, ``fsdp2`` = fsdp=2 inside it."""
    import jax

    n = jax.device_count()
    if layout == "dp":
        return dict(data=n)
    if layout == "fsdp2":
        if n % 2:
            raise ValueError(f"fsdp2 layout needs an even device count, got {n}")
        return dict(data=n // 2, fsdp=2)
    raise ValueError(f"unknown layout {layout!r} (expected dp or fsdp2)")


def build_layout_setup(
    layout: str,
    inject: Optional[Set[str]] = None,
    with_compile: bool = True,
) -> CheckSetup:
    """Lower (and, ``with_compile``, compile) the partitioned hot steps
    under one named layout. Compilation is only needed for CC003 (the
    SPMD partitioner inserts collectives at compile time) and the
    executable half of CC005 — skip it when auditing other contracts."""
    import jax

    from hydragnn_tpu.parallel.partitioner import Partitioner
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.state import create_train_state

    inject = inject or set()
    part = Partitioner(**_layout_config(layout))
    loader, nn, batch, model, variables = _tiny_flagship(
        device_stack=jax.device_count()
    )
    part.attach_loader(loader)
    tx = select_optimizer(nn["Training"])
    state = part.shard_init(create_train_state(variables, tx))
    placed = part.shard_batch(batch)

    entries: List[LoweredEntry] = []
    cfgp = part.config

    step = part.shard_train_step(model, tx)
    if "cc005" in inject:
        # de-donated step: the outer jit drops the inner donation, the
        # exact regression the r09 runtime gate caught in the wild
        step_fn = jax.jit(lambda s, b: step(s, b))
    elif "cc003" in inject and part.mesh is not None:
        # layout-mismatched collective: a shard_map permute over the
        # data axis — a collective the (data, fsdp) layout never emits
        from functools import partial

        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        ndata = part.mesh.shape.get("data", 1)
        perm = [(i, (i + 1) % ndata) for i in range(ndata)]

        @partial(
            shard_map,
            mesh=part.mesh,
            in_specs=P(),
            out_specs=P(),
            check_rep=False,
        )
        def _leak(x):
            return jax.lax.ppermute(x, "data", perm)

        step_fn = jax.jit(
            lambda s, b: (lambda out: (out[0], _leak(out[1]), out[2]))(step(s, b))
        )
    else:
        step_fn = step
    lowered = step_fn.lower(state, placed)
    compiled_text = lowered.compile().as_text() if with_compile else None
    entries.append(
        LoweredEntry(
            name=f"graftcheck/{layout}/train_step",
            lowered_text=lowered.as_text(),
            compiled_text=compiled_text,
            donated=True,
        )
    )

    eval_step = part.shard_eval_step(model)
    if "cc001" in inject:
        # planted host callback: the loss round-trips through python
        import jax.numpy as jnp

        def bad_eval(s, b):
            loss, tasks = eval_step(s, b)
            loss = jax.pure_callback(
                lambda x: x, jax.ShapeDtypeStruct((), jnp.float32), loss
            )
            return loss, tasks

        eval_lowered = jax.jit(bad_eval).lower(state, placed)
    else:
        eval_lowered = eval_step.lower(state, placed)
    entries.append(
        LoweredEntry(
            name=f"graftcheck/{layout}/eval_step",
            lowered_text=eval_lowered.as_text(),
        )
    )

    stats_step = part.shard_stats_step(model)
    entries.append(
        LoweredEntry(
            name=f"graftcheck/{layout}/stats_step",
            lowered_text=stats_step.lower(state, placed).as_text(),
        )
    )

    return CheckSetup(
        layout=layout,
        data=cfgp.data,
        fsdp=cfgp.fsdp,
        zero1=bool(cfgp.zero1),
        entries=entries,
        bucket_signatures=[],
        residency_shapes=[],
    )


def build_global_setup(inject: Optional[Set[str]] = None) -> CheckSetup:
    """Layout-independent entry points: the single-device scan epoch,
    the bf16 conv forward (CC002's scope — CGCNN is the conv family
    whose edge stream is matmul-shaped), and the serve bucket ladder
    (CC004/CC006)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.state import create_train_state, make_scan_epoch

    inject = inject or set()
    entries: List[LoweredEntry] = []

    # scan epoch (single-device whole-epoch dispatch; donates state)
    loader, nn, batch, model, variables = _tiny_flagship(device_stack=1)
    tx = select_optimizer(nn["Training"])
    state = create_train_state(variables, tx)
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), batch, batch)
    order = jnp.arange(2, dtype=jnp.int32)
    scan = make_scan_epoch(model, tx)
    entries.append(
        LoweredEntry(
            name="graftcheck/global/scan_epoch",
            lowered_text=scan.lower(state, stacked, order).as_text(),
            donated=True,
        )
    )

    # bf16 conv forward: CGCNN's decomposed edge-stream dots are where
    # a silent f32 upcast costs bandwidth. The cc002 injection builds
    # the model with the bf16 cast DROPPED while still claiming the
    # contract — exactly the regression CC002 exists to catch.
    _, nnc, cbatch, cmodel, cvars = _tiny_flagship(
        device_stack=1,
        conv_bf16=("cc002" not in inject),
        model_type="CGCNN",
    )
    fwd = jax.jit(lambda v, b: cmodel.apply(v, b, train=False))
    entries.append(
        LoweredEntry(
            name="graftcheck/global/conv_forward_bf16",
            lowered_text=fwd.lower(cvars, cbatch).as_text(),
            bf16_expected=True,
            edge_pad=int(cbatch.senders.shape[0]),
        )
    )

    # serve bucket ladder: lower the serving forward once per rung and
    # record each executable signature (CC004); the pad shapes feed the
    # CC006 residency audit.
    from hydragnn_tpu.data.synthetic import deterministic_graph_data
    from hydragnn_tpu.graph.batch import batch_graphs
    from hydragnn_tpu.serve.buckets import build_bucket_ladder

    # wider cells than the train miniature: the ladder needs real size
    # spread or bucket_pad_plans dedupes it to one rung
    samples = deterministic_graph_data(
        number_configurations=24,
        unit_cell_x_range=(2, 5),
        unit_cell_y_range=(2, 5),
        unit_cell_z_range=(2, 5),
        seed=0,
    )
    buckets = build_bucket_ladder(samples, max_batch=4, num_buckets=3)
    if "cc004" in inject and len(buckets) > 1:
        # colliding plans: rung 1 re-uses rung 0's pad plan, so two
        # buckets share one executable signature
        b0, b1 = buckets[0], buckets[1]
        buckets[1] = dataclasses.replace(
            b1, node_pad=b0.node_pad, edge_pad=b0.edge_pad, graph_pad=b0.graph_pad
        )
    feat = int(batch.nodes.shape[-1])
    serve_fwd = jax.jit(lambda v, b: model.apply(v, b, train=False))
    signatures: List[Tuple[str, Tuple]] = []
    hidden = int(nn["Architecture"]["hidden_dim"])
    shapes: List[Tuple[int, int]] = []
    for b in buckets:
        # the server's warm-batch recipe (serve/server.py): one minimal
        # graph matching the model's field spec, padded to the rung
        g = {
            "x": np.zeros((2, feat), dtype=np.float32),
            "senders": np.zeros((1,), dtype=np.int32),
            "receivers": np.ones((1,), dtype=np.int32),
        }
        if batch.pos is not None:
            g["pos"] = np.zeros((2, batch.pos.shape[-1]), dtype=np.float32)
        if batch.edge_attr is not None:
            g["edge_attr"] = np.zeros(
                (1, batch.edge_attr.shape[-1]), dtype=np.float32
            )
        warm = batch_graphs(
            [g],
            n_node_pad=b.node_pad,
            n_edge_pad=b.edge_pad,
            n_graph_pad=b.graph_pad,
        )
        low = serve_fwd.lower(variables, warm)
        name = f"graftcheck/global/serve_bucket_{b.index}"
        leaves = jax.tree_util.tree_leaves(warm)
        sig = tuple(
            (tuple(x.shape), str(x.dtype)) for x in leaves if hasattr(x, "shape")
        )
        signatures.append((name, sig))
        entries.append(LoweredEntry(name=name, lowered_text=low.as_text()))
        shapes.append((b.node_pad, hidden))

    return CheckSetup(
        layout="global",
        data=1,
        fsdp=1,
        zero1=False,
        entries=entries,
        bucket_signatures=signatures,
        residency_shapes=shapes,
        vmem_budget_override=(4096 if "cc006" in inject else None),
    )


# -- the checks -------------------------------------------------------------


def check_setup(
    setup: CheckSetup, contracts: Optional[Iterable[str]] = None
) -> List[Finding]:
    """Run the requested contracts (default: all) over one setup."""
    wanted = set(contracts) if contracts else set(CONTRACTS)
    findings: List[Finding] = []

    for e in setup.entries:
        if "CC001" in wanted:
            for marker in scan_host_transfers(e.lowered_text):
                findings.append(
                    _finding(
                        "CC001",
                        e.name,
                        f"host transfer in lowered module: {marker} — the "
                        "hot path must not round-trip through the host",
                    )
                )
        if "CC002" in wanted and e.bf16_expected:
            if count_bf16_values(e.lowered_text) == 0:
                findings.append(
                    _finding(
                        "CC002",
                        e.name,
                        "conv_bf16 is set but the lowered module carries no "
                        "bf16 values: the edge-stream casts were dropped",
                    )
                )
            elif e.edge_pad:
                for msg in scan_edge_f32_dots(e.lowered_text, e.edge_pad):
                    findings.append(_finding("CC002", e.name, msg))
        if "CC003" in wanted and e.compiled_text is not None:
            colls = parse_collectives(e.compiled_text)
            for msg in audit_collectives(
                colls, setup.data, setup.fsdp, setup.zero1
            ):
                findings.append(_finding("CC003", e.name, msg))
        if "CC004" in wanted and scan_dynamic_dims(e.lowered_text):
            findings.append(
                _finding(
                    "CC004",
                    e.name,
                    "dynamic dimension (tensor<?xx...>) in lowered module: a "
                    "shape-polymorphic leak defeats the bucket compile cache",
                )
            )
        if "CC005" in wanted and e.donated:
            if not scan_donation_markers(e.lowered_text):
                findings.append(
                    _finding(
                        "CC005",
                        e.name,
                        "donated entry point has no buffer-donation marker in "
                        "its lowered module: donation was dropped (r09 "
                        "donation_check_failed, statically)",
                    )
                )
            elif e.compiled_text is not None and not scan_compiled_aliasing(
                e.compiled_text
            ):
                findings.append(
                    _finding(
                        "CC005",
                        e.name,
                        "lowered module declares donors but the executable's "
                        "input_output_alias map is empty: donation did not land",
                    )
                )

    if "CC004" in wanted and setup.bucket_signatures:
        seen: Dict[Tuple, str] = {}
        for name, sig in setup.bucket_signatures:
            if sig in seen:
                findings.append(
                    _finding(
                        "CC004",
                        name,
                        f"bucket signature collides with {seen[sig]}: the "
                        "ladder must compile exactly one executable per rung",
                    )
                )
            else:
                seen[sig] = name

    if "CC006" in wanted and setup.residency_shapes:
        findings.extend(
            check_vmem_budget(
                setup.residency_shapes,
                budget_bytes=setup.vmem_budget_override,
                entry=f"graftcheck/{setup.layout}/fused_conv_residency",
            )
        )

    return findings


def check_vmem_budget(
    shapes: Sequence[Tuple[int, int]],
    budget_bytes: Optional[int] = None,
    entry: str = "graftcheck/global/fused_conv_residency",
) -> List[Finding]:
    """CC006: the cross-layer resident conv stack's VMEM claim at every
    hot-path (num_nodes, width) shape, from ``ops/fused_conv.py``'s own
    residency arithmetic — no kernel ever executes. Also bounds the
    configured budget by physical VMEM (a TPU core has ~16 MB and the
    pipeline needs headroom; a budget above that is a config lie)."""
    from hydragnn_tpu.ops.fused_conv import (
        residency_vmem_budget_bytes,
        residency_vmem_bytes,
    )

    budget = (
        budget_bytes if budget_bytes is not None else residency_vmem_budget_bytes()
    )
    findings: List[Finding] = []
    if budget > 16 * 2**20:
        findings.append(
            _finding(
                "CC006",
                entry,
                f"HYDRAGNN_RESIDENCY_VMEM_MB grants {budget / 2**20:.1f} MB "
                "but a TPU core has ~16 MB of VMEM: the budget over-promises",
            )
        )
    for n, width in sorted(set(shapes)):
        need = residency_vmem_bytes(n, width)
        if need > budget:
            findings.append(
                _finding(
                    "CC006",
                    entry,
                    f"resident conv stack at nodes={n} width={width} needs "
                    f"{need / 2**20:.2f} MB VMEM > budget "
                    f"{budget / 2**20:.2f} MB: the residency gate will "
                    "silently fall back to the HBM path",
                )
            )
    return findings


def run_graftcheck(
    layouts: Sequence[str] = ("dp", "fsdp2"),
    contracts: Optional[Iterable[str]] = None,
    inject: Optional[Set[str]] = None,
) -> List[Finding]:
    """The full pass ``tools/graftcheck.py`` drives: every requested
    layout's partitioned steps plus the layout-independent entries,
    checked under the requested contracts. Compilation (the expensive
    arm) only happens when CC003 or CC005 are in scope."""
    if inject is None:
        inject = active_injections()
    wanted = set(contracts) if contracts else set(CONTRACTS)
    unknown = wanted - set(CONTRACTS)
    if unknown:
        raise ValueError(f"unknown contract id(s): {sorted(unknown)}")
    with_compile = bool(wanted & {"CC003", "CC005"})
    findings: List[Finding] = []
    for layout in layouts:
        setup = build_layout_setup(layout, inject=inject, with_compile=with_compile)
        findings.extend(check_setup(setup, wanted))
    setup = build_global_setup(inject=inject)
    findings.extend(check_setup(setup, wanted))
    findings.sort(key=lambda f: (f.path, f.rule, f.message))
    return findings


# -- in-run manifest stamping ----------------------------------------------


def contract_block(
    lowered_text: Optional[str] = None,
    *,
    donated: bool = False,
    conv_bf16: bool = False,
    edge_pad: Optional[int] = None,
    compiled_text: Optional[str] = None,
    data: int = 1,
    fsdp: int = 1,
    zero1: bool = False,
    residency_shapes: Optional[Sequence[Tuple[int, int]]] = None,
) -> Dict[str, Any]:
    """The ``graftcheck`` block a run stamps into its flight manifest:
    the cheap static contracts, checked against the run's OWN lowered
    step (train/loop.py reuses the module it already lowers for the
    hardware ledger; bench.py and bench_serve.py do the same), so every
    recorded run says which contracts its executables passed.

    Contracts whose evidence is not available in-run (no compiled HLO,
    no bf16 config) report ``not_checked`` with the reason — an honest
    manifest beats a hollow green."""
    contracts: Dict[str, Dict[str, Any]] = {}
    violations: List[str] = []

    def mark(cid: str, status: str, detail: str = "") -> None:
        contracts[cid] = {"status": status}
        if detail:
            contracts[cid]["detail"] = detail

    if lowered_text is None:
        for cid in CONTRACTS:
            mark(cid, "not_checked", "no lowered module available")
        return {
            "schema": SCHEMA_VERSION,
            "contracts": contracts,
            "violations": violations,
        }

    markers = scan_host_transfers(lowered_text)
    if markers:
        mark("CC001", "fail", ", ".join(markers))
        violations.append(f"CC001: host transfer ({', '.join(markers)})")
    else:
        mark("CC001", "pass")

    if not conv_bf16:
        mark("CC002", "not_checked", "conv_bf16 off")
    else:
        bad = scan_edge_f32_dots(lowered_text, edge_pad) if edge_pad else []
        if count_bf16_values(lowered_text) == 0:
            mark("CC002", "fail", "no bf16 values in lowered module")
            violations.append("CC002: conv_bf16 set but no bf16 compute")
        elif bad:
            mark("CC002", "fail", bad[0])
            violations.append(f"CC002: {bad[0]}")
        else:
            mark("CC002", "pass")

    if compiled_text is None:
        mark("CC003", "not_checked", "no compiled HLO in-run")
    else:
        problems = audit_collectives(
            parse_collectives(compiled_text), data, fsdp, zero1
        )
        if problems:
            mark("CC003", "fail", problems[0])
            violations.extend(f"CC003: {p}" for p in problems)
        else:
            mark("CC003", "pass")

    mark("CC004", "not_checked", "serve-ladder contract; see tools/graftcheck.py")

    if not donated:
        mark("CC005", "not_checked", "entry point does not donate")
    elif not scan_donation_markers(lowered_text):
        mark("CC005", "fail", "no donation marker in lowered module")
        violations.append("CC005: donation dropped from lowered step")
    elif compiled_text is not None and not scan_compiled_aliasing(compiled_text):
        mark("CC005", "fail", "executable input_output_alias empty")
        violations.append("CC005: donation did not land in the executable")
    else:
        mark("CC005", "pass")

    if residency_shapes:
        probs = check_vmem_budget(residency_shapes)
        if probs:
            mark("CC006", "fail", probs[0].message)
            violations.extend(f"CC006: {p.message}" for p in probs)
        else:
            mark("CC006", "pass")
    else:
        mark("CC006", "not_checked", "no resident-conv shapes in this run")

    return {
        "schema": SCHEMA_VERSION,
        "contracts": contracts,
        "violations": violations,
    }
