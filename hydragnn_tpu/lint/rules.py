"""The graftlint rule set: HG001–HG008, one class per invariant.

Each rule encodes something a past PR paid to learn (docs/LINT.md has
the incident history). They are deliberately AST-shallow — no type
inference, no cross-module dataflow — tuned so that every finding on
this tree is a true positive and near-misses (the same call in a
legitimate position) stay silent. When a rule can't decide, it stays
quiet: the linter's contract is zero false positives on the shipped
tree, enforced by tests/test_graftlint.py's meta-test.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import (
    Finding,
    ParsedModule,
    Rule,
    dotted_name,
    load_flight_kinds,
    load_knob_registry,
    string_arg,
)

_KNOB_RE = re.compile(r"HYDRAGNN_[A-Z0-9_]*\Z")


def _functions_by_name(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    """Module-level and method-level function defs by bare name (last
    definition wins — fine for reachability)."""
    out: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def _called_names(func: ast.AST) -> Set[str]:
    """Bare names referenced anywhere in a function body — call
    targets, plus functions passed by name (``jax.jit(step)``)."""
    names: Set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Name):
            names.add(node.id)
    return names


def _nested_defs(func: ast.FunctionDef) -> List[ast.FunctionDef]:
    """Function defs nested (at any depth) inside ``func``."""
    out: List[ast.FunctionDef] = []

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(child)
            visit(child)

    visit(func)
    return out


class HostSyncInHotPath(Rule):
    """HG001 — no host synchronization inside traced step/epoch bodies.

    The step builders (``make_train_step``/``make_scan_epoch``/... and
    their sharded/edge-sharded twins) return jitted functions whose
    nested bodies are traced once and dispatched thousands of times; a
    ``block_until_ready``/``device_get``/``np.asarray``/``float()``
    there either fails tracing or — worse — silently forces a D2H
    round-trip per step (the r06 regression the async-dispatch PR
    removed). Builder-level host ops run once at build time and are
    fine, so only *nested* function bodies are scanned. ``obs/spans.py``
    is allowlisted wholesale: its sampled sync window is the one place
    a deliberate device sync belongs.
    """

    id = "HG001"
    name = "host-sync-in-hot-path"
    description = (
        "host sync (block_until_ready / device_get / np.asarray / "
        "float()/int() / .item()) inside a traced body reachable from a "
        "step/epoch builder"
    )
    exclude = ("obs/spans.py", "tests/", "examples/", "lint/")

    HOT_ROOTS = (
        "make_train_step",
        "make_scan_epoch",
        "make_scan_eval",
        "make_stats_step",
        "make_eval_step",
        "make_diagnostics_step",
        "make_sharded_train_step",
        "make_sharded_stats_step",
        "make_sharded_eval_step",
        "make_dp_edge_train_step",
        "make_dp_edge_eval_step",
        "make_dp_edge_stats_step",
    )
    _NP_ALIASES = ("np", "numpy", "onp")

    def _reachable(self, module: ParsedModule) -> List[ast.FunctionDef]:
        funcs = _functions_by_name(module.tree)
        todo = [n for n in self.HOT_ROOTS if n in funcs]
        seen: Set[str] = set()
        while todo:
            name = todo.pop()
            if name in seen:
                continue
            seen.add(name)
            for called in _called_names(funcs[name]):
                if called in funcs and called not in seen:
                    todo.append(called)
        return [funcs[n] for n in sorted(seen)]

    def _sync_call(self, call: ast.Call) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr == "block_until_ready":
                return "block_until_ready()"
            if func.attr == "device_get":
                return f"{dotted_name(func) or 'device_get'}()"
            if func.attr == "item":
                return ".item()"
            if func.attr in ("asarray", "array"):
                base = func.value
                if isinstance(base, ast.Name) and base.id in self._NP_ALIASES:
                    return f"{base.id}.{func.attr}()"
        elif isinstance(func, ast.Name) and func.id in ("float", "int"):
            if call.args and not isinstance(call.args[0], ast.Constant):
                return f"{func.id}() on a runtime value"
        return None

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        for builder in self._reachable(module):
            for body in _nested_defs(builder):
                for node in ast.walk(body):
                    if not isinstance(node, ast.Call):
                        continue
                    what = self._sync_call(node)
                    if what:
                        yield self.finding(
                            module,
                            node,
                            f"{what} inside traced body "
                            f"'{body.name}' of hot builder "
                            f"'{builder.name}' forces a per-step host "
                            "sync (docs/PERF.md sync discipline)",
                        )


class MeshOutsidePartitioner(Rule):
    """HG002 — ``Mesh`` is constructed in ``hydragnn_tpu/parallel/``
    and nowhere else.

    The AST-accurate replacement for the old ``grep -rn 'Mesh('`` gate
    in ci.sh's partitioner-smoke stage: it additionally sees ``jax.sharding.Mesh(...)``
    attribute calls, module aliases (``import jax.sharding as sh;
    sh.Mesh(...)``), and aliased imports (``from jax.sharding import
    Mesh as M``) that the grep missed. Every mesh must come from the
    Partitioner so train/serve/bench agree on axis layout.
    """

    id = "HG002"
    name = "mesh-outside-partitioner"
    description = (
        "jax.sharding.Mesh imported or constructed outside "
        "hydragnn_tpu/parallel/"
    )
    exclude = ("hydragnn_tpu/parallel/", "tests/", "lint/")

    _MESH_MODULES = ("jax.sharding", "jax.experimental.maps", "jax.interpreters.pxla")

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        sharding_aliases: Set[str] = set()
        mesh_names: Set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name in self._MESH_MODULES:
                        sharding_aliases.add(alias.asname or alias.name)
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name == "Mesh":
                        mesh_names.add(alias.asname or alias.name)
                        yield self.finding(
                            module,
                            node,
                            f"'Mesh' imported from {node.module or '.'}"
                            " — construct meshes via hydragnn_tpu.parallel"
                            " (Partitioner) only",
                        )
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if dn is None:
                continue
            if dn in mesh_names:
                yield self.finding(
                    module,
                    node,
                    f"mesh constructed via '{dn}(' outside "
                    "hydragnn_tpu/parallel/",
                )
            elif dn.endswith(".Mesh"):
                base = dn[: -len(".Mesh")]
                if base in sharding_aliases or base in self._MESH_MODULES:
                    yield self.finding(
                        module,
                        node,
                        f"mesh constructed via '{dn}(' outside "
                        "hydragnn_tpu/parallel/",
                    )


class DonationAfterDeserialize(Rule):
    """HG003 — deserialized executables only flow through the gated
    loader in ``utils/exec_cache.py``.

    On jax 0.4.x a deserialized executable with donated arguments is
    memory-unsafe unless the donation round-trip probe has passed
    (``exec_cache.donation_roundtrip_ok``). ``ExecCache.load`` wraps
    every ``deserialize_and_load`` with that gate plus digest and
    compat checks; a direct call anywhere else bypasses all three.
    """

    id = "HG003"
    name = "donation-after-deserialize"
    description = (
        "direct deserialize_and_load/deserialize_executable call outside "
        "utils/exec_cache.py bypasses the donation-probe gate"
    )
    exclude = ("utils/exec_cache.py", "tests/", "lint/")

    _LOADERS = ("deserialize_and_load", "deserialize_executable")

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if dn and dn.split(".")[-1] in self._LOADERS:
                yield self.finding(
                    module,
                    node,
                    f"'{dn}' called directly — use ExecCache.load, which "
                    "applies the digest, compat, and donation-probe gates "
                    "(utils/exec_cache.py)",
                )


class JitInLoop(Rule):
    """HG004 — no ``jax.jit``/``pjit`` construction inside a loop body.

    A jit wrapper built per iteration recompiles (or at best re-hashes)
    every pass — the classic silent 100x regression. Hoist the wrapper
    out of the loop or reuse a cached executable. Lexical check: any
    jit/pjit call (including via ``functools.partial``) whose nearest
    enclosing statement sits in a ``for``/``while`` body.

    Promoted warning -> error once the tree reached zero findings: a
    recompile-per-iteration hazard is never acceptable on the hot path,
    and the empty committed baseline keeps it that way.
    """

    id = "HG004"
    name = "jit-in-loop"
    severity = "error"
    description = "jax.jit/pjit called inside a for/while body (recompile hazard)"
    exclude = ("tests/", "examples/", "lint/")

    @staticmethod
    def _is_jit(call: ast.Call) -> bool:
        dn = dotted_name(call.func)
        if dn is None:
            return False
        leaf = dn.split(".")[-1]
        if leaf in ("jit", "pjit"):
            return True
        if leaf == "partial":
            for arg in call.args[:1]:
                adn = dotted_name(arg)
                if adn and adn.split(".")[-1] in ("jit", "pjit"):
                    return True
        return False

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        loops: List[ast.AST] = [
            n for n in ast.walk(module.tree) if isinstance(n, (ast.For, ast.While))
        ]
        seen: Set[int] = set()
        for loop in loops:
            for node in ast.walk(loop):
                if (
                    isinstance(node, ast.Call)
                    and id(node) not in seen
                    and self._is_jit(node)
                ):
                    seen.add(id(node))
                    yield self.finding(
                        module,
                        node,
                        "jit construction inside a loop body recompiles "
                        "per iteration — hoist the wrapper or use "
                        "ExecCache.get_or_compile",
                    )


class UnregisteredFlightKind(Rule):
    """HG005 — every ``record(kind, ...)`` literal is a registered
    flight-event kind.

    ``obs/flight.py`` validates committed flight artifacts against its
    ``_REQUIRED``/``FAULT_KINDS`` tables; an event kind recorded but
    never registered passes at write time and then fails (or silently
    escapes) every downstream ``validate_flight_record`` gate — schema
    drift of exactly the sort the r08 serve-resilience work burned a
    day on. Register the kind (with its required payload fields) in
    ``_REQUIRED`` first.
    """

    id = "HG005"
    name = "unregistered-flight-kind"
    description = (
        "record(kind=...) string literal not present in obs/flight.py's "
        "_REQUIRED/FAULT_KINDS tables"
    )
    exclude = ("tests/", "examples/", "lint/")

    def __init__(self, repo_root: str):
        self._kinds = load_flight_kinds(repo_root)

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            is_record = (
                isinstance(func, ast.Attribute) and func.attr == "record"
            ) or (isinstance(func, ast.Name) and func.id == "record")
            if not is_record:
                continue
            kind = string_arg(node, 0, "kind")
            if kind is not None and kind not in self._kinds:
                yield self.finding(
                    module,
                    node,
                    f"flight kind '{kind}' is not registered in "
                    "obs/flight.py _REQUIRED/FAULT_KINDS — "
                    "validate_flight_record will reject or ignore it",
                )


class UndeclaredEnvKnob(Rule):
    """HG006 — every ``HYDRAGNN_*`` name in the tree is declared in
    ``utils/knobs.py``, and every declared knob is still referenced.

    The registry is the single source for docs/KNOBS.md and the typed
    accessors; a string literal that bypasses it is an undocumented
    knob (or a typo that silently reads the default forever). Checked
    on every string constant matching ``HYDRAGNN_[A-Z0-9_]*`` — a
    literal that is a *prefix* of registered names (e.g. the
    ``HYDRAGNN_INJECT_`` family scans) is allowed and marks the whole
    family as referenced. Test files are scanned for reference
    tracking but never flagged (fixtures are deliberately invalid).
    The stale-registry arm only fires on full-tree scans.
    """

    id = "HG006"
    name = "undeclared-env-knob"
    description = (
        "HYDRAGNN_* literal absent from the utils/knobs.py registry "
        "(or a registered knob no longer referenced anywhere)"
    )
    exclude = ("utils/knobs.py",)

    def __init__(self, repo_root: str):
        self._registry = load_knob_registry(repo_root)
        self._knobs_path = "hydragnn_tpu/utils/knobs.py"
        self._used: Set[str] = set()

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        emit = "tests/" not in module.path and "lint/" not in module.path
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Constant) and isinstance(node.value, str)):
                continue
            text = node.value
            if not _KNOB_RE.fullmatch(text):
                continue
            if text in self._registry:
                self._used.add(text)
                continue
            family = [k for k in self._registry if k.startswith(text)]
            if family:
                # prefix scan (e.g. "HYDRAGNN_INJECT_") references the family
                self._used.update(family)
                continue
            if emit:
                yield self.finding(
                    module,
                    node,
                    f"env knob '{text}' is not declared in "
                    "hydragnn_tpu/utils/knobs.py — register it (and its "
                    "type/default/doc line) so docs/KNOBS.md stays true",
                )

    def finalize(self) -> Iterator[Finding]:
        for name in sorted(set(self._registry) - self._used):
            yield Finding(
                rule=self.id,
                path=self._knobs_path,
                line=self._registry[name],
                col=1,
                message=(
                    f"knob '{name}' is declared in the registry but "
                    "referenced nowhere in the tree — delete the stale "
                    "entry or restore its consumer"
                ),
                severity=self.severity,
                snippet=name,
            )


class BareAssertContract(Rule):
    """HG007 — no ``assert`` for runtime contracts in library code.

    ``python -O`` strips asserts, so a contract expressed as ``assert``
    is a no-op in optimized deployments (the r05 #2 bug class: a
    shape-contract assert compiled away and the bad batch reached the
    kernel). Raise a typed exception instead; tests and examples keep
    their asserts.
    """

    id = "HG007"
    name = "bare-assert-contract"
    description = "assert statement in library code (stripped under python -O)"
    exclude = ("tests/", "examples/", "lint/")

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assert):
                yield self.finding(
                    module,
                    node,
                    "bare assert in library code is stripped under "
                    "python -O — raise a typed exception "
                    "(e.g. ValueError / an AssertionError subclass)",
                )


class TracerLeak(Rule):
    """HG008 — no stores to ``self.``/globals inside jitted bodies.

    Assigning a traced value to an object attribute or module global
    from inside a jitted function leaks the tracer: the first call
    stores a tracer object that outlives the trace, and every later
    read raises ``TracerLeakError`` (or worse, silently holds stale
    constants after the first compile). Return the value instead.
    Checked inside functions that are jit-decorated or passed by name
    to ``jax.jit``/``pjit`` in the same module.
    """

    id = "HG008"
    name = "tracer-leak"
    description = (
        "assignment to self.*/global inside a jitted function body "
        "(tracer leak)"
    )
    exclude = ("tests/", "examples/", "lint/")

    @staticmethod
    def _is_jit_ref(node: ast.AST) -> bool:
        dn = dotted_name(node)
        return dn is not None and dn.split(".")[-1] in ("jit", "pjit")

    def _jitted_functions(self, tree: ast.Module) -> List[ast.FunctionDef]:
        funcs = _functions_by_name(tree)
        jitted: Dict[str, ast.FunctionDef] = {}
        for name, func in funcs.items():
            for dec in func.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if self._is_jit_ref(target):
                    jitted[name] = func
                elif isinstance(dec, ast.Call) and any(
                    self._is_jit_ref(a) for a in dec.args[:1]
                ):
                    jitted[name] = func  # functools.partial(jax.jit, ...)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and self._is_jit_ref(node.func):
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Name) and arg.id in funcs:
                        jitted[arg.id] = funcs[arg.id]
        return list(jitted.values())

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        for func in self._jitted_functions(module.tree):
            for node in ast.walk(func):
                if isinstance(node, ast.Global):
                    yield self.finding(
                        module,
                        node,
                        f"'global {', '.join(node.names)}' inside jitted "
                        f"'{func.name}' — a traced store to a global "
                        "leaks the tracer; return the value instead",
                    )
                elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for tgt in targets:
                        if (
                            isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                        ):
                            yield self.finding(
                                module,
                                node,
                                f"store to 'self.{tgt.attr}' inside jitted "
                                f"'{func.name}' leaks the tracer — return "
                                "the value instead",
                            )


def all_rules(repo_root: str) -> List[Rule]:
    """The shipped rule set, in id order."""
    return [
        HostSyncInHotPath(),
        MeshOutsidePartitioner(),
        DonationAfterDeserialize(),
        JitInLoop(),
        UnregisteredFlightKind(repo_root),
        UndeclaredEnvKnob(repo_root),
        BareAssertContract(),
        TracerLeak(),
    ]
