"""graftlint — the repo's AST-based invariant linter.

``core`` holds the framework (Finding/Rule/runner/suppressions/
baseline), ``rules`` the HG001–HG008 rule set, ``artifacts`` the
flight-record artifact validator behind ``graftlint --artifacts``.
docs/LINT.md is the human-facing catalog; ``tools/graftlint.py`` the
CLI (which loads this package standalone, without importing the
jax-heavy ``hydragnn_tpu`` root — keep this ``__init__`` free of
submodule imports so that bootstrap stays cheap and ordering-free).
"""
