"""graftlint/graftsync — the repo's AST-based static analyzers.

``core`` holds the shared framework (Finding/Rule/runner/
suppressions/baseline), ``rules`` the graftlint HG001–HG008 rule set,
``concurrency`` the graftsync HS001–HS006 thread-safety/
lock-discipline rules plus the static lock-order graph the runtime
witness (``utils/syncdebug.py``) seeds from, ``ir`` the graftcheck
compiled-IR contracts, and ``artifacts`` the flight-record artifact
validator behind ``graftlint --artifacts``. docs/LINT.md is the
human-facing catalog; ``tools/graftlint.py`` / ``tools/graftsync.py``
are the CLIs (each loads this package standalone, without importing
the jax-heavy ``hydragnn_tpu`` root — keep this ``__init__`` free of
submodule imports so that bootstrap stays cheap and ordering-free).
"""
