"""graftsync — thread-safety & lock-discipline static analysis (HS rules).

The r06–r12 substrate made hydragnn_tpu heavily multithreaded: serve
dispatch + DispatchSupervisor, the HangWatchdog heartbeat, loader
prefetch, diststore connection threads, the flight-recorder write lock,
the metrics registry, the Tracer ring, the process-wide profiler
capture slot, the IncidentRecorder. graftlint (HG rules) checks
AST/JAX invariants and graftcheck (CC rules) compiled IR; this module
is the third leg — it checks the CONCURRENCY discipline of the tree,
statically, from plain AST (stdlib-only, no jax import, milliseconds).

Model
-----
A class is *concurrent* when it owns a lock (``threading.Lock`` /
``RLock`` / ``Condition``, possibly wrapped in
``syncdebug.maybe_wrap``), when one of its methods is the target of a
``Thread(target=...)`` / ``threading.Timer`` spawn, or when its
``class`` line carries ``# graftsync: shared``. Thread roots are every
spawn target plus (implicitly) the main thread calling the public API,
so every mutable attribute of a concurrent class is cross-thread
visible and must declare its discipline:

    self._count = 0      # graftsync: guarded-by=batcher.MicroBatchQueue._cv
    self.enabled = True  # graftsync: thread-safe=GIL-atomic bool gate

Module globals written from functions (``global X`` or container
mutation) follow the same rule. Locks are named — derived
``<modstem>.<Class>.<attr>`` / ``<modstem>.<NAME>`` by default,
overridable with ``# graftsync: lock=<name>`` or the string passed to
``syncdebug.maybe_wrap``. A method whose callers hold a lock for it
declares ``# graftsync: holds=<lock>``; the analyzer then checks its
same-class call sites actually hold that lock. Spawn targets declare
``# graftsync: thread-root``. Suppressions use the shared graftlint
grammar: ``# graftsync: disable=HS001 -- reason``.

Rules (docs/LINT.md catalogs invariant + motivating incident):
  HS001 unguarded-shared-state      declaration + guard-discipline
  HS002 lock-acquire-without-release-path
  HS003 blocking-call-under-lock    (block_until_ready, queue.get,
                                     future resolution, profiler
                                     capture, sleeps/joins/waits)
  HS004 thread-spawn-without-join/daemon-policy
  HS005 undeclared-thread-root
  HS006 potential-deadlock          static lock-order cycle

The static lock-order graph HS006 builds is also exported through
:func:`build_lock_order` — ``tools/graftsync.py --order-graph`` dumps
it, and the runtime witness (``utils/syncdebug.py``,
``HYDRAGNN_LOCK_DEBUG=1``) seeds its observed-order assertion with it.

Scope: the production tree (tests/ and examples/ spawn threads
adversarially on purpose and are excluded, mirroring graftlint's
per-rule excludes). Checks are lexical — a ``with lock:`` region plus
``holds=`` bodies; call-graph reasoning is one level deep and only
where resolution is unambiguous, because a linter that guesses is a
linter that gets suppressed.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import Finding, ParsedModule, Rule, dotted_name

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_WRAP_TAILS = {"maybe_wrap"}

#: method names whose call mutates the receiver in place — a
#: ``self.X.append(...)`` is a write to shared state just like
#: ``self.X = ...``
_MUTATORS = {
    "append", "appendleft", "extend", "insert",
    "pop", "popleft", "popitem", "remove", "discard",
    "add", "clear", "update", "setdefault", "sort", "reverse",
    "put", "put_nowait",
}

#: dotted-tail names that block (or run arbitrary callbacks) and must
#: not execute while holding a lock; see _blocking_reason for the
#: context-sensitive members (.get/.wait/.join/.cancel)
_BLOCKING_TAILS = {
    "block_until_ready": "device sync",
    "device_get": "device transfer",
    "sleep": "sleep",
    "try_start_capture": "profiler capture",
    "stop_capture": "profiler capture",
    "start_trace": "profiler capture",
    "stop_trace": "profiler capture",
    "set_exception": "future resolution runs done-callbacks synchronously",
    "set_result": "future resolution runs done-callbacks synchronously",
    "result": "future wait",
}

_ANNOT_RE = re.compile(
    r"#\s*graftsync:\s*([a-z][a-z-]*)\s*(?:=\s*([^#]*?))?\s*$"
)

_ANNOT_KINDS = {
    "lock", "guarded-by", "thread-safe", "holds", "thread-root", "shared",
}


def _parse_annotations(lines: Sequence[str]) -> Dict[int, Tuple[str, str]]:
    """``{line: (kind, value)}`` for every graftsync annotation;
    ``disable``/``disable-file`` belong to core's suppression machinery
    and are skipped here."""
    out: Dict[int, Tuple[str, str]] = {}
    for i, line in enumerate(lines, start=1):
        m = _ANNOT_RE.search(line)
        if not m:
            continue
        kind = m.group(1)
        if kind not in _ANNOT_KINDS:
            continue
        value = (m.group(2) or "").strip()
        # an optional trailing "-- reason" on name-valued annotations
        if kind != "thread-safe" and "--" in value:
            value = value.split("--", 1)[0].strip()
        out[i] = (kind, value)
    return out


def _annot_at(annots: Dict[int, Tuple[str, str]], line: int,
              kind: str) -> Optional[str]:
    """Annotation of ``kind`` on ``line`` or the line directly above."""
    for at in (line, line - 1):
        entry = annots.get(at)
        if entry and entry[0] == kind:
            return entry[1]
    return None


def _contains_lock_ctor(node: ast.AST) -> bool:
    for call in ast.walk(node):
        if isinstance(call, ast.Call):
            name = dotted_name(call.func)
            if name and name.split(".")[-1] in _LOCK_CTORS:
                return True
    return False


def _wrap_name_arg(node: ast.AST) -> Optional[str]:
    """The lock name passed to ``syncdebug.maybe_wrap(<ctor>, "name")``
    anywhere inside an assignment value."""
    for call in ast.walk(node):
        if isinstance(call, ast.Call):
            name = dotted_name(call.func)
            if name and name.split(".")[-1] in _WRAP_TAILS:
                if len(call.args) > 1 and isinstance(call.args[1], ast.Constant):
                    v = call.args[1].value
                    if isinstance(v, str):
                        return v
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _base_self_attr(node: ast.AST) -> Optional[str]:
    """The ``X`` in a ``self.X[...]...`` chain — the attribute a
    subscript store or mutator call ultimately mutates."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        attr = _self_attr(node)
        if attr is not None:
            return attr
        node = node.value
    return None


class _SpawnSite:
    def __init__(self, call: ast.Call, kind: str, target: Optional[ast.AST],
                 owner_class: Optional[str], bound: Optional[str],
                 nested_in: Optional[str]):
        self.call = call
        self.kind = kind  # "Thread" | "Timer"
        self.target = target
        self.owner_class = owner_class
        self.bound = bound  # dotted name the spawn was assigned to
        self.nested_in = nested_in  # enclosing function name
        self.daemon = any(
            kw.arg == "daemon"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
            for kw in call.keywords
        )


class _ClassModel:
    def __init__(self, name: str, node: ast.ClassDef):
        self.name = name
        self.node = node
        self.lock_attrs: Dict[str, str] = {}  # attr -> lock name
        self.methods: Dict[str, ast.AST] = {}
        self.guards: Dict[str, str] = {}  # attr -> guarding lock name
        self.safe: Dict[str, str] = {}  # attr -> thread-safe reason
        self.decl_lines: Dict[str, int] = {}  # attr -> first assign line
        # attr -> [(method, node)] writes/mutations outside __init__
        self.mut_writes: Dict[str, List[Tuple[str, ast.AST]]] = {}
        # (attr, node, held names, method, nested) — every access
        self.accesses: List[Tuple[str, ast.AST, Tuple[str, ...], str, bool]] = []
        # self.M(...) call sites: (method called, held, caller, node, nested)
        self.self_calls: List[Tuple[str, Tuple[str, ...], str, ast.AST, bool]] = []
        self.thread_target_methods: Set[str] = set()
        self.holds: Dict[str, str] = {}  # method -> lock it runs under
        self.shared_annotated = False

    @property
    def concurrent(self) -> bool:
        return bool(
            self.lock_attrs or self.thread_target_methods
            or self.shared_annotated
        )


class _ModuleModel:
    """Everything the HS rules need from one parsed module."""

    def __init__(self, module: ParsedModule):
        self.module = module
        self.modstem = os.path.splitext(os.path.basename(module.path))[0]
        self.annots = _parse_annotations(module.lines)
        self.classes: Dict[str, _ClassModel] = {}
        self.module_locks: Dict[str, str] = {}  # global name -> lock name
        self.global_decl_lines: Dict[str, int] = {}
        self.global_guards: Dict[str, str] = {}
        self.global_safe: Dict[str, str] = {}
        # global -> [(func, node)] function-scope writes/mutations
        self.global_writes: Dict[str, List[Tuple[str, ast.AST]]] = {}
        # (name, node, held, func, nested)
        self.global_accesses: List[
            Tuple[str, ast.AST, Tuple[str, ...], str, bool]] = []
        self.spawns: List[_SpawnSite] = []
        # names that actually resolve to threading.Thread/threading.Timer
        # in this module: bare imports (from threading import Thread) and
        # module aliases (import threading [as th]). Keeps locally-defined
        # Thread/Timer classes (e.g. the utils.time_utils stopwatch) from
        # being mistaken for spawns.
        self.threading_names: Set[str] = set()
        self.threading_mods: Set[str] = {"threading"}
        self.functions: Dict[str, ast.AST] = {}  # module + nested defs
        self.daemon_assigns: Set[str] = set()  # dotted names with .daemon = True
        self.joined: Set[str] = set()  # dotted names with .join(...) calls
        self.cancelled: Set[str] = set()  # dotted names with .cancel(...) calls
        self.acquires: List[Tuple[str, str, ast.AST, str]] = []
        # ^ (lock name, dotted base, node, enclosing function)
        self.released_in_finally: Dict[str, Set[str]] = {}  # func -> bases
        # HS003 candidates: (node, tail, reason, held names)
        self.blocking: List[Tuple[ast.AST, str, str, Tuple[str, ...]]] = []
        # HS006: lock-order edges (held -> acquired, node)
        self.edges: List[Tuple[str, str, ast.AST]] = []
        # locks each function/method acquires directly: qual -> set
        self.fn_acquires: Dict[str, Set[str]] = {}
        # calls made while holding: (held names, callee qual or attr tail,
        #   resolved locally?, node, func)
        self.held_calls: List[
            Tuple[Tuple[str, ...], str, bool, ast.AST, str]] = []
        self._build()

    # -- construction ------------------------------------------------------

    def _build(self) -> None:
        tree = self.module.tree
        for stmt in tree.body:
            if isinstance(stmt, ast.ClassDef):
                self._build_class(stmt)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[stmt.name] = stmt
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                self._module_assign(stmt)
            elif isinstance(stmt, ast.Import):
                for a in stmt.names:
                    if a.name == "threading":
                        self.threading_mods.add(a.asname or "threading")
            elif isinstance(stmt, ast.ImportFrom):
                if stmt.module == "threading":
                    for a in stmt.names:
                        if a.name in ("Thread", "Timer"):
                            self.threading_names.add(a.asname or a.name)
        # second pass: scan executable code (module functions + methods)
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(stmt, owner=None)
            elif isinstance(stmt, ast.ClassDef):
                cm = self.classes.get(stmt.name)
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._scan_function(sub, owner=cm)

    def _module_assign(self, stmt) -> None:
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        )
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if not names or stmt.value is None:
            return
        for name in names:
            self.global_decl_lines.setdefault(name, stmt.lineno)
            if _contains_lock_ctor(stmt.value):
                lock_name = (
                    _annot_at(self.annots, stmt.lineno, "lock")
                    or _wrap_name_arg(stmt.value)
                    or f"{self.modstem}.{name}"
                )
                self.module_locks[name] = lock_name
            else:
                guard = _annot_at(self.annots, stmt.lineno, "guarded-by")
                safe = _annot_at(self.annots, stmt.lineno, "thread-safe")
                if guard:
                    self.global_guards[name] = guard
                if safe is not None:
                    self.global_safe[name] = safe

    def _build_class(self, node: ast.ClassDef) -> None:
        cm = _ClassModel(node.name, node)
        if _annot_at(self.annots, node.lineno, "shared") is not None:
            cm.shared_annotated = True
        for sub in node.body:
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cm.methods[sub.name] = sub
                holds = _annot_at(self.annots, sub.lineno, "holds")
                if holds:
                    cm.holds[sub.name] = holds
                if _annot_at(self.annots, sub.lineno, "thread-root") is not None:
                    pass  # recorded for HS005 via spawn resolution
                # attribute declarations (incl. lock creation)
                for inner in ast.walk(sub):
                    if isinstance(inner, (ast.Assign, ast.AnnAssign)):
                        tgts = (
                            inner.targets if isinstance(inner, ast.Assign)
                            else [inner.target]
                        )
                        for t in tgts:
                            attr = _self_attr(t)
                            if attr is None or inner.value is None:
                                continue
                            cm.decl_lines.setdefault(attr, inner.lineno)
                            if _contains_lock_ctor(inner.value):
                                lock_name = (
                                    _annot_at(self.annots, inner.lineno, "lock")
                                    or _wrap_name_arg(inner.value)
                                    or f"{self.modstem}.{cm.name}.{attr}"
                                )
                                cm.lock_attrs.setdefault(attr, lock_name)
                                continue
                            guard = _annot_at(
                                self.annots, inner.lineno, "guarded-by")
                            safe = _annot_at(
                                self.annots, inner.lineno, "thread-safe")
                            if guard:
                                cm.guards.setdefault(attr, guard)
                            if safe is not None:
                                cm.safe.setdefault(attr, safe)
        self.classes[node.name] = cm

    # -- lock/expression resolution ----------------------------------------

    def _resolve_lock(self, expr: ast.AST,
                      owner: Optional[_ClassModel]) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and owner is not None:
            return owner.lock_attrs.get(attr)
        if isinstance(expr, ast.Name):
            return self.module_locks.get(expr.id)
        return None

    # -- executable-code scan ----------------------------------------------

    def _scan_function(self, fn, owner: Optional[_ClassModel]) -> None:
        qual = f"{owner.name}.{fn.name}" if owner else fn.name
        held0: Tuple[Tuple[str, str], ...] = ()
        if owner:
            holds = owner.holds.get(fn.name)
        else:
            # module-level functions may declare holds= too (call-site
            # verification only happens for same-class methods)
            holds = _annot_at(self.annots, fn.lineno, "holds")
        if holds:
            held0 = ((holds, "<holds>"),)
        for stmt in fn.body:
            self._walk(stmt, held0, owner, fn.name, qual, nested=False)

    def _walk(self, node, held, owner, method, qual, nested) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.functions.setdefault(node.name, node)
            # a nested def body runs later, in an unknown lock context
            for stmt in node.body:
                self._walk(stmt, (), owner, method, qual, nested=True)
            return
        if isinstance(node, ast.Lambda):
            self._walk(node.body, (), owner, method, qual, nested=True)
            return
        if isinstance(node, ast.With):
            new_held = held
            for item in node.items:
                self._walk(item.context_expr, held, owner, method, qual, nested)
                lock = self._resolve_lock(item.context_expr, owner)
                if lock is not None:
                    expr_s = dotted_name(item.context_expr) or "<expr>"
                    if not nested:
                        for h, _ in new_held:
                            if h != lock:
                                self.edges.append((h, lock, node))
                        self.fn_acquires.setdefault(qual, set()).add(lock)
                    new_held = new_held + ((lock, expr_s),)
            for stmt in node.body:
                self._walk(stmt, new_held, owner, method, qual, nested)
            return

        if isinstance(node, ast.Global):
            for name in node.names:
                self.global_writes.setdefault(name, []).append((qual, node))
        if isinstance(node, ast.Call):
            self._handle_call(node, held, owner, method, qual, nested)
        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is not None and owner is not None:
                held_names = tuple(h for h, _ in held)
                owner.accesses.append((attr, node, held_names, method, nested))
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    owner.mut_writes.setdefault(attr, []).append((method, node))
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            attr = _base_self_attr(node)
            if attr is not None and owner is not None:
                owner.mut_writes.setdefault(attr, []).append((method, node))
        if isinstance(node, ast.Name):
            # module-global access from function scope (not locally bound)
            if (
                node.id in self.global_decl_lines
                and node.id not in self.module_locks
            ):
                held_names = tuple(h for h, _ in held)
                self.global_accesses.append(
                    (node.id, node, held_names, qual, nested)
                )
        if isinstance(node, ast.Assign):
            self._handle_assign(node, owner, method, qual, nested)

        for child in ast.iter_child_nodes(node):
            self._walk(child, held, owner, method, qual, nested)

    def _spawn_kind(self, func_name: Optional[str]) -> Optional[str]:
        """``"Thread"``/``"Timer"`` when ``func_name`` resolves to the
        threading ctor in this module's import table, else None."""
        if not func_name:
            return None
        parts = func_name.split(".")
        tail = parts[-1]
        if tail not in ("Thread", "Timer"):
            return None
        if len(parts) == 1:
            return tail if func_name in self.threading_names else None
        return tail if ".".join(parts[:-1]) in self.threading_mods else None

    def _handle_assign(self, node: ast.Assign, owner, method, qual,
                       nested) -> None:
        # spawn bound to a variable/attribute (for the HS004 join check)
        if isinstance(node.value, ast.Call):
            if self._spawn_kind(dotted_name(node.value.func)):
                for t in node.targets:
                    bound = dotted_name(t)
                    if bound:
                        self._last_spawn_binding = bound
        # X.daemon = True
        for t in node.targets:
            if (
                isinstance(t, ast.Attribute)
                and t.attr == "daemon"
                and isinstance(node.value, ast.Constant)
                and node.value.value is True
            ):
                base = dotted_name(t.value)
                if base:
                    self.daemon_assigns.add(base)

    def _handle_call(self, node: ast.Call, held, owner, method, qual,
                     nested) -> None:
        func_name = dotted_name(node.func)
        tail = func_name.split(".")[-1] if func_name else None
        held_names = tuple(h for h, _ in held)

        # thread/timer spawns
        spawn_kind = self._spawn_kind(func_name)
        if spawn_kind:
            tail = spawn_kind
            target = None
            if tail == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
            else:
                if len(node.args) > 1:
                    target = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "function":
                        target = kw.value
            bound = None
            # bound via enclosing Assign (recorded just before in _walk)
            bound = getattr(self, "_last_spawn_binding", None)
            self._last_spawn_binding = None
            self.spawns.append(_SpawnSite(
                node, tail, target,
                owner.name if owner else None, bound, qual,
            ))
            if target is not None:
                t_attr = _self_attr(target)
                if t_attr is not None and owner is not None:
                    owner.thread_target_methods.add(t_attr)

        # mutator calls on self attributes / globals
        if isinstance(node.func, ast.Attribute) and node.func.attr in _MUTATORS:
            base = node.func.value
            attr = _base_self_attr(base)
            if attr is not None and owner is not None:
                owner.mut_writes.setdefault(attr, []).append((method, node))
            elif isinstance(base, ast.Name) and base.id in self.global_decl_lines:
                self.global_writes.setdefault(base.id, []).append((qual, node))

        # join/cancel bookkeeping for HS004
        if isinstance(node.func, ast.Attribute):
            base_name = dotted_name(node.func.value)
            if node.func.attr == "join" and base_name:
                self.joined.add(base_name)
            if node.func.attr == "cancel" and base_name:
                self.cancelled.add(base_name)

        # bare acquire/release for HS002
        if isinstance(node.func, ast.Attribute) and node.func.attr in (
            "acquire", "release"
        ):
            lock = self._resolve_lock(node.func.value, owner)
            if lock is not None and node.func.attr == "acquire":
                base = dotted_name(node.func.value) or "<expr>"
                self.acquires.append((lock, base, node, qual))
                if not nested:
                    for h in held_names:
                        if h != lock:
                            self.edges.append((h, lock, node))
                    self.fn_acquires.setdefault(qual, set()).add(lock)

        # same-class calls (holds= verification + HS006 local edges)
        if owner is not None:
            m_attr = _self_attr(node.func)
            if m_attr is not None and m_attr in owner.methods:
                owner.self_calls.append(
                    (m_attr, held_names, method, node, nested))
        if held_names and not nested and tail:
            local = False
            if owner is not None and _self_attr(node.func) in owner.methods:
                local = True
                callee = f"{owner.name}.{_self_attr(node.func)}"
            elif isinstance(node.func, ast.Name) and tail in self.functions:
                local = True
                callee = tail
            else:
                callee = tail
            self.held_calls.append((held_names, callee, local, node, qual))

        # blocking-call candidates for HS003 (only matter when held)
        if held_names and not nested:
            reason = self._blocking_reason(node, tail, held)
            if reason is not None:
                self.blocking.append((node, tail or "<call>", reason,
                                      held_names))

    def _blocking_reason(self, node: ast.Call, tail: Optional[str],
                         held) -> Optional[str]:
        if tail in _BLOCKING_TAILS:
            return _BLOCKING_TAILS[tail]
        if not isinstance(node.func, ast.Attribute):
            return None
        base = node.func.value
        base_name = dotted_name(base) or ""
        if tail == "get":
            # queue.get() blocks; dict.get(key[, default]) never has
            # zero positional args — the zero-arg form is unambiguous
            kwargs = {kw.arg for kw in node.keywords}
            if not node.args and kwargs <= {"timeout", "block"}:
                return "queue get"
        if tail in ("wait", "wait_for"):
            # Condition.wait on the ONLY held lock releases it — legal;
            # any other wait blocks while something else stays held
            if len(held) == 1 and held[0][1] == base_name:
                return None
            return "wait while a lock is held"
        if tail == "cancel":
            parts = base_name.split(".")
            if any(p in ("future", "fut") for p in parts):
                return "future resolution runs done-callbacks synchronously"
        if tail == "join":
            if isinstance(base, ast.Constant):
                return None  # "sep".join(...)
            parts = base_name.split(".")
            if parts and parts[-1] == "path":
                return None  # os.path.join
            if len(node.args) >= 2:
                return None
            if len(node.args) == 1 and not isinstance(
                node.args[0], (ast.Constant, ast.Name, ast.Attribute)
            ):
                return None  # sep.join(genexpr)
            if any(
                p in ("thread", "worker", "monitor", "_thread", "_worker",
                      "_monitor", "t", "timer", "_timer", "proc")
                for p in parts
            ):
                return "thread join"
            return None
        return None


class _Analyzer:
    """Shared per-run cache: one :class:`_ModuleModel` per file, built
    lazily the first time any HS rule checks that module."""

    def __init__(self) -> None:
        self._models: Dict[str, _ModuleModel] = {}

    def model(self, module: ParsedModule) -> _ModuleModel:
        mm = self._models.get(module.path)
        if mm is None or mm.module is not module:
            mm = _ModuleModel(module)
            self._models[module.path] = mm
        return mm


_HS_EXCLUDE = ("tests/", "examples/", "lint/fixtures")


class _HSRule(Rule):
    severity = "error"
    exclude = _HS_EXCLUDE

    def __init__(self, analyzer: _Analyzer):
        self.analyzer = analyzer


class UnguardedSharedState(_HSRule):
    id = "HS001"
    name = "unguarded-shared-state"
    description = (
        "mutable state of a concurrent class (or a module global written "
        "from functions) must declare '# graftsync: guarded-by=<lock>' or "
        "'thread-safe=<reason>', and guarded accesses must hold the lock"
    )

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        mm = self.analyzer.model(module)
        for cm in mm.classes.values():
            if not cm.concurrent:
                continue
            yield from self._check_class(module, mm, cm)
        yield from self._check_globals(module, mm)

    def _check_class(self, module, mm, cm) -> Iterator[Finding]:
        flagged: Set[str] = set()
        for attr, writes in sorted(cm.mut_writes.items()):
            out_of_init = [
                (m, n) for m, n in writes if m not in ("__init__",)
            ]
            if not out_of_init or attr in cm.lock_attrs:
                continue
            if attr in cm.guards or attr in cm.safe:
                continue
            method, node = out_of_init[0]
            flagged.add(attr)
            yield self.finding(
                module, node,
                f"attribute '{attr}' of concurrent class '{cm.name}' is "
                f"mutated in '{method}' without a '# graftsync: "
                "guarded-by=<lock>' or 'thread-safe=<reason>' declaration "
                "on its assignment",
            )
        for attr, node, held, method, nested in cm.accesses:
            guard = cm.guards.get(attr)
            if guard is None or method == "__init__" or nested:
                continue
            if guard in held:
                continue
            yield self.finding(
                module, node,
                f"access to '{attr}' (declared guarded-by={guard}) in "
                f"'{cm.name}.{method}' without holding {guard} — wrap in "
                f"'with' or annotate the method '# graftsync: holds={guard}'",
            )
        # holds= methods must actually be called with the lock held
        for callee, held, caller, node, nested in cm.self_calls:
            need = cm.holds.get(callee)
            if need is None or nested:
                continue
            if need in held:
                continue
            yield self.finding(
                module, node,
                f"'{cm.name}.{caller}' calls '{callee}' (declared "
                f"holds={need}) without holding {need}",
            )
        # thread-safe declarations must carry a reason
        for attr, reason in cm.safe.items():
            if not reason and attr not in flagged:
                line = cm.decl_lines.get(attr, cm.node.lineno)
                yield Finding(
                    rule=self.id, path=module.path, line=line, col=1,
                    message=(
                        f"'# graftsync: thread-safe=' on '{cm.name}.{attr}' "
                        "needs a reason (why is unguarded access safe?)"
                    ),
                    severity=self.severity,
                    snippet=module.snippet(line),
                )

    def _check_globals(self, module, mm) -> Iterator[Finding]:
        for name, writes in sorted(mm.global_writes.items()):
            if name in mm.module_locks:
                continue
            if name in mm.global_guards or name in mm.global_safe:
                continue
            if name not in mm.global_decl_lines:
                continue
            _, node = writes[0]
            yield self.finding(
                module, node,
                f"module global '{name}' is written from function scope "
                "without a '# graftsync: guarded-by=<lock>' or "
                "'thread-safe=<reason>' declaration on its module-level "
                "assignment",
            )
        for name, node, held, func, nested in mm.global_accesses:
            guard = mm.global_guards.get(name)
            if guard is None or nested:
                continue
            if name not in mm.global_writes:
                # never written from functions: reads are of a constant
                continue
            if guard in held:
                continue
            yield self.finding(
                module, node,
                f"access to module global '{name}' (declared "
                f"guarded-by={guard}) in '{func}' without holding {guard}",
            )


class AcquireWithoutRelease(_HSRule):
    id = "HS002"
    name = "lock-acquire-without-release-path"
    description = (
        "a bare lock.acquire() must have a matching release() in a "
        "finally block of the same function (prefer 'with lock:')"
    )

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        mm = self.analyzer.model(module)
        if not mm.acquires:
            return
        # collect bases released inside finally blocks, per function
        released: Dict[str, Set[str]] = {}
        for scope_name, fn in self._all_functions(mm):
            for node in ast.walk(fn):
                if isinstance(node, ast.Try) and node.finalbody:
                    for inner in node.finalbody:
                        for call in ast.walk(inner):
                            if (
                                isinstance(call, ast.Call)
                                and isinstance(call.func, ast.Attribute)
                                and call.func.attr == "release"
                            ):
                                base = dotted_name(call.func.value)
                                if base:
                                    released.setdefault(
                                        scope_name, set()).add(base)
        for lock, base, node, qual in mm.acquires:
            if base in released.get(qual, set()):
                continue
            yield self.finding(
                module, node,
                f"bare acquire of {lock} without a release() in a finally "
                "block on every exit path — use 'with' or try/finally",
            )

    @staticmethod
    def _all_functions(mm):
        for name, fn in mm.functions.items():
            yield name, fn
        for cm in mm.classes.values():
            for name, fn in cm.methods.items():
                yield f"{cm.name}.{name}", fn


class BlockingCallUnderLock(_HSRule):
    id = "HS003"
    name = "blocking-call-under-lock"
    description = (
        "device syncs, queue gets, sleeps, thread joins, profiler "
        "captures and future resolution must not run while holding a lock"
    )

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        mm = self.analyzer.model(module)
        for node, tail, reason, held in mm.blocking:
            yield self.finding(
                module, node,
                f"blocking call '{tail}' ({reason}) while holding "
                f"{', '.join(held)}",
            )


class SpawnPolicy(_HSRule):
    id = "HS004"
    name = "thread-spawn-without-join-or-daemon"
    description = (
        "every Thread/Timer spawn must be daemon=True, be joined, or "
        "(Timer) be cancelled somewhere — otherwise shutdown leaks it"
    )

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        mm = self.analyzer.model(module)
        for sp in mm.spawns:
            if sp.daemon:
                continue
            if sp.bound and (
                sp.bound in mm.daemon_assigns
                or sp.bound in mm.joined
                or (sp.kind == "Timer" and sp.bound in mm.cancelled)
            ):
                continue
            yield self.finding(
                module, sp.call,
                f"{sp.kind} spawned without daemon=True and without a "
                f"join(){' or cancel()' if sp.kind == 'Timer' else ''} "
                "in this module — declare the shutdown policy",
            )



class UndeclaredThreadRoot(_HSRule):
    id = "HS005"
    name = "undeclared-thread-root"
    description = (
        "every resolvable Thread/Timer target must carry a "
        "'# graftsync: thread-root' annotation on its def"
    )

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        mm = self.analyzer.model(module)
        for sp in mm.spawns:
            if sp.target is None:
                continue
            if isinstance(sp.target, ast.Lambda):
                yield self.finding(
                    module, sp.target,
                    "lambda thread target cannot be annotated — name the "
                    "function and mark it '# graftsync: thread-root'",
                )
                continue
            fn = self._resolve_target(mm, sp)
            if fn is None:
                continue  # dynamic target: stay quiet rather than guess
            if _annot_at(mm.annots, fn.lineno, "thread-root") is None:
                yield self.finding(
                    module, sp.call,
                    f"thread target '{self._target_label(sp)}' lacks a "
                    "'# graftsync: thread-root' annotation on its def "
                    f"(line {fn.lineno})",
                )

    @staticmethod
    def _target_label(sp: _SpawnSite) -> str:
        return dotted_name(sp.target) or "<target>"

    @staticmethod
    def _resolve_target(mm: _ModuleModel, sp: _SpawnSite):
        attr = _self_attr(sp.target)
        if attr is not None and sp.owner_class:
            cm = mm.classes.get(sp.owner_class)
            if cm:
                return cm.methods.get(attr)
            return None
        if isinstance(sp.target, ast.Name):
            return mm.functions.get(sp.target.id)
        return None


class PotentialDeadlock(_HSRule):
    id = "HS006"
    name = "potential-deadlock"
    description = (
        "the static lock-order graph (every nested acquire site, plus "
        "calls made under a lock into methods that acquire) must be a DAG"
    )

    def __init__(self, analyzer: _Analyzer):
        super().__init__(analyzer)
        # edge (a, b) -> (path, line, snippet) of one witness site
        self._edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        # lock-acquiring callables across the whole scan, by bare name:
        # name -> set of lock names (ambiguity tracked by set size > ...)
        self._method_locks: Dict[str, Set[str]] = {}
        self._deferred: List[
            Tuple[Tuple[str, ...], str, str, int, str]] = []

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        mm = self.analyzer.model(module)
        for a, b, node in mm.edges:
            self._note_edge(a, b, module, node)
        for qual, locks in mm.fn_acquires.items():
            tail = qual.split(".")[-1]
            self._method_locks.setdefault(tail, set()).update(locks)
        for held, callee, local, node, _ in mm.held_calls:
            if local:
                locks = mm.fn_acquires.get(callee, set())
                for h in held:
                    for lock in locks:
                        if lock != h:
                            self._note_edge(h, lock, module, node)
            else:
                line = getattr(node, "lineno", 1)
                self._deferred.append(
                    (held, callee, module.path, line, module.snippet(line))
                )
        return iter(())

    def _note_edge(self, a: str, b: str, module: ParsedModule,
                   node: ast.AST) -> None:
        line = getattr(node, "lineno", 1)
        self._edges.setdefault(
            (a, b), (module.path, line, module.snippet(line)))

    def finalize(self) -> Iterator[Finding]:
        # resolve deferred cross-module calls: only when the callee name
        # unambiguously maps to exactly one lock-acquiring method
        for held, callee, path, line, snippet in self._deferred:
            locks = self._method_locks.get(callee)
            if not locks or len(locks) != 1:
                continue
            (lock,) = tuple(locks)
            for h in held:
                if h != lock:
                    self._edges.setdefault((h, lock), (path, line, snippet))
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self._edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        seen_cycles: Set[frozenset] = set()
        color: Dict[str, int] = {}
        stack: List[str] = []

        def dfs(u: str) -> Iterator[List[str]]:
            color[u] = 1
            stack.append(u)
            for v in sorted(adj.get(u, ())):
                if color.get(v, 0) == 0:
                    yield from dfs(v)
                elif color.get(v) == 1:
                    cycle = stack[stack.index(v):] + [v]
                    yield cycle
            stack.pop()
            color[u] = 2

        findings: List[Finding] = []
        for node in sorted(adj):
            if color.get(node, 0) == 0:
                for cycle in dfs(node):
                    key = frozenset(cycle)
                    if key in seen_cycles:
                        continue
                    seen_cycles.add(key)
                    a, b = cycle[0], cycle[1]
                    path, line, snippet = self._edges[(a, b)]
                    findings.append(Finding(
                        rule=self.id, path=path, line=line, col=1,
                        message=(
                            "lock-order cycle (potential deadlock): "
                            + " -> ".join(cycle)
                        ),
                        severity=self.severity,
                        snippet=snippet,
                    ))
        return iter(findings)

    def graph(self) -> Dict[str, List]:
        """The accumulated static lock-order graph (call after a scan)."""
        locks: Set[str] = set()
        edges = []
        for held, callee, path, line, snippet in self._deferred:
            locks_c = self._method_locks.get(callee)
            if locks_c and len(locks_c) == 1:
                (lock,) = tuple(locks_c)
                for h in held:
                    if h != lock:
                        self._edges.setdefault((h, lock),
                                               (path, line, snippet))
        for (a, b), (path, line, _) in sorted(self._edges.items()):
            locks.update((a, b))
            edges.append({"from": a, "to": b, "site": f"{path}:{line}"})
        return {"locks": sorted(locks), "edges": edges}


def concurrency_rules(repo_root: str) -> List[Rule]:
    """A fresh HS001–HS006 rule set sharing one analysis cache —
    build a new set per scan (HS006 accumulates cross-file state)."""
    analyzer = _Analyzer()
    return [
        UnguardedSharedState(analyzer),
        AcquireWithoutRelease(analyzer),
        BlockingCallUnderLock(analyzer),
        SpawnPolicy(analyzer),
        UndeclaredThreadRoot(analyzer),
        PotentialDeadlock(analyzer),
    ]


def build_lock_order(
    repo_root: str, paths: Optional[Sequence[str]] = None
) -> Dict[str, List]:
    """Scan the tree (or ``paths``) and return the static lock-order
    graph ``{"locks": [...], "edges": [{"from", "to", "site"}, ...]}``.
    This is what ``tools/graftsync.py --order-graph`` dumps and what the
    runtime witness (``utils/syncdebug.py``) seeds its assertion with."""
    from .core import run_lint

    rules = concurrency_rules(repo_root)
    hs006 = next(r for r in rules if r.id == "HS006")
    run_lint(repo_root, [hs006], paths=paths, baseline=None, full_tree=True)
    return hs006.graph()
