"""LSMS data-prep side tools (reference: utils/lsms/).

Two host-side utilities for binary-alloy LSMS datasets:

- ``convert_raw_data_energy_to_gibbs`` — rewrite each raw file's header
  total energy as the formation Gibbs energy: enthalpy relative to the
  linear mix of the two pure-element energies, minus T times the ideal
  configurational-entropy term (reference:
  utils/lsms/convert_total_energy_to_formation_gibbs.py:30-186).
- ``compositional_histogram_cutoff`` — downselect to at most N samples per
  composition bin (reference: utils/lsms/compositional_histogram_cutoff.py:16-76).

The binomial term uses ``math.lgamma`` instead of ``log(comb(n, k))`` so it
stays finite for arbitrarily large supercells.
"""

from __future__ import annotations

import math
import os
import shutil
from typing import Dict, List, Sequence, Tuple

import numpy as np

# LSMS energies are in Rydberg; k_B converted accordingly (same constants
# as the reference, convert_total_energy_to_formation_gibbs.py:175-177).
_KB_JOULE_PER_KELVIN = 1.380649e-23
_JOULE_PER_RYDBERG_INV = 4.5874208973812e17
KB_RYDBERG_PER_KELVIN = _KB_JOULE_PER_KELVIN * _JOULE_PER_RYDBERG_INV


def _read_lsms(path: str) -> Tuple[str, List[str], np.ndarray]:
    """(total_energy_token, raw_lines, atoms[n, cols]); one header line,
    atom rows after (col 0 = atomic number)."""
    with open(path, "r") as f:
        lines = f.readlines()
    energy_token = lines[0].split()[0]
    atoms = np.loadtxt(lines[1:], ndmin=2)
    return energy_token, lines, atoms


def _log_comb(n: int, k: int) -> float:
    return math.lgamma(n + 1) - math.lgamma(k + 1) - math.lgamma(n - k + 1)


def compute_formation_enthalpy(
    elements_list: Sequence[float],
    pure_elements_energy: Dict[float, float],
    total_energy: float,
    atoms: np.ndarray,
) -> Tuple[float, float, float, float, float]:
    """(composition_of_element0, total_energy, linear_mixing_energy,
    formation_enthalpy, entropy) for one binary-alloy configuration."""
    elements_list = sorted(elements_list)
    if len(elements_list) != 2:
        raise ValueError("binary alloys only")
    elements, counts = np.unique(atoms[:, 0], return_counts=True)
    for e in elements:
        if e not in elements_list:
            raise ValueError(f"element {e} not in binary {elements_list}")
    count_map = dict(zip(elements.tolist(), counts.tolist()))
    counts_full = [count_map.get(e, 0) for e in elements_list]

    num_atoms = int(atoms.shape[0])
    composition = counts_full[0] / num_atoms
    linear_mixing_energy = (
        pure_elements_energy[elements_list[0]] * composition
        + pure_elements_energy[elements_list[1]] * (1.0 - composition)
    ) * num_atoms
    formation_enthalpy = total_energy - linear_mixing_energy
    # thermodynamic (not statistical) entropy of the ideal mixture
    entropy = KB_RYDBERG_PER_KELVIN * _log_comb(num_atoms, counts_full[0])
    return composition, total_energy, linear_mixing_energy, formation_enthalpy, entropy


def convert_raw_data_energy_to_gibbs(
    dir: str,
    elements_list: Sequence[float],
    temperature_kelvin: float = 0.0,
    overwrite_data: bool = False,
    create_plots: bool = True,
) -> str:
    """Rewrite every LSMS file under ``dir`` into ``<dir>_gibbs_energy/``
    with the header total energy replaced by the formation Gibbs energy.
    Returns the output directory path."""
    dir = dir.rstrip("/")
    new_dir = dir + "_gibbs_energy/"
    if os.path.exists(new_dir) and overwrite_data:
        shutil.rmtree(new_dir)
    os.makedirs(new_dir, exist_ok=True)

    elements_list = sorted(elements_list)
    pure_elements_energy: Dict[float, float] = {}
    all_files = sorted(os.listdir(dir))
    for filename in all_files:
        energy_token, _, atoms = _read_lsms(os.path.join(dir, filename))
        pure = np.unique(atoms[:, 0])
        if len(pure) == 1:
            pure_elements_energy[float(pure[0])] = (
                float(energy_token) / atoms.shape[0]
            )
    if len(pure_elements_energy) != 2:
        raise ValueError("Must have two single element files.")

    comps = np.empty(len(all_files))
    totals = np.empty(len(all_files))
    mixing = np.empty(len(all_files))
    enthalpies = np.empty(len(all_files))
    gibbs = np.empty(len(all_files))
    for i, filename in enumerate(all_files):
        path = os.path.join(dir, filename)
        energy_token, lines, atoms = _read_lsms(path)
        comp, total, lin, enth, entropy = compute_formation_enthalpy(
            elements_list, pure_elements_energy, float(energy_token), atoms
        )
        g = enth - temperature_kelvin * entropy
        comps[i], totals[i], mixing[i], enthalpies[i], gibbs[i] = (
            comp, total, lin, enth, g,
        )
        lines[0] = lines[0].replace(energy_token, str(g))
        with open(os.path.join(new_dir, filename), "w") as f:
            f.write("".join(lines))

    print("Min formation enthalpy: ", float(gibbs.min()))
    print("Max formation enthalpy: ", float(gibbs.max()))

    if create_plots:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        for fname, xs, ys, xl, yl in [
            ("linear_mixing_energy.png", totals, mixing,
             "Total energy (Rydberg)", "Linear mixing energy (Rydberg)"),
            ("formation_enthalpy.png", comps, enthalpies,
             "Concentration", "Formation enthalpy (Rydberg)"),
            ("formation_gibbs_energy.png", comps, gibbs,
             "Concentration", "Formation Gibbs energy (Rydberg)"),
        ]:
            fig, ax = plt.subplots()
            ax.scatter(xs, ys, edgecolor="b", facecolor="none")
            ax.set_xlabel(xl)
            ax.set_ylabel(yl)
            fig.savefig(fname)
            plt.close(fig)
    return new_dir


def find_bin(comp: float, nbins: int) -> int:
    bins = np.linspace(0, 1, nbins)
    for bi in range(len(bins) - 1):
        if bins[bi] < comp < bins[bi + 1]:
            return bi
    return nbins - 1


def compositional_histogram_cutoff(
    dir: str,
    elements_list: Sequence[float],
    histogram_cutoff: int,
    num_bins: int,
    overwrite_data: bool = False,
    create_plots: bool = True,
) -> str:
    """Symlink at most ``histogram_cutoff`` samples per composition bin into
    ``<dir>_histogram_cutoff/``. Returns the output directory path."""
    dir = dir.rstrip("/")
    new_dir = dir + "_histogram_cutoff/"
    if os.path.exists(new_dir):
        if overwrite_data:
            shutil.rmtree(new_dir)
        else:
            print("Exiting: path to histogram cutoff data already exists")
            return new_dir
    os.makedirs(new_dir, exist_ok=True)

    elements_list = sorted(elements_list)
    comp_final: List[float] = []
    comp_all = np.zeros(num_bins)
    for filename in sorted(os.listdir(dir)):
        path = os.path.join(dir, filename)
        atoms = np.loadtxt(path, skiprows=1, ndmin=2)
        elements, counts = np.unique(atoms[:, 0], return_counts=True)
        count_map = dict(zip(elements.tolist(), counts.tolist()))
        counts_full = [count_map.get(e, 0) for e in elements_list]
        composition = counts_full[0] / atoms.shape[0]

        b = find_bin(composition, num_bins)
        comp_all[b] += 1
        if comp_all[b] < histogram_cutoff:
            comp_final.append(composition)
            os.symlink(os.path.abspath(path), os.path.join(new_dir, filename))

    if create_plots:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots()
        ax.hist(comp_final, bins=num_bins)
        fig.savefig("composition_histogram_cutoff.png")
        plt.close(fig)
        fig, ax = plt.subplots()
        ax.bar(np.linspace(0, 1, num_bins), comp_all, width=1 / num_bins)
        fig.savefig("composition_initial.png")
        plt.close(fig)
    return new_dir
