"""On-chip TPU kernel selfcheck (VERDICT r02 item 3).

Every Pallas test in the default suite runs interpret-mode on the CPU
mesh; since r02 flipped ``HYDRAGNN_PALLAS=auto`` to kernel-on-TPU, the
path real training takes was validated only by bench-time spot checks.
This module exercises the DEFAULT TPU kernel path on the actual chip:

  1. family kernel vs the fused XLA pass — f32 and bf16 data, boolean
     and float-weight masks, two CSR shapes (multi-chunk included);
  2. sum-only kernel (the VJP hot path) vs ``jax.ops.segment_sum``;
  3. one flagship-shaped PNA train step, Pallas vs XLA dispatch — loss
     must agree to mixed-precision tolerance;
  4. (``--bench``) the bf16-vs-f32 kernel bandwidth A/B that r02 left
     roofline-derived: scan-slope timing (the op chained K times inside
     one ``lax.scan`` dispatch, slope between two K values — cancels
     the tunnel's per-dispatch RTT; docs/PERF.md protocol).

Dispatch budget: the tunneled dev chip throttles after ~100 fast
dispatches (memory: post-burst ~100x slowdown), so the default check
set stays under ~40 dispatches including compiles.

Run via ``ci.sh`` (CI_TPU=1 -> tests/test_tpu_chip.py subprocess; the
in-process pytest session pins a CPU mesh, so the chip work happens
here) or directly: ``python -m hydragnn_tpu.tools.tpu_selfcheck``.
Exit code 0 = all checks passed. Prints one JSON line per check.
"""

from __future__ import annotations

import json
import sys


def _fail(name: str, **kw) -> None:
    print(json.dumps({"check": name, "ok": False, **kw}))


def _ok(name: str, **kw) -> None:
    print(json.dumps({"check": name, "ok": True, **kw}))


def _allclose(a, b, rtol, atol) -> bool:
    import numpy as np

    return bool(
        np.allclose(np.asarray(a, np.float32), np.asarray(b, np.float32), rtol=rtol, atol=atol)
    )


def check_kernels() -> bool:
    """Family + sum kernels vs XLA on-chip, multiple dtypes/masks."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from hydragnn_tpu.ops.segment_pallas import (
        segment_sum_family_pallas,
        segment_sum_family_xla,
        segment_sum_pallas,
    )

    ok = True
    rng = np.random.default_rng(0)
    shapes = [(4096, 128, 1024), (120_000, 128, 5136)]  # (E, H, N); 2nd = bench shape
    for e, h, n in shapes:
        recv = np.sort(rng.integers(0, n, e)).astype(np.int32)
        data32 = rng.normal(size=(e, h)).astype(np.float32)
        bmask = rng.random(e) > 0.2
        wmask = rng.random(e).astype(np.float32)
        for dtype, rtol, atol in ((jnp.float32, 1e-5, 1e-4), (jnp.bfloat16, 1e-2, 1e-2)):
            data = jnp.asarray(data32).astype(dtype)
            for mask, mname in ((None, "none"), (jnp.asarray(bmask), "bool"), (jnp.asarray(wmask), "float")):
                s, sq, c = segment_sum_family_pallas(
                    data, jnp.asarray(recv), n, mask, indices_are_sorted=True
                )
                rs, rsq, rc = segment_sum_family_xla(
                    # XLA reference on the SAME (possibly bf16-rounded) data
                    data, jnp.asarray(recv), n, mask, indices_are_sorted=True
                )
                good = (
                    _allclose(s, rs, rtol, atol)
                    and _allclose(sq, rsq, rtol, max(atol, 1e-2))
                    and _allclose(c, rc, 1e-6, 1e-6)
                )
                name = f"family_E{e}_{dtype.__name__}_mask-{mname}"
                (_ok if good else _fail)(name)
                ok &= good
        # sum-only kernel: one representative config per shape
        out = segment_sum_pallas(
            jnp.asarray(data32), jnp.asarray(recv), n,
            jnp.asarray(bmask), indices_are_sorted=True,
        )
        ref = jax.ops.segment_sum(
            jnp.asarray(data32 * bmask[:, None]), jnp.asarray(recv), n,
            indices_are_sorted=True,
        )
        good = _allclose(out, ref, 1e-5, 1e-4)
        (_ok if good else _fail)(f"sum_E{e}_f32_mask-bool")
        ok &= good
    # CSR-broadcast row gather (r03: the backward's widening gathers):
    # must be bit-exact vs indexing on-chip — dense, jumpy (low-degree,
    # multi-window chunks), f32 and bf16
    from hydragnn_tpu.ops.segment_pallas import _bcast_kernel_call

    for e, n, h, tag in ((120_000, 5136, 128, "dense"), (8192, 60_000, 128, "jumpy")):
        ids = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
        table32 = jnp.asarray(rng.normal(size=(n, h)).astype(np.float32))
        for dtype in (jnp.float32, jnp.bfloat16):
            table = table32.astype(dtype)
            out = _bcast_kernel_call(table, ids, interpret=False)
            good = bool(np.array_equal(np.asarray(out), np.asarray(table[ids])))
            (_ok if good else _fail)(f"bcast_{tag}_{dtype.__name__}")
            ok &= good
    # TINY-MAGNITUDE table rows (r03 advisor): the extremum backward's
    # tie detection (data == gather(out)) needs the f32 3x-bf16-split
    # gather to be bit-exact. Probed on v5e (r04): exactness holds down
    # to |x| ~ 1e-35 — below that the split's residual terms fall under
    # bf16's subnormal floor (9.2e-41 x 2^16) and degrade to hi-term
    # (8-bit) accuracy; under bf16's subnormal min the value flushes
    # CLEANLY to 0. Segments whose extremum sits below 1e-35 therefore
    # drop their extremum gradient — numerically-zero segments, a
    # documented non-issue for training. The gate asserts the VERIFIED
    # contract so a regression of either half (exactness in range,
    # clean flush below) is caught at startup.
    # Measured decay curve (v5e probe, r04): bit-exact >= ~1e-30 (all
    # three split terms stay bf16-NORMAL); the lo term flushes first
    # (rel error ~2^-16 by 1e-33), then the mid term (~2^-8 by 3e-36);
    # below bf16's min normal (1.18e-38) even the hi term is a flushed
    # subnormal and the value reads back exactly 0. Each band is
    # asserted with margin so EITHER a range shrink or garbage (vs
    # clean flush) fails the gate.
    sub = np.zeros((256, 128), dtype=np.float32)
    for j, mag in enumerate((1e-30, 1e-34, 1e-36, 1e-39)):
        sub[j::4] = np.float32(mag) * (
            1 + rng.random((64, 128)).astype(np.float32)
        )
    ids = jnp.asarray(np.sort(rng.integers(0, 256, 2048)).astype(np.int32))
    table = jnp.asarray(sub)
    out = np.asarray(_bcast_kernel_call(table, ids, interpret=False))
    ref = np.asarray(table)[np.asarray(ids)]
    a = np.abs(ref)
    exact_b = a >= 1e-30
    lo_b = (a >= 1e-35) & ~exact_b  # lo-term flushed: <= 2^-9 rel
    mid_b = (a >= 3e-38) & (a < 1e-35)  # mid-term flushed too: <= 2^-6 rel
    flush_b = a < 1.1e-38
    good = bool(
        np.array_equal(out[exact_b], ref[exact_b])
        and np.all(np.abs(out[lo_b] - ref[lo_b]) <= 2.0 ** -9 * a[lo_b])
        and np.all(np.abs(out[mid_b] - ref[mid_b]) <= 2.0 ** -6 * a[mid_b])
        and np.all((out[flush_b] == 0) | (out[flush_b] == ref[flush_b]))
    )
    (_ok if good else _fail)("bcast_tiny_magnitude_f32")
    ok &= good
    # Same decay-band contract for the f32 SUM kernel's 3-term bf16
    # split (r04 advisor: only the gather was gated). All elements of a
    # segment share sign and magnitude band here, so the segment sum's
    # relative error is bounded by the per-element band.
    seg_ids = jnp.asarray(np.sort(rng.integers(0, 256, 2048)).astype(np.int32))
    vals = np.zeros((2048, 128), dtype=np.float32)
    band_of = np.asarray(seg_ids) % 4
    mags = (1e-28, 1e-34, 1e-36, 1e-39)
    for j, mag in enumerate(mags):
        sel = band_of == j
        vals[sel] = np.float32(mag) * (
            1 + rng.random((int(sel.sum()), 128)).astype(np.float32)
        )
    ssum_tiny = np.asarray(
        segment_sum_pallas(
            jnp.asarray(vals), seg_ids, 256, None, indices_are_sorted=True
        )
    )
    sref_tiny = np.asarray(
        jax.ops.segment_sum(jnp.asarray(vals), seg_ids, 256, indices_are_sorted=True)
    )
    seg_band = np.arange(256) % 4
    amag = np.abs(sref_tiny)
    err = np.abs(ssum_tiny - sref_tiny)
    with np.errstate(invalid="ignore", divide="ignore"):
        rel = np.where(amag > 0, err / np.maximum(amag, 1e-45), 0.0)
    good = bool(
        np.all(rel[seg_band == 0] <= 2.0 ** -12)  # all terms normal
        and np.all(rel[seg_band == 1] <= 2.0 ** -7)  # lo term flushed
        and np.all(rel[seg_band == 2] <= 2.0 ** -5)  # mid term flushed
        and np.all(
            (ssum_tiny[seg_band == 3] == 0) | (rel[seg_band == 3] <= 1.0)
        )  # below bf16 min normal: clean flush or hi-term remnant
    )
    (_ok if good else _fail)("sum_tiny_magnitude_f32")
    ok &= good
    # local-window variant (r04: unsorted-but-local ids — the sender
    # gather/scatter path): bit-exact gather + exact-sum scatter
    from hydragnn_tpu.ops.segment_pallas import segment_sum_local_pallas
    from hydragnn_tpu.graph.batch import _block_windows

    g_of = np.sort(rng.integers(0, 64, 20_000))
    lsend = (g_of * 80 + rng.integers(0, 80, 20_000)).astype(np.int32)
    lperm = np.argsort(lsend, kind="stable").astype(np.int32)
    win = jnp.asarray(_block_windows(lsend, lperm, 5136))
    ltab = jnp.asarray(rng.normal(size=(5136, 128)).astype(np.float32))
    lout = _bcast_kernel_call(ltab, jnp.asarray(lsend), False, False)
    good = bool(np.array_equal(np.asarray(lout), np.asarray(ltab[lsend])))
    (_ok if good else _fail)("bcast_local_unsorted_f32")
    ok &= good
    data = jnp.asarray(rng.normal(size=(20_000, 128)).astype(np.float32))
    ssum = segment_sum_local_pallas(data, jnp.asarray(lsend), win, 5136)
    sref = jax.ops.segment_sum(data, jnp.asarray(lsend), 5136)
    good = _allclose(ssum, sref, 1e-5, 1e-4)
    (_ok if good else _fail)("segment_sum_local_f32")
    ok &= good
    # fused gather + K-group pre-reduction (r05): stats and extremum
    # outputs vs the unfused composition over a materialized gather —
    # f32 and bf16, with partial and whole-group masking
    from hydragnn_tpu.ops.segment_pallas import (
        _gather_stats_call,
        _presum_stats_ref,
    )

    e_f, n_f, h_f, kk = 8192, 2048, 128, 8
    gtab32 = np.round(rng.normal(size=(n_f, h_f)) * 4).astype(np.float32) / 4
    ggrp = np.sort(rng.integers(0, 64, e_f))
    gsend = (ggrp * 32 + rng.integers(0, 32, e_f)).astype(np.int32)
    gmask = rng.random(e_f) > 0.25
    gmask[128:136] = False  # one whole K-group masked
    for dtype in (jnp.float32, jnp.bfloat16):
        gt = jnp.asarray(gtab32).astype(dtype)
        s_k, b_k = _gather_stats_call(
            gt, jnp.asarray(gsend), jnp.asarray(gmask), kk, interpret=False
        )
        s_r, b_r = _presum_stats_ref(
            gt[jnp.asarray(gsend)], jnp.asarray(gmask), kk
        )
        good = _allclose(s_k, s_r, 1e-5, 1e-4) and bool(
            np.array_equal(
                np.asarray(b_k, np.float32), np.asarray(b_r, np.float32)
            )
        )
        (_ok if good else _fail)(f"gather_presum_{dtype.__name__}")
        ok &= good
    return ok


def check_train_step() -> bool:
    """Flagship-shaped PNA train step: Pallas dispatch vs forced-XLA
    must produce the same loss (the end-to-end gate: VJPs, gathers,
    extremum backwards all route differently)."""
    import os

    import numpy as np
    import jax.numpy as jnp

    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer

    config, model, variables, loader = build_flagship(
        n_samples=160, hidden_dim=128, num_conv_layers=2, batch_size=128,
        unit_cells=(2, 4),
    )
    tx = select_optimizer(config["NeuralNetwork"]["Training"])
    batch = next(iter(loader))

    losses = {}
    kernel_in_hlo = {}
    for knob in ("auto", "0"):
        os.environ["HYDRAGNN_PALLAS"] = knob
        try:
            step = make_train_step(model, tx, compute_dtype=jnp.bfloat16)
            state = create_train_state(variables, tx, seed=0)
            compiled = step.lower(state, batch).compile()
            # positive control: the kernel must actually BE in the auto
            # step (pallas lowers to tpu_custom_call) and absent from
            # the forced-XLA step — equal losses alone can't tell a
            # working A/B from two identical dispatches
            try:
                text = compiled.as_text()
            except Exception:
                text = ""
            # pallas lowers to the Mosaic "tpu_custom_call" target
            # specifically — plain "custom_call" also matches unrelated
            # XLA custom calls and cannot discriminate the paths
            kernel_in_hlo[knob] = "tpu_custom_call" in text
            _, loss, _ = compiled(state, batch)
            losses[knob] = float(np.asarray(loss))
        finally:
            os.environ.pop("HYDRAGNN_PALLAS", None)
    diff = abs(losses["auto"] - losses["0"]) / max(abs(losses["0"]), 1e-9)
    good = diff < 5e-3  # bf16 mixed precision; r02 measured 7e-6 on f32
    if kernel_in_hlo.get("auto") is False:
        good = False  # auto on TPU must dispatch the kernel
    if kernel_in_hlo.get("0") is True:
        good = False  # forced-XLA arm must NOT contain it, or the A/B is vacuous
    (_ok if good else _fail)(
        "train_step_pallas_vs_xla",
        losses=losses,
        rel_diff=diff,
        kernel_in_hlo=kernel_in_hlo,
    )
    return good


def bench_bf16_ab() -> None:
    """Measured bf16-vs-f32 family-kernel A/B at the bench shape
    (PERF.md left the bf16-DMA gain roofline-derived in r02). Scan-slope
    protocol; prints ms/op and effective HBM GB/s for both dtypes."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from hydragnn_tpu.ops.segment_pallas import segment_sum_family_pallas

    e, h, n = 120_000, 128, 5136
    rng = np.random.default_rng(1)
    recv = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    base = rng.normal(size=(e, h)).astype(np.float32)

    from hydragnn_tpu.utils.profile import scan_slope_ms

    def slope_ms(data):
        def body(carry, _):
            s, sq, c = segment_sum_family_pallas(
                carry, recv, n, None, indices_are_sorted=True
            )
            # chain: feed the gathered sum back so iterations depend
            return (carry + s[recv] * 1e-9).astype(data.dtype), c[0]

        def make_chain(k):
            fn = jax.jit(lambda d: jax.lax.scan(body, d, None, length=k))

            def run():
                _, cs = fn(data)
                np.asarray(cs[-1])  # D2H sync (block_until_ready lies here)

            return run

        return scan_slope_ms(make_chain, 16, 64)

    for dtype in (jnp.float32, jnp.bfloat16):
        data = jnp.asarray(base).astype(dtype)
        ms = slope_ms(data)
        if ms <= 0:
            # scan_slope_ms contract: non-positive slope is RTT noise,
            # not data — record the discard, never a negative bandwidth
            print(json.dumps({
                "check": f"bench_family_{dtype.__name__}", "ok": True,
                "ms_per_op": None, "note": "non-positive slope (tunnel noise), discarded",
            }))
            continue
        nbytes = e * h * (2 if dtype == jnp.bfloat16 else 4)  # one read of data
        print(json.dumps({
            "check": f"bench_family_{dtype.__name__}",
            "ok": True,
            "ms_per_op": round(ms, 4),
            "data_read_gb_s": round(nbytes / (ms / 1e3) / 1e9, 1),
        }))


def main() -> int:
    import jax

    backend = jax.default_backend()
    if backend != "tpu":
        print(json.dumps({"check": "backend", "ok": False, "backend": backend,
                          "note": "selfcheck requires a real TPU"}))
        return 2
    _ok("backend", device=getattr(jax.devices()[0], "device_kind", "?"))
    ok = check_kernels()
    ok &= check_train_step()
    if "--bench" in sys.argv:
        bench_bf16_ab()
    print(json.dumps({"check": "ALL", "ok": bool(ok)}))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
