from hydragnn_tpu.tools.lsms_tools import (
    compositional_histogram_cutoff,
    compute_formation_enthalpy,
    convert_raw_data_energy_to_gibbs,
)

__all__ = [
    "compositional_histogram_cutoff",
    "compute_formation_enthalpy",
    "convert_raw_data_energy_to_gibbs",
]
