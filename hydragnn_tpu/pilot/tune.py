"""Incremental fine-tune child: retrain the serving model over a
pinned request-spool window, warm-started from the serving checkpoint.

Runnable (the pilot launches it under the restart supervisor)::

    python -m hydragnn_tpu.pilot.tune \
        --log-dir ./logs/ --serving-run <run> --spool-dir <spool> \
        --candidate <run>-pilot-c1 [--shards shard-000001,...] [--epochs 2]

The child re-derives nothing: it loads the serving run's SAVED
resolved config (``<log_dir>/<run>/config.json`` — already through
``update_config``, minmax and head layouts included) and the spool
shards' samples, which are already prepared/model-space (obs/spool.py
stores predictions as target fields, so a shard loads as a labelled
dataset with the old weights' predictions as pseudo-labels). Loaders
are built directly over those samples — no re-normalization pass that
would distort already-normalized data — the fresh state is restored
from the serving checkpoint through the validating loader, and
``train_validate_test`` runs a short fine-tune under a DISTINCT
candidate run name so the serving checkpoint is never written to.

Exit-code contract (resilience/preempt.py, what the supervisor
classifies): 0 completed, 78 config error (deterministic — retrying
cannot help: missing config/checkpoint/too-few samples), anything
else crash-class (retried with backoff).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence

from hydragnn_tpu.resilience import inject
from hydragnn_tpu.resilience.preempt import EXIT_CONFIG_ERROR
from hydragnn_tpu.utils import knobs


def _split(samples: Sequence) -> tuple:
    """Deterministic ~80/10/10 split that never leaves a split empty
    (the loaders need at least one sample each)."""
    n = len(samples)
    if n < 3:
        raise ValueError(
            f"fine-tune needs at least 3 spooled samples, got {n}"
        )
    val = [s for i, s in enumerate(samples) if i % 10 == 8]
    test = [s for i, s in enumerate(samples) if i % 10 == 9]
    train = [s for i, s in enumerate(samples) if i % 10 < 8]
    if not val:
        val = [train.pop()]
    if not test:
        test = [train.pop()]
    return train, val, test


def _load_window(
    spool_dir: Optional[str], shards: Optional[Sequence[str]]
) -> List[Any]:
    """Samples of the pinned window (specific shards when given, the
    whole spool otherwise)."""
    from hydragnn_tpu.data.container import ContainerDataset
    from hydragnn_tpu.obs.spool import list_shards

    if spool_dir is None:
        raise ValueError("fine-tune needs a spool directory")
    if shards:
        dirs = [os.path.join(spool_dir, os.path.basename(s)) for s in shards]
    else:
        dirs = list_shards(spool_dir)
    out: List[Any] = []
    for d in dirs:
        out.extend(ContainerDataset(d).samples())
    return out


def fine_tune(
    log_dir: str,
    serving_run: str,
    candidate: str,
    spool_dir: Optional[str] = None,
    shards: Optional[Sequence[str]] = None,
    epochs: Optional[int] = None,
) -> Dict[str, Any]:
    """Run the incremental fine-tune; returns a small result manifest.
    Raises ``ValueError``/``FileNotFoundError`` on deterministic
    configuration problems (the CLI maps those to exit 78)."""
    # injected wedge (HYDRAGNN_INJECT_PILOT_HUNG_TUNE) fires before any
    # work so the supervisor's wall-clock belt is what kills us
    inject.maybe_pilot_hang()

    cfg_path = os.path.join(log_dir, serving_run, "config.json")
    with open(cfg_path) as f:
        config = json.load(f)
    nn_config = config["NeuralNetwork"]
    training = nn_config["Training"]
    training["num_epoch"] = int(
        epochs
        if epochs is not None
        else knobs.get_int("HYDRAGNN_PILOT_TUNE_EPOCHS", 2)
    )
    # the serving run's own continue/startfrom must not leak into the
    # fine-tune; the warm start below is explicit
    training.pop("continue", None)
    training.pop("startfrom", None)

    samples = _load_window(spool_dir, shards)
    train, val, test = _split(samples)

    from hydragnn_tpu.api import _example_for_init, create_dataloaders
    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.train import (
        create_train_state,
        select_optimizer,
        train_validate_test,
    )
    from hydragnn_tpu.utils.checkpoint import load_existing_model, save_model
    from hydragnn_tpu.utils.config import save_config

    train_loader, val_loader, test_loader = create_dataloaders(
        train, val, test, config
    )
    example = _example_for_init(next(iter(train_loader)), 1)
    model, variables = create_model_config(nn_config, example)
    freeze = bool(nn_config["Architecture"].get("freeze_conv_layers"))
    tx = select_optimizer(training, freeze_conv=freeze)
    state = create_train_state(variables, tx)
    # warm start: the serving checkpoint through the VALIDATING loader
    # (sha256 sidecars, torn-pointer fallback — utils/checkpoint.py)
    state = load_existing_model(state, serving_run, log_dir)
    state, history = train_validate_test(
        model,
        tx,
        state,
        train_loader,
        val_loader,
        test_loader,
        nn_config,
        log_name=candidate,
        log_dir=log_dir,
        run_config=config,
        manifest_extra={
            "fine_tune": {
                "from_run": serving_run,
                "spool_dir": spool_dir,
                "shards": list(shards or []),
                "num_samples": len(samples),
            }
        },
    )
    save_model(state, candidate, log_dir)
    save_config(config, candidate, log_dir)
    return {
        "candidate": candidate,
        "serving_run": serving_run,
        "num_samples": len(samples),
        "epochs": training["num_epoch"],
        "splits": [len(train), len(val), len(test)],
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--log-dir", required=True)
    p.add_argument("--serving-run", required=True)
    p.add_argument("--candidate", required=True)
    p.add_argument("--spool-dir", default=None)
    p.add_argument(
        "--shards",
        default=None,
        help="comma-separated shard basenames (the pinned window); "
        "default: every shard in the spool",
    )
    p.add_argument("--epochs", type=int, default=None)
    args = p.parse_args(argv)

    # injected pre-training crash (HYDRAGNN_INJECT_PILOT_TRAIN_CRASH):
    # crash-class exit; the supervisor's strip-on-restart makes the
    # retried attempt run clean
    if inject.pilot_train_crashes() > 0:
        print("pilot.tune: injected train crash", file=sys.stderr)
        return 70

    shards = args.shards.split(",") if args.shards else None
    try:
        out = fine_tune(
            args.log_dir,
            args.serving_run,
            args.candidate,
            spool_dir=args.spool_dir,
            shards=shards,
            epochs=args.epochs,
        )
    except (FileNotFoundError, ValueError, KeyError) as exc:
        print(f"pilot.tune: config error: {exc!r}", file=sys.stderr)
        return EXIT_CONFIG_ERROR
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
