"""Crash-safe journal for the retrain pilot's state machine.

One append-only JSONL file (``pilot_journal.jsonl``) records every
state transition with the cycle number and the consecutive-failure
counter. The journal is the pilot's durability story:

  - every ``append`` is one line, flushed and fsynced before the
    in-memory transition is considered committed — a SIGKILL between
    transitions loses nothing, a SIGKILL mid-write leaves one torn
    tail line that :meth:`entries` skips;
  - :meth:`recover` classifies the tail on restart: a RESTING state
    (``idle`` / ``cooldown`` / ``stuck``) means the previous pilot
    exited at rest and its counters carry over; a MID-CYCLE state
    (``drift_confirmed`` / ``fine_tuning`` / ``canary`` /
    ``reloading``) is the crashed-mid-cycle signature — the new pilot
    counts that cycle as failed and enters cooldown (or escalates if
    the failure budget is spent) instead of resuming a half-done
    retrain against a spool that has moved on.

The journal never decides policy — it reports what it finds and the
pilot (pilot/pilot.py) applies the recovery rules.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

RESTING_STATES = ("idle", "cooldown", "stuck")
MID_CYCLE_STATES = ("drift_confirmed", "fine_tuning", "canary", "reloading")
JOURNAL_NAME = "pilot_journal.jsonl"


class PilotJournal:
    """Append-only transition log; single-writer (the pilot serializes
    transitions under its own lock), any-reader."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)

    # -- write --------------------------------------------------------------

    def append(
        self,
        state: str,
        cycle: int,
        failed_cycles: int,
        **detail: Any,
    ) -> Dict[str, Any]:
        """Durably commit one transition; returns the record written."""
        record = {
            "t": time.time(),
            "state": str(state),
            "cycle": int(cycle),
            "failed_cycles": int(failed_cycles),
        }
        if detail:
            record["detail"] = detail
        line = json.dumps(record)
        # a kill mid-write leaves a torn tail with NO newline; gluing
        # the next record onto it would corrupt that record too, so
        # open in binary append and start on a fresh line when needed
        with open(self.path, "ab") as f:
            if f.tell() > 0:
                with open(self.path, "rb") as r:
                    r.seek(-1, os.SEEK_END)
                    torn = r.read(1) != b"\n"
                if torn:
                    f.write(b"\n")
            f.write(line.encode("utf-8") + b"\n")
            f.flush()
            os.fsync(f.fileno())
        return record

    # -- read ---------------------------------------------------------------

    def entries(self) -> List[Dict[str, Any]]:
        """Every committed record, oldest first. A torn tail line (kill
        mid-write) parses as nothing and is skipped, not an error."""
        if not os.path.exists(self.path):
            return []
        out: List[Dict[str, Any]] = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and "state" in rec:
                    out.append(rec)
        return out

    def last(self) -> Optional[Dict[str, Any]]:
        entries = self.entries()
        return entries[-1] if entries else None

    # -- restart classification ---------------------------------------------

    def recover(self) -> Dict[str, Any]:
        """Classify the journal tail for a restarting pilot:

        - ``{"status": "fresh"}`` — no journal, first flight;
        - ``{"status": "clean", ...}`` — previous pilot exited at rest;
          the tail's state/cycle/failed_cycles carry over;
        - ``{"status": "crashed_mid_cycle", ...}`` — the tail is a
          mid-cycle state: the previous pilot died inside a retrain.
        """
        last = self.last()
        if last is None:
            return {"status": "fresh"}
        base = {
            "state": last["state"],
            "cycle": int(last.get("cycle", 0)),
            "failed_cycles": int(last.get("failed_cycles", 0)),
        }
        if last["state"] in RESTING_STATES:
            return {"status": "clean", **base}
        return {"status": "crashed_mid_cycle", **base}
