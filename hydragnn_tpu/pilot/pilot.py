"""The retrain pilot: a fault-tolerant drift -> fine-tune -> canary ->
hot-reload state machine over one serving stack.

States (one journaled + flight-recorded transition each)::

    idle -> drift_confirmed -> fine_tuning -> canary -> reloading
         -> cooldown -> idle            (success: drift sketches reset)
                     -> cooldown        (any failure: old weights serve)
                     -> stuck           (K consecutive failed cycles)

Fault tolerance is the point, so every stage is allowed to fail and
none of them can take the serving path down:

  - the fine-tune runs as a CHILD process under the bounded restart
    supervisor (``resilience/supervisor.py``) with exponential backoff
    and a hard wall-clock kill (``wall_clock_runner``) for jobs wedged
    where no in-process watchdog can fire;
  - the candidate must beat the canary gate on BOTH the held-out
    reference slice and the drifted spool window before any weight
    swap is attempted; a regression on either slice rejects it;
  - the reload itself is the server's canary-gated, rollback-built-in
    ``reload()`` (or the fleet's ``rolling_reload``) — a torn or
    non-finite candidate leaves the old weights serving;
  - a single-retrain lock plus a cooldown window stop retrain storms
    (drift incidents during cooldown are counted, never acted on);
  - ``HYDRAGNN_PILOT_STUCK_AFTER`` consecutive failed cycles escalate
    to a terminal ``stuck`` state and a ``pilot_stuck`` incident —
    the pilot stops flapping and pages a human;
  - every transition is committed to the on-disk journal
    (pilot/journal.py) BEFORE it takes effect, so a pilot killed
    mid-cycle restarts into a safe state instead of resuming a
    half-done retrain.

The pilot pins the incident's spool shards for the WHOLE cycle (its
own pin references, independent of the incident's), so the fine-tune's
input set cannot be evicted mid-training even after the incident
bundle closes.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from hydragnn_tpu.pilot.journal import JOURNAL_NAME, PilotJournal
from hydragnn_tpu.resilience import inject
from hydragnn_tpu.utils import knobs, syncdebug

PILOT_STATES = (
    "idle",
    "drift_confirmed",
    "fine_tuning",
    "canary",
    "reloading",
    "cooldown",
    "stuck",
)
#: Gauge encoding for ``<prefix>.pilot.state`` (serve_probe reads it).
STATE_CODES = {name: i for i, name in enumerate(PILOT_STATES)}


@dataclasses.dataclass
class PilotConfig:
    """Pilot policy; every default is the matching HYDRAGNN_PILOT_*
    knob read at construction (docs/KNOBS.md)."""

    cooldown_s: float = dataclasses.field(
        default_factory=lambda: knobs.get_float("HYDRAGNN_PILOT_COOLDOWN_S", 60.0)
    )
    stuck_after: int = dataclasses.field(
        default_factory=lambda: knobs.get_int("HYDRAGNN_PILOT_STUCK_AFTER", 3)
    )
    tune_attempts: int = dataclasses.field(
        default_factory=lambda: knobs.get_int("HYDRAGNN_PILOT_TUNE_ATTEMPTS", 2)
    )
    tune_backoff_s: float = dataclasses.field(
        default_factory=lambda: knobs.get_float("HYDRAGNN_PILOT_TUNE_BACKOFF_S", 1.0)
    )
    max_wall_s: float = dataclasses.field(
        default_factory=lambda: knobs.get_float("HYDRAGNN_PILOT_MAX_WALL_S", 600.0)
    )
    canary_samples: int = dataclasses.field(
        default_factory=lambda: knobs.get_int("HYDRAGNN_PILOT_CANARY_SAMPLES", 16)
    )
    canary_tol: float = dataclasses.field(
        default_factory=lambda: knobs.get_float("HYDRAGNN_PILOT_CANARY_TOL", 0.2)
    )
    tune_epochs: int = dataclasses.field(
        default_factory=lambda: knobs.get_int("HYDRAGNN_PILOT_TUNE_EPOCHS", 2)
    )


class RetrainPilot:
    """One pilot per served model; attach with
    ``server.attach_pilot(pilot)`` so drift incidents flow in.

    Seams (all injectable for tests): ``tuner(candidate) -> result
    dict`` replaces the supervised child fine-tune; ``reloader
    (candidate)`` replaces the hot-reload (defaults to the server's
    ``reload``, or the fleet's ``rolling_reload`` when ``fleet``/
    ``fleet_model`` are given); ``clock`` drives cooldown arithmetic.
    ``async_cycles=False`` runs the whole cycle inline on the notifying
    thread (tests); the default spawns one worker thread per cycle so
    the server's dispatch loop never blocks on training.
    """

    def __init__(
        self,
        server,
        serving_run: str,
        *,
        reference_samples: Optional[Sequence] = None,
        config: Optional[PilotConfig] = None,
        tuner: Optional[Callable[[str], Dict[str, Any]]] = None,
        reloader: Optional[Callable[[str], Any]] = None,
        fleet=None,
        fleet_model: Optional[str] = None,
        journal_path: Optional[str] = None,
        flight=None,
        clock: Callable[[], float] = time.monotonic,
        async_cycles: bool = True,
    ):
        self.server = server
        self.serving_run = serving_run
        self.log_dir = server.log_dir
        self.reference_samples = list(reference_samples or [])
        self.config = config or PilotConfig()
        self.tuner = tuner or self._default_tuner
        self.reloader = reloader or self._default_reloader
        self.fleet = fleet
        self.fleet_model = fleet_model
        self.flight = flight if flight is not None else server.flight
        self.clock = clock
        self.async_cycles = async_cycles
        # graftsync: thread-safe=appends serialized under _lock; readers skip torn tails
        self.journal = PilotJournal(
            journal_path
            or os.path.join(self.log_dir, serving_run, JOURNAL_NAME)
        )
        self._lock = syncdebug.maybe_wrap(
            threading.RLock(), "pilot.RetrainPilot._lock"
        )
        # graftsync: guarded-by=pilot.RetrainPilot._lock
        self.state = "idle"
        self.cycle = 0  # graftsync: guarded-by=pilot.RetrainPilot._lock
        # graftsync: guarded-by=pilot.RetrainPilot._lock
        self.failed_cycles = 0
        # graftsync: guarded-by=pilot.RetrainPilot._lock
        self.suppressed = 0
        # graftsync: guarded-by=pilot.RetrainPilot._lock
        self.last_cycle_ok: Optional[bool] = None
        # graftsync: guarded-by=pilot.RetrainPilot._lock
        self._cooldown_t0 = 0.0
        # graftsync: guarded-by=pilot.RetrainPilot._lock
        self._pins: List[str] = []
        # graftsync: thread-safe=written by the cycle owner before the worker starts; joined before reuse
        self._worker: Optional[threading.Thread] = None
        reg = server.metrics.registry
        prefix = server.metrics.prefix
        self._g_state = reg.gauge(f"{prefix}.pilot.state")
        self._g_last_ok = reg.gauge(f"{prefix}.pilot.last_cycle_ok")
        self._g_cycles = reg.gauge(f"{prefix}.pilot.cycles")
        self._g_failed = reg.gauge(f"{prefix}.pilot.failed_cycles")
        self._g_suppressed = reg.gauge(f"{prefix}.pilot.suppressed")
        self._g_last_ok.set(-1.0)  # no cycle flown yet
        self._recover()

    # -- restart recovery ----------------------------------------------------

    def _recover(self) -> None:
        """Apply the journal's restart classification (journal.py):
        resting tails carry over; a mid-cycle tail means the previous
        pilot was killed inside a retrain — count that cycle as failed
        and land in cooldown (or stuck when the budget is spent). The
        crashed cycle's pins died with the old process, so there is
        nothing to release here."""
        rec = self.journal.recover()
        with self._lock:
            if rec["status"] == "fresh":
                self._transition_locked("idle", reason="fresh")
                return
            self.cycle = rec["cycle"]
            self.failed_cycles = rec["failed_cycles"]
            if rec["status"] == "clean":
                if rec["state"] == "stuck":
                    self._transition_locked("stuck", reason="recovered_stuck")
                elif rec["state"] == "cooldown":
                    self._cooldown_t0 = self.clock()
                    self._transition_locked(
                        "cooldown", reason="recovered_cooldown"
                    )
                else:
                    self._transition_locked("idle", reason="recovered_idle")
                return
            # crashed mid-cycle: the half-done retrain is abandoned, the
            # interruption counts against the failure budget
            self.failed_cycles += 1
            self.last_cycle_ok = False
            self._g_last_ok.set(0.0)
            if self.failed_cycles >= self.config.stuck_after:
                self._escalate_stuck_locked(
                    f"crashed in {rec['state']} (cycle {rec['cycle']})"
                )
            else:
                self._cooldown_t0 = self.clock()
                self._transition_locked(
                    "cooldown",
                    reason="recovered_after_crash",
                    crashed_in=rec["state"],
                )

    # -- transitions ---------------------------------------------------------

    # graftsync: holds=pilot.RetrainPilot._lock
    def _transition_locked(self, state: str, **detail: Any) -> None:
        """Commit one transition: journal FIRST (durability), then the
        in-memory state, the gauges, and the flight narration."""
        self.journal.append(state, self.cycle, self.failed_cycles, **detail)
        self.state = state
        self._g_state.set(float(STATE_CODES[state]))
        self._g_cycles.set(float(self.cycle))
        self._g_failed.set(float(self.failed_cycles))
        if self.flight is not None:
            self.flight.record(
                "pilot", state=state, cycle=self.cycle,
                failed_cycles=self.failed_cycles, **detail,
            )

    # graftsync: holds=pilot.RetrainPilot._lock
    def _maybe_leave_cooldown_locked(self) -> None:
        if (
            self.state == "cooldown"
            and self.clock() - self._cooldown_t0 >= self.config.cooldown_s
        ):
            self._transition_locked("idle", reason="cooldown_elapsed")

    def poll(self) -> str:
        """Advance time-driven transitions (cooldown expiry) and return
        the current state — probes and tests call this."""
        with self._lock:
            self._maybe_leave_cooldown_locked()
            return self.state

    # -- incident intake (server dispatch thread) ----------------------------

    def on_drift_incident(self, incident, verdict) -> bool:
        """One drift incident arrives (after its evidence bundle is
        written). Starts a retrain cycle iff the pilot is idle — the
        single-retrain lock and cooldown hysteresis live here. Returns
        whether a cycle started."""
        with self._lock:
            self._maybe_leave_cooldown_locked()
            if self.state != "idle":
                self.suppressed += 1
                self._g_suppressed.set(float(self.suppressed))
                if self.flight is not None:
                    self.flight.record(
                        "pilot", state=self.state, cycle=self.cycle,
                        suppressed_incident=getattr(incident, "id", None),
                        suppressed_total=self.suppressed,
                    )
                return False
            self.cycle += 1
            cycle = self.cycle
            # the pilot's OWN pins: the incident's pins release when its
            # bundle closes, these survive until the cycle ends
            window = self.server.pin_spool(self._incident_shards(incident))
            self._pins = window
            self._transition_locked(
                "drift_confirmed",
                rule=verdict.rule,
                rule_kind=verdict.kind,
                incident=getattr(incident, "id", None),
                pinned_shards=window,
            )
        if self.async_cycles:
            self._worker = threading.Thread(
                target=self._run_cycle, name=f"pilot-cycle-{cycle}",
                daemon=True,
            )
            self._worker.start()
        else:
            self._run_cycle()
        return True

    @staticmethod
    def _incident_shards(incident) -> List[str]:
        """The spool shards the incident's drift evidence references
        (written by the server's ``_attach_drift_evidence``)."""
        import json

        try:
            with open(
                os.path.join(incident.dir, "drift_report.json")
            ) as f:
                report = json.load(f)
            return list(
                report.get("pinned_shards")
                or report.get("spool_window", {}).get("shards")
                or []
            )
        except Exception:
            return []

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for an in-flight cycle's worker thread (tests, stop)."""
        w = self._worker
        if w is not None and w.is_alive():
            w.join(timeout)

    # -- one retrain cycle ----------------------------------------------------

    # graftsync: thread-root
    def _run_cycle(self) -> None:
        with self._lock:
            candidate = f"{self.serving_run}-pilot-c{self.cycle}"
        try:
            with self._lock:
                self._transition_locked("fine_tuning", candidate=candidate)
            try:
                result = self.tuner(candidate)
            except Exception as exc:
                self._fail_cycle(
                    "fine_tune_error", candidate, error=repr(exc)[-200:]
                )
                return
            if not result or result.get("status") != "completed":
                self._fail_cycle(
                    "fine_tune_" + str((result or {}).get("status", "failed")),
                    candidate,
                    attempts=(result or {}).get("attempts"),
                    cause=(result or {}).get("cause"),
                )
                return
            with self._lock:
                self._transition_locked("canary", candidate=candidate)
            try:
                verdict = self._canary(candidate)
            except Exception as exc:
                self._fail_cycle(
                    "canary_error", candidate, error=repr(exc)[-200:]
                )
                return
            if not verdict["ok"]:
                self._fail_cycle("canary_regression", candidate, **verdict)
                return
            if inject.pilot_torn_reload():
                _tear_checkpoint(self.log_dir, candidate)
            with self._lock:
                self._transition_locked(
                    "reloading", candidate=candidate, **verdict
                )
            try:
                self.reloader(candidate)
            except Exception as exc:
                # the reload path's own canary/rollback kept the old
                # weights serving; the pilot just records the rejection
                self._fail_cycle(
                    "reload_failed", candidate, error=repr(exc)[-200:]
                )
                return
            # a fresh model must not re-trip the drift rules on sketch
            # mass the OLD weights accumulated
            self.server.reset_drift()
            with self._lock:
                self.failed_cycles = 0
                self.last_cycle_ok = True
                self._g_last_ok.set(1.0)
                self._cooldown_t0 = self.clock()
                self._transition_locked(
                    "cooldown", reason="reloaded", candidate=candidate,
                    **verdict,
                )
        finally:
            with self._lock:
                pins, self._pins = self._pins, []
            if pins:
                self.server.unpin_spool(pins)

    def _fail_cycle(self, reason: str, candidate: str, **detail: Any) -> None:
        with self._lock:
            self.failed_cycles += 1
            self.last_cycle_ok = False
            self._g_last_ok.set(0.0)
            if self.failed_cycles >= self.config.stuck_after:
                self._escalate_stuck_locked(reason, candidate=candidate, **detail)
                return
            self._cooldown_t0 = self.clock()
            self._transition_locked(
                "cooldown", reason=reason, candidate=candidate, **detail
            )

    # graftsync: holds=pilot.RetrainPilot._lock
    def _escalate_stuck_locked(self, reason: str, **detail: Any) -> None:
        """Terminal state: persistent drift the loop cannot fix. The
        pilot stops retrying (a human must intervene) and raises a
        ``pilot_stuck`` incident bundle as the page."""
        self._transition_locked("stuck", reason=reason, **detail)
        from hydragnn_tpu.obs.triggers import TriggerVerdict

        verdict = TriggerVerdict(
            rule="pilot",
            kind="pilot_stuck",
            metric=f"{self.server.metrics.prefix}.pilot.failed_cycles",
            observed=float(self.failed_cycles),
            threshold=float(self.config.stuck_after),
            fired_t=time.time(),
            detail={"reason": reason},
        )
        try:
            self.server.open_pilot_incident(verdict)
        except Exception:
            pass  # the journal + flight event remain the escalation record

    # -- default fine-tune launcher ------------------------------------------

    def _default_tuner(self, candidate: str) -> Dict[str, Any]:
        """Supervised child fine-tune: ``python -m hydragnn_tpu.pilot.
        tune`` under the bounded restart supervisor with the hard
        wall-clock runner — crash-class exits retry with exponential
        backoff up to ``tune_attempts``, a wedged child is killed after
        ``max_wall_s`` and classified hung."""
        from hydragnn_tpu.resilience.supervisor import (
            Supervisor,
            SupervisorPolicy,
            wall_clock_runner,
        )

        spool = self.server.spool_dir()
        argv = [
            sys.executable, "-m", "hydragnn_tpu.pilot.tune",
            "--log-dir", self.log_dir,
            "--serving-run", self.serving_run,
            "--candidate", candidate,
            "--epochs", str(self.config.tune_epochs),
        ]
        if spool:
            argv += ["--spool-dir", spool]
        with self._lock:
            pins = list(self._pins)
        if pins:
            argv += ["--shards", ",".join(pins)]
        policy = SupervisorPolicy(
            max_restarts=self.config.tune_attempts,
            backoff_base_s=self.config.tune_backoff_s,
        )
        sup = Supervisor(
            argv,
            policy=policy,
            env=dict(os.environ),
            runner=wall_clock_runner(self.config.max_wall_s),
        )
        return sup.run()

    # -- canary gate ----------------------------------------------------------

    def _default_reloader(self, candidate: str):
        if self.fleet is not None:
            return self.fleet.rolling_reload(
                self.fleet_model, candidate, log_dir=self.log_dir
            )
        return self.server.reload(candidate, log_dir=self.log_dir)

    def _canary(self, candidate: str) -> Dict[str, Any]:
        """Score serving weights vs the candidate on the held-out
        reference slice AND the pinned drifted window; the candidate
        must stay within ``canary_tol`` of baseline on BOTH. The
        absolute ``+ tol`` headroom matters on the drifted window,
        whose targets are the old weights' own predictions (baseline
        MAE ~0 by construction)."""
        from hydragnn_tpu.serve.registry import load_served_variables

        cand_vars = load_served_variables(
            self.server.served, candidate, self.log_dir
        )
        cand_vars = self.server.partitioner.shard_variables(cand_vars)
        base_vars = self.server.served.variables
        tol = self.config.canary_tol
        inflate = 1e6 if inject.pilot_canary_regress() else 0.0
        slices = {
            "reference": list(self.reference_samples),
            "window": self._window_samples(),
        }
        out: Dict[str, Any] = {"ok": True}
        for name, samples in slices.items():
            if not samples:
                out[name] = None
                continue
            base = self._score(base_vars, samples)
            cand = self._score(cand_vars, samples) + inflate
            passed = bool(cand <= base * (1.0 + tol) + tol)
            out[name] = {
                "baseline_mae": round(base, 6),
                "candidate_mae": round(cand, 6),
                "passed": passed,
            }
            if not passed:
                out["ok"] = False
        return out

    def _window_samples(self) -> List[Any]:
        from hydragnn_tpu.data.container import ContainerDataset

        root = self.server.spool_dir()
        if not root:
            return []
        with self._lock:
            pins = list(self._pins)
        out: List[Any] = []
        for name in pins:
            try:
                out.extend(ContainerDataset(os.path.join(root, name)).samples())
            except Exception:
                continue  # a shard torn below the pilot is a smaller
                # window, not a failed canary
        return out

    def _score(self, variables: Dict[str, Any], samples: Sequence) -> float:
        """Mean per-sample MAE of ``variables`` over ``samples`` —
        the eager single-graph path the server's oversize fallback
        uses, bounded by ``canary_samples``."""
        from hydragnn_tpu.graph.batch import batch_graphs
        from hydragnn_tpu.serve.server import request_to_dict

        srv = self.server
        errs: List[float] = []
        for s in list(samples)[: self.config.canary_samples]:
            g = request_to_dict(s)
            n = int(np.asarray(g["x"]).shape[0])
            batch = batch_graphs(
                [g],
                node_multiple=srv.config.node_multiple,
                edge_multiple=srv.config.edge_multiple,
            )
            batch = srv.partitioner.shard_inference_batch(batch)
            outs = srv.served.forward(variables, batch)
            result = srv._slice_result(
                outs, graph_index=0, node_offset=0, num_nodes=n
            )
            errs.append(_sample_mae(result, s))
        return float(np.mean(errs)) if errs else 0.0

    # -- status ---------------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self.state,
                "cycle": self.cycle,
                "failed_cycles": self.failed_cycles,
                "suppressed": self.suppressed,
                "last_cycle_ok": self.last_cycle_ok,
                "pinned_shards": list(self._pins),
            }


def _sample_mae(result: Dict[str, np.ndarray], sample) -> float:
    """MAE of one predicted result dict against the sample's targets
    (graph heads + node heads, whichever the sample carries)."""
    gts = getattr(sample, "graph_targets", None) or {}
    nts = getattr(sample, "node_targets", None) or {}
    diffs: List[float] = []
    for name, pred in result.items():
        p = np.asarray(pred, dtype=np.float64).reshape(-1)
        if name in gts:
            t = np.asarray(gts[name], dtype=np.float64).reshape(-1)
        elif name in nts:
            t = np.asarray(nts[name], dtype=np.float64).reshape(-1)
        else:
            continue
        if t.size == p.size and p.size:
            diffs.append(float(np.mean(np.abs(p - t))))
    return float(np.mean(diffs)) if diffs else 0.0


def _tear_checkpoint(log_dir: str, candidate: str) -> None:
    """HYDRAGNN_INJECT_PILOT_TORN_RELOAD: truncate the candidate's
    checkpoint after the pilot canary passed, so the RELOAD path's own
    validating loader + canary must reject it (proving any reload
    failure leaves the old weights serving)."""
    path = os.path.join(log_dir, candidate, f"{candidate}.mp")
    try:
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))
    except OSError:
        pass
