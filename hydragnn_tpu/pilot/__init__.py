"""Continual-learning retrain pilot (docs/RESILIENCE.md "Closed loop").

The pilot closes the loop the observability plane opened: a drift
incident (obs/triggers.py) becomes a supervised fine-tune over the
pinned request-spool window (obs/spool.py), a canary-gated candidate,
and a zero-downtime hot reload — or a clean rejection that leaves the
old weights serving. Every transition is journaled to disk so a
crashed pilot recovers instead of flapping, and narrated as a
``pilot`` flight event on the run's one trace timeline.
"""

from hydragnn_tpu.pilot.journal import PilotJournal
from hydragnn_tpu.pilot.pilot import PilotConfig, RetrainPilot, PILOT_STATES

__all__ = ["PilotConfig", "PilotJournal", "RetrainPilot", "PILOT_STATES"]
