"""Output denormalization and per-num-nodes unscaling.

TPU-native equivalent of the reference postprocess
(reference: hydragnn/postprocess/postprocess.py:13-54). Values here are
per-head numpy arrays (the ``test_epoch`` collection format), so the
min-max inverse transform is vectorized instead of the reference's
triple-nested Python loop.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np


def output_denormalize(
    y_minmax: Sequence[Sequence[float]],
    true_values: List[np.ndarray],
    predicted_values: List[np.ndarray],
):
    """Inverse min-max transform per head: v*(max-min)+min
    (reference: postprocess.py:13-27)."""
    out_true, out_pred = [], []
    for ihead in range(len(y_minmax)):
        ymin = np.asarray(y_minmax[ihead][0], dtype=np.float64)
        ymax = np.asarray(y_minmax[ihead][1], dtype=np.float64)
        scale = ymax - ymin
        out_true.append(np.asarray(true_values[ihead]) * scale + ymin)
        out_pred.append(np.asarray(predicted_values[ihead]) * scale + ymin)
    return out_true, out_pred


def unscale_features_by_num_nodes(
    datasets_list: List[List[np.ndarray]],
    scaled_index_list: Sequence[int],
    nodes_num_list: Sequence[int],
):
    """Multiply ``*_scaled_num_nodes`` heads back by each sample's node
    count (reference: postprocess.py:30-42). ``datasets_list`` entries are
    per-head lists of per-sample arrays."""
    for dataset in datasets_list:
        for scaled_index in scaled_index_list:
            head_value = dataset[scaled_index]
            for isample, n in enumerate(nodes_num_list):
                head_value[isample] = np.asarray(head_value[isample]) * n
    return datasets_list


def unscale_features_by_num_nodes_config(
    config: Dict, datasets_list, nodes_num_list
):
    """Config-driven variant keyed on ``*_scaled_num_nodes`` head names
    (reference: postprocess.py:45-55)."""
    var_config = config["NeuralNetwork"]["Variables_of_interest"]
    output_names = var_config["output_names"]
    scaled_feature_index = [
        i for i in range(len(output_names)) if "_scaled_num_nodes" in output_names[i]
    ]
    if scaled_feature_index:
        if not var_config["denormalize_output"]:
            raise ValueError(
                "Cannot unscale features without 'denormalize_output'"
            )
        datasets_list = unscale_features_by_num_nodes(
            datasets_list, scaled_feature_index, nodes_num_list
        )
    return datasets_list
