"""Result visualization: parity scatters, error histograms, loss curves.

Compact TPU-build counterpart of the reference Visualizer (reference:
hydragnn/postprocess/visualizer.py:24-742, methods listed at :66-741).
Same artifact set — per-head parity scatter plots, error histograms,
2-D density contour with conditional mean, loss-history curves, node-count
histogram — rendered with the Agg backend into ``logs/<name>/``. Values
arrive as per-head numpy arrays (the ``test_epoch`` collection format)
rather than lists of per-sample tensors, so everything vectorizes.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402


class Visualizer:
    def __init__(
        self,
        model_with_config_name: str,
        num_heads: int = 1,
        head_names: Optional[Sequence[str]] = None,
        log_dir: str = "./logs/",
    ):
        self.name = model_with_config_name
        self.num_heads = num_heads
        self.head_names = list(head_names or [f"head{i}" for i in range(num_heads)])
        self.out_dir = os.path.join(log_dir, model_with_config_name)
        os.makedirs(self.out_dir, exist_ok=True)

    # ---- per-head parity scatter (reference create_scatter_plots) ----

    def create_scatter_plots(
        self,
        true_values: List[np.ndarray],
        predicted_values: List[np.ndarray],
        output_names: Optional[Sequence[str]] = None,
        iepoch: Optional[int] = None,
    ) -> List[str]:
        names = list(output_names or self.head_names)
        paths = []
        for ihead in range(len(true_values)):
            t = np.asarray(true_values[ihead]).reshape(-1)
            p = np.asarray(predicted_values[ihead]).reshape(-1)
            fig, ax = plt.subplots(figsize=(5, 5))
            ax.scatter(t, p, s=4, alpha=0.4, edgecolors="none")
            lo = float(min(t.min(), p.min())) if t.size else 0.0
            hi = float(max(t.max(), p.max())) if t.size else 1.0
            ax.plot([lo, hi], [lo, hi], "k--", linewidth=1)
            ax.set_xlabel("True")
            ax.set_ylabel("Predicted")
            suffix = "" if iepoch is None else f"_epoch{iepoch}"
            ax.set_title(f"{names[ihead]}{suffix}")
            path = os.path.join(self.out_dir, f"scatter_{names[ihead]}{suffix}.png")
            fig.tight_layout()
            fig.savefig(path, dpi=100)
            plt.close(fig)
            paths.append(path)
        return paths

    # ---- per-head error histogram (reference create_error_histograms) ----

    def create_error_histograms(
        self,
        true_values: List[np.ndarray],
        predicted_values: List[np.ndarray],
        output_names: Optional[Sequence[str]] = None,
        iepoch: Optional[int] = None,
    ) -> List[str]:
        names = list(output_names or self.head_names)
        paths = []
        for ihead in range(len(true_values)):
            err = (
                np.asarray(predicted_values[ihead]).reshape(-1)
                - np.asarray(true_values[ihead]).reshape(-1)
            )
            fig, ax = plt.subplots(figsize=(5, 4))
            ax.hist(err, bins=50)
            ax.set_xlabel("Predicted - True")
            ax.set_ylabel("Count")
            suffix = "" if iepoch is None else f"_epoch{iepoch}"
            ax.set_title(f"{names[ihead]} error{suffix}")
            path = os.path.join(self.out_dir, f"errhist_{names[ihead]}{suffix}.png")
            fig.tight_layout()
            fig.savefig(path, dpi=100)
            plt.close(fig)
            paths.append(path)
        return paths

    # ---- 2-D density + conditional mean (reference create_plot_global) ----

    def create_plot_global(
        self,
        true_values: List[np.ndarray],
        predicted_values: List[np.ndarray],
        output_names: Optional[Sequence[str]] = None,
    ) -> List[str]:
        names = list(output_names or self.head_names)
        paths = []
        for ihead in range(len(true_values)):
            t = np.asarray(true_values[ihead]).reshape(-1)
            p = np.asarray(predicted_values[ihead]).reshape(-1)
            fig, axes = plt.subplots(1, 3, figsize=(13, 4))
            if t.size:
                h, xe, ye = np.histogram2d(t, p, bins=50)
                xc = 0.5 * (xe[:-1] + xe[1:])
                yc = 0.5 * (ye[:-1] + ye[1:])
                hmax = h.max() if h.max() > 0 else 1.0
                axes[0].contourf(xc, yc, (h / hmax).T, levels=10)
                # conditional mean error per true-value bin
                bin_ids = np.clip(np.digitize(t, xe) - 1, 0, len(xc) - 1)
                cond_mean = np.full(len(xc), np.nan)
                for b in range(len(xc)):
                    sel = bin_ids == b
                    if sel.any():
                        cond_mean[b] = (p[sel] - t[sel]).mean()
                axes[1].plot(xc, cond_mean)
                axes[2].hist(p - t, bins=50, density=True)
            axes[0].set_title(f"{names[ihead]} density")
            axes[1].set_title("conditional mean error")
            axes[2].set_title("error pdf")
            path = os.path.join(self.out_dir, f"global_{names[ihead]}.png")
            fig.tight_layout()
            fig.savefig(path, dpi=100)
            plt.close(fig)
            paths.append(path)
        return paths

    # ---- loss-history curves (reference plot_history) ----

    def plot_history(self, history: Dict[str, list]) -> str:
        fig, ax = plt.subplots(figsize=(6, 4))
        for key in ("train_loss", "val_loss", "test_loss"):
            if history.get(key):
                ax.plot(history[key], label=key)
        ax.set_xlabel("Epoch")
        ax.set_ylabel("Loss")
        ax.set_yscale("log")
        ax.legend()
        path = os.path.join(self.out_dir, "history.png")
        fig.tight_layout()
        fig.savefig(path, dpi=100)
        plt.close(fig)
        return path

    # ---- node-count histogram (reference num_nodes_plot) ----

    def num_nodes_plot(self, num_nodes_list: Sequence[int]) -> str:
        fig, ax = plt.subplots(figsize=(5, 4))
        ax.hist(np.asarray(num_nodes_list), bins=30)
        ax.set_xlabel("Nodes per graph")
        ax.set_ylabel("Count")
        path = os.path.join(self.out_dir, "num_nodes.png")
        fig.tight_layout()
        fig.savefig(path, dpi=100)
        plt.close(fig)
        return path
