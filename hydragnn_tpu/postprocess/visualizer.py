"""Result visualization: parity scatters, error histograms, loss curves.

Compact TPU-build counterpart of the reference Visualizer (reference:
hydragnn/postprocess/visualizer.py:24-742, methods listed at :66-741).
Same artifact set — per-head parity scatter plots, error histograms,
2-D density contour with conditional mean, loss-history curves, node-count
histogram — rendered with the Agg backend into ``logs/<name>/``. Values
arrive as per-head numpy arrays (the ``test_epoch`` collection format)
rather than lists of per-sample tensors, so everything vectorizes.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402


class Visualizer:
    def __init__(
        self,
        model_with_config_name: str,
        num_heads: int = 1,
        head_names: Optional[Sequence[str]] = None,
        log_dir: str = "./logs/",
    ):
        self.name = model_with_config_name
        self.num_heads = num_heads
        self.head_names = list(head_names or [f"head{i}" for i in range(num_heads)])
        self.out_dir = os.path.join(log_dir, model_with_config_name)
        os.makedirs(self.out_dir, exist_ok=True)

    # ---- per-head parity scatter (reference create_scatter_plots) ----

    def create_scatter_plots(
        self,
        true_values: List[np.ndarray],
        predicted_values: List[np.ndarray],
        output_names: Optional[Sequence[str]] = None,
        iepoch: Optional[int] = None,
    ) -> List[str]:
        names = list(output_names or self.head_names)
        paths = []
        for ihead in range(len(true_values)):
            t = np.asarray(true_values[ihead]).reshape(-1)
            p = np.asarray(predicted_values[ihead]).reshape(-1)
            fig, ax = plt.subplots(figsize=(5, 5))
            ax.scatter(t, p, s=4, alpha=0.4, edgecolors="none")
            lo = float(min(t.min(), p.min())) if t.size else 0.0
            hi = float(max(t.max(), p.max())) if t.size else 1.0
            ax.plot([lo, hi], [lo, hi], "k--", linewidth=1)
            ax.set_xlabel("True")
            ax.set_ylabel("Predicted")
            suffix = "" if iepoch is None else f"_epoch{iepoch}"
            ax.set_title(f"{names[ihead]}{suffix}")
            path = os.path.join(self.out_dir, f"scatter_{names[ihead]}{suffix}.png")
            fig.tight_layout()
            fig.savefig(path, dpi=100)
            plt.close(fig)
            paths.append(path)
        return paths

    # ---- per-head error histogram (reference create_error_histograms) ----

    def create_error_histograms(
        self,
        true_values: List[np.ndarray],
        predicted_values: List[np.ndarray],
        output_names: Optional[Sequence[str]] = None,
        iepoch: Optional[int] = None,
    ) -> List[str]:
        names = list(output_names or self.head_names)
        paths = []
        for ihead in range(len(true_values)):
            err = (
                np.asarray(predicted_values[ihead]).reshape(-1)
                - np.asarray(true_values[ihead]).reshape(-1)
            )
            fig, ax = plt.subplots(figsize=(5, 4))
            ax.hist(err, bins=50)
            ax.set_xlabel("Predicted - True")
            ax.set_ylabel("Count")
            suffix = "" if iepoch is None else f"_epoch{iepoch}"
            ax.set_title(f"{names[ihead]} error{suffix}")
            path = os.path.join(self.out_dir, f"errhist_{names[ihead]}{suffix}.png")
            fig.tight_layout()
            fig.savefig(path, dpi=100)
            plt.close(fig)
            paths.append(path)
        return paths

    # ---- 2-D density + conditional mean (reference create_plot_global) ----

    def create_plot_global(
        self,
        true_values: List[np.ndarray],
        predicted_values: List[np.ndarray],
        output_names: Optional[Sequence[str]] = None,
    ) -> List[str]:
        names = list(output_names or self.head_names)
        paths = []
        for ihead in range(len(true_values)):
            t = np.asarray(true_values[ihead]).reshape(-1)
            p = np.asarray(predicted_values[ihead]).reshape(-1)
            fig, axes = plt.subplots(1, 3, figsize=(13, 4))
            if t.size:
                h, xe, ye = np.histogram2d(t, p, bins=50)
                xc = 0.5 * (xe[:-1] + xe[1:])
                yc = 0.5 * (ye[:-1] + ye[1:])
                hmax = h.max() if h.max() > 0 else 1.0
                axes[0].contourf(xc, yc, (h / hmax).T, levels=10)
                # conditional mean error per true-value bin
                bin_ids = np.clip(np.digitize(t, xe) - 1, 0, len(xc) - 1)
                cond_mean = np.full(len(xc), np.nan)
                for b in range(len(xc)):
                    sel = bin_ids == b
                    if sel.any():
                        cond_mean[b] = (p[sel] - t[sel]).mean()
                axes[1].plot(xc, cond_mean)
                axes[2].hist(p - t, bins=50, density=True)
            axes[0].set_title(f"{names[ihead]} density")
            axes[1].set_title("conditional mean error")
            axes[2].set_title("error pdf")
            path = os.path.join(self.out_dir, f"global_{names[ihead]}.png")
            fig.tight_layout()
            fig.savefig(path, dpi=100)
            plt.close(fig)
            paths.append(path)
        return paths

    # ---- vector parity grid (reference create_parity_plot_vector,
    # hydragnn/postprocess/visualizer.py:467-516) ----

    def create_parity_plot_vector(
        self,
        varname: str,
        true_values: np.ndarray,
        predicted_values: np.ndarray,
        head_dim: int,
        iepoch: Optional[int] = None,
    ) -> str:
        """Per-component parity scatters for a vector head: one panel per
        component in a near-square grid."""
        t = np.asarray(true_values).reshape(-1, head_dim)
        p = np.asarray(predicted_values).reshape(-1, head_dim)
        nrow = int(np.floor(np.sqrt(head_dim))) or 1
        ncol = int(np.ceil(head_dim / nrow))
        fig, axs = plt.subplots(nrow, ncol, figsize=(ncol * 4, nrow * 4), squeeze=False)
        axs = axs.flatten()
        markers = ["o", "s", "d"]
        for ic in range(head_dim):
            self._parity_panel(
                axs[ic], t[:, ic], p[:, ic],
                marker=markers[ic % len(markers)], title=f"comp:{ic}",
            )
        for iext in range(head_dim, axs.size):
            axs[iext].axis("off")
        suffix = "" if iepoch is None else f"_epoch{iepoch}"
        path = os.path.join(self.out_dir, f"vector_{varname}{suffix}.png")
        fig.tight_layout()
        fig.savefig(path, dpi=100)
        plt.close(fig)
        return path

    # ---- per-node error histograms (reference
    # create_error_histogram_per_node, visualizer.py:387-466) ----

    def create_error_histogram_per_node(
        self,
        varname: str,
        true_values: np.ndarray,
        predicted_values: np.ndarray,
        iepoch: Optional[int] = None,
    ) -> Optional[str]:
        """Error PDF per node site for fixed-size graphs (the LSMS
        multihead diagnostic): inputs [num_samples, num_nodes], one panel
        per node plus a per-sample SUM panel and a per-node
        summed-over-samples panel."""
        t = np.asarray(true_values)
        p = np.asarray(predicted_values)
        if t.ndim != 2 or t.shape[1] == 1:
            return None
        n_nodes = t.shape[1]
        nrow = int(np.floor(np.sqrt(n_nodes + 2))) or 1
        ncol = int(np.ceil((n_nodes + 2) / nrow))
        fig, axs = plt.subplots(
            nrow, ncol, figsize=(ncol * 3.5, nrow * 3.2), squeeze=False
        )
        axs = axs.flatten()

        for inode in range(n_nodes):
            self._errpdf_panel(
                axs[inode], p[:, inode] - t[:, inode], f"node:{inode}"
            )
        self._errpdf_panel(axs[n_nodes], p.sum(axis=1) - t.sum(axis=1), "SUM")
        self._errpdf_panel(
            axs[n_nodes + 1],
            p.sum(axis=0) - t.sum(axis=0),
            f"SMP_Mean4sites:0-{n_nodes}",
        )
        for iext in range(n_nodes + 2, axs.size):
            axs[iext].axis("off")
        suffix = "" if iepoch is None else f"_epoch{iepoch}"
        path = os.path.join(self.out_dir, f"errhist_pernode_{varname}{suffix}.png")
        fig.tight_layout()
        fig.savefig(path, dpi=100)
        plt.close(fig)
        return path

    # ---- per-node vector parity grid (reference
    # create_parity_plot_per_node_vector, visualizer.py:519-613) ----

    def create_parity_plot_per_node_vector(
        self,
        varname: str,
        true_values: np.ndarray,
        predicted_values: np.ndarray,
        head_dim: int = 3,
        iepoch: Optional[int] = None,
    ) -> Optional[str]:
        """Per-node parity panels for a nodal VECTOR head on fixed-size
        graphs: inputs [num_samples, num_nodes * head_dim]; one panel per
        node with a marker per component, plus per-sample SUM and
        per-node summed-over-samples panels."""
        t = np.asarray(true_values)
        p = np.asarray(predicted_values)
        if t.ndim != 2 or t.shape[1] % head_dim:
            return None
        s = t.shape[0]
        t = t.reshape(s, -1, head_dim)
        p = p.reshape(s, -1, head_dim)
        n_nodes = t.shape[1]
        markers = ["o", "s", "d"]
        nrow = int(np.floor(np.sqrt(n_nodes + 2))) or 1
        ncol = int(np.ceil((n_nodes + 2) / nrow))
        fig, axs = plt.subplots(nrow, ncol, figsize=(ncol * 3, nrow * 3), squeeze=False)
        axs = axs.flatten()
        for inode in range(n_nodes):
            for ic in range(head_dim):
                self._parity_panel(
                    axs[inode], t[:, inode, ic], p[:, inode, ic],
                    marker=markers[ic % len(markers)], title=f"node:{inode}", s=6,
                )
        for ic in range(head_dim):
            self._parity_panel(
                axs[n_nodes], t[:, :, ic].sum(1), p[:, :, ic].sum(1),
                marker=markers[ic % len(markers)], title="SUM", s=40,
            )
            self._parity_panel(
                axs[n_nodes + 1], t[:, :, ic].sum(0), p[:, :, ic].sum(0),
                marker=markers[ic % len(markers)],
                title=f"SMP_Mean4sites:0-{n_nodes}", s=40,
            )
        for iext in range(n_nodes + 2, axs.size):
            axs[iext].axis("off")
        suffix = "" if iepoch is None else f"_epoch{iepoch}"
        path = os.path.join(self.out_dir, f"parity_pernode_{varname}{suffix}.png")
        fig.tight_layout()
        fig.savefig(path, dpi=100)
        plt.close(fig)
        return path

    # ---- global analysis (reference create_plot_global_analysis,
    # visualizer.py:134-280: scalar 1x3 / vector 3x3 with length & sum
    # rows and conditional-mean-abs-error overlays) ----

    def create_plot_global_analysis(
        self,
        varname: str,
        true_values: np.ndarray,
        predicted_values: np.ndarray,
    ) -> str:
        t = np.asarray(true_values)
        p = np.asarray(predicted_values)
        if t.ndim == 1:
            t, p = t[:, None], p[:, None]
        if t.shape[1] == 1:
            fig, axs = plt.subplots(1, 3, figsize=(15, 4.5))
            self._parity_panel(axs[0], t[:, 0], p[:, 0], title="Scalar output")
            self._condmean_panel(axs[1], t[:, 0], p[:, 0])
            self._errpdf_panel(axs[2], p[:, 0] - t[:, 0], "Scalar output: error PDF")
        else:
            fig, axs = plt.subplots(3, 3, figsize=(15, 13))
            vlen_t = np.linalg.norm(t, axis=1)
            vlen_p = np.linalg.norm(p, axis=1)
            vsum_t, vsum_p = t.sum(axis=1), p.sum(axis=1)
            w = 1.0 / np.sqrt(t.shape[1])
            for col, (tt, pp, label, weight) in enumerate(
                (
                    (vlen_t, vlen_p, "length", w),
                    (vsum_t, vsum_p, "sum", w),
                    (t.reshape(-1), p.reshape(-1), "components", 1.0),
                )
            ):
                self._parity_panel(axs[0, col], tt, pp, title=f"Vector output: {label}")
                self._condmean_panel(axs[1, col], tt, pp, weight=weight)
                self._errpdf_panel(axs[2, col], pp - tt, f"{label}: error PDF")
        path = os.path.join(self.out_dir, f"global_analysis_{varname}.png")
        fig.tight_layout()
        fig.savefig(path, dpi=100)
        plt.close(fig)
        return path

    # ---- the full reference artifact set for one test pass ----

    def create_reference_plot_suite(
        self,
        true_values: List[np.ndarray],
        predicted_values: List[np.ndarray],
        output_types: Sequence[str],
        nodes_per_graph: Optional[Sequence[int]] = None,
        iepoch: Optional[int] = None,
    ) -> List[str]:
        """Dispatch every applicable reference plot family per head:
        vector parity grids for dim>1 heads; per-node error histograms /
        per-node vector grids for nodal heads when all test graphs share
        one size (the LSMS use case — per-node panels are meaningless for
        ragged graph sizes); global-analysis figures for every head."""
        paths: List[str] = []
        fixed = (
            nodes_per_graph is not None
            and len(set(int(n) for n in nodes_per_graph)) == 1
        )
        n_nodes = int(nodes_per_graph[0]) if fixed else 0
        # one panel per node only makes sense for small fixed cells (the
        # LSMS 32-atom diagnostic); a supercell dataset would render a
        # thousand-panel figure (or exceed matplotlib's pixel limit)
        if n_nodes > 64:
            fixed = False
        for ihead, name in enumerate(self.head_names[: len(true_values)]):
            t = np.asarray(true_values[ihead])
            p = np.asarray(predicted_values[ihead])
            dim = t.shape[1] if t.ndim == 2 else 1
            if dim > 1:
                paths.append(
                    self.create_parity_plot_vector(name, t, p, dim, iepoch)
                )
            if output_types[ihead] == "node" and fixed and n_nodes > 1:
                # rows arrive node-major per graph: [S * n_nodes, dim]
                per_node_t = t.reshape(-1, n_nodes * dim)
                per_node_p = p.reshape(-1, n_nodes * dim)
                if dim == 1:
                    r = self.create_error_histogram_per_node(
                        name, per_node_t, per_node_p, iepoch
                    )
                else:
                    r = self.create_parity_plot_per_node_vector(
                        name, per_node_t, per_node_p, dim, iepoch
                    )
                if r:
                    paths.append(r)
            paths.append(self.create_plot_global_analysis(name, t, p))
        return paths

    # ---- shared panel helpers ----

    def _parity_panel(self, ax, t, p, marker="o", title="", s=6):
        t = np.asarray(t).reshape(-1)
        p = np.asarray(p).reshape(-1)
        ax.scatter(t, p, s=s, alpha=0.5, marker=marker, edgecolors="none")
        if t.size:
            lo = float(min(t.min(), p.min()))
            hi = float(max(t.max(), p.max()))
            # panels drawn in several calls (one per vector component)
            # must keep limits covering EVERY component, not the last
            prev = getattr(ax, "_hgt_parity_lim", None)
            if prev is not None:
                lo, hi = min(lo, prev[0]), max(hi, prev[1])
            ax._hgt_parity_lim = (lo, hi)
            ax.plot([lo, hi], [lo, hi], "k--", linewidth=1)
            ax.set_xlim(lo, hi)
            ax.set_ylim(lo, hi)
        if title:
            ax.set_title(title)

    def _condmean_panel(self, ax, t, p, weight=1.0, bins=40):
        """Conditional mean ABSOLUTE error vs the true value (reference
        __err_condmean, visualizer.py:100-132)."""
        t = np.asarray(t).reshape(-1)
        p = np.asarray(p).reshape(-1)
        if t.size:
            edges = np.histogram_bin_edges(t, bins=bins)
            ids = np.clip(np.digitize(t, edges) - 1, 0, bins - 1)
            err = np.abs(p - t) * weight
            sums = np.bincount(ids, weights=err, minlength=bins)
            cnts = np.bincount(ids, minlength=bins)
            centers = 0.5 * (edges[:-1] + edges[1:])
            good = cnts > 0
            ax.plot(centers[good], sums[good] / cnts[good], "ro", markersize=3)
        ax.set_title("Conditional mean abs. error")
        ax.set_xlabel("True")
        ax.set_ylabel("abs. error")

    def _errpdf_panel(self, ax, err, title):
        err = np.asarray(err).reshape(-1)
        if err.size:
            hist, edges = np.histogram(err, bins=40, density=True)
            ax.plot(0.5 * (edges[:-1] + edges[1:]), hist, "ro", markersize=3)
        ax.set_title(title)
        ax.set_xlabel("Error")
        ax.set_ylabel("PDF")

    # ---- loss-history curves (reference plot_history) ----

    def plot_history(self, history: Dict[str, list]) -> str:
        fig, ax = plt.subplots(figsize=(6, 4))
        for key in ("train_loss", "val_loss", "test_loss"):
            if history.get(key):
                ax.plot(history[key], label=key)
        ax.set_xlabel("Epoch")
        ax.set_ylabel("Loss")
        ax.set_yscale("log")
        ax.legend()
        path = os.path.join(self.out_dir, "history.png")
        fig.tight_layout()
        fig.savefig(path, dpi=100)
        plt.close(fig)
        return path

    # ---- node-count histogram (reference num_nodes_plot) ----

    def num_nodes_plot(self, num_nodes_list: Sequence[int]) -> str:
        fig, ax = plt.subplots(figsize=(5, 4))
        ax.hist(np.asarray(num_nodes_list), bins=30)
        ax.set_xlabel("Nodes per graph")
        ax.set_ylabel("Count")
        path = os.path.join(self.out_dir, "num_nodes.png")
        fig.tight_layout()
        fig.savefig(path, dpi=100)
        plt.close(fig)
        return path
