"""Native (C++) core loader: builds libhgc.so on first use, ctypes-binds it.

The library provides the container read hot path (mmap, threaded batched
row-gather, node-local shm copy) — the TPU-native stand-in for the ADIOS2
C++ engine the reference depends on (SURVEY.md §2.9). A pure-numpy
fallback keeps every feature working where a compiler is unavailable;
``HAVE_NATIVE`` reports which path is active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRCS = [
    os.path.join(_REPO_ROOT, "native", "hgc.cpp"),
    os.path.join(_REPO_ROOT, "native", "radius.cpp"),
]
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")

# graftsync: thread-safe=idempotent memoization; a racing _load() builds an equivalent CDLL and the GIL-atomic store keeps either
_lib: Optional[ctypes.CDLL] = None
# graftsync: thread-safe=GIL-atomic one-way False->True latch; sticky: never retry the compile per-call (hot path)
_LOAD_FAILED = False
# graftsync: thread-safe=GIL-atomic one-way False->True latch set after _lib
HAVE_NATIVE = False


def _build_library() -> Optional[str]:
    so_path = os.path.join(_BUILD_DIR, "libhgc.so")
    if os.path.exists(so_path) and all(
        os.path.getmtime(so_path) >= os.path.getmtime(src) for src in _SRCS
    ):
        return so_path
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # Build into a temp name + atomic rename: concurrent processes (pytest
    # workers, multi-process training) race to compile safely.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD_DIR)
    os.close(fd)
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        "-Werror=return-type",  # missing return in C++ is silent UB
        *_SRCS, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so_path)
        return so_path
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired, OSError):
        if os.path.exists(tmp):
            os.unlink(tmp)
        return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _LOAD_FAILED, HAVE_NATIVE
    if _lib is not None:
        return _lib
    if _LOAD_FAILED:
        return None
    so_path = _build_library()
    if so_path is None:
        _LOAD_FAILED = True
        return None
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        _LOAD_FAILED = True
        return None
    lib.hgc_mmap.restype = ctypes.c_void_p
    lib.hgc_mmap.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64)]
    lib.hgc_munmap.restype = None
    lib.hgc_munmap.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.hgc_gather.restype = None
    lib.hgc_gather.argtypes = [
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int,
    ]
    lib.hgc_copy_file.restype = ctypes.c_int
    lib.hgc_copy_file.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.rg_pairs.restype = ctypes.c_int64
    lib.rg_pairs.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.c_double,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64, ctypes.c_int,
    ]
    _lib = lib
    HAVE_NATIVE = True
    return lib


def native_radius_pairs(src_pos, dst_pos, r):
    """All (src, dst, dist) pairs with dist <= r via the C++ cell-list
    kernel; returns None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    src = np.ascontiguousarray(src_pos, dtype=np.float64)
    dst = np.ascontiguousarray(dst_pos, dtype=np.float64)
    n_src, n_dst = src.shape[0], dst.shape[0]
    capacity = max(1024, n_dst * 48)
    for _ in range(2):
        s = np.empty(capacity, dtype=np.int64)
        t = np.empty(capacity, dtype=np.int64)
        d = np.empty(capacity, dtype=np.float64)
        total = _rg_pairs_raw(lib, src, dst, n_src, n_dst, r, s, t, d, capacity)
        if total < 0:
            return None  # dense grid unsuited (outliers/sparse cloud)
        if total <= capacity:
            return s[:total], t[:total], d[:total]
        capacity = int(total)
    raise RuntimeError("rg_pairs capacity retry failed")  # pragma: no cover


def _rg_pairs_raw(lib, src, dst, n_src, n_dst, r, s, t, d, capacity):
    return lib.rg_pairs(
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ctypes.c_int64(n_src),
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ctypes.c_int64(n_dst),
            ctypes.c_double(float(r)),
            s.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            t.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            d.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ctypes.c_int64(capacity),
            ctypes.c_int(0),
        )


class MappedFile:
    """A read-only mmap of one field file (native when available, else
    np.memmap). Exposes ``.view(dtype, row_shape)`` as a numpy array over
    the mapping (zero-copy) and threaded ``gather`` into a packed buffer."""

    def __init__(self, path: str):
        self.path = path
        self._native_base = None
        self._size = os.path.getsize(path)
        if self._size == 0:
            # legitimately empty field (e.g. no sample has edges): mmap of
            # a 0-byte file is invalid, an empty view is fine
            self._np = np.zeros(0, dtype=np.uint8)
            return
        lib = _load()
        if lib is not None:
            size = ctypes.c_int64(0)
            base = lib.hgc_mmap(path.encode(), ctypes.byref(size))
            if base:
                self._native_base = base
                self._size = size.value
        if self._native_base is None:
            self._np = np.memmap(path, dtype=np.uint8, mode="r")
            self._size = self._np.shape[0]
        else:
            # numpy view over the native mapping for zero-copy reads
            buf = (ctypes.c_char * self._size).from_address(self._native_base)
            self._np = np.frombuffer(buf, dtype=np.uint8)

    @property
    def nbytes(self) -> int:
        return self._size

    def view(self, dtype, row_shape) -> np.ndarray:
        itemsize = np.dtype(dtype).itemsize
        row_elems = int(np.prod(row_shape)) if row_shape else 1
        n_rows = self._size // (itemsize * row_elems)
        return self._np[: n_rows * itemsize * row_elems].view(dtype).reshape(
            (n_rows,) + tuple(row_shape)
        )

    def gather(
        self,
        row_bytes: int,
        src_off: np.ndarray,
        cnt: np.ndarray,
        out_off: np.ndarray,
        out: np.ndarray,
        n_threads: int = 0,
    ) -> None:
        """Copy ragged row ranges into ``out`` (uint8, C-contiguous)."""
        lib = _load()
        n = len(src_off)
        if lib is not None and self._native_base is not None:
            so = np.ascontiguousarray(src_off, dtype=np.int64)
            ct = np.ascontiguousarray(cnt, dtype=np.int64)
            oo = np.ascontiguousarray(out_off, dtype=np.int64)
            lib.hgc_gather(
                ctypes.c_void_p(self._native_base),
                ctypes.c_int64(row_bytes),
                so.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                ct.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                oo.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                ctypes.c_int64(n),
                out.ctypes.data_as(ctypes.c_void_p),
                ctypes.c_int(n_threads),
            )
            return
        flat = self._np
        for k in range(n):
            s = src_off[k] * row_bytes
            d = out_off[k] * row_bytes
            nb = cnt[k] * row_bytes
            out.reshape(-1)[d : d + nb] = flat[s : s + nb]

    def close(self) -> None:
        lib = _lib
        if self._native_base is not None and lib is not None:
            self._np = None
            lib.hgc_munmap(ctypes.c_void_p(self._native_base), ctypes.c_int64(self._size))
            self._native_base = None


def copy_to_shm(src_path: str, shm_dir: str) -> str:
    """One-copy node-local preload: copy ``src_path`` into ``shm_dir``
    (typically under /dev/shm) with an atomic rename so exactly one
    process on the host does the copy and peers reuse it (the
    AdiosDataset shmem mode, reference adiosdataset.py:266-314).

    An existing copy is reused only when size matches AND it is at least
    as new as the source — a regenerated dataset with identical sizes must
    not serve stale bytes."""
    os.makedirs(shm_dir, exist_ok=True)
    dst = os.path.join(shm_dir, os.path.basename(src_path))
    if (
        os.path.exists(dst)
        and os.path.getsize(dst) == os.path.getsize(src_path)
        and os.path.getmtime(dst) >= os.path.getmtime(src_path)
    ):
        return dst
    fd, tmp = tempfile.mkstemp(dir=shm_dir)
    os.close(fd)
    lib = _load()
    ok = False
    if lib is not None:
        ok = lib.hgc_copy_file(src_path.encode(), tmp.encode()) == 0
    if not ok:
        import shutil

        shutil.copyfile(src_path, tmp)
    os.replace(tmp, dst)
    return dst
