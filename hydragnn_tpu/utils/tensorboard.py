"""Tensorboard scalar writer (rank-0), with a jsonl fallback.

The reference creates a rank-0 SummaryWriter and logs total/per-task
losses each epoch (reference: hydragnn/utils/model.py:57-61 and
train_validate_test.py:130-137 — upstream has a bug where the writer is
never returned, so scalars are silently skipped; here it works).
When the tensorboard package is unavailable the writer degrades to a
no-op (the epoch metrics are independently persisted to metrics.jsonl
by the train loop).
"""

from __future__ import annotations

import os
from typing import Optional


class _NullWriter:
    def add_scalar(self, tag: str, value, step: int) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


def get_summary_writer(log_name: str, log_dir: str = "./logs/"):
    """Rank-0 SummaryWriter under ``<log_dir>/<log_name>``; null writer on
    other ranks or when tensorboard is not importable."""
    import jax

    if jax.process_index() != 0:
        return _NullWriter()
    try:
        from torch.utils.tensorboard import SummaryWriter
    except ImportError:
        return _NullWriter()
    path = os.path.join(log_dir, log_name)
    os.makedirs(path, exist_ok=True)
    return SummaryWriter(log_dir=path)
