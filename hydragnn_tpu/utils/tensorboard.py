"""Tensorboard scalar writer (rank-0), with a jsonl fallback.

The reference creates a rank-0 SummaryWriter and logs total/per-task
losses each epoch (reference: hydragnn/utils/model.py:57-61 and
train_validate_test.py:130-137 — upstream has a bug where the writer is
never returned, so scalars are silently skipped; here it works).
When the tensorboard package is unavailable the writer degrades to a
no-op (the epoch metrics are independently persisted to metrics.jsonl
by the train loop).
"""

from __future__ import annotations

import os
from typing import Optional


class _NullWriter:
    def add_scalar(self, tag: str, value, step: int) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


def write_scalar_dict(writer, scalars: dict, step: int, prefix: str = "") -> int:
    """Flush a (possibly nested) dict of numbers to ``writer`` as
    ``prefix/key/subkey`` scalar tags; non-numeric leaves are skipped.
    Returns the number of scalars written. The serving metrics surface
    (hydragnn_tpu/serve/metrics.py:ServeMetrics.to_tensorboard) exports
    through this, so serve dashboards ride the same rank-0 writer
    plumbing as training losses."""
    import numbers

    written = 0
    for key, value in scalars.items():
        tag = f"{prefix}/{key}" if prefix else str(key)
        if isinstance(value, dict):
            written += write_scalar_dict(writer, value, step, prefix=tag)
        elif isinstance(value, bool):
            continue
        # numbers.Real also admits numpy scalar floats/ints — the
        # metrics-registry snapshots (hydragnn_tpu/obs) carry those
        elif isinstance(value, numbers.Real):
            writer.add_scalar(tag, float(value), step)
            written += 1
    return written


def get_summary_writer(log_name: str, log_dir: str = "./logs/"):
    """Rank-0 SummaryWriter under ``<log_dir>/<log_name>``; null writer on
    other ranks or when tensorboard is not importable."""
    import jax

    if jax.process_index() != 0:
        return _NullWriter()
    try:
        from torch.utils.tensorboard import SummaryWriter
    except ImportError:
        return _NullWriter()
    path = os.path.join(log_dir, log_name)
    os.makedirs(path, exist_ok=True)
    return SummaryWriter(log_dir=path)
