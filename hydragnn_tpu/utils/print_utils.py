"""Verbosity-leveled, process-aware printing and logging.

Mirrors the reference's scheme (reference:
hydragnn/utils/print_utils.py:20-104): 5 verbosity levels (0 silent ->
4 all-processes), process-0 filtering, and a per-run file+console logger.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Iterable, Optional

VERBOSITY_LEVELS = (0, 1, 2, 3, 4)
# graftsync: thread-safe=idempotent memoization; a racing setup builds an equivalent logger and the GIL-atomic store keeps either
_logger: Optional[logging.Logger] = None


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def print_distributed(verbosity_level: int, *args) -> None:
    if verbosity_level not in VERBOSITY_LEVELS:
        raise ValueError(f"Unknown verbosity level: {verbosity_level}")
    # Levels 3 and 4 print on every process (reference print_utils.py maps
    # both to print_all_processes); 1-2 print on process 0 only.
    if verbosity_level >= 3 or (verbosity_level > 0 and _process_index() == 0):
        print(f"[{_process_index()}]", *args)


def iterate_tqdm(iterable: Iterable, verbosity_level: int, **kwargs):
    """Wrap with tqdm at verbosity >= 2 on process 0 (reference:
    print_utils.py:56-60); falls back to the plain iterable."""
    if verbosity_level >= 2 and _process_index() == 0:
        try:
            from tqdm import tqdm

            return tqdm(iterable, **kwargs)
        except Exception:
            return iterable
    return iterable


def setup_log(prefix: str, log_dir: str = "./logs") -> logging.Logger:
    """File+console logger under ``log_dir/<prefix>/run.log`` (reference:
    print_utils.py:63-88); every process writes its own file suffix."""
    global _logger
    path = os.path.join(log_dir, prefix)
    os.makedirs(path, exist_ok=True)
    rank = _process_index()
    logger = logging.getLogger(f"hydragnn_tpu.{prefix}")
    logger.setLevel(logging.INFO)
    logger.handlers.clear()
    fh = logging.FileHandler(os.path.join(path, f"run{'' if rank == 0 else rank}.log"))
    fh.setFormatter(logging.Formatter("%(asctime)s %(message)s"))
    logger.addHandler(fh)
    if rank == 0:
        sh = logging.StreamHandler(sys.stdout)
        logger.addHandler(sh)
    _logger = logger
    return logger


def log(*args) -> None:
    msg = " ".join(str(a) for a in args)
    if _logger is not None:
        _logger.info(msg)
    elif _process_index() == 0:
        print(msg)


def print_peak_memory(verbosity_level: int = 2, prefix: str = "") -> Optional[int]:
    """Device peak/in-use memory print (reference: print_peak_memory,
    hydragnn/utils/distributed.py:236-243, which reads
    torch.cuda.max_memory_allocated). TPU/GPU backends expose
    ``Device.memory_stats()``; CPU returns None silently."""
    import jax

    dev = jax.local_devices()[0]
    stats = None
    try:
        stats = dev.memory_stats()
    except (NotImplementedError, RuntimeError, AttributeError):
        pass
    if not stats:
        return None
    peak = stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))
    print_distributed(
        verbosity_level, f"{prefix} peak device memory: {peak / 1e6:.1f} MB"
    )
    return int(peak)


def print_model(params, verbosity_level: int = 2) -> int:
    """Per-parameter shape/size table + total (reference:
    hydragnn/utils/model.py:112-120 print_model). ``params`` is a model
    params pytree (e.g. ``state.params``). Returns total param count."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    total = 0
    for path, leaf in flat:
        name = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        size = int(getattr(leaf, "size", 0))
        total += size
        print_distributed(verbosity_level, f"{name}: {tuple(leaf.shape)} {size}")
    print_distributed(verbosity_level, f"Total number of parameters: {total}")
    return total
