"""Checkpoint / resume.

The reference saves one ``.pk`` file holding model+optimizer state dicts,
written by rank 0 (after ZeRO consolidation), and supports config-driven
continuation (reference: hydragnn/utils/model.py:41-86, config keys
``Training.continue``/``startfrom``). Two TPU-native backends behind the
same single-name "continue" UX:

  - ``msgpack`` (default single-process): the whole ``TrainState``
    pytree (params, batch_stats, optimizer state, step, rng) in one
    flax-msgpack file; process 0 writes, every process reads. Sharded
    arrays are consolidated to host first (the ZeRO-consolidation
    analog).
  - ``orbax`` (default multi-process): Orbax sharded checkpoint — every
    host writes its addressable shards in parallel and restore places
    shards directly onto the target sharding, so pod-scale ZeRO-1 state
    never funnels through one host.

``load_existing_model`` auto-detects which backend wrote a run.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization

#: On-disk checkpoint format generation, stamped into the meta sidecar
#: and every pod-shard manifest/COMMIT (resilience/podckpt.py). History:
#: 1 = the original UNVERSIONED layout (absent stamp == 1; always
#: accepted), 2 = adds the stamp itself + the pod sharded-generation
#: layout. Readers accept <= CURRENT and refuse newer with a TYPED
#: error — a checkpoint from a future build must fail loudly, not as
#: an incidental KeyError three frames deep.
CHECKPOINT_FORMAT_VERSION = 2


class CheckpointFormatError(RuntimeError):
    """The checkpoint on disk was written by a NEWER format_version
    than this build understands. Typed so supervisors/CLIs can tell an
    upgrade refusal (fail fast, don't retry) from bit-rot (fall back a
    version)."""


def _checkpoint_path(log_name: str, path: str = "./logs/") -> str:
    return os.path.join(path, log_name, f"{log_name}.mp")


def _to_host(x: Any) -> np.ndarray:
    """Fetch one leaf to host. Leaves sharded across non-addressable
    devices (multi-host ZeRO-1 optimizer state) are first all-gathered to
    a replicated layout with an XLA collective — the ZeRO consolidation
    step (reference: consolidate_state_dict, model.py:44-45)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = x.sharding.mesh
        x = jax.jit(lambda a: a, out_shardings=NamedSharding(mesh, PartitionSpec()))(x)
    return np.asarray(x)


def _orbax_dir(log_name: str, path: str) -> str:
    return os.path.abspath(os.path.join(path, log_name, f"{log_name}.orbax"))


def _sha256_hex(data: bytes) -> str:
    import hashlib

    return hashlib.sha256(data).hexdigest()


def _versioned_path(log_name: str, path: str, step: int) -> str:
    return os.path.join(path, log_name, f"{log_name}.step{step:010d}.mp")


def list_versioned_checkpoints(log_name: str, path: str = "./logs/"):
    """Retained keep-last-K checkpoint versions, NEWEST first, as
    ``[(step, path)]``."""
    import glob
    import re

    out = []
    pat = re.compile(re.escape(log_name) + r"\.step(\d+)\.mp$")
    for p in glob.glob(os.path.join(path, log_name, f"{log_name}.step*.mp")):
        m = pat.search(os.path.basename(p))
        if m:
            out.append((int(m.group(1)), p))
    return sorted(out, reverse=True)


def validate_checkpoint_file(ckpt_path: str) -> bool:
    """Integrity check for one msgpack checkpoint file: the sha256
    sidecar when present (bit-rot), else parse-validation (a truncated
    msgpack stream — torn write, SIGKILL mid-checkpoint — fails to
    restore). Missing file -> False."""
    if not os.path.isfile(ckpt_path):
        return False
    try:
        with open(ckpt_path, "rb") as f:
            data = f.read()
    except OSError:
        return False
    sidecar = ckpt_path + ".sha256"
    if os.path.exists(sidecar):
        try:
            with open(sidecar) as f:
                want = f.read().strip()
            return _sha256_hex(data) == want
        except OSError:
            return False
    try:
        serialization.msgpack_restore(data)
        return True
    except Exception:
        return False


def _atomic_write(final_path: str, data: bytes) -> None:
    # pid-unique tmp: concurrent simulated pod hosts (resilience/
    # podckpt.py) write the SAME shared targets (latest pointer, meta
    # sidecar); a fixed tmp name would let writer B's os.replace race
    # writer A's and raise on the vanished tmp. Unique tmps make the
    # pair of writes last-writer-wins, each replace still atomic.
    tmp = f"{final_path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, final_path)


def _prune_versions(log_name: str, path: str, keep_last: int) -> None:
    for _, p in list_versioned_checkpoints(log_name, path)[keep_last:]:
        for victim in (p, p + ".sha256"):
            try:
                os.remove(victim)
            except OSError:
                pass


def save_model(
    state: Any,
    log_name: str,
    path: str = "./logs/",
    verbosity: int = 0,
    backend: str = "auto",
    keep_last: Optional[int] = None,
) -> str:
    """Write the TrainState under ``<path>/<log_name>/`` (reference:
    rank-0 save, model.py:41-54). ``backend``: "msgpack", "orbax", or
    "auto" (orbax when multi-process — parallel sharded writes).

    ``keep_last=K`` (msgpack backend; config
    ``Training.checkpoint_keep_last``) additionally retains the K most
    recent step-versioned copies (``<log_name>.step<N>.mp`` + sha256
    sidecar, pruned beyond K). Restore validates integrity and falls
    back down the retained set (:func:`load_existing_model`), so a
    checkpoint torn by a crash mid-write never strands the run."""
    if backend == "auto":
        backend = "orbax" if jax.process_count() > 1 else "msgpack"
    if backend == "orbax":
        import orbax.checkpoint as ocp

        ckpt_dir = _orbax_dir(log_name, path)
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(ckpt_dir, state, force=True)
        return ckpt_dir
    ckpt_path = _checkpoint_path(log_name, path)
    host_state = jax.tree_util.tree_map(_to_host, state)
    if jax.process_index() == 0:
        os.makedirs(os.path.dirname(ckpt_path), exist_ok=True)
        data = serialization.to_bytes(host_state)
        if keep_last:
            step = int(np.asarray(host_state.step)) if hasattr(host_state, "step") else 0
            vp = _versioned_path(log_name, path, step)
            _atomic_write(vp, data)
            _atomic_write((vp + ".sha256"), _sha256_hex(data).encode())
            _prune_versions(log_name, path, int(keep_last))
        # deterministic torn-write fault injection (docs/RESILIENCE.md):
        # under HYDRAGNN_INJECT_KILL_CHECKPOINT the K-th save leaves the
        # latest-pointer file truncated and SIGKILLs the process — the
        # scenario the validation + versioned fallback above recovers
        from hydragnn_tpu.resilience.inject import maybe_kill_checkpoint

        maybe_kill_checkpoint(ckpt_path, data)
        # atomic replace: a crash mid-write (the exact scenario per-epoch
        # checkpointing exists for) must not destroy the previous good file
        _atomic_write(ckpt_path, data)
    return ckpt_path


def _restore_bytes_into(state: Any, data: bytes) -> Any:
    restored = serialization.from_bytes(state, data)

    # preserve the target's placement: leaves restored as host arrays go
    # back onto the sharding the caller's state carries (ZeRO-1 layouts
    # survive a msgpack resume)
    def _place(tgt, val):
        if isinstance(tgt, jax.Array) and hasattr(tgt, "sharding"):
            return jax.device_put(val, tgt.sharding)
        return val

    return jax.tree_util.tree_map(_place, state, restored)


def load_existing_model(
    state: Any, log_name: str, path: str = "./logs/"
) -> Any:
    """Restore a TrainState from the run's checkpoint. ``state`` is the
    freshly-constructed target (its pytree structure = the schema; with
    sharded leaves, orbax restores shards onto their shardings directly).
    The backend that wrote the run is auto-detected.

    msgpack restores validate integrity first and FALL BACK down the
    retained version set (``save_model(keep_last=...)``): the latest
    pointer file is preferred; if it is truncated/corrupt (torn write —
    e.g. SIGKILL mid-checkpoint), the newest valid ``.step<N>.mp``
    version is restored instead, with a loud warning naming what was
    rejected. Only when every candidate fails does the restore raise.

    Pod-sharded runs (resilience/podckpt.py) are probed FIRST: when the
    run dir holds committed generations, the newest valid one is
    reassembled — elastically, onto whatever layout ``state`` carries —
    and the meta sidecar is reconciled to the committed generation (a
    host may have written a later meta for a generation that never
    committed). Only if every pod generation fails does the restore
    fall through to the msgpack chain below."""
    _check_meta_format(log_name, path)
    run_dir = os.path.join(path, log_name)
    if os.path.isdir(os.path.join(run_dir, "podckpt")):
        from hydragnn_tpu.resilience import podckpt

        restored, info = podckpt.restore_pod_checkpoint(state, run_dir)
        if info is not None:
            reconcile_pod_meta(log_name, path, info)
            return restored
    orbax_dir = _orbax_dir(log_name, path)
    if os.path.isdir(orbax_dir):
        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as ckptr:
            target = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, state)
            return ckptr.restore(orbax_dir, target)
    ckpt_path = _checkpoint_path(log_name, path)
    versioned = [p for _, p in list_versioned_checkpoints(log_name, path)]
    if not versioned:
        # no retained versions: the historical single-file path, raising
        # naturally (FileNotFoundError / parse error) on a bad file
        with open(ckpt_path, "rb") as f:
            return _restore_bytes_into(state, f.read())
    rejected = []
    candidates = [ckpt_path] + [p for p in versioned if p != ckpt_path]
    for p in candidates:
        if not validate_checkpoint_file(p):
            rejected.append(p)
            continue
        with open(p, "rb") as f:
            data = f.read()
        try:
            restored = _restore_bytes_into(state, data)
        except Exception:
            rejected.append(p)
            continue
        if rejected:
            import warnings

            warnings.warn(
                f"checkpoint integrity: rejected {rejected} (truncated/"
                f"corrupt); restored the previous valid checkpoint {p}",
                RuntimeWarning,
                stacklevel=2,
            )
        return restored
    raise ValueError(
        f"no valid checkpoint for run {log_name!r} under {path!r}: "
        f"all candidates failed integrity validation: {rejected}"
    )


def save_train_meta(meta: dict, log_name: str, path: str = "./logs/") -> None:
    """Rank-0 JSON sidecar with host-side training-loop state (epoch,
    scheduler, early-stop counters, history) so a resumed run continues
    exactly where it left off. The reference restores only
    model+optimizer (SURVEY §5: resume "not epoch/scheduler/sampler
    state"); this closes that gap."""
    if jax.process_index() != 0:
        return
    import json

    meta = dict(meta)
    meta.setdefault("format_version", CHECKPOINT_FORMAT_VERSION)
    out_dir = os.path.join(path, log_name)
    os.makedirs(out_dir, exist_ok=True)
    _atomic_write(
        os.path.join(out_dir, f"{log_name}.meta.json"),
        json.dumps(meta).encode(),
    )


def _check_meta_format(log_name: str, path: str) -> None:
    """Refuse (typed) a meta sidecar stamped by a future format_version.
    An ABSENT stamp is the legacy layout (format 1) and is accepted —
    old runs must keep resuming under new builds."""
    meta = load_train_meta(log_name, path)
    if not meta:
        return
    fv = meta.get("format_version")
    if fv is not None and int(fv) > CHECKPOINT_FORMAT_VERSION:
        raise CheckpointFormatError(
            f"checkpoint meta for run {log_name!r} was written by "
            f"format_version {fv}; this build understands <= "
            f"{CHECKPOINT_FORMAT_VERSION}"
        )


def reconcile_pod_meta(log_name: str, path: str, info: dict) -> None:
    """Rewrite the meta sidecar to agree with the pod generation that
    actually COMMITTED. A host can write meta for epoch N and die
    before generation N commits (the commit marker is always last); a
    resume would then skip epoch N with generation N-1's weights.
    Truth lives in the COMMIT marker, so the sidecar follows it: epoch
    pinned to the committed gen, history truncated to match, early-stop
    state cleared (its counters described epochs being re-run)."""
    gen = int(info["gen"])
    meta = load_train_meta(log_name, path)
    if meta is None:
        meta = {}
    if int(meta.get("epoch", -1)) == gen and meta.get("early_stopped") is not True:
        return
    meta["epoch"] = gen
    if info.get("step") is not None:
        meta["step"] = int(info["step"])
    meta["early_stopped"] = False
    history = meta.get("history")
    if isinstance(history, dict):
        meta["history"] = {
            k: (v[:gen] if isinstance(v, list) else v) for k, v in history.items()
        }
    save_train_meta(meta, log_name, path)


def load_train_meta(log_name: str, path: str = "./logs/") -> Optional[dict]:
    import json

    p = os.path.join(path, log_name, f"{log_name}.meta.json")
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)


def load_existing_model_config(
    state: Any, training_config: dict, path: str = "./logs/"
) -> Any:
    """Config-driven continue (reference: model.py:64-67, keys
    ``Training.continue`` and ``Training.startfrom``)."""
    if "continue" in training_config and training_config["continue"] == 1:
        if "startfrom" not in training_config:
            raise ValueError("Training.continue=1 requires Training.startfrom")
        return load_existing_model(state, training_config["startfrom"], path)
    return state


def checkpoint_exists(log_name: str, path: str = "./logs/") -> bool:
    if (
        os.path.exists(_checkpoint_path(log_name, path))
        or os.path.isdir(_orbax_dir(log_name, path))
        or bool(list_versioned_checkpoints(log_name, path))
    ):
        return True
    if os.path.isdir(os.path.join(path, log_name, "podckpt")):
        from hydragnn_tpu.resilience import podckpt

        return bool(podckpt.list_committed_generations(os.path.join(path, log_name)))
    return False
