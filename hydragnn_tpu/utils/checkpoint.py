"""Checkpoint / resume.

The reference saves one ``.pk`` file holding model+optimizer state dicts,
written by rank 0 (after ZeRO consolidation), and supports config-driven
continuation (reference: hydragnn/utils/model.py:41-86, config keys
``Training.continue``/``startfrom``). TPU equivalent: the whole
``TrainState`` pytree (params, batch_stats, optimizer state, step, rng) is
serialized with flax msgpack into one file per run — process 0 writes,
every process reads. Loading targets an already-constructed state, so the
structure acts as the schema (the analog of ``load_state_dict``); sharded
multi-host array state is pulled to host before writing.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization


def _checkpoint_path(log_name: str, path: str = "./logs/") -> str:
    return os.path.join(path, log_name, f"{log_name}.mp")


def _to_host(x: Any) -> np.ndarray:
    """Fetch one leaf to host. Leaves sharded across non-addressable
    devices (multi-host ZeRO-1 optimizer state) are first all-gathered to
    a replicated layout with an XLA collective — the ZeRO consolidation
    step (reference: consolidate_state_dict, model.py:44-45)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = x.sharding.mesh
        x = jax.jit(lambda a: a, out_shardings=NamedSharding(mesh, PartitionSpec()))(x)
    return np.asarray(x)


def save_model(state: Any, log_name: str, path: str = "./logs/", verbosity: int = 0) -> str:
    """Write the TrainState to ``<path>/<log_name>/<log_name>.mp``
    (process-0 write, like the reference's rank-0 save, model.py:41-54)."""
    ckpt_path = _checkpoint_path(log_name, path)
    host_state = jax.tree_util.tree_map(_to_host, state)
    if jax.process_index() == 0:
        os.makedirs(os.path.dirname(ckpt_path), exist_ok=True)
        with open(ckpt_path, "wb") as f:
            f.write(serialization.to_bytes(host_state))
    return ckpt_path


def load_existing_model(
    state: Any, log_name: str, path: str = "./logs/"
) -> Any:
    """Restore a TrainState from the run's checkpoint file. ``state`` is the
    freshly-constructed target (its pytree structure = the schema)."""
    ckpt_path = _checkpoint_path(log_name, path)
    with open(ckpt_path, "rb") as f:
        data = f.read()
    return serialization.from_bytes(state, data)


def load_existing_model_config(
    state: Any, training_config: dict, path: str = "./logs/"
) -> Any:
    """Config-driven continue (reference: model.py:64-67, keys
    ``Training.continue`` and ``Training.startfrom``)."""
    if "continue" in training_config and training_config["continue"] == 1:
        model_name = training_config["startfrom"]
        return load_existing_model(state, model_name, path)
    return state


def checkpoint_exists(log_name: str, path: str = "./logs/") -> bool:
    return os.path.exists(_checkpoint_path(log_name, path))
