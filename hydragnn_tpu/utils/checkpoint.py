"""Checkpoint / resume.

The reference saves one ``.pk`` file holding model+optimizer state dicts,
written by rank 0 (after ZeRO consolidation), and supports config-driven
continuation (reference: hydragnn/utils/model.py:41-86, config keys
``Training.continue``/``startfrom``). Two TPU-native backends behind the
same single-name "continue" UX:

  - ``msgpack`` (default single-process): the whole ``TrainState``
    pytree (params, batch_stats, optimizer state, step, rng) in one
    flax-msgpack file; process 0 writes, every process reads. Sharded
    arrays are consolidated to host first (the ZeRO-consolidation
    analog).
  - ``orbax`` (default multi-process): Orbax sharded checkpoint — every
    host writes its addressable shards in parallel and restore places
    shards directly onto the target sharding, so pod-scale ZeRO-1 state
    never funnels through one host.

``load_existing_model`` auto-detects which backend wrote a run.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization


def _checkpoint_path(log_name: str, path: str = "./logs/") -> str:
    return os.path.join(path, log_name, f"{log_name}.mp")


def _to_host(x: Any) -> np.ndarray:
    """Fetch one leaf to host. Leaves sharded across non-addressable
    devices (multi-host ZeRO-1 optimizer state) are first all-gathered to
    a replicated layout with an XLA collective — the ZeRO consolidation
    step (reference: consolidate_state_dict, model.py:44-45)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = x.sharding.mesh
        x = jax.jit(lambda a: a, out_shardings=NamedSharding(mesh, PartitionSpec()))(x)
    return np.asarray(x)


def _orbax_dir(log_name: str, path: str) -> str:
    return os.path.abspath(os.path.join(path, log_name, f"{log_name}.orbax"))


def save_model(
    state: Any,
    log_name: str,
    path: str = "./logs/",
    verbosity: int = 0,
    backend: str = "auto",
) -> str:
    """Write the TrainState under ``<path>/<log_name>/`` (reference:
    rank-0 save, model.py:41-54). ``backend``: "msgpack", "orbax", or
    "auto" (orbax when multi-process — parallel sharded writes)."""
    if backend == "auto":
        backend = "orbax" if jax.process_count() > 1 else "msgpack"
    if backend == "orbax":
        import orbax.checkpoint as ocp

        ckpt_dir = _orbax_dir(log_name, path)
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(ckpt_dir, state, force=True)
        return ckpt_dir
    ckpt_path = _checkpoint_path(log_name, path)
    host_state = jax.tree_util.tree_map(_to_host, state)
    if jax.process_index() == 0:
        os.makedirs(os.path.dirname(ckpt_path), exist_ok=True)
        # atomic replace: a crash mid-write (the exact scenario per-epoch
        # checkpointing exists for) must not destroy the previous good file
        tmp = ckpt_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(serialization.to_bytes(host_state))
        os.replace(tmp, ckpt_path)
    return ckpt_path


def load_existing_model(
    state: Any, log_name: str, path: str = "./logs/"
) -> Any:
    """Restore a TrainState from the run's checkpoint. ``state`` is the
    freshly-constructed target (its pytree structure = the schema; with
    sharded leaves, orbax restores shards onto their shardings directly).
    The backend that wrote the run is auto-detected."""
    orbax_dir = _orbax_dir(log_name, path)
    if os.path.isdir(orbax_dir):
        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as ckptr:
            target = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, state)
            return ckptr.restore(orbax_dir, target)
    ckpt_path = _checkpoint_path(log_name, path)
    with open(ckpt_path, "rb") as f:
        data = f.read()
    restored = serialization.from_bytes(state, data)

    # preserve the target's placement: leaves restored as host arrays go
    # back onto the sharding the caller's state carries (ZeRO-1 layouts
    # survive a msgpack resume)
    def _place(tgt, val):
        if isinstance(tgt, jax.Array) and hasattr(tgt, "sharding"):
            return jax.device_put(val, tgt.sharding)
        return val

    return jax.tree_util.tree_map(_place, state, restored)


def save_train_meta(meta: dict, log_name: str, path: str = "./logs/") -> None:
    """Rank-0 JSON sidecar with host-side training-loop state (epoch,
    scheduler, early-stop counters, history) so a resumed run continues
    exactly where it left off. The reference restores only
    model+optimizer (SURVEY §5: resume "not epoch/scheduler/sampler
    state"); this closes that gap."""
    if jax.process_index() != 0:
        return
    import json

    out_dir = os.path.join(path, log_name)
    os.makedirs(out_dir, exist_ok=True)
    tmp = os.path.join(out_dir, f"{log_name}.meta.json.tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(out_dir, f"{log_name}.meta.json"))


def load_train_meta(log_name: str, path: str = "./logs/") -> Optional[dict]:
    import json

    p = os.path.join(path, log_name, f"{log_name}.meta.json")
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)


def load_existing_model_config(
    state: Any, training_config: dict, path: str = "./logs/"
) -> Any:
    """Config-driven continue (reference: model.py:64-67, keys
    ``Training.continue`` and ``Training.startfrom``)."""
    if "continue" in training_config and training_config["continue"] == 1:
        if "startfrom" not in training_config:
            raise ValueError("Training.continue=1 requires Training.startfrom")
        return load_existing_model(state, training_config["startfrom"], path)
    return state


def checkpoint_exists(log_name: str, path: str = "./logs/") -> bool:
    return os.path.exists(_checkpoint_path(log_name, path)) or os.path.isdir(
        _orbax_dir(log_name, path)
    )
