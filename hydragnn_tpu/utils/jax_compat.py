"""Version-compat shims for the jax APIs this tree straddles.

The dev TPU image runs a recent jax (``jax.shard_map``, ``check_vma``,
varying-manual-axes types via ``jax.typeof``/``jax.lax.pvary``,
``ShapeDtypeStruct(..., vma=...)``); CPU CI containers can carry 0.4.x,
where shard_map lives under ``jax.experimental`` with the kwarg named
``check_rep`` and the vma machinery does not exist at all. One tree must
import and run on both, so every usage goes through here:

  - :data:`shard_map` — resolved once; translates ``check_vma`` to
    ``check_rep`` when needed (same semantics, renamed kwarg).
  - :func:`typeof_vma` / :func:`pvary` — the manual-axes queries; on jax
    without vma tracking they degrade to "varies over nothing" / identity,
    which is exactly the pre-vma behavior those versions implement.
  - :func:`shape_dtype_struct` — drops the ``vma`` argument when the
    constructor predates it.

Import this module, not the jax spellings, anywhere version-sensitive.
"""

from __future__ import annotations

import functools
import inspect

import jax

try:
    _shard_map_impl = jax.shard_map
except AttributeError:  # jax < 0.5 keeps shard_map under experimental
    from jax.experimental.shard_map import shard_map as _shard_map_impl

if "check_vma" in inspect.signature(_shard_map_impl).parameters:
    shard_map = _shard_map_impl
else:

    @functools.wraps(_shard_map_impl)
    def shard_map(*args, check_vma=None, **kwargs):
        if check_vma is not None:
            kwargs.setdefault("check_rep", check_vma)
        return _shard_map_impl(*args, **kwargs)


_HAS_VMA = hasattr(jax, "typeof") and hasattr(jax.lax, "pvary")
_SDS_HAS_VMA = "vma" in inspect.signature(jax.ShapeDtypeStruct.__init__).parameters


def typeof_vma(x) -> frozenset:
    """The manual-mesh axes ``x`` varies over (empty outside shard_map,
    and always empty on jax without vma tracking)."""
    if not _HAS_VMA:
        return frozenset()
    return frozenset(getattr(jax.typeof(x), "vma", frozenset()))


def pvary(x, axes):
    """``jax.lax.pvary`` where it exists; identity otherwise (pre-vma jax
    has no per-operand varying-axes check to satisfy)."""
    axes = tuple(axes)
    if not axes or not _HAS_VMA:
        return x
    return jax.lax.pvary(x, axes)


def shape_dtype_struct(shape, dtype, vma: frozenset = frozenset()):
    """``jax.ShapeDtypeStruct`` carrying ``vma`` when the constructor
    supports it (required under check_vma=True shard_map); without
    support the plain struct is exactly what that jax expects."""
    if _SDS_HAS_VMA and vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def def_partition(op, *, partition, infer_sharding_from_operands, sharding_rule):
    """``custom_partitioning.def_partition`` across versions: the shardy
    ``sharding_rule`` spec only exists on newer jax; 0.4.x takes the same
    partition/infer pair and propagates through classic GSPMD."""
    kwargs = dict(
        partition=partition,
        infer_sharding_from_operands=infer_sharding_from_operands,
    )
    if "sharding_rule" in inspect.signature(op.def_partition).parameters:
        kwargs["sharding_rule"] = sharding_rule
    op.def_partition(**kwargs)
