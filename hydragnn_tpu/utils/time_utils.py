"""Named cumulative timers with cross-process reduction.

Mirrors the reference Timer registry (reference:
hydragnn/utils/time_utils.py:22-138): named timers accumulate wall time
across start/stop pairs; ``print_timers`` reports min/max/avg across
processes (a host-side psum when running multi-process).
"""

from __future__ import annotations

import time
from typing import Dict

from hydragnn_tpu.utils.print_utils import print_distributed

# graftsync: thread-safe=process-global stopwatch registry touched only from the run-driving thread
_REGISTRY: Dict[str, "Timer"] = {}


class Timer:
    def __init__(self, name: str):
        self.name = name
        existing = _REGISTRY.get(name)
        if existing is not None:
            self.__dict__ = existing.__dict__
            return
        self.elapsed = 0.0
        self.count = 0
        self._start = None
        _REGISTRY[name] = self

    def start(self) -> None:
        if self._start is not None:
            raise RuntimeError(f"Timer {self.name} already running")
        self._start = time.perf_counter()

    def stop(self) -> None:
        if self._start is None:
            raise RuntimeError(f"Timer {self.name} not running")
        self.elapsed += time.perf_counter() - self._start
        self.count += 1
        self._start = None

    def stop_if_running(self) -> None:
        """Exception-path stop: registry timers are process-global, so a
        run that unwinds mid-interval must close it or every later run
        in the process dies with 'Timer already running'."""
        if self._start is not None:
            self.stop()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def reset_timers() -> None:
    _REGISTRY.clear()


def timers_snapshot() -> Dict[str, Dict[str, float]]:
    """This process's timers as plain numbers (no printing, no
    cross-process reduction) — the shape the run flight recorder
    (hydragnn_tpu/obs/flight.py) embeds in its run_end summary. A
    still-running timer reports the elapsed time of its completed
    start/stop pairs."""
    return {
        name: {"elapsed_s": round(t.elapsed, 6), "count": t.count}
        for name, t in sorted(_REGISTRY.items())
    }


def print_timers(verbosity: int = 1) -> Dict[str, Dict[str, float]]:
    """Report each timer; multi-process runs reduce min/max/avg across
    processes with a host-side allgather through jax."""
    import numpy as np

    stats = {}
    names = sorted(_REGISTRY)
    values = np.array([_REGISTRY[n].elapsed for n in names])
    try:
        import jax

        nproc = jax.process_count()
    except Exception:
        nproc = 1
    if nproc > 1 and len(values):
        from jax.experimental import multihost_utils

        all_vals = multihost_utils.process_allgather(values)
        vmin, vmax, vavg = all_vals.min(0), all_vals.max(0), all_vals.mean(0)
    else:
        vmin = vmax = vavg = values
    for i, n in enumerate(names):
        stats[n] = {"min": float(vmin[i]), "max": float(vmax[i]), "avg": float(vavg[i])}
        print_distributed(
            verbosity,
            f"timer {n}: avg {vavg[i]:.4f}s min {vmin[i]:.4f}s max {vmax[i]:.4f}s "
            f"(n={_REGISTRY[n].count})",
        )
    return stats
