from hydragnn_tpu.utils.config import (
    load_config,
    update_config,
    get_log_name_config,
    save_config,
    check_if_graph_size_variable,
    max_in_degree,
    pna_degree_histogram,
)
from hydragnn_tpu.utils.print_utils import (
    print_distributed,
    iterate_tqdm,
    setup_log,
    log,
)
from hydragnn_tpu.utils.time_utils import Timer, print_timers, reset_timers

__all__ = [
    "load_config",
    "update_config",
    "get_log_name_config",
    "save_config",
    "check_if_graph_size_variable",
    "max_in_degree",
    "pna_degree_histogram",
    "print_distributed",
    "iterate_tqdm",
    "setup_log",
    "log",
    "Timer",
    "print_timers",
    "reset_timers",
]
