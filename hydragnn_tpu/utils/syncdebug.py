"""Runtime lock-order witness (``HYDRAGNN_LOCK_DEBUG=1``).

graftsync (``lint/concurrency.py``) proves the STATIC lock-order graph
is a DAG; this module watches the DYNAMIC order. Every declared lock in
the tree is created through :func:`maybe_wrap` — with the knob off (the
default) that returns the raw lock untouched, so production pays
nothing. With ``HYDRAGNN_LOCK_DEBUG=1`` each lock is wrapped in a
:class:`WitnessLock` that records per-thread acquisition order into a
process-wide order graph, seeded with graftsync's static edges. An
acquisition that contradicts the graph (acquiring A while holding B
when A→B is already an observed/static order) is a potential deadlock
in the making: the witness dumps every thread's stack into the flight
record as a ``lock_order`` event (``obs/flight.py``), prints a warning,
and CONTINUES — a witness that deadlocks or raises on the serve path
would be worse than the bug it hunts.

``HYDRAGNN_INJECT_LOCK_ORDER="<lockA>,<lockB>"`` is the one-shot
self-test: once both named locks exist, the witness synthesizes an
A→B acquisition followed by the B→A inversion (bookkeeping only — no
real lock is taken, so the injection cannot deadlock), driving the
full violation path end to end. ci.sh uses it to prove a real serve
process converts an inversion into a validated ``lock_order`` flight
event without going down.

Lock identity is by NAME (``<modstem>.<Class>.<attr>`` — the graftsync
naming scheme), not by instance: all Counters share one node, which is
the standard lockdep coarsening and exactly what the static graph
reasons about.
"""

from __future__ import annotations

import os
import sys
import threading
import traceback
import weakref
from typing import Dict, List, Optional, Set, Tuple

from hydragnn_tpu.utils import knobs

_ENABLED: Optional[bool] = None  # graftsync: thread-safe=write-once None->bool latch; GIL-atomic, worst case two threads read the env twice to the same value
_STATE_LOCK = threading.Lock()  # graftsync: lock=syncdebug._STATE_LOCK
# observed + static order edges: name -> set of successors
_ORDER: Dict[str, Set[str]] = {}  # graftsync: guarded-by=syncdebug._STATE_LOCK
_REGISTERED: Set[str] = set()  # graftsync: guarded-by=syncdebug._STATE_LOCK
_SEEN_EDGES: Set[Tuple[str, str]] = set()  # graftsync: guarded-by=syncdebug._STATE_LOCK
_VIOLATIONS: List[dict] = []  # graftsync: guarded-by=syncdebug._STATE_LOCK
_FLIGHTS: List = []  # graftsync: guarded-by=syncdebug._STATE_LOCK
_STATIC_SEEDED = False  # graftsync: guarded-by=syncdebug._STATE_LOCK
_INJECT_FIRED = False  # graftsync: guarded-by=syncdebug._STATE_LOCK
_TLS = threading.local()


def enabled() -> bool:
    """Whether the witness is on — ``HYDRAGNN_LOCK_DEBUG`` read once
    and cached (wrap decisions must be consistent for process life)."""
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = knobs.get_bool("HYDRAGNN_LOCK_DEBUG", False)
    return _ENABLED


def maybe_wrap(lock, name: str):
    """Wrap ``lock`` in a :class:`WitnessLock` under ``name`` when the
    witness is enabled; return it untouched otherwise. Every declared
    lock in the tree is created through this call."""
    if not enabled():
        return lock
    _register(name)
    return WitnessLock(lock, name)


def register_flight(recorder) -> None:
    """Point the witness at a flight recorder (held weakly) so a
    violation lands in the run's event log. ``FlightRecorder`` calls
    this from its own ``__init__``; no-op while the witness is off."""
    if not enabled():
        return
    with _STATE_LOCK:
        _FLIGHTS.append(weakref.ref(recorder))


def violations() -> List[dict]:
    """Violations recorded so far (copies)."""
    with _STATE_LOCK:
        return [dict(v) for v in _VIOLATIONS]


def reset() -> None:
    """Forget all witness state INCLUDING the cached enable decision —
    test isolation only; never call this from library code."""
    global _ENABLED, _STATIC_SEEDED, _INJECT_FIRED
    with _STATE_LOCK:
        _ENABLED = None
        _ORDER.clear()
        _REGISTERED.clear()
        _SEEN_EDGES.clear()
        _VIOLATIONS.clear()
        _FLIGHTS.clear()
        _STATIC_SEEDED = False
        _INJECT_FIRED = False
    _TLS.held = []


# -- internals ---------------------------------------------------------------


def _held() -> List[str]:
    held = getattr(_TLS, "held", None)
    if held is None:
        held = _TLS.held = []
    return held


def _register(name: str) -> None:
    with _STATE_LOCK:
        first = name not in _REGISTERED
        _REGISTERED.add(name)
    if first:
        _seed_static()
        _maybe_inject()


def _seed_static() -> None:
    """Seed the order graph with graftsync's static edges (once): a
    runtime acquisition contradicting the STATIC order then fires even
    if the other direction was never observed at runtime."""
    global _STATIC_SEEDED
    with _STATE_LOCK:
        if _STATIC_SEEDED:
            return
        _STATIC_SEEDED = True
    try:
        from hydragnn_tpu.lint.concurrency import build_lock_order

        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        graph = build_lock_order(repo_root)
    except Exception:
        return  # no source tree (installed wheel): observed-only mode
    with _STATE_LOCK:
        for edge in graph.get("edges", ()):
            a, b = edge.get("from"), edge.get("to")
            if a and b:
                _ORDER.setdefault(a, set()).add(b)
                _SEEN_EDGES.add((a, b))


# graftsync: holds=syncdebug._STATE_LOCK
def _path_exists_locked(src: str, dst: str) -> bool:
    """DFS reachability src -> dst in _ORDER; caller holds _STATE_LOCK."""
    stack, seen = [src], {src}
    while stack:
        u = stack.pop()
        if u == dst:
            return True
        for v in _ORDER.get(u, ()):
            if v not in seen:
                seen.add(v)
                stack.append(v)
    return False


def _note_acquire(name: str, injected: bool = False) -> None:
    held = _held()
    if held:
        for h in held:
            if h == name:
                continue  # re-entrant (RLock) or same-name sibling
            # graftsync: disable=HS001 -- deliberate lock-free fast path; a stale read only means we take _STATE_LOCK and re-check below
            if (h, name) in _SEEN_EDGES:
                continue  # edge already known and validated
            with _STATE_LOCK:
                if (h, name) in _SEEN_EDGES:
                    continue
                conflict = _path_exists_locked(name, h)
                _ORDER.setdefault(h, set()).add(name)
                _SEEN_EDGES.add((h, name))
            if conflict:
                _violation(h, name, injected)
    held.append(name)


def _note_release(name: str) -> None:
    held = _held()
    # remove the most recent acquisition of this name (lock release
    # order need not be LIFO; Python allows arbitrary release order)
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


def _all_thread_stacks() -> Dict[str, List[str]]:
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in sys._current_frames().items():
        label = f"{names.get(ident, 'unknown')}({ident})"
        out[label] = [
            line.rstrip("\n")
            for line in traceback.format_stack(frame)[-12:]
        ]
    return out


def _violation(held_name: str, acquiring: str, injected: bool) -> None:
    """The witness caught an order inversion: record a ``lock_order``
    flight event with every thread's stack, warn, and keep going —
    never raise, never block the acquiring thread's progress."""
    event = {
        "locks": [held_name, acquiring],
        "edge": f"{held_name}->{acquiring}",
        "conflict": f"{acquiring}->{held_name}",
        "thread": threading.current_thread().name,
        "injected": bool(injected),
        "stacks": _all_thread_stacks(),
    }
    with _STATE_LOCK:
        _VIOLATIONS.append(event)
        flights = [ref() for ref in _FLIGHTS]
    try:
        print(
            "syncdebug: LOCK-ORDER VIOLATION: acquiring "
            f"{acquiring!r} while holding {held_name!r} contradicts the "
            f"known order {acquiring} -> {held_name}"
            + (" [injected self-test]" if injected else ""),
            file=sys.stderr,
        )
    except Exception:
        pass
    for flight in flights:
        if flight is None:
            continue
        try:
            flight.record("lock_order", **event)
        except Exception:
            pass  # a witness must never take the run down


def _maybe_inject() -> None:
    """``HYDRAGNN_INJECT_LOCK_ORDER="A,B"`` one-shot: once both locks
    are registered, synthesize the A→B order then the B→A inversion —
    bookkeeping only, no real lock is taken, so the self-test cannot
    deadlock anything."""
    global _INJECT_FIRED
    spec = knobs.get_str("HYDRAGNN_INJECT_LOCK_ORDER")
    if not spec or "," not in spec:
        return
    a, b = (s.strip() for s in spec.split(",", 1))
    with _STATE_LOCK:
        if _INJECT_FIRED or a not in _REGISTERED or b not in _REGISTERED:
            return
        _INJECT_FIRED = True
    _note_acquire(a, injected=True)
    _note_acquire(b, injected=True)
    _note_release(b)
    _note_release(a)
    _note_acquire(b, injected=True)
    _note_acquire(a, injected=True)  # <- fires: a->b is on record
    _note_release(a)
    _note_release(b)


class WitnessLock:
    """Order-witnessing wrapper around a ``Lock``/``RLock``/``Condition``.

    Supports the full context-manager + acquire/release protocol;
    ``Condition.wait``/``wait_for`` pop the lock from the held stack for
    the duration (wait releases the underlying lock) and re-note it on
    return. Everything else delegates.
    """

    __slots__ = ("_inner", "_name")

    def __init__(self, inner, name: str):
        self._inner = inner
        self._name = name

    # -- lock protocol -----------------------------------------------------

    def acquire(self, *args, **kwargs):
        got = self._inner.acquire(*args, **kwargs)
        if got is not False:
            _note_acquire(self._name)
        return got

    def release(self) -> None:
        self._inner.release()
        _note_release(self._name)

    def __enter__(self):
        self._inner.__enter__()
        _note_acquire(self._name)
        return self

    def __exit__(self, *exc):
        _note_release(self._name)
        return self._inner.__exit__(*exc)

    def locked(self) -> bool:
        return self._inner.locked()

    # -- condition protocol ------------------------------------------------

    def wait(self, timeout=None):
        _note_release(self._name)
        try:
            return self._inner.wait(timeout)
        finally:
            _note_acquire(self._name)

    def wait_for(self, predicate, timeout=None):
        _note_release(self._name)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            _note_acquire(self._name)

    def __getattr__(self, attr):
        return getattr(self._inner, attr)

    def __repr__(self) -> str:
        return f"WitnessLock({self._name!r}, {self._inner!r})"
