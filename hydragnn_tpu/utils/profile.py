"""Epoch-gated profiler over ``jax.profiler`` tensorboard traces.

Reference: hydragnn/utils/profile.py:9-70 — a torch.profiler subclass with
schedule wait=5/warmup=3/active=3 gated to one target epoch, writing
tensorboard traces, configured from ``NeuralNetwork.Profile``
({"enable": 1, "target_epoch": E}) and driven by the train loop
(set_current_epoch / context manager around the epoch / step per batch).

The JAX profiler traces a time window rather than a step schedule, so the
schedule is emulated: within the target epoch, tracing starts after
``wait + warmup`` steps and stops after ``active`` more. Traces land in
``<prefix>/plugins/profile`` and open in TensorBoard / XProf (including
TPU HLO timelines when run on TPU).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

import jax

from hydragnn_tpu.utils import syncdebug

# jax allows ONE active profiler trace per process; this slot is the
# arbiter between the epoch-gated Profiler below and incident captures
# (obs/triggers.py), and the signal obs/spans.py uses to suppress its
# sampled block_until_ready fence while a capture is live (the fence
# would serialize the very step being profiled). The slot is
# tri-state — "idle" / "active" / "stopping" — because both
# start_trace and stop_trace block (device sync) and must run OUTSIDE
# the lock, yet the slot has to stay busy through them: a two-state
# flag cleared before stop_trace() returns would let a concurrent
# try_start_capture start a trace the old owner's stop then kills.
_CAPTURE_LOCK = syncdebug.maybe_wrap(threading.Lock(), "profile._CAPTURE_LOCK")
_CAPTURE_STATE = "idle"  # graftsync: guarded-by=profile._CAPTURE_LOCK


def capture_active() -> bool:
    """Whether a jax profiler trace is being captured (or torn down)."""
    with _CAPTURE_LOCK:
        return _CAPTURE_STATE != "idle"


def try_start_capture(prefix: str) -> bool:
    """Start a jax profiler trace into ``prefix`` if no capture is
    live; returns whether this caller now owns the capture. Refusal
    (not an exception) is the contract — an incident firing during the
    epoch-gated profiler's window simply captures nothing."""
    global _CAPTURE_STATE
    with _CAPTURE_LOCK:
        if _CAPTURE_STATE != "idle":
            return False
        _CAPTURE_STATE = "active"
    try:
        os.makedirs(prefix, exist_ok=True)
        jax.profiler.start_trace(prefix)
    except Exception:
        with _CAPTURE_LOCK:
            _CAPTURE_STATE = "idle"
        return False
    return True


def stop_capture() -> None:
    """Stop the live capture (no-op when none is). The slot stays busy
    ("stopping") until stop_trace returns, so a concurrent
    try_start_capture cannot start a trace this teardown would kill."""
    global _CAPTURE_STATE
    with _CAPTURE_LOCK:
        if _CAPTURE_STATE != "active":
            return
        _CAPTURE_STATE = "stopping"
    try:
        jax.profiler.stop_trace()
    finally:
        with _CAPTURE_LOCK:
            _CAPTURE_STATE = "idle"


class Profiler:
    def __init__(
        self,
        prefix: str = "",
        enable: bool = False,
        target_epoch: int = 0,
        wait: int = 5,
        warmup: int = 3,
        active: int = 3,
    ):
        self.prefix = prefix or "./logs/profile"
        self.enable = enable
        self.target_epoch = target_epoch
        self.current_epoch = -1
        self.wait = wait
        self.warmup = warmup
        self.active = active
        self.done = False
        self._step_in_epoch = 0
        self._tracing = False
        # observer hook: called as on_trace(prefix, epoch) when a trace
        # window closes — the train loop points it at the run flight
        # recorder so the trace artifact is discoverable from the run's
        # event log (hydragnn_tpu/obs/flight.py "profile_trace" events)
        self.on_trace = None

    def setup(self, config: dict) -> None:
        """Configure from the ``Profile`` config section (reference keys:
        ``enable``, ``target_epoch``; profile.py:32-42). ``enable``
        accepts 1/"1"/True (JSON configs vary)."""
        self.enable = str(config.get("enable", 0)).lower() in ("1", "true")
        self.target_epoch = int(config.get("target_epoch", 0))

    def set_current_epoch(self, current_epoch: int) -> None:
        self.current_epoch = current_epoch
        self._step_in_epoch = 0

    @property
    def _armed(self) -> bool:
        return (
            self.enable
            and not self.done
            and self.current_epoch == self.target_epoch
        )

    def step(self) -> None:
        """Call once per training batch (reference: profiler.step() in the
        hot loop, train_validate_test.py:362)."""
        if not self._armed:
            return
        self._step_in_epoch += 1
        start_at = self.wait + self.warmup
        if not self._tracing and self._step_in_epoch == start_at:
            self._tracing = try_start_capture(self.prefix)
        elif self._tracing and self._step_in_epoch >= start_at + self.active:
            self._stop()

    def _stop(self) -> None:
        if self._tracing:
            stop_capture()
            self._tracing = False
            self.done = True
            print(f"Profiler trace written to {self.prefix} (epoch {self.target_epoch})")
            if self.on_trace is not None:
                self.on_trace(self.prefix, self.target_epoch)

    def __enter__(self) -> "Profiler":
        return self

    def __exit__(self, exc_type, exc_value, tb) -> bool:
        # end of the epoch: close an in-flight trace even if the epoch had
        # fewer steps than wait+warmup+active
        self._stop()
        return False

    def reset(self) -> None:
        self._step_in_epoch = 0
        self.done = False


def trace_annotation(name: str):
    """Named span inside jitted/host code for the profiler timeline — the
    analog of torch.profiler.record_function spans
    (reference: train_validate_test.py:349-358) and the gptl4py/nvtx shim
    (reference: hydragnn/utils/gptl4py_dummy.py)."""
    return jax.profiler.TraceAnnotation(name)


def scan_slope_ms(make_chain, k1: int, k2: int) -> float:
    """Per-iteration time (ms) of a K-chained computation by the
    scan-slope protocol: time the chain at two lengths and take the
    slope — cancels per-dispatch RTT and server-side overhead, which on
    tunneled dev chips varies 10-120 ms with burst history and would
    otherwise swamp sub-ms ops (docs/PERF.md). ``make_chain(k)`` returns
    a zero-arg callable that runs the k-chained computation and blocks
    on a REAL D2H readback (``np.asarray`` of a chain-dependent value —
    ``block_until_ready`` returns at dispatch-ack on such tunnels).
    The caller must treat a non-positive slope as noise, not data."""
    import time

    times = {}
    for k in (k1, k2):
        run = make_chain(k)
        run()  # compile + warmup
        t0 = time.perf_counter()
        run()
        times[k] = time.perf_counter() - t0
    return (times[k2] - times[k1]) / (k2 - k1) * 1e3
