"""No-op GPTL timing shim + device trace ranges.

The reference ships ``gptl4py_dummy`` (reference:
hydragnn/utils/gptl4py_dummy.py:1-64), a drop-in no-op mirror of the
gptl4py HPC timing library so instrumented code runs unchanged off
Summit. Same pattern here: every gptl4py symbol is a no-op, and the
nvtx-range helper maps to ``jax.profiler.TraceAnnotation`` so ranges
show up in TPU profiler traces when one is active.

    import hydragnn_tpu.utils.gptl as gp
    gp.initialize()
    with gp.nvtx_range("epoch"):
        gp.start("train"); ...; gp.stop("train")
    gp.pr_file("timings.txt"); gp.finalize()
"""

from __future__ import annotations

import contextlib


def initialize() -> int:  # gptl4py_dummy.initialize
    return 0


def finalize() -> int:
    return 0


def start(name: str) -> int:
    return 0


def stop(name: str) -> int:
    return 0


def setoption(*args) -> int:
    return 0


def reset() -> int:
    return 0


def pr(rank: int = 0) -> int:
    return 0


def pr_file(fname: str) -> int:
    return 0


def pr_summary(comm=None) -> int:
    return 0


def pr_summary_file(fname: str, comm=None) -> int:
    return 0


@contextlib.contextmanager
def nvtx_range(name: str):
    """Device trace span (the reference wraps nvtx.range_push/pop)."""
    try:
        import jax

        annotation = jax.profiler.TraceAnnotation(name)
    except ImportError:  # pragma: no cover
        annotation = contextlib.nullcontext()
    with annotation:
        yield


# decorator form, mirroring gptl4py's profile decorator usage
def profile(name=None):
    def wrap(fn):
        label = name or fn.__name__

        def inner(*args, **kwargs):
            with nvtx_range(label):
                return fn(*args, **kwargs)

        return inner

    return wrap
