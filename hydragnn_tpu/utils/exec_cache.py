"""Persistent AOT executable cache — warm cold-starts across processes.

Every process today re-lowers and re-compiles executables that an
earlier identical process already paid for: each serving replica AOT
compiles its whole bucket ladder, every supervisor auto-resume restart
recompiles the train step, and every bench driver round re-pays
lowering. This module makes those executables durable: a
content-addressed on-disk cache of serialized XLA executables (via
``jax.experimental.serialize_executable``), so a second replica, a
restarted trainer, or a repeated bench round deserializes in
milliseconds instead of compiling in seconds.

Cache anatomy (docs/PERF.md "r09 cold start"):

  - **Entry filename** = sha256 of the *logical identity*: consumer
    kind (``serve`` / ``train_step`` / ``scan_epoch`` / ``bench``),
    model-architecture fingerprint, pad-plan / input-shape fingerprint,
    and compute dtype. Same logical program -> same file.
  - **Compat manifest** stored *inside* the entry: jax / jaxlib /
    libtpu versions, backend, ``device_kind``, and the partitioner
    layout ``(data, fsdp, edge)``. A logical hit whose compat manifest
    mismatches is classified loudly (``version_skew`` /
    ``layout_changed``) instead of silently deserializing an
    executable built for different hardware or sharding.
  - **Integrity**: atomic writes (unique tmp + ``os.replace``) with
    ``.sha256`` sidecars — the checkpoint-integrity pattern
    (``utils/checkpoint.py``). A digest mismatch or unpicklable entry
    is a ``corrupt`` miss that EVICTS the single bad entry and falls
    through to a live compile; it never takes the process down.
  - **LRU size bound**: entries are touched on hit; when the directory
    exceeds ``HYDRAGNN_EXEC_CACHE_MAX_MB`` (default 512) the
    oldest-mtime entries are deleted.

Miss reasons (``absent`` / ``corrupt`` / ``version_skew`` /
``layout_changed`` / ``donation_check_failed`` / ``unavailable``) are
recorded as ``exec_cache`` flight-record events and ServeMetrics
counters — a warm start that silently recompiles is a regression this
observability exists to catch.

DONATION GATE (the PR 1 correctness constraint): a deserialized
DONATED executable is NOT trustworthy on this jax/jaxlib (0.4.x). The
input/output aliasing baked into the binary round-trips, and trivial
probes — and even bit-exact chained replays of the real train step in
a clean process — pass; but executed inside a full training process
(restored checkpoint, async diagnostics reads, eval jits live) the
same executable intermittently corrupts memory: scrambled output
pytrees (``nu`` subtrees swapping dict keys), scattered-NaN leaves,
``Check failed: !tracked_device_buffer_`` aborts, segfaults. The
repo's consumers therefore NEVER cache a donated program: the train
loop and the bench drivers cache a donation-free twin of the step (a
plain jit of the same body — one extra state-sized buffer while the
cache is on), and serving forwards are donation-free already. The
gate machinery stays as defense-in-depth for any caller that does
pass ``donated=True``: :func:`donation_roundtrip_ok` — a one-time
serialize/deserialize probe of a tiny donated function whose output
must bit-match the fresh compile, persisted per environment
fingerprint in the cache dir — plus a first-execution landing check
in ``train/loop.py`` (the cached step's output ``step`` must be input
``step + delta``). A failed (or injected:
``HYDRAGNN_INJECT_DONATION_CHECK_FAIL``) check evicts the entry and
falls through to a live compile with a ``donation_check_failed`` miss
reason. But a passing probe is necessary, not sufficient — which is
exactly why the defaults above refuse donated caching outright.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import sys
import time
from typing import Any, Callable, Dict, Optional, Tuple
from hydragnn_tpu.utils import knobs

#: every classification a load can record (docs/PERF.md table)
MISS_REASONS = (
    "absent",
    "corrupt",
    "version_skew",
    "layout_changed",
    "donation_check_failed",
    "unavailable",
)

_ENV_DIR = "HYDRAGNN_EXEC_CACHE"
_ENV_MAX_MB = "HYDRAGNN_EXEC_CACHE_MAX_MB"


def _serialize_mod():
    """The serialize_executable module, or None when this jax cannot
    round-trip executables (the cache then misses with reason
    ``unavailable`` and every consumer live-compiles as before)."""
    try:
        from jax.experimental import serialize_executable as se

        if hasattr(se, "serialize") and hasattr(se, "deserialize_and_load"):
            return se
    except ImportError:
        pass
    return None


def _sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _atomic_write(final_path: str, data: bytes) -> None:
    """Unique-tmp + ``os.replace``: two processes warming the same key
    concurrently each publish a complete file; the loser's replace just
    overwrites the winner's identical bytes (tested in
    tests/test_warm_exec_cache.py concurrent-writer case)."""
    tmp = f"{final_path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, final_path)


# -- identity fingerprints -------------------------------------------------


def _canon(obj: Any, depth: int = 0) -> Any:
    """Canonical, order-stable structure for hashing arbitrary identity
    components (configs, dataclasses, pytrees of arrays)."""
    if depth > 10:
        return repr(obj)
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return obj
    if isinstance(obj, dict):
        return tuple(
            (str(k), _canon(v, depth + 1)) for k, v in sorted(obj.items(), key=lambda kv: str(kv[0]))
        )
    if isinstance(obj, (list, tuple)):
        return tuple(_canon(v, depth + 1) for v in obj)
    shape = getattr(obj, "shape", None)
    dtype = getattr(obj, "dtype", None)
    if shape is not None and dtype is not None:
        return ("array", tuple(shape), str(dtype))
    if hasattr(obj, "__dataclass_fields__"):
        import dataclasses

        return _canon(dataclasses.asdict(obj), depth + 1)
    return repr(obj)


def fingerprint(*components: Any) -> str:
    """Stable sha256 hex over the canonical form of the components."""
    return _sha256_hex(repr(_canon(components)).encode())


def abstract_fingerprint(tree: Any) -> str:
    """Fingerprint of a pytree's STRUCTURE: leaf paths, shapes, dtypes
    — the pad-plan / architecture identity of a batch, a variables
    tree, or a TrainState, independent of the values it holds."""
    import jax

    leaves = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        leaves.append(
            (
                jax.tree_util.keystr(path),
                tuple(getattr(leaf, "shape", ())),
                str(getattr(leaf, "dtype", type(leaf).__name__)),
            )
        )
    return _sha256_hex(repr(tuple(leaves)).encode())


def _versions() -> Dict[str, str]:
    out = {}
    try:
        import jax

        out["jax"] = jax.__version__
    except Exception:
        out["jax"] = "unavailable"
    try:
        import jaxlib

        out["jaxlib"] = getattr(jaxlib, "__version__", "unknown")
    except Exception:
        out["jaxlib"] = "unavailable"
    try:
        import libtpu  # type: ignore

        out["libtpu"] = getattr(libtpu, "__version__", "present")
    except Exception:
        out["libtpu"] = "none"
    return out


def compat_manifest(
    layout: Tuple[int, int, int] = (1, 1, 1),
    compute_dtype: Any = None,
) -> Dict[str, Any]:
    """The environment half of the cache key: everything that makes a
    serialized executable VALID here, beyond its logical program. The
    partitioner layout is included because an executable lowered for
    ``fsdp=4`` shards state differently than one for pure DP
    (docs/PARALLELISM.md)."""
    man: Dict[str, Any] = dict(_versions())
    try:
        import jax

        man["backend"] = jax.default_backend()
        man["device_kind"] = jax.devices()[0].device_kind
    except Exception:
        man["backend"] = man["device_kind"] = "unavailable"
    man["layout"] = tuple(int(x) for x in layout)
    man["compute_dtype"] = str(compute_dtype) if compute_dtype is not None else "f32"
    return man


def environment_fingerprint() -> str:
    """Short fingerprint of the version/backend environment — the key
    the persisted donation-probe verdict is stored under."""
    man = _versions()
    try:
        import jax

        man["backend"] = jax.default_backend()
        man["device_kind"] = jax.devices()[0].device_kind
    except Exception:
        pass
    return _sha256_hex(json.dumps(man, sort_keys=True).encode())[:16]


def _classify_compat(want: Dict[str, Any], got: Dict[str, Any]) -> Optional[str]:
    """None when the entry is valid here, else the loud miss reason."""
    if list(want.get("layout", ())) != list(got.get("layout", ())):
        return "layout_changed"
    for field in ("jax", "jaxlib", "libtpu", "backend", "device_kind", "compute_dtype"):
        if want.get(field) != got.get(field):
            return "version_skew"
    return None


# -- donation gate ---------------------------------------------------------

_DONATION_MEMO: Dict[str, bool] = {}


def donation_roundtrip_ok(cache_dir: Optional[str] = None) -> bool:
    """Whether a donated executable survives the serialize/deserialize
    round trip on THIS jax: a tiny ``donate_argnums=(0,)`` function is
    AOT-compiled, round-tripped, and both are run on fresh inputs —
    the outputs must bit-match. The verdict is memoized per process and
    persisted per environment fingerprint under ``cache_dir`` (warm
    restarts read it back: zero probe compiles).

    ``HYDRAGNN_INJECT_DONATION_CHECK_FAIL=1`` forces a failing verdict
    without touching the persisted one — the deterministic driver for
    the evict-and-recompile path (tests/test_warm_exec_cache.py, ci.sh)."""
    if knobs.is_set("HYDRAGNN_INJECT_DONATION_CHECK_FAIL"):
        return False
    fp = environment_fingerprint()
    if fp in _DONATION_MEMO:
        return _DONATION_MEMO[fp]
    verdict_path = (
        os.path.join(cache_dir, "donation_probe.json") if cache_dir else None
    )
    if verdict_path and os.path.exists(verdict_path):
        try:
            with open(verdict_path) as f:
                stored = json.load(f)
            if fp in stored:
                _DONATION_MEMO[fp] = bool(stored[fp])
                return _DONATION_MEMO[fp]
        except (OSError, json.JSONDecodeError, TypeError):
            pass
    ok = _run_donation_probe()
    _DONATION_MEMO[fp] = ok
    if verdict_path:
        try:
            stored = {}
            if os.path.exists(verdict_path):
                with open(verdict_path) as f:
                    stored = json.load(f)
            stored[fp] = ok
            _atomic_write(verdict_path, json.dumps(stored).encode())
        except (OSError, json.JSONDecodeError, TypeError):
            pass
    return ok


def _run_donation_probe() -> bool:
    se = _serialize_mod()
    if se is None:
        return False
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        g = jax.jit(
            lambda s, x: (s + x, (s * x).sum()), donate_argnums=(0,)
        )
        a = jnp.arange(16.0, dtype=jnp.float32).reshape(4, 4)
        compiled = g.lower(a, a).compile()
        payload, in_tree, out_tree = se.serialize(compiled)
        loaded = se.deserialize_and_load(payload, in_tree, out_tree)
        s1, l1 = compiled(jnp.ones((4, 4), jnp.float32), a)
        s2, l2 = loaded(jnp.ones((4, 4), jnp.float32), a)
        return bool(
            np.array_equal(np.asarray(s1), np.asarray(s2))
            and np.array_equal(np.asarray(l1), np.asarray(l2))
        )
    except Exception:
        return False


# -- the cache -------------------------------------------------------------


class ExecCache:
    """One directory of serialized executables + integrity sidecars.

    Constructed with ``cache_dir=None`` the cache is inert (every
    ``load`` returns None silently, ``store`` is a no-op) so call sites
    need no gate of their own. ``flight`` / ``metrics`` are optional
    sinks for the per-event observability (``exec_cache`` flight events;
    ``ServeMetrics.record_exec_cache``)."""

    def __init__(
        self,
        cache_dir: Optional[str],
        *,
        max_bytes: Optional[int] = None,
        flight=None,
        metrics=None,
        consumer: str = "",
    ):
        self.dir = cache_dir or None
        self.flight = flight
        self.metrics = metrics
        self.consumer = consumer
        if max_bytes is None:
            max_bytes = int(knobs.get_float(_ENV_MAX_MB, 512.0) * 1024 * 1024)
        self.max_bytes = max_bytes
        self.stats: Dict[str, Any] = {
            "hits": 0,
            "misses": 0,
            "stores": 0,
            "evictions": 0,
            "miss_reasons": {},
        }
        if self.dir:
            os.makedirs(self.dir, exist_ok=True)

    @classmethod
    def from_env(cls, **kw) -> "ExecCache":
        """The ``HYDRAGNN_EXEC_CACHE`` directory, or an inert cache.
        The env var (not ``HYDRAGNN_INJECT_*``) deliberately SURVIVES
        supervisor restarts — warm resume is its whole point."""
        return cls(knobs.raw(_ENV_DIR) or None, **kw)

    @property
    def enabled(self) -> bool:
        return self.dir is not None

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}.bin")

    # -- events ------------------------------------------------------------

    def _emit(self, event: str, key: str, reason: Optional[str] = None, **extra):
        if self.flight is not None:
            self.flight.record(
                "exec_cache",
                event=event,
                key=key[:16],
                consumer=self.consumer,
                **({"reason": reason} if reason else {}),
                **extra,
            )
        if self.metrics is not None and event in ("hit", "miss"):
            self.metrics.record_exec_cache(hit=(event == "hit"), reason=reason)

    def _miss(self, key: str, reason: str, **extra) -> None:
        self.stats["misses"] += 1
        self.stats["miss_reasons"][reason] = (
            self.stats["miss_reasons"].get(reason, 0) + 1
        )
        self._emit("miss", key, reason, **extra)
        return None

    def _evict(self, key: str, reason: str) -> None:
        path = self._path(key)
        for victim in (path, path + ".sha256"):
            try:
                os.remove(victim)
            except OSError:
                pass
        self.stats["evictions"] += 1
        self._emit("evict", key, reason)
        # loud by design: a corrupt or donation-unsafe entry being
        # dropped is an incident the operator should see without
        # opening the flight record
        print(
            f"exec_cache: evicted entry {key[:16]} ({reason})",
            file=sys.stderr,
        )

    # -- load / store ------------------------------------------------------

    def load(
        self,
        key: str,
        compat: Dict[str, Any],
        *,
        donated: bool = False,
        label: Optional[str] = None,
    ) -> Optional[Callable]:
        """The deserialized executable for ``key``, or None with the
        miss reason recorded. ``donated=True`` routes through the
        donation gate (module docstring) — a failing gate EVICTS the
        entry so a later fixed environment re-stores it fresh."""
        if self.dir is None:
            return None
        se = _serialize_mod()
        if se is None:
            return self._miss(key, "unavailable", label=label)
        path = self._path(key)
        if not os.path.exists(path):
            return self._miss(key, "absent", label=label)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return self._miss(key, "absent", label=label)
        sidecar = path + ".sha256"
        if os.path.exists(sidecar):
            try:
                with open(sidecar) as f:
                    want = f.read().strip()
            except OSError:
                want = ""
            if _sha256_hex(data) != want:
                self._evict(key, "corrupt")
                return self._miss(key, "corrupt", label=label)
        try:
            entry = pickle.loads(data)
            meta = entry["meta"]
            payload = entry["payload"]
            in_tree = entry["in_tree"]
            out_tree = entry["out_tree"]
        except Exception:
            self._evict(key, "corrupt")
            return self._miss(key, "corrupt", label=label)
        mismatch = _classify_compat(compat, meta.get("compat", {}))
        if mismatch is not None:
            # the entry is fine for the environment that wrote it —
            # loud miss, no eviction (LRU reclaims it eventually)
            return self._miss(key, mismatch, label=label)
        if donated and not donation_roundtrip_ok(self.dir):
            self._evict(key, "donation_check_failed")
            return self._miss(key, "donation_check_failed", label=label)
        try:
            exe = se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception:
            self._evict(key, "corrupt")
            return self._miss(key, "corrupt", label=label)
        try:
            now = time.time()
            os.utime(path, (now, now))  # LRU touch
        except OSError:
            pass
        self.stats["hits"] += 1
        self._emit("hit", key, label=label)
        return exe

    def store(
        self,
        key: str,
        compiled,
        compat: Dict[str, Any],
        *,
        label: Optional[str] = None,
    ) -> bool:
        """Serialize ``compiled`` under ``key``. False (with a
        ``store_failed`` flight event) when this executable cannot be
        serialized — the caller keeps its live executable either way."""
        if self.dir is None:
            return False
        se = _serialize_mod()
        if se is None:
            return False
        try:
            payload, in_tree, out_tree = se.serialize(compiled)
            data = pickle.dumps(
                {
                    "meta": {"compat": dict(compat), "label": label, "t": time.time()},
                    "payload": payload,
                    "in_tree": in_tree,
                    "out_tree": out_tree,
                }
            )
        except Exception as exc:
            self._emit("store_failed", key, error=str(exc)[-200:])
            return False
        path = self._path(key)
        try:
            _atomic_write(path, data)
            _atomic_write(path + ".sha256", _sha256_hex(data).encode())
        except OSError as exc:
            self._emit("store_failed", key, error=str(exc)[-200:])
            return False
        self.stats["stores"] += 1
        self._emit("store", key, label=label, bytes=len(data))
        self._enforce_lru()
        return True

    def get_or_compile(
        self,
        key: str,
        jitted,
        lower_args: tuple,
        compat: Dict[str, Any],
        *,
        donated: bool = False,
        label: Optional[str] = None,
    ) -> Tuple[Callable, bool, float]:
        """(executable, was_hit, build_seconds). A miss AOT-compiles
        ``jitted`` against ``lower_args`` and stores the result."""
        t0 = time.perf_counter()
        exe = self.load(key, compat, donated=donated, label=label)
        if exe is not None:
            return exe, True, time.perf_counter() - t0
        compiled = jitted.lower(*lower_args).compile()
        if not donated or donation_roundtrip_ok(self.dir):
            self.store(key, compiled, compat, label=label)
        return compiled, False, time.perf_counter() - t0

    # -- LRU ---------------------------------------------------------------

    def _enforce_lru(self) -> None:
        if self.dir is None or self.max_bytes <= 0:
            return
        entries = []
        total = 0
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for name in names:
            if not name.endswith(".bin"):
                continue
            p = os.path.join(self.dir, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            size = st.st_size
            try:
                size += os.stat(p + ".sha256").st_size
            except OSError:
                pass
            entries.append((st.st_mtime, size, name[: -len(".bin")]))
            total += size
        entries.sort()  # oldest mtime first
        while total > self.max_bytes and len(entries) > 1:
            mtime, size, key = entries.pop(0)
            self._evict(key, "lru")
            total -= size

    def manifest(self) -> Dict[str, Any]:
        """The flight-manifest block: where the cache lives and what it
        did this process."""
        return {
            "enabled": self.enabled,
            "dir": self.dir,
            "serialize_available": _serialize_mod() is not None,
            **{k: v for k, v in self.stats.items()},
        }
