"""Config system: JSON schema loading and data-driven inference.

Same JSON schema as the reference (sections ``Verbosity``, ``Dataset``,
``NeuralNetwork{Architecture, Variables_of_interest, Training, Profile}``,
``Visualization``) and the same ``update_config`` contract (reference:
hydragnn/utils/config_utils.py:23-99): after the data is loaded, the config
is completed from the data itself — output dimensions, input_dim,
max_neighbours (max in-degree over the train split), the PNA degree
histogram, edge_dim rules, and defaults.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from hydragnn_tpu.data.dataset import GraphSample


def load_config(config_file_or_dict) -> Dict[str, Any]:
    if isinstance(config_file_or_dict, dict):
        return config_file_or_dict
    with open(config_file_or_dict, "r") as f:
        return json.load(f)


def check_if_graph_size_variable(*splits: Sequence[GraphSample]) -> bool:
    """True if node counts differ across any samples (reference:
    hydragnn/preprocess/utils.py:22-77; the collective variants collapse to
    this host-side check — multi-host runs share the splits by
    construction of the sharded loader)."""
    sizes = {s.num_nodes for split in splits for s in split}
    return len(sizes) > 1


def max_in_degree(samples: Sequence[GraphSample]) -> int:
    """Max in-degree over a split (reference: config_utils.py:43-51)."""
    md = 0
    for s in samples:
        if s.num_edges == 0:
            continue
        counts = np.bincount(s.edge_index[1], minlength=s.num_nodes)
        md = max(md, int(counts.max()))
    return md


def pna_degree_histogram(samples: Sequence[GraphSample], max_degree: int) -> List[int]:
    """In-degree histogram over the train split (reference:
    hydragnn/utils/model.py:92-109 calculate_PNA_degree)."""
    hist = np.zeros(max_degree + 1, dtype=np.int64)
    for s in samples:
        counts = np.bincount(s.edge_index[1], minlength=s.num_nodes)
        hist += np.bincount(
            np.clip(counts, 0, max_degree), minlength=max_degree + 1
        )
    return hist.tolist()


def check_output_dim_consistent(sample: GraphSample, config: Dict[str, Any]) -> None:
    """Declared feature dims must match packed target dims (reference:
    config_utils.py:102-117)."""
    voi = config["NeuralNetwork"]["Variables_of_interest"]
    ds = config.get("Dataset")
    if ds is None:
        return
    for typ, idx, name in zip(voi["type"], voi["output_index"], voi["output_names"]):
        if typ == "graph":
            expected = ds["graph_features"]["dim"][idx]
            actual = int(np.asarray(sample.graph_targets[name]).reshape(-1).shape[0])
        else:
            expected = ds["node_features"]["dim"][idx]
            actual = int(np.asarray(sample.node_targets[name]).shape[-1])
        if actual != expected:
            raise ValueError(
                f"head {name}: packed dim {actual} != declared dim {expected}"
            )


def update_config(
    config: Dict[str, Any],
    train: Sequence[GraphSample],
    val: Sequence[GraphSample],
    test: Sequence[GraphSample],
) -> Dict[str, Any]:
    """Complete the config from the prepared data splits."""
    nn = config["NeuralNetwork"]
    arch = nn["Architecture"]
    voi = nn["Variables_of_interest"]

    graph_size_variable = check_if_graph_size_variable(train, val, test)
    first = train[0]
    if "Dataset" in config:
        check_output_dim_consistent(first, config)

    # ---- output dims from the packed targets (config_utils.py:120-156) ----
    dims_list = []
    for typ, name in zip(voi["type"], voi["output_names"]):
        if typ == "graph":
            dims_list.append(int(np.asarray(first.graph_targets[name]).reshape(-1).shape[0]))
        elif typ == "node":
            if (
                graph_size_variable
                and arch.get("output_heads", {}).get("node", {}).get("type")
                == "mlp_per_node"
            ):
                raise ValueError(
                    '"mlp_per_node" is not allowed for variable graph size; '
                    'set output_heads.node.type to "mlp" or "conv"'
                )
            dims_list.append(int(np.asarray(first.node_targets[name]).shape[-1]))
        else:
            raise ValueError(f"Unknown output type {typ}")
    arch["output_dim"] = dims_list
    arch["output_type"] = list(voi["type"])
    arch["num_nodes"] = first.num_nodes

    arch["input_dim"] = len(voi["input_node_features"])

    # ---- max_neighbours := max observed in-degree (config_utils.py:43-51) ----
    arch["max_neighbours"] = max_in_degree(train)

    if arch["model_type"] == "PNA":
        arch["pna_deg"] = pna_degree_histogram(train, arch["max_neighbours"])
    else:
        arch["pna_deg"] = None

    for key in ("radius", "num_gaussians", "num_filters"):
        arch.setdefault(key, None)

    # ---- edge_dim rules (config_utils.py:87-99) ----
    arch["edge_dim"] = None
    edge_models = ["PNA", "CGCNN", "SchNet"]
    if arch.get("edge_features"):
        if arch["model_type"] not in edge_models:
            raise ValueError(
                "Edge features can only be used with PNA, CGCNN, SchNet."
            )
        arch["edge_dim"] = len(arch["edge_features"])
    elif arch["model_type"] == "CGCNN":
        arch["edge_dim"] = 0
    # Dataset.Descriptors grow the edge attributes (ingest appends them
    # after the length column): +2 spherical angles, +4 point-pair
    # features. The reference's edge_dim rules ignore descriptors (its
    # descriptor path cannot run as written — abstractrawdataset.py:
    # 380-383 assigns the transform CLASS call to data); here the model's
    # edge_dim must match what the pipeline actually built.
    desc = config["Dataset"].get("Descriptors", {})
    extra = (2 if desc.get("SphericalCoordinates") else 0) + (
        4 if desc.get("PointPairFeatures") else 0
    )
    if extra:
        if not arch.get("edge_features"):
            raise ValueError(
                "Dataset.Descriptors require Architecture.edge_features "
                '(e.g. ["lengths"]) so the edge attributes are wired into '
                "an edge-aware model (PNA, CGCNN, SchNet)"
            )
        arch["edge_dim"] += extra

    arch.setdefault("freeze_conv_layers", False)
    arch.setdefault("initial_bias", None)
    # fused conv-layer Pallas kernel (ops/fused_conv.py): default on;
    # the knob only selects between numerically-matching paths, so off
    # is purely a debugging/ablation escape hatch
    arch.setdefault("fused_conv", True)
    nn["Training"].setdefault("Optimizer", {"type": "AdamW", "learning_rate": 1e-3})
    nn["Training"].setdefault("loss_function_type", "mse")
    arch.setdefault("SyncBatchNorm", False)
    # model-level introspection knobs (hydragnn_tpu/obs/introspect.py,
    # docs/OBSERVABILITY.md "Model-level diagnostics"): per-head
    # gradient diagnostics + hardware-efficiency ledger, sampled every
    # diag_every steps (0 = once per epoch); prometheus_dir enables the
    # per-epoch train.prom textfile export when set
    nn["Training"].setdefault("diagnostics", True)
    nn["Training"].setdefault("diag_every", 0)
    # NeuralNetwork.Parallel: the unified Partitioner's axis widths
    # (hydragnn_tpu/parallel/partitioner.py, docs/PARALLELISM.md).
    # ``fsdp`` shards parameters AND optimizer state over their own mesh
    # axis (models beyond one chip's HBM); ``edge`` shards each
    # sub-batch's edge arrays (giant graphs). The data width is derived
    # from the available devices, never configured here. No reference
    # analog (the reference's only model-parallel axis is DDP).
    nn.setdefault("Parallel", {})
    nn["Parallel"].setdefault("fsdp", 1)
    nn["Parallel"].setdefault("edge", 1)

    config = normalize_output_config(config)
    return config


def normalize_output_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """Wire up denormalization minmax tables (reference:
    config_utils.py:159-207). The tables come from the ingest step
    (prepare_dataset returns them); callers put them in Variables_of_interest
    as ``minmax_graph_feature``/``minmax_node_feature``."""
    voi = config["NeuralNetwork"]["Variables_of_interest"]
    if voi.get("denormalize_output"):
        node_mm = np.asarray(voi["minmax_node_feature"])
        graph_mm = np.asarray(voi["minmax_graph_feature"])
        voi["x_minmax"] = [node_mm[:, i].tolist() for i in voi["input_node_features"]]
        voi["y_minmax"] = []
        for typ, idx in zip(voi["type"], voi["output_index"]):
            mm = graph_mm if typ == "graph" else node_mm
            voi["y_minmax"].append(mm[:, idx].tolist())
    else:
        voi["denormalize_output"] = False
    return config


def get_log_name_config(config: Dict[str, Any]) -> str:
    """Deterministic run-dir name from hyperparameters (reference:
    config_utils.py:210-243)."""
    nn = config["NeuralNetwork"]
    arch, training = nn["Architecture"], nn["Training"]
    name = config["Dataset"]["name"] if "Dataset" in config else "dataset"
    cut = name.rfind("_") if name.rfind("_") > 0 else None
    return (
        f"{arch['model_type']}-r-{arch.get('radius')}"
        f"-ncl-{arch['num_conv_layers']}-hd-{arch['hidden_dim']}"
        f"-ne-{training['num_epoch']}"
        f"-lr-{training['Optimizer']['learning_rate']}"
        f"-bs-{training['batch_size']}"
        f"-data-{name[:cut]}"
        "-node_ft-"
        + "".join(str(x) for x in nn["Variables_of_interest"]["input_node_features"])
        + "-task_weights-"
        + "".join(f"{w}-" for w in arch["task_weights"])
    )


def save_config(config: Dict[str, Any], log_name: str, path: str = "./logs/") -> None:
    """Rank-0 JSON dump of the completed config (reference:
    config_utils.py:246-252)."""
    import jax

    if jax.process_index() != 0:
        return
    out_dir = os.path.join(path, log_name)
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(_jsonable(config), f)


def _jsonable(obj):
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return obj
