"""Make the ``JAX_PLATFORMS`` env var actually effective.

Some images register an accelerator PJRT plugin from ``sitecustomize``
that wins over the env var, silently landing "CPU" runs on the real
device (observed with the tunneled-TPU image this project develops on).
Pinning the config before first backend use restores the documented env
semantics; example drivers and subprocess tests call this at startup so
``JAX_PLATFORMS=cpu python driver.py`` means what it says.
"""

from __future__ import annotations

import os


def pin_platform_from_env() -> None:
    """If ``JAX_PLATFORMS`` is set, pin it via ``jax.config`` and verify
    the backend actually honors it. Callers should invoke this before any
    other jax use; if the backend initialized first (pin arrives too
    late) the mismatch is loudly reported instead of silently landing the
    run on the wrong device — the exact failure this module prevents."""
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import sys

    import jax

    try:
        jax.config.update("jax_platforms", plat)
    except RuntimeError:
        pass  # backend already up; the check below reports the mismatch
    # JAX_PLATFORMS may be a priority list ("tpu,cpu"); any entry is a
    # legitimate outcome (jax falls back down the list)
    wants = [p.strip().lower() for p in plat.split(",") if p.strip()]
    got = jax.default_backend().lower()
    if got not in wants:
        print(
            f"WARNING: JAX_PLATFORMS={plat!r} requested but the jax backend "
            f"is {got!r} — the platform was pinned after backend "
            "initialization; call pin_platform_from_env() earlier",
            file=sys.stderr,
        )
