"""Make the ``JAX_PLATFORMS`` env var actually effective.

Some images register an accelerator PJRT plugin from ``sitecustomize``
that wins over the env var, silently landing "CPU" runs on the real
device (observed with the tunneled-TPU image this project develops on).
Pinning the config before first backend use restores the documented env
semantics; example drivers and subprocess tests call this at startup so
``JAX_PLATFORMS=cpu python driver.py`` means what it says.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


class BackendInitError(RuntimeError):
    """The pinned JAX backend failed to initialize.

    Raised by :func:`pin_platform_from_env` instead of letting the raw
    jax RuntimeError unwind: driver-facing scripts (bench.py,
    bench_serve.py) catch this and emit ``.record`` — a compact
    structured failure line — rather than dying mid-traceback (the r05
    ``rc=1`` capture this exists for). ``.record`` keeps the backend's
    message truncated so the whole record survives a ~2000-char stdout
    tail capture."""

    def __init__(self, platform: str, cause: BaseException, stage: str = "backend_init"):
        msg = str(cause).strip() or repr(cause)
        # keep the tail: jax backend errors put the actionable line last
        short = msg[-400:] if len(msg) > 400 else msg
        super().__init__(
            f"JAX backend init failed for JAX_PLATFORMS={platform!r}: {short}"
        )
        self.record = {
            "failure": "backend_init",
            "stage": stage,
            "jax_platforms": platform,
            "error": short,
            "error_type": type(cause).__name__,
        }


def pin_virtual_cpu_mesh(n_devices: int = 8) -> None:
    """Force jax onto a virtual CPU mesh of at least ``n_devices`` devices.

    The single source of the recipe used by ``tests/conftest.py`` and
    ``__graft_entry__.dryrun_multichip``: set ``JAX_PLATFORMS=cpu``,
    ensure ``XLA_FLAGS`` requests >= ``n_devices`` host devices (raising
    a pre-existing smaller count, since XLA honors whatever value is
    present when the backend initializes), and pin ``jax_platforms`` via
    config so the sitecustomize-registered accelerator plugin cannot win.

    Must be called before the jax backend initializes. This module
    imports no jax at module level precisely so callers can import it
    (by path if needed) before jax.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(_COUNT_FLAG + r"=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"{_COUNT_FLAG}={n_devices}"
        )

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already up; require_virtual_cpu_mesh diagnoses it


def require_virtual_cpu_mesh(n_devices: int) -> None:
    """Fail fast (explicit raise — survives ``python -O``) if jax did not
    land on a CPU backend with >= ``n_devices`` devices, i.e. the backend
    initialized before :func:`pin_virtual_cpu_mesh` took effect."""
    import jax

    if jax.default_backend() != "cpu":
        raise RuntimeError(
            "expected the virtual CPU mesh but the jax backend is "
            f"{jax.default_backend()!r} — jax initialized before "
            "pin_virtual_cpu_mesh() was called"
        )
    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"need {n_devices} virtual CPU devices, got {len(jax.devices())} "
            "— XLA_FLAGS was read before "
            f"{_COUNT_FLAG} took effect (backend initialized too early)"
        )


# Substrings that mark a backend-init failure as TRANSIENT (the device
# is momentarily unreachable/held and a later attempt can succeed):
# gRPC status names the tunneled-TPU plugin surfaces, connection-layer
# noise, and the device-held-by-a-dying-process window that
# tools/chip_hygiene.py exists to diagnose. Anything else (unknown
# platform name, missing plugin, bad flags) is a genuine config error —
# retrying it just burns two minutes to fail identically.
_TRANSIENT_PATTERNS = (
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "resource_exhausted",
    "resource exhausted",
    "failed to connect",
    "connection reset",
    "connection refused",
    "socket closed",
    "temporarily",
    "timed out",
    "device or resource busy",
    "already in use",
    "libtpu",
    "unreachable",
)


def is_transient_backend_error(exc: BaseException) -> bool:
    msg = str(exc).lower()
    return any(p in msg for p in _TRANSIENT_PATTERNS)


def _clear_failed_backends() -> None:
    """Best-effort reset of jax's cached backend state so the next
    ``jax.devices()`` re-attempts initialization instead of replaying
    the cached failure. API location moved across jax versions; all
    paths are optional."""
    try:
        from jax.extend import backend as _jex_backend

        _jex_backend.clear_backends()
        return
    except Exception:
        pass
    try:
        from jax._src import xla_bridge as _bridge

        _bridge._clear_backends()
    except Exception:
        pass


def init_backend_with_retry(
    attempts: int = 5,
    delays: tuple = (5.0, 10.0, 30.0, 60.0),
    sleep=None,
    on_retry=None,
):
    """Pin the platform and bring the jax backend up, retrying TRANSIENT
    failures with backoff (default: 5 attempts over ~2 minutes — long
    enough for a lingering chip-holder from the previous run to die,
    short enough that a driver's capture window still sees the result).

    Returns ``(devices, retries_used)``. Genuine config errors raise on
    the FIRST attempt; after the last attempt the error propagates
    either way. Whatever raises is normalized to :class:`BackendInitError`
    whose ``.record`` carries ``retries`` — the structured failure line
    bench.py prints gains the count (VERDICT next-round #1).

    ``on_retry(attempt, exc, delay)`` observes each retry (benches log a
    flight-record event + stderr line).
    """
    import time

    if sleep is None:
        sleep = time.sleep
    last: BaseException = RuntimeError("init_backend_with_retry: attempts < 1")
    for attempt in range(max(attempts, 1)):
        try:
            if attempt > 0:
                _clear_failed_backends()
            pin_platform_from_env()
            import jax

            return jax.devices(), attempt
        except (BackendInitError, RuntimeError, AssertionError) as exc:
            last = exc
            transient = is_transient_backend_error(exc)
            final = attempt >= max(attempts, 1) - 1
            if not transient or final:
                break
            delay = delays[min(attempt, len(delays) - 1)] if delays else 0.0
            if on_retry is not None:
                on_retry(attempt + 1, exc, delay)
            sleep(delay)
    if isinstance(last, BackendInitError):
        last.record["retries"] = attempt
        raise last
    err = BackendInitError(os.environ.get("JAX_PLATFORMS", ""), last)
    err.record["retries"] = attempt
    raise err from last


def pin_platform_from_env() -> None:
    """If ``JAX_PLATFORMS`` is set, pin it via ``jax.config`` and verify
    the backend actually honors it. Callers should invoke this before any
    other jax use; if the backend initialized first (pin arrives too
    late) the mismatch is loudly reported instead of silently landing the
    run on the wrong device — the exact failure this module prevents."""
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import sys

    import jax

    try:
        jax.config.update("jax_platforms", plat)
    except RuntimeError:
        pass  # backend already up; the check below reports the mismatch
    # JAX_PLATFORMS may be a priority list ("tpu,cpu"); any entry is a
    # legitimate outcome (jax falls back down the list)
    wants = [p.strip().lower() for p in plat.split(",") if p.strip()]
    try:
        got = jax.default_backend().lower()
    # RuntimeError on current jax; older xla_bridge builds can surface a
    # bare AssertionError from backends() when no platform comes up
    except (RuntimeError, AssertionError) as exc:
        # the pinned backend exists but cannot come up (driver handed us
        # an unreachable device, plugin crash, ...): surface a typed,
        # structured failure the calling script can report cleanly
        raise BackendInitError(plat, exc) from exc
    if got not in wants:
        print(
            f"WARNING: JAX_PLATFORMS={plat!r} requested but the jax backend "
            f"is {got!r} — the platform was pinned after backend "
            "initialization; call pin_platform_from_env() earlier",
            file=sys.stderr,
        )
