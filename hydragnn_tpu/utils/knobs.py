"""Central registry of every ``HYDRAGNN_*`` environment knob.

Nine PRs scattered ``os.environ.get("HYDRAGNN_...")`` reads across
ops/, train/, serve/, data/, resilience/ and utils/ with no single
place that says what exists, what type each value is, what the default
is, or who consumes it. This module is that place: every knob is
declared here once (name, type, default, consumer module, one doc
line), every library read goes through the typed accessors below, and
two enforcement arms keep it honest:

  - **Static**: graftlint rule HG006 (``hydragnn_tpu/lint/rules.py``)
    fails CI on any ``HYDRAGNN_*`` string literal in the tree that is
    not declared here — a new knob cannot ship undocumented — and on
    any declared knob no longer referenced anywhere (stale registry).
  - **Runtime**: the accessors raise :class:`UndeclaredKnobError` for
    names missing from the registry, so a typo'd read fails loudly at
    the call site instead of silently returning the default forever.

``docs/KNOBS.md`` is GENERATED from this registry
(``python -m hydragnn_tpu.utils.knobs --write docs/KNOBS.md``);
tests/test_graftlint.py asserts the committed file matches, so the
docs cannot drift from the code.

This module must stay stdlib-only: the linter and the docs generator
load it without initializing jax or the rest of the package.
"""

from __future__ import annotations

import os
from typing import Dict, List, NamedTuple, Optional


class Knob(NamedTuple):
    name: str
    type: str  # "str" | "int" | "float" | "bool" | "flag" | "spec" | "path"
    default: Optional[str]  # None = unset means disabled/absent
    consumer: str  # the module that reads it
    doc: str


_K = Knob  # registry-entry marker the linter's AST parser keys on

#: Every ``HYDRAGNN_*`` env knob the tree reads, alphabetical. A
#: ``spec``-typed knob carries a structured value (``N``, ``N:M``, a
#: path, ...) documented in its consumer; a ``flag`` is significant
#: merely by being set non-empty.
KNOBS: Dict[str, Knob] = {
    k.name: k
    for k in (
        _K("HYDRAGNN_AUTO_RESUME", "flag", None, "resilience/preempt.py",
           "Set to 1 by the restart supervisor: resume from the run's own "
           "checkpoint instead of starting over."),
        _K("HYDRAGNN_BCAST_CE", "int", "1024", "ops/segment_pallas.py",
           "Edges per DMA chunk for the CSR-broadcast gather kernel "
           "(multiple of 16; overrides the TUNE_TILES.json table)."),
        _K("HYDRAGNN_BENCH_GATE_TOL", "float", "0.15", "tools/bench_gate.py",
           "Fractional regression tolerance for the CI perf gate's "
           "graphs/sec, MFU, and traffic arms."),
        _K("HYDRAGNN_BN", "int", "128", "ops/segment_pallas.py",
           "Output rows (nodes) per grid step in the segment kernels "
           "(multiple of 16; overrides the TUNE_TILES.json table)."),
        _K("HYDRAGNN_CE", "int", "512", "ops/segment_pallas.py",
           "Edges DMA'd per inner chunk in the segment-sum kernels "
           "(multiple of 16; overrides the TUNE_TILES.json table)."),
        _K("HYDRAGNN_DEBUG_BATCH", "bool", "0", "data/loader.py",
           "Validate layout contracts (sorted receivers, masked-edge "
           "targeting, window coverage) on every host batch."),
        _K("HYDRAGNN_DEVICE_KIND", "str", "default", "ops/segment_pallas.py",
           "Row selector into TUNE_TILES.json for block/chunk defaults "
           "(never read from jax.devices(): import must not init a backend)."),
        _K("HYDRAGNN_DIAGNOSTICS", "bool", "1", "train/loop.py",
           "Force-disable model introspection (per-head grad norms, MFU "
           "ledger) regardless of config; the tier-1 suite sets 0."),
        _K("HYDRAGNN_DRIFT_REF", "path", None, "serve/server.py",
           "Drift reference window: a training flight.jsonl (the "
           "run_start.manifest stats block) or a bare stats JSON. Arms "
           "the DriftMonitor + drift trigger rules on server start."),
        _K("HYDRAGNN_EXEC_CACHE", "path", None, "utils/exec_cache.py",
           "Directory of the persistent AOT executable cache; unset = "
           "inert. Deliberately survives supervisor restart env-strips."),
        _K("HYDRAGNN_EXEC_CACHE_MAX_MB", "float", "512", "utils/exec_cache.py",
           "LRU size bound for the executable cache directory."),
        _K("HYDRAGNN_FLEET_COOLDOWN_S", "float", "30", "fleet/controller.py",
           "Minimum seconds between autoscaler scale decisions (up, down, "
           "or replace each re-arm it)."),
        _K("HYDRAGNN_FLEET_EVAL_EVERY_S", "float", "1.0", "fleet/controller.py",
           "Period of the fleet controller's background evaluation loop."),
        _K("HYDRAGNN_FLEET_MAX_REPLICAS", "int", "4", "fleet/controller.py",
           "Upper replica bound: a breach verdict at the cap records a "
           "fleet_scale hold event instead of spawning."),
        _K("HYDRAGNN_FLEET_MIN_REPLICAS", "int", "1", "fleet/controller.py",
           "Lower replica bound the quiet-fleet scale-down never crosses."),
        _K("HYDRAGNN_FLEET_QUIET_S", "float", "60", "fleet/controller.py",
           "Seconds the fleet queue must stay below the quiet threshold "
           "before the controller retires a replica."),
        _K("HYDRAGNN_FLEET_TENANT_BURST", "float", "32", "fleet/router.py",
           "Default per-tenant token-bucket burst capacity (tokens; one "
           "admission costs one token)."),
        _K("HYDRAGNN_FLEET_TENANT_RATE", "float", "0", "fleet/router.py",
           "Default per-tenant admission refill rate in requests/s for "
           "tenants without an explicit quota; 0 = unlimited."),
        _K("HYDRAGNN_FULL_MATRIX", "flag", None, "tests/test_train_matrix.py",
           "Opt into the full 7-model acceptance matrix (~15 min)."),
        _K("HYDRAGNN_GRAFTCHECK", "bool", "1", "train/loop.py",
           "Stamp the compiled-IR contract block (lint/ir.py CC001-CC006) "
           "into every run_start flight manifest; 0 skips the lowering."),
        _K("HYDRAGNN_GRAFTCHECK_LAYOUTS", "str", "dp,fsdp2",
           "tools/graftcheck.py",
           "Comma-separated named Partitioner layouts the graftcheck CLI "
           "audits by default (dp = pure data parallel, fsdp2 = fsdp=2)."),
        _K("HYDRAGNN_INCIDENT_COOLDOWN_S", "float", "300",
           "obs/triggers.py",
           "Minimum seconds between admitted SLO trigger firings (the "
           "engine's rate limit against incident storms)."),
        _K("HYDRAGNN_INCIDENT_MAX", "int", "5", "obs/triggers.py",
           "Incident count cap per engine per run; further verdicts are "
           "suppressed (counted in the run_end triggers block)."),
        _K("HYDRAGNN_INCIDENT_OVERHEAD_PCT", "float", "5",
           "obs/triggers.py",
           "Profiler-capture overhead budget as a percent of run wall "
           "time; a new incident that would exceed it is suppressed."),
        _K("HYDRAGNN_INCIDENT_PROFILE_S", "float", "10", "obs/triggers.py",
           "Wall-time bound on one incident's profiler capture (whichever "
           "of steps/seconds trips first stops the trace)."),
        _K("HYDRAGNN_INCIDENT_PROFILE_STEPS", "int", "3", "obs/triggers.py",
           "Step-count bound on one incident's profiler capture "
           "(ticks of the capturing loop, train steps or serve batches)."),
        _K("HYDRAGNN_INJECT_DONATION_CHECK_FAIL", "flag", None,
           "utils/exec_cache.py",
           "Force the donation round-trip gate to report failure: the "
           "cached donated executable is evicted and live-compiled."),
        _K("HYDRAGNN_INJECT_DRIFT", "spec", None, "resilience/inject.py",
           "SHIFT: add a deterministic covariate shift of SHIFT to every "
           "incoming request's node features at admission (drives the "
           "feature_drift trigger end to end)."),
        _K("HYDRAGNN_INJECT_GRAFTCHECK", "spec", None, "lint/ir.py",
           "cc001..cc006 (comma-separated): plant one real compiled-IR "
           "violation per named contract for the graftcheck self-test."),
        _K("HYDRAGNN_INJECT_KILL_CHECKPOINT", "spec", None,
           "resilience/inject.py",
           "K: during the K-th checkpoint save, write a torn file and "
           "SIGKILL the process (integrity-validation drill)."),
        _K("HYDRAGNN_INJECT_LOCK_ORDER", "spec", None, "utils/syncdebug.py",
           "LOCKA,LOCKB: once both named locks register with the runtime "
           "witness, synthesize an A->B acquisition then the B->A "
           "inversion (one-shot; bookkeeping only, no real lock taken) "
           "to drive the lock_order violation path end to end."),
        _K("HYDRAGNN_INJECT_NAN_STEP", "spec", None, "resilience/inject.py",
           "N[:M]: replace node features with NaN for train steps "
           "N..N+M-1 (drives the non-finite sentry)."),
        _K("HYDRAGNN_INJECT_PILOT_CANARY_REGRESS", "flag", None,
           "resilience/inject.py",
           "Inflate the retrain candidate's canary scores so the gate "
           "rejects it (the pilot must cool down on the old weights)."),
        _K("HYDRAGNN_INJECT_PILOT_HUNG_TUNE", "spec", None,
           "resilience/inject.py",
           "S: the pilot's fine-tune job wedges for S seconds before "
           "doing any work (drives the supervisor wall-clock kill)."),
        _K("HYDRAGNN_INJECT_PILOT_TORN_RELOAD", "flag", None,
           "resilience/inject.py",
           "Corrupt the retrain candidate's weights between canary and "
           "reload (the server's own reload canary must reject them)."),
        _K("HYDRAGNN_INJECT_PILOT_TRAIN_CRASH", "spec", None,
           "resilience/inject.py",
           "N: the pilot's first N fine-tune attempts exit nonzero "
           "before training (N=1 proves retry-with-backoff; N >= the "
           "attempt budget proves the failed-cycle path)."),
        _K("HYDRAGNN_INJECT_POD_BARRIER_STALL", "spec", None,
           "resilience/inject.py",
           "H:S: simulated host H sleeps S seconds before entering any "
           "pod_barrier (once per process) — peers must time out, "
           "proceed, and record the missing host."),
        _K("HYDRAGNN_INJECT_POD_KILL_HOST", "spec", None,
           "resilience/inject.py",
           "H:G: host H SIGKILLs itself during the generation-G pod "
           "checkpoint save, after its shard bytes but before its "
           "manifest (the torn-generation drill)."),
        _K("HYDRAGNN_INJECT_POD_LOST_HEARTBEAT", "spec", None,
           "resilience/inject.py",
           "H:E: host H stops writing liveness heartbeats from epoch E "
           "on while continuing to train (drives host_lost detection)."),
        _K("HYDRAGNN_INJECT_POD_TORN_SHARD", "spec", None,
           "resilience/inject.py",
           "H:G: host H writes its generation-G pod shard truncated "
           "while the sha256 sidecar keeps the good digest (restore "
           "must reject by checksum and fall back a generation)."),
        _K("HYDRAGNN_INJECT_SERVE_KILL_DISPATCH", "spec", None,
           "resilience/inject.py",
           "K: the K-th dispatched serve batch raises outside request "
           "isolation, killing the dispatch thread."),
        _K("HYDRAGNN_INJECT_SERVE_NAN", "spec", None, "resilience/inject.py",
           "N: serve outputs become NaN for any batch holding request N "
           "(silent-corruption poison)."),
        _K("HYDRAGNN_INJECT_SERVE_RAISE", "spec", None, "resilience/inject.py",
           "N: the serving forward raises for any batch holding request "
           "N (poison request)."),
        _K("HYDRAGNN_INJECT_SERVE_TORN_RELOAD", "flag", None,
           "resilience/inject.py",
           "Corrupt reload candidate weights before the canary (the "
           "canary must fail and the old weights keep serving)."),
        _K("HYDRAGNN_INJECT_SERVE_WEDGE", "spec", None,
           "resilience/inject.py",
           "N[:S]: the dispatch thread sleeps S seconds (default 5) in "
           "the forward of the batch holding request N."),
        _K("HYDRAGNN_INJECT_SIGTERM_EPOCH", "spec", None,
           "resilience/inject.py",
           "E: SIGTERM self-signal at the start of epoch E."),
        _K("HYDRAGNN_INJECT_SIGTERM_STEP", "spec", None,
           "resilience/inject.py",
           "N: SIGTERM self-signal before train step N."),
        _K("HYDRAGNN_INJECT_STALL_LOADER", "spec", None,
           "resilience/inject.py",
           "B:S: the loader's producer sleeps S seconds before building "
           "batch B of an epoch (drives the hang watchdog)."),
        _K("HYDRAGNN_INJECT_STRAGGLER", "spec", None, "obs/spans.py",
           "HOST:MS: when this process's podview host index equals HOST, "
           "sleep MS milliseconds inside every train step's span path — a "
           "deterministic straggler that drives the step_skew trigger "
           "(being an INJECT knob it also forces per-step dispatch)."),
        _K("HYDRAGNN_INJECT_TRIGGER", "spec", None, "resilience/inject.py",
           "RULE: force-fire the named SLO trigger rule once at the next "
           "TriggerEngine.evaluate (drives incident capture on demand)."),
        _K("HYDRAGNN_LOCAL_MIN_ROWS", "int", "200000", "ops/segment_pallas.py",
           "Row threshold below which the local-window kernel family "
           "falls back (its fixed per-call cost needs large operands)."),
        _K("HYDRAGNN_LOCK_DEBUG", "bool", "0", "utils/syncdebug.py",
           "Wrap every declared lock in the runtime lock-order witness: "
           "observed acquisition order is checked against graftsync's "
           "static lock-order graph; a violation dumps all thread stacks "
           "into the flight record as a lock_order event (never raises)."),
        _K("HYDRAGNN_MATRIX_REPORT", "path", None, "tests/test_train_e2e.py",
           "Write the acceptance-matrix JSON report to this path."),
        _K("HYDRAGNN_NUM_PREFETCH", "int", "2", "data/loader.py",
           "Default loader prefetch depth (an explicit constructor "
           "argument wins)."),
        _K("HYDRAGNN_PALLAS", "str", "auto", "ops/segment_pallas.py",
           "Kernel dispatch: auto = Pallas on TPU for sorted 128-lane "
           "data; 1 = force on TPU; interpret = interpret mode anywhere "
           "(CPU tests); 0 = force XLA."),
        _K("HYDRAGNN_PILOT_CANARY_SAMPLES", "int", "16", "pilot/pilot.py",
           "Per-slice sample bound for the canary eval (reference slice "
           "and drifted window each score at most this many samples)."),
        _K("HYDRAGNN_PILOT_CANARY_TOL", "float", "0.2", "pilot/pilot.py",
           "Allowed fractional MAE regression of the retrain candidate "
           "vs the serving weights on EACH canary slice; worse than "
           "baseline*(1+tol) on either slice rejects the candidate."),
        _K("HYDRAGNN_PILOT_COOLDOWN_S", "float", "60", "pilot/pilot.py",
           "Hysteresis window after any retrain cycle (success or "
           "failure) during which new drift incidents are counted but "
           "never start another cycle — the anti-storm belt."),
        _K("HYDRAGNN_PILOT_MAX_WALL_S", "float", "600", "pilot/pilot.py",
           "Hard wall clock per fine-tune attempt; a hung job is killed "
           "and classified hung/79 by the supervisor wall-clock runner."),
        _K("HYDRAGNN_PILOT_STUCK_AFTER", "int", "3", "pilot/pilot.py",
           "Consecutive failed recovery cycles before the pilot stops "
           "flapping and escalates a terminal pilot_stuck incident."),
        _K("HYDRAGNN_PILOT_TUNE_ATTEMPTS", "int", "2", "pilot/pilot.py",
           "Crash-class restart budget for one cycle's fine-tune job "
           "(the supervisor's max_restarts)."),
        _K("HYDRAGNN_PILOT_TUNE_BACKOFF_S", "float", "1.0", "pilot/pilot.py",
           "Base of the exponential backoff between fine-tune restart "
           "attempts within one cycle."),
        _K("HYDRAGNN_PILOT_TUNE_EPOCHS", "int", "2", "pilot/tune.py",
           "Epochs the incremental fine-tune runs over the pinned spool "
           "window (starting from the serving checkpoint)."),
        _K("HYDRAGNN_PODVIEW", "bool", "0", "obs/podview.py",
           "Force-enable the pod-visibility plane (per-host flight "
           "shards + SkewMonitor) even in a single-process run — the "
           "simulated-host mode ci.sh and the tests use. Real multihost "
           "runs (jax.process_count() > 1) enable it automatically."),
        _K("HYDRAGNN_PODVIEW_HOST", "int", "-1", "obs/podview.py",
           "Override this process's podview host index (simulated hosts "
           "on one machine); -1/unset = use jax.process_index()."),
        _K("HYDRAGNN_PODVIEW_HOSTS", "int", "0", "obs/podview.py",
           "Override the expected host count the SkewMonitor and the "
           "merge reader wait for; 0/unset = jax.process_count()."),
        _K("HYDRAGNN_PODVIEW_RUN_ID", "str", None, "obs/podview.py",
           "Shared run id stamped into host_epoch events — the merge "
           "join key across host shards; unset = the run's log name."),
        _K("HYDRAGNN_PODVIEW_SKEW", "float", "0", "train/loop.py",
           "step_skew trigger threshold on podview.skew_frac; 0/unset = "
           "derive from the committed scaling model's skew_tolerance "
           "block (fallback 0.25)."),
        _K("HYDRAGNN_PODVIEW_STALL_S", "float", "120", "train/loop.py",
           "host_stall trigger threshold: seconds since the least-recent "
           "host's last flight event before the stall incident fires."),
        _K("HYDRAGNN_POD_BARRIER_TIMEOUT_S", "float", "60",
           "resilience/podckpt.py",
           "Bounded-wait limit for pod_barrier rendezvous; on expiry "
           "the host PROCEEDS and records the missing peers (a pod "
           "must degrade to evidence, never to a hang)."),
        _K("HYDRAGNN_POD_CKPT", "bool", "1", "train/loop.py",
           "Pod-sharded generation checkpointing (resilience/podckpt.py) "
           "whenever the run spans more than one podview host; 0 keeps "
           "only the single-host msgpack path."),
        _K("HYDRAGNN_POD_COMMIT_TIMEOUT_S", "float", "120",
           "resilience/podckpt.py",
           "How long rank 0 waits for every host's shard manifest "
           "before giving up on committing a generation (the COMMIT "
           "marker is only ever written after all manifests validate)."),
        _K("HYDRAGNN_POD_HEARTBEAT_S", "float", "1.0",
           "resilience/podckpt.py",
           "Write period of each host's liveness heartbeat file in the "
           "pod sync dir."),
        _K("HYDRAGNN_POD_KEEP_GENS", "int", "3", "resilience/podckpt.py",
           "Committed pod checkpoint generations retained; older ones "
           "are pruned (marker first, then shards) after each commit."),
        _K("HYDRAGNN_POD_LOST_AFTER_S", "float", "0",
           "resilience/podckpt.py",
           "Declare a peer host lost when its newest heartbeat is older "
           "than this many seconds (host_lost flight event + trigger). "
           "0/unset = detection off — required for the sequential "
           "simulated-host CI mode where stale beats are normal."),
        _K("HYDRAGNN_RESIDENCY_VMEM_MB", "float", "12", "ops/fused_conv.py",
           "VMEM budget the cross-layer resident conv-stack kernel may "
           "claim (a TPU core has ~16 MB; the pipeline needs headroom)."),
        _K("HYDRAGNN_SPOOL", "bool", "0", "serve/server.py",
           "Enable the served-traffic request spool (obs/spool.py): "
           "sampled requests + predictions appended to rotating HGC "
           "shards under <log_dir>/serve/spool."),
        _K("HYDRAGNN_SPOOL_MAX_MB", "float", "64", "serve/server.py",
           "Disk bound for the request spool; once finalized shards "
           "exceed it, the oldest shards are LRU-evicted."),
        _K("HYDRAGNN_SPOOL_SAMPLE", "int", "8", "serve/server.py",
           "Spool every Nth answered request (1 = every request)."),
        _K("HYDRAGNN_TELEMETRY", "bool", "1", "obs/registry.py",
           "Process-wide telemetry gate: 0/false/off disables the "
           "registry, flight recorder, spans, and compile monitor."),
        _K("HYDRAGNN_TILE_SHAPE", "str", "default", "ops/segment_pallas.py",
           "Shape-tag selector into TUNE_TILES.json for block/chunk "
           "defaults."),
        _K("HYDRAGNN_TPU_TESTS", "flag", None, "tests/test_tpu_chip.py",
           "Opt into the real-chip TPU kernel suite (needs hardware)."),
        _K("HYDRAGNN_TRACE", "bool", "1", "obs/trace.py",
           "Per-request/step distributed tracing gate (within the "
           "process-wide HYDRAGNN_TELEMETRY gate): 0 disables tracing."),
        _K("HYDRAGNN_TRACE_SAMPLE", "int", "100", "obs/trace.py",
           "Record every Nth finished trace into the flight record as a "
           "trace_capture event (the first trace is always recorded)."),
        _K("HYDRAGNN_WATCHDOG_S", "float", "0", "train/loop.py",
           "Hang-watchdog stall threshold in seconds; 0/unset = off. "
           "Must be sized above the worst expected compile time."),
    )
}

#: The injection family prefix: the restart supervisor strips matching
#: vars from restarted children, and the scan-epoch eligibility check
#: refuses whole-epoch dispatch while any non-serve member is set.
INJECT_PREFIX = "HYDRAGNN_INJECT_"
_FALSE_WORDS = ("0", "false", "off")


class UndeclaredKnobError(KeyError):
    """A ``HYDRAGNN_*`` name was read that the registry does not
    declare — add a :class:`Knob` entry (and regenerate docs/KNOBS.md)
    before wiring a new knob into code."""


def _check_declared(name: str) -> None:
    if name not in KNOBS:
        raise UndeclaredKnobError(
            f"{name} is not declared in hydragnn_tpu/utils/knobs.py; "
            "register it (and regenerate docs/KNOBS.md) before reading it"
        )


def raw(name: str, default: Optional[str] = None) -> Optional[str]:
    """The raw env string (or ``default`` when unset). The one
    registry-validated primitive every other accessor goes through."""
    _check_declared(name)
    return os.environ.get(name, default)


def get_str(name: str, default: Optional[str] = None) -> Optional[str]:
    return raw(name, default)


def get_int(name: str, default: int) -> int:
    v = raw(name)
    return default if v is None or v == "" else int(v)


def get_float(name: str, default: float) -> float:
    v = raw(name)
    return default if v is None or v == "" else float(v)


def get_bool(name: str, default: bool) -> bool:
    """The repo's boolean-knob convention: any of 0/false/off (any
    case) is False, everything else set is True."""
    v = raw(name)
    if v is None:
        return default
    return v.lower() not in _FALSE_WORDS


def is_set(name: str) -> bool:
    """Flag semantics: set to any non-empty value."""
    return bool(raw(name))


def active_injections(
    include_serve: bool = True, env: Optional[Dict[str, str]] = None
) -> List[str]:
    """Sorted ``HYDRAGNN_INJECT_*`` names currently set in the
    environment (or in ``env`` when given — the restart supervisor
    passes a CHILD's environment to derive its strip set from the same
    registry view everything else uses). ``include_serve=False`` drops
    the serve-side family — what the scan-epoch eligibility check cares
    about (train-side injections are step-indexed and need per-step
    dispatch)."""
    src = os.environ if env is None else env
    return sorted(
        k
        for k in src
        if k.startswith(INJECT_PREFIX)
        and (include_serve or not k.startswith("HYDRAGNN_INJECT_SERVE"))
    )


def generate_docs() -> str:
    """docs/KNOBS.md, rendered from the registry."""
    lines = [
        "# Environment knobs",
        "",
        "GENERATED from `hydragnn_tpu/utils/knobs.py` — edit the registry,",
        "then `python -m hydragnn_tpu.utils.knobs --write docs/KNOBS.md`.",
        "`tests/test_graftlint.py` asserts this file matches the registry,",
        "and lint rule HG006 (docs/LINT.md) fails CI on any `HYDRAGNN_*`",
        "read the registry does not declare.",
        "",
        "A `flag` knob is significant merely by being set non-empty; a",
        "`spec` knob carries a structured value documented below; `bool`",
        "knobs treat 0/false/off (any case) as false and anything else",
        "set as true.",
        "",
        "| Knob | Type | Default | Consumer | What it does |",
        "|---|---|---|---|---|",
    ]
    for k in sorted(KNOBS.values()):
        default = "*(unset)*" if k.default is None else f"`{k.default}`"
        lines.append(
            f"| `{k.name}` | {k.type} | {default} | `{k.consumer}` | {k.doc} |"
        )
    lines += [
        "",
        "The `HYDRAGNN_INJECT_*` family is deterministic fault injection",
        "(`hydragnn_tpu/resilience/inject.py`, docs/RESILIENCE.md): every",
        "member is a no-op unless set, and the restart supervisor strips",
        "the whole family from restarted children so each injected fault",
        "fires exactly once per supervised run.",
        "",
    ]
    return "\n".join(lines)


def _main(argv: List[str]) -> int:
    if argv[:1] == ["--write"] and len(argv) == 2:
        with open(argv[1], "w") as f:
            f.write(generate_docs())
        print(f"wrote {argv[1]} ({len(KNOBS)} knobs)")
        return 0
    if argv[:1] == ["--check"] and len(argv) == 2:
        try:
            with open(argv[1]) as f:
                committed = f.read()
        except OSError:
            committed = ""
        if committed != generate_docs():
            print(
                f"{argv[1]} is stale: regenerate with "
                "python -m hydragnn_tpu.utils.knobs --write " + argv[1]
            )
            return 1
        print(f"{argv[1]} matches the registry ({len(KNOBS)} knobs)")
        return 0
    print(generate_docs(), end="")
    return 0


if __name__ == "__main__":
    import sys

    raise SystemExit(_main(sys.argv[1:]))
