from hydragnn_tpu.models.base import (
    HydraModel,
    ModelConfig,
    PerNodeMLP,
    masked_loss,
    model_loss,
)
from hydragnn_tpu.models.create import (
    create_model,
    create_model_config,
    model_config_from_dict,
)
from hydragnn_tpu.models import convs
from hydragnn_tpu.models import layers

__all__ = [
    "HydraModel",
    "ModelConfig",
    "PerNodeMLP",
    "masked_loss",
    "model_loss",
    "create_model",
    "create_model_config",
    "model_config_from_dict",
    "convs",
    "layers",
]
