"""The model chassis: shared message-passing encoder + multi-head decoders.

TPU-native re-design of the reference's ``Base`` class (reference:
hydragnn/models/Base.py:22-378): one conv stack with interleaved
BatchNorm+ReLU, masked global mean pooling, then N decoder heads — graph
heads share a dense trunk (Base.py:168-177) with per-head MLPs, node heads
come in three flavors ``mlp`` / ``mlp_per_node`` / ``conv``
(Base.py:205-235) — and a weighted multi-task loss with normalized weights
(Base.py:69-80,304-321).

Differences by design:
  - all shapes static, all reductions masked (padding-graph slots never
    contribute to pooling, BN stats, or the loss);
  - targets are a dict-of-heads on the GraphBatch instead of the ragged
    ``data.y``/``y_loc`` contract — per-head selection happens in the data
    layer (see hydragnn_tpu/data), not with index lists in the hot loop
    (reference: hydragnn/train/train_validate_test.py:218-281);
  - the reference's conv-type node head applies every hidden conv to the
    encoder output ``x`` (Base.py:267-271), which only type-checks when all
    widths match; here the layers chain (x -> h1 -> h2 -> out), the sane
    reading of the same architecture;
  - ``freeze_conv`` (Base.py:117-121) is honored by the optimizer via a
    parameter-label mask rather than requires_grad (see train/optimizer.py).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from hydragnn_tpu.graph import segment as S
from hydragnn_tpu.graph.batch import GraphBatch
from hydragnn_tpu.models import convs as C
from hydragnn_tpu.models.layers import MLP, MaskedBatchNorm

KNOWN_MODELS = ("GIN", "PNA", "GAT", "MFC", "CGCNN", "SAGE", "SchNet")


@dataclasses.dataclass(frozen=True, eq=True)
class ModelConfig:
    """Static (hashable) model configuration; a Flax module attribute."""

    model_type: str
    input_dim: int
    hidden_dim: int
    output_dim: Tuple[int, ...]
    output_type: Tuple[str, ...]  # each "graph" | "node"
    output_names: Tuple[str, ...]
    task_weights: Tuple[float, ...]
    num_conv_layers: int = 16
    loss_function_type: str = "mse"
    # graph-head config (reference config_heads["graph"])
    graph_num_sharedlayers: int = 0
    graph_dim_sharedlayers: int = 0
    graph_num_headlayers: int = 0
    graph_dim_headlayers: Tuple[int, ...] = ()
    # node-head config (reference config_heads["node"])
    node_num_headlayers: int = 0
    node_dim_headlayers: Tuple[int, ...] = ()
    node_head_type: str = "mlp"  # mlp | mlp_per_node | conv
    num_nodes: Optional[int] = None  # required for mlp_per_node
    # edge features
    edge_dim: Optional[int] = None
    # model-specific knobs
    gat_heads: int = 6
    gat_negative_slope: float = 0.05
    dropout: float = 0.25
    max_neighbours: Optional[int] = None  # MFC max_degree
    pna_avg_deg_lin: float = 1.0
    pna_avg_deg_log: float = 1.0
    num_gaussians: Optional[int] = None
    num_filters: Optional[int] = None
    radius: Optional[float] = None
    # SchNet: rebuild the interaction graph inside the forward pass from
    # positions (the reference's RadiusInteractionGraph, SCFStack.py:63-76)
    # instead of consuming host-precomputed edges. Static-shape neighbor
    # search; see ops/dynamic_radius.py for the O(N^2) trade.
    inforward_radius: bool = False
    freeze_conv: bool = False
    initial_bias: Optional[float] = None
    # Architecture.fused_conv (default on): run each conv layer's
    # gather -> edge-network -> scatter chain as ONE Pallas kernel
    # where the backend/knob support it (ops/fused_conv.py); layers
    # fall back to the composed segment-op paths elsewhere, so the
    # knob only ever selects between numerically-matching paths.
    fused_conv: bool = True
    # Architecture.conv_bf16 (default off): stream the conv hot path's
    # activation bytes (x, gathered sender windows, receiver tables,
    # per-edge scale) in bfloat16 with f32 MXU accumulation — halves
    # the dominant HBM traffic on the bandwidth-bound profile
    # (docs/PERF.md r08). Params and the inter-layer BN+relu stream
    # stay f32; numerics are tolerance-bounded vs the f32 path
    # (tests/test_conv_traffic.py pins the bound).
    conv_bf16: bool = False
    # Architecture.conv_residency (default off): opt IN to the
    # multi-layer VMEM-resident conv stack (ops/fused_conv.py:
    # fused_conv_stack) where a consumer can use it. The chassis
    # encoder interleaves MaskedBatchNorm between conv layers, which
    # breaks cross-layer residency by construction — the knob is
    # threaded for external/headless stacks and recorded in the flight
    # manifest; docs/PERF.md r08 documents the VMEM-budget decision
    # rule and this limitation honestly.
    conv_residency: bool = False
    # SyncBatchNorm equivalent: name of the mapped device axis to psum
    # batch statistics over (reference: SyncBatchNorm convert,
    # hydragnn/utils/distributed.py:227-228). None = per-device stats,
    # matching DDP's default non-synced BatchNorm.
    bn_axis_name: Optional[str] = None

    def __post_init__(self):
        if self.model_type not in KNOWN_MODELS:
            raise ValueError(f"Unknown model_type: {self.model_type}")
        if len(self.output_dim) != len(self.output_type) or len(self.output_dim) != len(
            self.output_names
        ):
            raise ValueError("output_dim/output_type/output_names length mismatch")
        if len(self.task_weights) != len(self.output_dim):
            raise ValueError(
                "Inconsistent number of loss weights and tasks: "
                f"{len(self.task_weights)} VS {len(self.output_dim)}"
            )
        if self.node_head_type == "mlp_per_node" and not self.num_nodes:
            raise ValueError("num_nodes must be positive integer for mlp_per_node")
        if self.inforward_radius and (self.radius is None or self.max_neighbours is None):
            # an implicit cap default would silently diverge from the
            # (uncapped-by-default) host pipeline's edge set
            raise ValueError(
                "radius_graph_in_forward requires explicit radius and max_neighbours"
            )
        if self.model_type == "CGCNN" and self.hidden_dim != self.input_dim:
            raise ValueError("CGCNN preserves width: hidden_dim must equal input_dim")
        if self.model_type == "CGCNN" and self.node_head_type == "conv" and "node" in self.output_type:
            raise ValueError("CGCNN does not support conv-type node heads")

    @property
    def num_heads(self) -> int:
        return len(self.output_dim)

    @property
    def normalized_weights(self) -> Tuple[float, ...]:
        total = sum(abs(w) for w in self.task_weights)
        return tuple(w / total for w in self.task_weights)

    @property
    def use_edge_attr(self) -> bool:
        return self.edge_dim is not None and self.edge_dim > 0

    @property
    def encoder_out_dim(self) -> int:
        return self.hidden_dim


class HydraModel(nn.Module):
    """Encoder + multi-head decoder. Forward returns one output per head:
    [G, dim] for graph heads, [N, dim] for node heads (matching the
    reference forward contract, Base.py:244-275)."""

    cfg: ModelConfig

    def _make_conv(self, out_dim: int, concat: bool = True, name: Optional[str] = None) -> nn.Module:
        cfg = self.cfg
        mt = cfg.model_type
        if mt == "GIN":
            return C.GINConv(out_dim, name=name)
        if mt == "SAGE":
            return C.SAGEConv(out_dim, name=name)
        if mt == "MFC":
            if cfg.max_neighbours is None:
                raise ValueError("MFC requires max_neighbours")
            return C.MFConv(out_dim, max_degree=cfg.max_neighbours, name=name)
        if mt == "CGCNN":
            return C.CGConv(out_dim, name=name)
        if mt == "PNA":
            return C.PNAConv(
                out_dim,
                avg_deg_lin=cfg.pna_avg_deg_lin,
                avg_deg_log=cfg.pna_avg_deg_log,
                edge_dim=cfg.edge_dim,
                name=name,
            )
        if mt == "GAT":
            return C.GATv2Conv(
                out_dim,
                heads=cfg.gat_heads,
                negative_slope=cfg.gat_negative_slope,
                dropout=cfg.dropout,
                concat=concat,
                name=name,
            )
        if mt == "SchNet":
            if not (cfg.num_gaussians and cfg.num_filters and cfg.radius):
                raise ValueError(
                    "SchNet requires num_gaussians, num_filters, and radius"
                )
            return C.CFConv(
                out_dim,
                num_filters=cfg.num_filters,
                num_gaussians=cfg.num_gaussians,
                cutoff=cfg.radius,
                name=name,
            )
        raise ValueError(mt)

    def _conv_args(self, batch: GraphBatch) -> C.EdgeContext:
        """Build the EdgeContext (reference: Base._conv_args Base.py:111-115
        and SCFStack._conv_args SCFStack.py:63-76)."""
        cfg = self.cfg
        edge_attr = batch.edge_attr if cfg.use_edge_attr else None
        edge_weight = None
        if cfg.model_type == "SchNet":
            if cfg.inforward_radius:
                if batch.pos is None:
                    raise ValueError(
                        "radius_graph_in_forward requires node positions; "
                        "this batch has pos=None"
                    )
                # in-forward interaction graph (reference: SCFStack.py:74
                # RadiusInteractionGraph) — nearest-K within the cutoff,
                # rebuilt from positions on every forward
                from hydragnn_tpu.ops.dynamic_radius import radius_graph_in_forward

                if batch.pos.shape[0] > 20_000:
                    # trace-time (static shape): the builder computes an
                    # all-pairs O(N_pad^2) distance matrix — molecular
                    # batches only; supercell-scale pads would allocate
                    # gigabytes in HBM before XLA fails opaquely
                    import warnings

                    warnings.warn(
                        "radius_graph_in_forward is O(N_pad^2): node pad "
                        f"{batch.pos.shape[0]} implies ~"
                        f"{batch.pos.shape[0] ** 2 * 12 / 1e9:.1f} GB of "
                        "pairwise temporaries (the [N,N,3] displacement "
                        "tensor dominates); precompute edges on host for "
                        "graphs this large "
                        "(Architecture.radius_graph_in_forward=false)",
                        RuntimeWarning,
                        stacklevel=2,
                    )

                senders, receivers, edge_weight, edge_mask = radius_graph_in_forward(
                    batch.pos,
                    batch.node_graph,
                    batch.node_mask,
                    cfg.radius,
                    cfg.max_neighbours,
                )
                edge_attr = C.gaussian_smearing(
                    edge_weight, 0.0, cfg.radius, cfg.num_gaussians
                )
                return C.EdgeContext(
                    senders=senders,
                    receivers=receivers,
                    edge_mask=edge_mask,
                    node_mask=batch.node_mask,
                    edge_attr=edge_attr,
                    edge_weight=edge_weight,
                    fused_conv=cfg.fused_conv,
                    conv_bf16=cfg.conv_bf16,
                    # in-forward edges are rebuilt per step with their
                    # own mask layout; no host occupancy bound applies
                )
            if cfg.use_edge_attr and batch.edge_attr is not None:
                edge_weight = jnp.linalg.norm(batch.edge_attr, axis=-1)
            elif batch.pos is not None:
                # The reference recomputes a radius interaction graph in the
                # forward pass (SCFStack.py:74). Dynamic neighbor search does
                # not jit; the data pipeline already builds the same radius
                # graph, so distances over the provided edges are equivalent.
                diff = batch.pos[batch.receivers] - batch.pos[batch.senders]
                edge_weight = jnp.linalg.norm(diff, axis=-1)
            else:
                raise ValueError("SchNet requires edge_attr or node positions")
            edge_attr = C.gaussian_smearing(
                edge_weight, 0.0, cfg.radius, cfg.num_gaussians
            )
        return C.EdgeContext(
            senders=batch.senders,
            receivers=batch.receivers,
            edge_mask=batch.edge_mask,
            node_mask=batch.node_mask,
            edge_attr=edge_attr,
            edge_weight=edge_weight,
            # argsort(senders), reused by every layer's sender-gather
            # backward (convs._gather_senders) — the sorted segment sum
            # beats XLA's unsorted scatter-add ~2x at flagship shapes.
            # The loader precomputes it on host (graph/batch.py) because
            # the in-step argsort is a serial row-bound op (~ms at
            # E=699k); recompute only for externally-built batches.
            sender_perm=(
                batch.sender_perm
                if batch.sender_perm is not None
                else jnp.argsort(batch.senders)
            ),
            in_degree=(
                batch.in_degree
                if batch.in_degree is not None
                else C.sorted_in_degree(batch.receivers, batch.num_nodes)
            ),
            dense_senders=batch.dense_senders,
            dense_mask=batch.dense_mask,
            dense_edge_attr=(
                batch.dense_edge_attr.reshape(-1, batch.dense_edge_attr.shape[-1])
                if batch.dense_edge_attr is not None
                else None
            ),
            dense_sender_perm=(
                batch.dense_sender_perm
                if batch.dense_sender_perm is not None
                else (
                    jnp.argsort(batch.dense_senders.reshape(-1))
                    if batch.dense_senders is not None
                    else None
                )
            ),
            sender_win=batch.sender_win,
            dense_sender_win=batch.dense_sender_win,
            edge_occ=batch.edge_occupancy,
            run_align=batch.run_align,
            fused_conv=cfg.fused_conv,
            conv_bf16=cfg.conv_bf16,
        )

    def _apply_conv(self, conv, x, ctx, train: bool):
        if isinstance(conv, C.GATv2Conv):
            return conv(x, ctx, deterministic=not train)
        return conv(x, ctx)

    @nn.compact
    def __call__(
        self,
        batch: GraphBatch,
        train: bool = False,
        bn_train: Optional[bool] = None,
    ) -> List[jnp.ndarray]:
        """``train`` drives dropout; ``bn_train`` (default = ``train``)
        drives BatchNorm batch-vs-running statistics separately, so
        BatchNorm recalibration can run batch-stats forward passes with
        dropout off (hydragnn_tpu/train/state.py:make_stats_step)."""
        cfg = self.cfg
        bn = train if bn_train is None else bn_train
        ctx = self._conv_args(batch)
        x = batch.nodes
        n = x.shape[0]

        # ---- encoder: conv -> BN -> ReLU, x num_conv_layers ----
        # GAT widens hidden layers by `heads` with concat=True except the
        # last layer (reference: GATStack._init_conv GATStack.py:35-46).
        is_gat = cfg.model_type == "GAT"
        for layer in range(cfg.num_conv_layers):
            last = layer == cfg.num_conv_layers - 1
            concat = not last if is_gat else True
            width = cfg.hidden_dim
            bn_width = (
                cfg.hidden_dim * cfg.gat_heads if (is_gat and not last) else cfg.hidden_dim
            )
            # Explicit names make the encoder stack addressable by the
            # optimizer's freeze_conv mask (reference: Base._freeze_conv
            # Base.py:117-121 freezes self.convs only, not batch norms).
            conv = self._make_conv(width, concat=concat, name=f"conv_{layer}")
            x = self._apply_conv(conv, x, ctx, train)
            x = MaskedBatchNorm(bn_width, axis_name=cfg.bn_axis_name)(x, mask=batch.node_mask, train=bn)
            x = nn.relu(x)

        # ---- masked global mean pool (reference: Base.py:256-258) ----
        x_graph = S.segment_mean(
            x, batch.node_graph, batch.num_graphs, mask=batch.node_mask
        )

        # ---- decoders ----
        outputs: List[jnp.ndarray] = []
        graph_shared = None
        if "graph" in cfg.output_type:
            dims = (cfg.graph_dim_sharedlayers,) * cfg.graph_num_sharedlayers
            graph_shared = MLP(dims, relu_last=True, name="graph_shared")(x_graph)

        for ihead in range(cfg.num_heads):
            if cfg.output_type[ihead] == "graph":
                dims = tuple(cfg.graph_dim_headlayers[: cfg.graph_num_headlayers]) + (
                    cfg.output_dim[ihead],
                )
                outputs.append(MLP(dims, name=f"graph_head_{ihead}")(graph_shared))
            else:
                outputs.append(self._node_head(ihead, x, batch, ctx, train, bn))
        return outputs

    def _node_head(self, ihead, x, batch: GraphBatch, ctx, train: bool, bn: Optional[bool] = None):
        bn = train if bn is None else bn
        cfg = self.cfg
        nht = cfg.node_head_type
        dims_hidden = tuple(cfg.node_dim_headlayers[: cfg.node_num_headlayers])
        out_dim = cfg.output_dim[ihead]
        if nht == "mlp":
            return MLP(dims_hidden + (out_dim,), name=f"node_head_{ihead}")(x)
        if nht == "mlp_per_node":
            return PerNodeMLP(
                num_nodes=cfg.num_nodes,
                hidden_dims=dims_hidden,
                out_dim=out_dim,
                name=f"node_head_{ihead}",
            )(x, batch)
        if nht == "conv":
            # conv head: hidden convs + BN + ReLU, then output conv + BN
            # (reference: Base._init_node_conv Base.py:130-163).
            is_gat = cfg.model_type == "GAT"
            h = x
            for li, dim in enumerate(dims_hidden):
                conv = self._make_conv(dim, concat=True)
                bn_width = dim * cfg.gat_heads if is_gat else dim
                h = self._apply_conv(conv, h, ctx, train)
                h = MaskedBatchNorm(bn_width, axis_name=cfg.bn_axis_name)(h, mask=batch.node_mask, train=bn)
                h = nn.relu(h)
            conv = self._make_conv(out_dim, concat=False)
            h = self._apply_conv(conv, h, ctx, train)
            h = MaskedBatchNorm(out_dim, axis_name=cfg.bn_axis_name)(h, mask=batch.node_mask, train=bn)
            return h
        raise ValueError(
            f"Unknown head NN structure for node features {nht}; currently only "
            "support 'mlp', 'mlp_per_node' or 'conv'"
        )

    # ---- loss (reference: Base.loss_hpweighted Base.py:304-321) ----

    def graph_loss(
        self, outputs: List[jnp.ndarray], batch: GraphBatch
    ) -> Tuple[jnp.ndarray, List[jnp.ndarray]]:
        return model_loss(self.cfg, outputs, batch)


class PerNodeMLP(nn.Module):
    """One MLP per intra-graph node position (reference: MLPNode with
    ``mlp_per_node``, Base.py:327-375). Requires every graph to have
    exactly ``num_nodes`` nodes. Implemented as stacked per-position
    weights gathered by node position — a batched matmul, no Python loop."""

    num_nodes: int
    hidden_dims: Tuple[int, ...]
    out_dim: int

    @nn.compact
    def __call__(self, x: jnp.ndarray, batch: GraphBatch) -> jnp.ndarray:
        n = x.shape[0]
        starts = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(batch.n_node)[:-1].astype(jnp.int32)]
        )
        pos = jnp.arange(n, dtype=jnp.int32) - starts[batch.node_graph]
        pos = jnp.clip(pos, 0, self.num_nodes - 1)

        dims = (x.shape[1],) + tuple(self.hidden_dims) + (self.out_dim,)
        init = nn.initializers.lecun_normal()
        h = x
        for li in range(len(dims) - 1):
            w = self.param(f"w_{li}", init, (self.num_nodes, dims[li], dims[li + 1]))
            b = self.param(f"b_{li}", nn.initializers.zeros, (self.num_nodes, dims[li + 1]))
            h = jnp.einsum("ni,nio->no", h, w[pos]) + b[pos]
            if li < len(dims) - 2:
                h = nn.relu(h)
        return h


def masked_loss(
    kind: str, pred: jnp.ndarray, target: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """Masked mean-reduced loss, matching the reference's selection
    (reference: hydragnn/utils/model.py loss_function_selection)."""
    m = mask.astype(pred.dtype)[:, None]
    denom = jnp.maximum(m.sum() * pred.shape[1], 1.0)
    diff = (pred - target) * m
    if kind == "mse":
        return (diff * diff).sum() / denom
    if kind == "mae":
        return jnp.abs(diff).sum() / denom
    if kind == "rmse":
        return jnp.sqrt((diff * diff).sum() / denom)
    raise ValueError(f"Unknown loss function type: {kind}")


def model_loss(
    cfg: ModelConfig, outputs: List[jnp.ndarray], batch: GraphBatch
) -> Tuple[jnp.ndarray, List[jnp.ndarray]]:
    """Weighted multi-task loss over masked heads
    (reference: Base.loss_hpweighted Base.py:304-321)."""
    weights = cfg.normalized_weights
    tasks_loss = []
    total = 0.0
    for ihead in range(cfg.num_heads):
        name = cfg.output_names[ihead]
        if cfg.output_type[ihead] == "graph":
            target = batch.graph_targets[name]
            mask = batch.graph_mask
        else:
            target = batch.node_targets[name]
            mask = batch.node_mask
        head_loss = masked_loss(cfg.loss_function_type, outputs[ihead], target, mask)
        tasks_loss.append(head_loss)
        total = total + weights[ihead] * head_loss
    return total, tasks_loss
