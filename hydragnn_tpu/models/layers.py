"""Shared neural building blocks: masked BatchNorm and MLP.

The reference interleaves torch BatchNorm1d (via torch_geometric BatchNorm)
with every conv layer (reference: hydragnn/models/Base.py:103-109,249-251).
Under padding, naive BatchNorm would fold padding rows into the batch
statistics, so this BatchNorm is mask-aware. With an ``axis_name`` it
``psum``s the statistics across devices, which is the SyncBatchNorm
equivalent (reference: hydragnn/utils/distributed.py:227-228) — under plain
``jit`` over a sharded batch XLA already computes global statistics, so
SyncBN comes for free there.

Torch parity details: momentum 0.1 (new = 0.9*old + 0.1*batch), eps 1e-5,
normalization uses biased variance, running variance stores the unbiased
estimate.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import flax.linen as nn


class MaskedBatchNorm(nn.Module):
    features: int
    momentum: float = 0.1
    eps: float = 1e-5
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        train: bool = True,
    ) -> jnp.ndarray:
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((self.features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((self.features,), jnp.float32)
        )
        scale = self.param("scale", nn.initializers.ones, (self.features,))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))

        # statistics always in f32: batch-wide sums in bf16 (mixed
        # precision) lose enough mantissa to corrupt the running stats
        in_dtype = x.dtype
        x = x.astype(jnp.float32)
        if train:
            if mask is None:
                count = jnp.asarray(x.shape[0], jnp.float32)
                total = x.sum(axis=0)
                total_sq = (x * x).sum(axis=0)
            else:
                m = mask.astype(x.dtype)[:, None]
                count = m.sum()
                total = (x * m).sum(axis=0)
                total_sq = (x * x * m).sum(axis=0)
            if self.axis_name is not None:
                count = jax.lax.psum(count, self.axis_name)
                total = jax.lax.psum(total, self.axis_name)
                total_sq = jax.lax.psum(total_sq, self.axis_name)
            safe_count = jnp.maximum(count, 1.0)
            mean = total / safe_count
            var = jnp.maximum(total_sq / safe_count - mean * mean, 0.0)

            if not self.is_initializing() and self.is_mutable_collection("batch_stats"):
                unbiased = var * safe_count / jnp.maximum(count - 1.0, 1.0)
                mom = self.momentum
                ra_mean.value = (1.0 - mom) * ra_mean.value + mom * mean
                ra_var.value = (1.0 - mom) * ra_var.value + mom * unbiased
        else:
            mean, var = ra_mean.value, ra_var.value

        y = (x - mean) * jax.lax.rsqrt(var + self.eps) * scale + bias
        return y.astype(in_dtype)


class MLP(nn.Module):
    """Dense stack: Linear(+ReLU) x hidden, then a final Linear.

    ``relu_last`` appends ReLU after the output layer too (the reference's
    graph-head trunks end in ReLU, reference: hydragnn/models/Base.py:170-177).
    """

    layer_dims: Sequence[int]
    relu_last: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        n = len(self.layer_dims)
        for i, dim in enumerate(self.layer_dims):
            x = nn.Dense(dim)(x)
            if i < n - 1 or self.relu_last:
                x = nn.relu(x)
        return x
