"""Model factory: config dict -> (HydraModel, initialized variables).

Mirrors the reference factory's dispatch and per-model requirements
(reference: hydragnn/models/create.py:29-214): PNA needs the train-set
degree histogram (create.py:104), MFC needs max_neighbours (create.py:142),
SchNet needs num_gaussians/num_filters/radius (create.py:188-190), GAT uses
heads=6 and negative_slope=0.05 (create.py:122-124). Parameters are
initialized from a fixed PRNG seed, the analog of the reference's
``torch.manual_seed(0)`` (create.py:83).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from hydragnn_tpu.graph.batch import GraphBatch
from hydragnn_tpu.models.base import HydraModel, ModelConfig
from hydragnn_tpu.models.convs import avg_degree_stats


def model_config_from_dict(
    config: Dict[str, Any], bn_axis_name: Optional[str] = None
) -> ModelConfig:
    """Build a static ModelConfig from the reference-shaped config dict
    (the ``NeuralNetwork`` section, after update_config inference).

    ``bn_axis_name`` is the mapped device axis the caller will bind (via
    shard_map) — required for ``Architecture.SyncBatchNorm`` to take
    effect; it is ignored when the config does not request SyncBN
    (reference: SyncBatchNorm convert, hydragnn/utils/distributed.py:
    227-228, default injected at config_utils.py:82-83)."""
    arch = config["Architecture"]
    training = config.get("Training", {})
    heads_cfg = arch.get("output_heads", {})
    graph_cfg = heads_cfg.get("graph", {})
    node_cfg = heads_cfg.get("node", {})

    pna_lin, pna_log = 1.0, 1.0
    if arch.get("pna_deg") is not None:
        pna_lin, pna_log = avg_degree_stats(arch["pna_deg"])

    model_type = arch["model_type"]
    if arch.get("radius_graph_in_forward") and arch.get("periodic_boundary_conditions"):
        # the in-forward builder is plain Euclidean; silently dropping
        # cross-boundary images would train on physically wrong graphs
        raise ValueError(
            "radius_graph_in_forward does not support periodic_boundary_conditions; "
            "use host-precomputed edges for PBC datasets"
        )
    input_dim = int(arch["input_dim"])
    hidden_dim = int(arch["hidden_dim"])
    if model_type == "CGCNN":
        # CGCNN preserves width; hidden == input (reference CGCNNStack.py:30-40)
        hidden_dim = input_dim

    return ModelConfig(
        model_type=model_type,
        input_dim=input_dim,
        hidden_dim=hidden_dim,
        output_dim=tuple(int(d) for d in arch["output_dim"]),
        output_type=tuple(arch["output_type"]),
        output_names=tuple(config["Variables_of_interest"]["output_names"])
        if "Variables_of_interest" in config
        else tuple(f"head_{i}" for i in range(len(arch["output_dim"]))),
        task_weights=tuple(float(w) for w in arch["task_weights"]),
        num_conv_layers=int(arch["num_conv_layers"]),
        loss_function_type=training.get("loss_function_type", "mse"),
        graph_num_sharedlayers=int(graph_cfg.get("num_sharedlayers", 0)),
        graph_dim_sharedlayers=int(graph_cfg.get("dim_sharedlayers", 0)),
        graph_num_headlayers=int(graph_cfg.get("num_headlayers", 0)),
        graph_dim_headlayers=tuple(graph_cfg.get("dim_headlayers", ())),
        node_num_headlayers=int(node_cfg.get("num_headlayers", 0)),
        node_dim_headlayers=tuple(node_cfg.get("dim_headlayers", ())),
        node_head_type=node_cfg.get("type", "mlp"),
        num_nodes=arch.get("num_nodes"),
        edge_dim=arch.get("edge_dim"),
        max_neighbours=arch.get("max_neighbours"),
        pna_avg_deg_lin=pna_lin,
        pna_avg_deg_log=pna_log,
        num_gaussians=arch.get("num_gaussians"),
        num_filters=arch.get("num_filters"),
        radius=arch.get("radius"),
        inforward_radius=bool(arch.get("radius_graph_in_forward", False)),
        fused_conv=bool(arch.get("fused_conv", True)),
        conv_bf16=bool(arch.get("conv_bf16", False)),
        conv_residency=bool(arch.get("conv_residency", False)),
        freeze_conv=bool(arch.get("freeze_conv_layers", False)),
        initial_bias=arch.get("initial_bias"),
        bn_axis_name=bn_axis_name if arch.get("SyncBatchNorm") else None,
    )


def create_model_config(
    config: Dict[str, Any],
    example_batch: GraphBatch,
    seed: int = 0,
    verbosity: int = 0,
    bn_axis_name: Optional[str] = None,
) -> Tuple[HydraModel, Dict[str, Any]]:
    cfg = model_config_from_dict(config, bn_axis_name=bn_axis_name)
    return create_model(cfg, example_batch, seed=seed)


def create_model(
    cfg: ModelConfig, example_batch: GraphBatch, seed: int = 0
) -> Tuple[HydraModel, Dict[str, Any]]:
    """Instantiate and initialize; returns (model, variables) where
    variables = {'params': ..., 'batch_stats': ...}."""
    if cfg.model_type == "PNA" and cfg.pna_avg_deg_lin <= 0:
        raise AssertionError("PNA requires degree input.")
    if cfg.node_head_type == "mlp_per_node" and "node" in cfg.output_type:
        # mlp_per_node requires every graph to have exactly num_nodes nodes
        # (reference: Base.py:209-212 + node_features_reshape); validate on
        # the concrete example batch rather than silently clipping.
        import numpy as np

        n_node = np.asarray(example_batch.n_node)
        gmask = np.asarray(example_batch.graph_mask)
        if not np.all(n_node[gmask] == cfg.num_nodes):
            raise ValueError(
                "mlp_per_node requires every graph to have exactly "
                f"num_nodes={cfg.num_nodes} nodes; got {sorted(set(n_node[gmask]))}"
            )
    model = HydraModel(cfg)
    rngs = {"params": jax.random.PRNGKey(seed), "dropout": jax.random.PRNGKey(seed + 1)}
    variables = model.init(rngs, example_batch, train=False)
    if cfg.initial_bias is not None:
        variables = _set_initial_bias(variables, cfg)
    return model, variables


def _set_initial_bias(variables, cfg: ModelConfig):
    """Fill the final bias of each graph head with a large initial value
    (UQ option; reference: Base._set_bias Base.py:123-128)."""
    import flax

    params = flax.core.unfreeze(variables["params"])
    for ihead in range(cfg.num_heads):
        if cfg.output_type[ihead] != "graph":
            continue
        head = params.get(f"graph_head_{ihead}")
        if head is None:
            continue
        last = sorted(
            (k for k in head if k.startswith("Dense_")), key=lambda k: int(k.split("_")[1])
        )[-1]
        head[last]["bias"] = jnp.full_like(head[last]["bias"], cfg.initial_bias)
    new_vars = dict(variables)
    new_vars["params"] = params
    return new_vars
