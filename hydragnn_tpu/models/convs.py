"""Message-passing conv layers as Flax modules over masked segment ops.

Each layer reimplements the semantics of the torch_geometric conv the
reference plugs into its ``Base.get_conv`` slot (reference:
hydragnn/models/*Stack.py), redesigned for TPU: dense matmuls feed the MXU,
edge aggregation is an XLA segment reduction, and every op is mask-correct
under static padding. Message direction matches PyG: sender j -> receiver i,
aggregation groups by receiver.

Call convention: ``conv(x, ctx)`` where ``ctx`` is an EdgeContext holding
senders/receivers/masks and optional edge features, so one chassis drives
every flavor (mirrors Base._conv_args, reference hydragnn/models/Base.py:111-115).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from hydragnn_tpu.graph import segment as S


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EdgeContext:
    """Edge structure handed to every conv layer by the chassis."""

    senders: jnp.ndarray  # [E] int32
    # CONTRACT: receivers must be sorted ascending (batch_graphs
    # canonicalizes receiver-major edge order; radius_graph_in_forward
    # emits it) — every conv passes indices_are_sorted=True to its
    # segment reductions, and a violated hint silently corrupts sums
    # on TPU rather than erroring.
    receivers: jnp.ndarray  # [E] int32, sorted ascending
    edge_mask: jnp.ndarray  # [E] bool
    node_mask: jnp.ndarray  # [N] bool
    edge_attr: Optional[jnp.ndarray] = None  # [E, De]
    edge_weight: Optional[jnp.ndarray] = None  # [E] distances (SchNet)
    # argsort(senders), computed ONCE per step by the chassis: lets every
    # layer's sender-gather backward run as a SORTED segment sum (the
    # Pallas CSR kernel on TPU) instead of XLA's unsorted scatter-add
    sender_perm: Optional[jnp.ndarray] = None  # [E] int32
    # per-node count of REAL incoming edges, computed once per step by
    # the chassis WITHOUT a scatter (receivers are sorted, so it is a
    # searchsorted difference; padding edges point at a padding node and
    # never inflate a real node's count). Layers that need degree (PNA
    # scalers/has, MFC dispatch) read this instead of paying the [E,1]
    # count scatter XLA otherwise emits (~6 ms at E=699k, r03 trace).
    in_degree: Optional[jnp.ndarray] = None  # [N] float32
    # dense per-node edge-slot map (loader-emitted — graph/batch.py):
    # lets PNA run its aggregations as DENSE [N, D, H] reductions (one
    # fused XLA pass fwd, broadcasts bwd) instead of scatter/segment
    # ops. dense_edge_attr is FLAT [N*D, De]; dense_sender_perm is
    # argsort of the flattened dense senders, computed once per step by
    # the chassis for the sender-gather backward (like sender_perm).
    dense_senders: Optional[jnp.ndarray] = None  # [N, D] int32
    dense_mask: Optional[jnp.ndarray] = None  # [N, D] bool
    dense_edge_attr: Optional[jnp.ndarray] = None  # [N*D, De]
    dense_sender_perm: Optional[jnp.ndarray] = None  # [N*D] int32
    # loader-emitted per-node-block position windows (graph/batch.py:
    # _block_windows; block size derived from the window shape — see
    # GraphBatch.sender_win): when present, sender gathers ride the
    # windowed kernels in BOTH directions — no cotangent permute in
    # the backward
    sender_win: Optional[jnp.ndarray] = None  # [2, n_blocks] int32
    dense_sender_win: Optional[jnp.ndarray] = None  # [2, n_blocks] int32
    # loader-emitted edge occupancy (GraphBatch.edge_occupancy): index
    # after the last slot that can hold a REAL edge. Handed to the fused
    # kernel as its chunk-loop bound so fully-padded tail chunks (bucket
    # ladders, _mask_out filler) cost zero DMAs/MXU work. None = process
    # the full pad (externally-built batches; always correct).
    edge_occ: Optional[jnp.ndarray] = None  # [] int32
    # static: run-aligned edge layout factor (GraphBatch.run_align).
    # K > 0 guarantees every K-group of edge slots shares one receiver
    # (or is batch tail), so segment reductions pre-reduce K-fold with
    # one fused pass (_run_groups) before the serial scatter/segment op.
    run_align: int = 0
    # static: Architecture.fused_conv — route the gather -> edge-network
    # -> scatter chain through the single fused Pallas kernel
    # (ops/fused_conv.py) where the knob/backend allow; layers fall back
    # to the composed segment-op paths otherwise.
    fused_conv: bool = False
    # static: Architecture.conv_bf16 — flow the conv hot path's
    # activation streams (x, gathered sender windows, receiver tables,
    # per-edge scale) in bfloat16 with f32 MXU accumulation, halving the
    # dominant HBM byte streams (ISSUE 10). Applies to BOTH the fused
    # kernel and the composed fallback so the two stay within the
    # documented tolerance of each other; the inter-layer stream is
    # restored to the caller dtype on return (BN + relu run f32).
    conv_bf16: bool = False


def _local_kernels(n_rows: int) -> bool:
    """Trace-time gate for the local-window gather/scatter pair: the
    kernels carry a fixed per-call cost (window plan + grid setup) that
    only pays off when the serial alternative is large — measured on
    v5e: 811k-row flagship wins big, 61k-row qm9 dense LOSES 8.2 vs
    3.4 ms scan-step (tools/ab_qm9.py). Below the threshold the
    permuted-sorted path is faster."""
    from hydragnn_tpu.ops.segment_pallas import (
        local_kernel_active,
        local_min_rows,
    )

    return n_rows >= local_min_rows() and local_kernel_active()


def _fused_active(ctx: EdgeContext) -> bool:
    """Trace-time gate for the fused conv kernel (ops/fused_conv.py):
    the config knob (EdgeContext.fused_conv <- Architecture.fused_conv)
    AND the shared HYDRAGNN_PALLAS knob/backend contract. Receivers are
    sorted by the EdgeContext contract, so no shape check is needed —
    narrow widths lane-pad inside the op."""
    if not ctx.fused_conv:
        return False
    from hydragnn_tpu.ops.fused_conv import fused_conv_active

    return fused_conv_active()


def _gather_scatter(
    x: jnp.ndarray,
    ctx: EdgeContext,
    n: int,
    scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """``sum_e mask_e * (x[send_e] * scale_e?)`` grouped by receiver —
    ONE fused Pallas kernel (gather + optional per-edge scale + scatter
    all in VMEM, no [E, H] HBM intermediate) when active, else the
    composed gather + masked segment sum the layers always used.
    Returns x.dtype. ``ctx.conv_bf16`` rounds the streamed operands to
    bf16 in BOTH paths (accumulation stays f32 — the segment-sum family
    contract); the result is cast back to the incoming dtype."""
    xd = x.dtype
    if ctx.conv_bf16:
        x = x.astype(jnp.bfloat16)
        if scale is not None:
            scale = scale.astype(jnp.bfloat16)
    if _fused_active(ctx):
        from hydragnn_tpu.ops.fused_conv import fused_conv

        return fused_conv(
            x, ctx.senders, ctx.receivers, ctx.edge_mask, n,
            scale=scale, win=ctx.sender_win, real_edges=ctx.edge_occ,
        ).astype(xd)
    vals = _gather_senders(x, ctx)
    if scale is not None:
        vals = vals * scale
    return _segment_sum_edges(vals, ctx, n).astype(xd)


def _run_presum(vals: jnp.ndarray, ctx: EdgeContext) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pre-reduce masked edge values over the K-aligned run groups:
    one fused [E/K, K, H] reshape-sum (accumulated f32 — the family
    contract) replaces K-1 of every K rows the downstream segment sum
    would otherwise scatter serially (XLA's TPU scatter loops per ROW
    at ~6-9 ms per 699k-row pass regardless of width; docs/PERF.md).
    Returns (summed [E/K, H] f32, receivers[::K]) — valid because the
    run-aligned layout guarantees each K-group lies within one node's
    receiver-run or the batch tail, and masked slots contribute 0."""
    K = ctx.run_align
    vf = jnp.where(ctx.edge_mask[:, None], vals, 0).astype(jnp.float32)
    v8 = vf.reshape(-1, K, vals.shape[-1]).sum(axis=1)
    return v8, ctx.receivers[::K]


def _segment_sum_edges(vals: jnp.ndarray, ctx: EdgeContext, n: int) -> jnp.ndarray:
    """Masked sum of per-edge values into receiver rows — pre-reduced
    K-fold on run-aligned batches, the plain masked sorted segment sum
    otherwise. Returns the values' dtype."""
    if ctx.run_align:
        v8, recv8 = _run_presum(vals, ctx)
        return S.segment_sum_sorted(
            v8, recv8, n, grad_dtype=vals.dtype
        ).astype(vals.dtype)
    return S.segment_sum(
        vals, ctx.receivers, n, mask=ctx.edge_mask, indices_are_sorted=True
    )


def _edge_count(ctx: EdgeContext, n: int) -> jnp.ndarray:
    """Real in-degree: the loader-precomputed field, else a masked count."""
    if ctx.in_degree is not None:
        return ctx.in_degree
    return S.segment_count(ctx.receivers, n, mask=ctx.edge_mask, indices_are_sorted=True)


def sorted_in_degree(receivers: jnp.ndarray, num_nodes: int) -> jnp.ndarray:
    """Per-node incoming-edge count from SORTED receivers — two
    searchsorted passes instead of XLA's per-row [E,1] count scatter.
    Valid when masked edges cannot point at real nodes (the loader
    contract: padding edges target a padding node)."""
    bounds = jnp.searchsorted(
        receivers, jnp.arange(num_nodes + 1, dtype=receivers.dtype), side="left"
    )
    return (bounds[1:] - bounds[:-1]).astype(jnp.float32)


def _gather_senders(x: jnp.ndarray, ctx: EdgeContext) -> jnp.ndarray:
    """x[ctx.senders] with the fastest available backward: the
    local-window kernel pair when the loader emitted block windows AND
    the kernels lower here (no cotangent permute at all), else the
    permuted sorted segment sum via the chassis ``sender_perm``."""
    if ctx.sender_win is not None and _local_kernels(ctx.senders.shape[0]):
        return S.gather_rows_local(x, ctx.senders, ctx.sender_win, x.shape[0])
    if ctx.sender_perm is not None:
        return S.gather_rows_permuted(x, ctx.senders, ctx.sender_perm, x.shape[0])
    return x[ctx.senders]


class GINConv(nn.Module):
    """GIN with a 2-layer MLP, trainable eps initialized to 100.0
    (reference: hydragnn/models/GINStack.py:25-36)."""

    out_dim: int

    @nn.compact
    def __call__(self, x: jnp.ndarray, ctx: EdgeContext) -> jnp.ndarray:
        eps = self.param("eps", lambda _: jnp.asarray(100.0, jnp.float32))
        agg = _gather_scatter(x, ctx, x.shape[0])
        h = (1.0 + eps) * x + agg
        h = nn.Dense(self.out_dim)(h)
        h = nn.relu(h)
        h = nn.Dense(self.out_dim)(h)
        return h


class SAGEConv(nn.Module):
    """GraphSAGE, mean aggregation: W_l(mean_j x_j) + W_r x_i
    (reference: hydragnn/models/SAGEStack.py:15-19; PyG SAGEConv defaults)."""

    out_dim: int

    @nn.compact
    def __call__(self, x: jnp.ndarray, ctx: EdgeContext) -> jnp.ndarray:
        n = x.shape[0]
        total = _gather_scatter(x, ctx, n)
        cnt = _edge_count(ctx, n)
        agg = total / jnp.maximum(cnt, 1.0)[:, None].astype(total.dtype)
        return nn.Dense(self.out_dim)(agg) + nn.Dense(self.out_dim, use_bias=False)(x)


class MFConv(nn.Module):
    """Molecular-fingerprint conv: degree-indexed weight matrices
    (reference: hydragnn/models/MFCStack.py:21-28; PyG MFConv).

    out_i = W_l[deg_i](sum_j x_j) + W_r[deg_i](x_i), degree clamped to
    ``max_degree``. The per-degree dispatch is a gather over a stacked
    weight tensor followed by a batched matmul — no data-dependent Python
    loop, so the whole thing stays one fused XLA computation.
    """

    out_dim: int
    max_degree: int

    @nn.compact
    def __call__(self, x: jnp.ndarray, ctx: EdgeContext) -> jnp.ndarray:
        n, fin = x.shape
        ndeg = self.max_degree + 1
        agg = _gather_scatter(x, ctx, n)
        deg = jnp.clip(_edge_count(ctx, n).astype(jnp.int32), 0, self.max_degree)

        # init parity with the reference: PyG MFConv holds one torch
        # Linear per degree — lins_l with kaiming-uniform weights
        # (var 1/(3 fan_in)) + uniform(-1/sqrt(fan_in), .) bias, lins_r
        # with bias=False. batch_axis=0 keeps fan_in = fin for the
        # stacked per-degree weights (otherwise jax counts ndeg*fin).
        # With flax's lecun_normal + zero bias the same training budget
        # lands ~0.28 MAE on the deterministic dataset vs the 0.20 bar.
        init = nn.initializers.variance_scaling(
            1.0 / 3.0, "fan_in", "uniform", batch_axis=0
        )
        bound = 1.0 / float(fin) ** 0.5

        def bias_init(key, shape, dtype=jnp.float32):
            return jax.random.uniform(key, shape, dtype, -bound, bound)

        w_l = self.param("w_l", init, (ndeg, fin, self.out_dim))
        b_l = self.param("b_l", bias_init, (ndeg, self.out_dim))
        w_r = self.param("w_r", init, (ndeg, fin, self.out_dim))

        out = jnp.einsum("ni,nio->no", agg, w_l[deg]) + b_l[deg]
        out = out + jnp.einsum("ni,nio->no", x, w_r[deg])
        return out


class CGConv(nn.Module):
    """Crystal-graph conv, aggr="add", dimension-preserving
    (reference: hydragnn/models/CGCNNStack.py:19-49; PyG CGConv).

    z_ij = [x_i, x_j, e_ij];  out_i = x_i + sum_j sigmoid(W_f z) * softplus(W_s z)

    Fused path (TPU / interpret — the PNA message-elimination idea
    applied to the gate): each Dense over the concat splits exactly into
    a receiver part (a NODE-level matmul, bias folded in), a sender
    part (the only true edge-level matmul), and an edge-attr part —
    ``W z = x_i W[:F] + x_j W[F:2F] + e W[2F:]``. The [E, 2F+De] concat
    never exists, and the whole gather -> two-branch MLP ->
    sigmoid*softplus -> scatter chain runs in ONE Pallas kernel
    (ops/fused_conv.py) with the receiver parts gathered in-VMEM from
    node-blocked tables. The params stay the ORIGINAL ``nn.Dense``
    children (the fused path slices the same kernels), so off-TPU the
    layer computes — and initializes — bit-identically to the
    pre-fusion form."""

    out_dim: int  # must equal input dim; CGConv preserves width

    @nn.compact
    def __call__(self, x: jnp.ndarray, ctx: EdgeContext) -> jnp.ndarray:
        n, fin = x.shape
        h = self.out_dim
        use_edge = ctx.edge_attr is not None
        # conv_bf16 rounds the streamed operands (x, receiver tables,
        # edge features) to bf16 in both branches of this layer; params
        # stay f32 (param_dtype default), so the knob changes only the
        # hot-path byte streams, not initialization or the checkpoint.
        cdt = jnp.bfloat16 if ctx.conv_bf16 else None
        dense_f = nn.Dense(h, dtype=cdt)  # gate (Dense_0)
        dense_s = nn.Dense(h, dtype=cdt)  # core (Dense_1)
        xc = x.astype(jnp.bfloat16) if ctx.conv_bf16 else x
        if not _fused_active(ctx):
            xi = S.gather_rows(xc, ctx.receivers, n, True)
            xj = _gather_senders(xc, ctx)
            z = [xi, xj]
            if use_edge:
                z.append(ctx.edge_attr.astype(xc.dtype))
            z = jnp.concatenate(z, axis=-1)
            gate = jax.nn.sigmoid(dense_f(z))
            core = jax.nn.softplus(dense_s(z))
            agg = _segment_sum_edges(gate * core, ctx, n).astype(x.dtype)
            return x + agg

        # materialize the children's params on a dummy row (same shapes
        # and RNG draws as the concat form), then decompose their
        # kernels for the fused kernel's branch layout
        de = ctx.edge_attr.shape[-1] if use_edge else 0
        zdim = 2 * fin + de
        dummy = jnp.zeros((1, zdim), x.dtype)
        dense_f(dummy)
        dense_s(dummy)
        wf = dense_f.variables["params"]["kernel"].astype(xc.dtype)
        bf = dense_f.variables["params"]["bias"].astype(xc.dtype)
        ws = dense_s.variables["params"]["kernel"].astype(xc.dtype)
        bs = dense_s.variables["params"]["bias"].astype(xc.dtype)

        # receiver-side parts as node-level matmuls (bias folded in)
        af = xc @ wf[:fin] + bf
        ac = xc @ ws[:fin] + bs
        cf = cs = None
        if use_edge:
            ea = ctx.edge_attr.astype(xc.dtype)
            cf = ea @ wf[2 * fin :]
            cs = ea @ ws[2 * fin :]

        from hydragnn_tpu.ops.fused_conv import fused_conv

        agg = fused_conv(
            xc, ctx.senders, ctx.receivers, ctx.edge_mask, n,
            branches=(
                (wf[fin : 2 * fin], None, af, cf),
                (ws[fin : 2 * fin], None, ac, cs),
            ),
            acts=("sigmoid", "softplus"),
            win=ctx.sender_win,
            real_edges=ctx.edge_occ,
        ).astype(x.dtype)
        return x + agg


class GATv2Conv(nn.Module):
    """GATv2 multi-head attention conv
    (reference: hydragnn/models/GATStack.py:91-101; PyG GATv2Conv with
    heads=6, negative_slope=0.05, dropout=0.25, add_self_loops=True).

    Self-loops are appended in-graph for real nodes (static shape: E + N
    edges), matching PyG's add_self_loops on the un-padded graph.
    """

    out_dim: int  # per-head output width
    heads: int = 6
    negative_slope: float = 0.05
    dropout: float = 0.25
    concat: bool = True

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, ctx: EdgeContext, deterministic: bool = True
    ) -> jnp.ndarray:
        n = x.shape[0]
        h, d = self.heads, self.out_dim

        senders = jnp.concatenate([ctx.senders, jnp.arange(n, dtype=ctx.senders.dtype)])
        receivers = jnp.concatenate([ctx.receivers, jnp.arange(n, dtype=ctx.receivers.dtype)])
        emask = jnp.concatenate([ctx.edge_mask, ctx.node_mask])

        x_l = nn.Dense(h * d)(x).reshape(n, h, d)  # source transform
        x_r = nn.Dense(h * d)(x).reshape(n, h, d)  # target transform
        feat = x_l[senders] + x_r[receivers]  # [E', h, d]
        feat = nn.leaky_relu(feat, self.negative_slope)
        att = self.param("att", nn.initializers.lecun_normal(), (1, h, d))
        logits = (feat * att).sum(-1)  # [E', h]
        alpha = S.segment_softmax(logits, receivers, n, mask=emask[:, None])
        alpha = nn.Dropout(self.dropout, deterministic=deterministic)(alpha)
        msg = x_l[senders] * alpha[..., None]  # [E', h, d]
        out = S.segment_sum(msg, receivers, n, mask=emask)
        if self.concat:
            out = out.reshape(n, h * d)
            out = out + self.param("bias", nn.initializers.zeros, (h * d,))
        else:
            out = out.mean(axis=1)
            out = out + self.param("bias", nn.initializers.zeros, (d,))
        return out


class PNAConv(nn.Module):
    """Principal Neighbourhood Aggregation conv
    (reference: hydragnn/models/PNAStack.py:19-54; PyG PNAConv with
    aggregators [mean,min,max,std], scalers [identity,amplification,
    attenuation,linear], towers=1, pre/post_layers=1, divide_input=False).

    TPU-first message elimination: the pre-aggregation network is ONE
    linear layer (pre_layers=1), so the per-edge message decomposes
    exactly as

        msg_e = W @ [x_i, x_j, e_ij] + b
              = (x_i @ W_i + b) + x_j @ W_j + e_ij @ W_e
              =       a[recv_e] +  bsend[send_e] + c_e

    with ``a``/``bsend`` computed as NODE-level matmuls. Every PNA
    aggregator then needs only segment reductions of v_e = bsend[send_e]
    (+ c_e) over receivers: mean(msg) = a + mean(v), max(msg) = a +
    max(v), min likewise, and std(msg) = std(v) because variance is
    shift-invariant. The [E, 3H] concat, the [E, *] pre-Dense matmul,
    and the [E, H] message array — plus all their backward mirrors —
    never exist; the only edge-width intermediate is the single gather
    ``v``. This is the r03 answer to the measured HBM-bound profile
    (161 GB/step at 995 GFLOPs — docs/PERF.md): attack bytes, not
    roofline fraction. The torch path cannot do this: PyG materializes
    messages per edge by design (torch_geometric MessagePassing).

    ``avg_deg_lin``/``avg_deg_log`` are precomputed on host from the
    train-set degree histogram (reference: hydragnn/utils/model.py:92-109,
    config_utils.py:54-58) so the layer itself is purely static.
    """

    out_dim: int
    avg_deg_lin: float
    avg_deg_log: float
    edge_dim: Optional[int] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, ctx: EdgeContext) -> jnp.ndarray:
        n, fin = x.shape
        use_edge = (
            self.edge_dim is not None and self.edge_dim > 0 and ctx.edge_attr is not None
        )
        # pre_nn (pre_layers=1) as explicit slices of one kernel so the
        # receiver/sender parts apply at node level. Init matches
        # nn.Dense(fin) on the concat: lecun_normal with fan_in = zdim.
        zdim = (3 if use_edge else 2) * fin
        w = self.param("pre_kernel", nn.initializers.lecun_normal(), (zdim, fin))
        b_pre = self.param("pre_bias", nn.initializers.zeros, (fin,))
        w = w.astype(x.dtype)
        a = x @ w[:fin] + b_pre.astype(x.dtype)  # receiver part [N, fin]
        bsend = x @ w[fin : 2 * fin]  # sender part [N, fin]

        # DENSE path (loader-emitted slot map): aggregations become
        # [N, D, fin] reshape reductions — one fused XLA pass forward,
        # pure broadcasts backward — skipping every scatter/segment op
        # (XLA's TPU scatter-extremum alone is ~7-9 ms per pass at
        # E=699k; docs/PERF.md r03). The sender gather and its
        # permuted-CSR backward are unchanged in structure.
        dense = ctx.dense_senders is not None and (
            not use_edge or ctx.dense_edge_attr is not None
        )
        if dense:
            nslots = ctx.dense_senders.shape[1]
            flat = ctx.dense_senders.reshape(-1)
            if ctx.dense_sender_win is not None and _local_kernels(flat.shape[0]):
                v = S.gather_rows_local(bsend, flat, ctx.dense_sender_win, n)
            else:
                v = S.gather_rows_permuted(bsend, flat, ctx.dense_sender_perm, n)
            if use_edge:
                v = v + nn.Dense(fin)(ctx.dense_edge_attr) @ w[2 * fin :]
            v3 = v.reshape(n, nslots, fin)
            m3 = ctx.dense_mask[:, :, None]
            # one fused read of v3 computes sum, sumsq, max and min —
            # accumulation in f32 like the family kernel contract
            vf = jnp.where(m3, v3, 0).astype(jnp.float32)
            vsum = vf.sum(axis=1)
            vsumsq = (vf * vf).sum(axis=1)
            neg = jnp.finfo(v.dtype).min
            vmax = jnp.where(m3, v3, neg).max(axis=1)
            vmin = jnp.where(m3, v3, -neg).min(axis=1)
            cnt = (
                ctx.in_degree
                if ctx.in_degree is not None
                else ctx.dense_mask.sum(axis=1).astype(jnp.float32)
            )
            # empty-clean from the fill value itself (like the CSR
            # path's both-cleanup): cnt/in_degree counts the padding
            # NODE's masked edges by design, so it cannot be the gate
            max_v = jnp.where(vmax <= neg, 0, vmax).astype(v.dtype)
            min_v = jnp.where(vmin >= -neg, 0, vmin).astype(v.dtype)
        else:
            # CSR path: the ONLY edge-width intermediate is v_e =
            # bsend[send_e] (+ edge term); the sender gather's backward
            # is a sorted segment sum via the chassis-provided argsort.
            # Aggregation is ONE fused op: sum + sumsq (family kernel)
            # and the [v,-v] scatter-max forward, with the two-kernel
            # fused backward emitting the complete grad_v in one pass
            # (hydragnn_tpu/ops/segment_pallas.py:pna_aggregate).
            # indices_are_sorted: the data pipeline emits edges
            # receiver-major sorted (data/radius_graph.py:_cap_and_sort;
            # batch_graphs canonicalizes), which also enables the Pallas
            # CSR kernels on TPU.
            from hydragnn_tpu.ops import pna_aggregate
            from hydragnn_tpu.ops.segment_pallas import (
                gather_presum_eligible,
                gather_presum_stats,
            )

            if (
                ctx.run_align
                and not use_edge
                and gather_presum_eligible(
                    bsend, ctx.senders, ctx.sender_win, ctx.run_align
                )
            ):
                # Fused gather + K-group pre-reduction (r05): the kernel
                # keeps v = bsend[senders] in VMEM and emits the four
                # statistics at E/K rows directly — the [E, H] v array
                # and its 4-6 full re-reads (the "fwd reduce_sum" block
                # of the r05 trace) never touch HBM. Backward regathers
                # v once and differentiates the identical composition
                # (ops/segment_pallas.py:_presum_stats_ref). use_edge
                # keeps the unfused path: the edge term breaks the
                # pure-gather structure. fin % 128 == 0 by eligibility,
                # so no lane split is needed in the slicing below.
                K = ctx.run_align
                v = bsend  # dtype source for the shared tail
                stats8, both8 = gather_presum_stats(
                    bsend, ctx.senders, ctx.edge_mask, ctx.sender_win, n, K
                )
                recv8 = ctx.receivers[::K]
                pair = S.segment_sum_sorted(
                    stats8, recv8, n, grad_dtype=bsend.dtype
                )
                vsum, vsumsq = pair[:, :fin], pair[:, fin : 2 * fin]
                both = S.segment_max(
                    both8, recv8, n, indices_are_sorted=True, empty_value=0.0
                )
                cnt = _edge_count(ctx, n)
            else:
                v = _gather_senders(bsend, ctx)
                if use_edge:
                    v = v + nn.Dense(fin)(ctx.edge_attr) @ w[2 * fin :]
                if ctx.run_align:
                    # Run-aligned pre-reduction (graph/batch.py run_align):
                    # every aggregation statistic first collapses K-fold
                    # with fused elementwise passes, then the segment ops
                    # run on E/K rows — the serial scatter-max that
                    # dominated the r04 trace (6 x ~9 ms at E=699k) costs
                    # 1/K, and the fused K1/K2 backward kernels are
                    # replaced by plain AD through broadcasts + the
                    # E/K-scale segment VJPs.
                    K = ctx.run_align
                    m = ctx.edge_mask[:, None]
                    # Narrow widths run at LANE width on TPU: a [E', fin<8]
                    # elementwise chain uses ~fin/128 of each VPU tile
                    # (conv_0's fin=1 backward measured 7 GB/s, r04 trace);
                    # zero columns ride along and are sliced off after the
                    # segment ops.
                    lane_w = fin
                    if fin % 128 and jax.default_backend() == "tpu":
                        lane_w = (fin + 127) // 128 * 128
                        v = jnp.concatenate(
                            [v, jnp.zeros((v.shape[0], lane_w - fin), v.dtype)], axis=1
                        )
                    # The statistics composition is SHARED with the
                    # fused kernel's contract (_presum_stats_ref is
                    # also what its custom VJP recompute targets), so
                    # fused and fallback configs cannot silently
                    # diverge. It runs one pass per statistic — an r05
                    # experiment packed (vf | vf^2) and (max | -min)
                    # into E-level lane-concats hoping XLA would fuse
                    # them into the reshape-reduce; it materialized the
                    # f32 [E', 2W] concats instead (110 ms/step vs
                    # 77.8, +27 GB/step), same failure mode as r04's
                    # [msg,-msg] concat. The E/K-level concats inside
                    # _presum_stats_ref are bandwidth-trivial.
                    from hydragnn_tpu.ops.segment_pallas import (
                        _presum_stats_ref,
                    )

                    stats8, both8 = _presum_stats_ref(v, ctx.edge_mask, K)
                    recv8 = ctx.receivers[::K]
                    pair = S.segment_sum_sorted(
                        stats8, recv8, n, grad_dtype=v.dtype
                    )
                    vsum, vsumsq = pair[:, :fin], pair[:, lane_w : lane_w + fin]
                    both = S.segment_max(
                        both8, recv8, n, indices_are_sorted=True, empty_value=0.0
                    )
                    both = jnp.concatenate(
                        [both[:, :fin], both[:, lane_w : lane_w + fin]], axis=-1
                    )
                    cnt = _edge_count(ctx, n)
                else:
                    vsum, vsumsq, cnt, both = pna_aggregate(
                        v, ctx.receivers, n, mask=ctx.edge_mask, indices_are_sorted=True
                    )
                    if ctx.in_degree is not None:
                        # chassis-precomputed degree (searchsorted over the
                        # sorted receivers): the aggregate's own count scatter
                        # then has no consumer and XLA dead-code-eliminates it
                        cnt = ctx.in_degree
            max_v = both[:, :fin]
            min_v = -both[:, fin:]
        # mean/var formed in f32 (both paths accumulate f32); cast back
        # to the compute dtype only after the cancellation
        safe_cnt = jnp.maximum(cnt, 1.0)[:, None]
        has = (cnt > 0.0)[:, None]
        mean_v = vsum / safe_cnt
        mean = jnp.where(has, a.astype(jnp.float32) + mean_v, 0.0)
        # PyG 'std': sqrt(relu(mean(x^2) - mean(x)^2) + eps); the a-shift
        # cancels exactly, so this is the variance of v alone — and for
        # empty receivers sqrt(eps), digit-identical to the message form
        var = jax.nn.relu(vsumsq / safe_cnt - mean_v * mean_v)
        std = jnp.sqrt(var + 1e-5)
        has_c = has.astype(v.dtype)
        aggs = [
            mean.astype(v.dtype),
            (a + min_v) * has_c,
            (a + max_v) * has_c,
            std.astype(v.dtype),
        ]
        agg = jnp.concatenate(aggs, axis=-1)  # [N, 4*fin]

        # Padding-node slots: cnt counts their masked edges (thousands at
        # flagship scale), and an ungated 'linear' scaler would amplify
        # the padding rows by ~deg/avg_deg — bounded-magnitude garbage
        # only because downstream consumers mask padding nodes. Gate on
        # node_mask so padding rows scale by exactly 1 (r03 advisor).
        deg = jnp.where(
            ctx.node_mask, jnp.maximum(cnt, 1.0), 1.0
        ).astype(v.dtype)
        log_deg = jnp.log(deg + 1.0)[:, None]
        amplification = log_deg / self.avg_deg_log
        attenuation = self.avg_deg_log / log_deg
        linear = deg[:, None] / self.avg_deg_lin
        scaled = jnp.concatenate(
            [agg, agg * amplification, agg * attenuation, agg * linear], axis=-1
        )  # [N, 16*fin]

        out = jnp.concatenate([x, scaled], axis=-1)
        return nn.Dense(self.out_dim)(out)  # post_nn, post_layers=1


class CFConv(nn.Module):
    """SchNet continuous-filter conv
    (reference: hydragnn/models/SCFStack.py:48-62; PyG schnet.CFConv).

    W_ij = filter_mlp(gaussian(d_ij)) * cosine_cutoff(d_ij)
    out_i = W2( sum_j W1(x_j) * W_ij )
    Expects ``ctx.edge_weight`` (distances) and ``ctx.edge_attr``
    (Gaussian-smeared distances) prepared by the SchNet chassis hook.
    """

    out_dim: int
    num_filters: int
    num_gaussians: int
    cutoff: float

    @nn.compact
    def __call__(self, x: jnp.ndarray, ctx: EdgeContext) -> jnp.ndarray:
        if ctx.edge_weight is None or ctx.edge_attr is None:
            raise ValueError("CFConv requires edge_weight and edge_attr")
        d = ctx.edge_weight
        # init parity with the reference: the filter MLP is plain torch
        # Linear init (kaiming-uniform a=sqrt(5) -> var 1/(3 fan_in));
        # lin1/lin2 are xavier-uniform with zero bias (PyG
        # CFConv.reset_parameters). At the CI accuracy thresholds this
        # scale difference vs flax's lecun_normal default is measurable.
        torch_init = nn.initializers.variance_scaling(1.0 / 3.0, "fan_in", "uniform")
        xavier = nn.initializers.xavier_uniform()

        def torch_bias(fan_in):
            bound = 1.0 / float(fan_in) ** 0.5

            def init(key, shape, dtype=jnp.float32):
                return jax.random.uniform(key, shape, dtype, -bound, bound)

            return init

        w = nn.Dense(
            self.num_filters,
            kernel_init=torch_init,
            bias_init=torch_bias(self.num_gaussians),
        )(ctx.edge_attr)
        w = shifted_softplus(w)
        w = nn.Dense(
            self.num_filters,
            kernel_init=torch_init,
            bias_init=torch_bias(self.num_filters),
        )(w)
        c = 0.5 * (jnp.cos(d * jnp.pi / self.cutoff) + 1.0)
        c = jnp.where(d <= self.cutoff, c, 0.0)
        w = w * c[:, None]

        h = nn.Dense(self.num_filters, use_bias=False, kernel_init=xavier)(x)
        # fused path: gather + per-edge filter product + scatter in one
        # kernel — the [E, F] message array never touches HBM
        agg = _gather_scatter(h, ctx, x.shape[0], scale=w).astype(x.dtype)
        return nn.Dense(self.out_dim, kernel_init=xavier)(agg)


def shifted_softplus(x: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.softplus(x) - jnp.log(2.0)


def gaussian_smearing(
    d: jnp.ndarray, start: float, stop: float, num_gaussians: int
) -> jnp.ndarray:
    """PyG GaussianSmearing: RBF expansion of distances
    (reference usage: hydragnn/models/SCFStack.py:42,70)."""
    offset = jnp.linspace(start, stop, num_gaussians)
    coeff = -0.5 / float((stop - start) / (num_gaussians - 1)) ** 2
    diff = d[:, None] - offset[None, :]
    return jnp.exp(coeff * diff * diff)


def avg_degree_stats(deg_histogram) -> Tuple[float, float]:
    """(avg_deg_lin, avg_deg_log) from a train-set degree histogram,
    mirroring PyG PNAConv's init-time computation."""
    import numpy as np

    hist = np.asarray(deg_histogram, dtype=np.float64)
    total = max(hist.sum(), 1.0)
    degrees = np.arange(len(hist), dtype=np.float64)
    lin = float((hist * degrees).sum() / total)
    log = float((hist * np.log(degrees + 1.0)).sum() / total)
    return max(lin, 1e-6), max(log, 1e-6)
