"""Train state and jitted step functions.

The reference hot loop (hydragnn/train/train_validate_test.py:333-371) does
zero_grad -> head indexing -> H2D copy -> forward -> loss -> backward ->
step per batch. Here the whole step is ONE jitted function over a
``TrainState`` pytree: forward + weighted multi-task loss + grad + optax
update + BatchNorm running-stat update, compiled once (fixed batch shapes
come from the loader's pad plan). Head indexing does not exist — targets
are already a dict-of-heads on the batch.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

from hydragnn_tpu.graph.batch import GraphBatch
from hydragnn_tpu.models.base import HydraModel, model_loss


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    batch_stats: Any
    opt_state: Any
    rng: jnp.ndarray


def create_train_state(
    variables: Dict[str, Any], tx: optax.GradientTransformation, seed: int = 0
) -> TrainState:
    # The jitted step donates the state's buffers; copy so the caller's
    # ``variables`` stay usable after the first step (e.g. re-init paths).
    params = jax.tree_util.tree_map(jnp.copy, variables["params"])
    batch_stats = jax.tree_util.tree_map(jnp.copy, variables.get("batch_stats", {}))
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        rng=jax.random.PRNGKey(seed),
    )


def create_eval_state(
    variables: Dict[str, Any], tx: optax.GradientTransformation, seed: int = 0
) -> TrainState:
    """TrainState with the full checkpoint SCHEMA but no device-side
    optimizer state: opt leaves are host zero-arrays shaped by
    ``jax.eval_shape(tx.init)``. Restoring a checkpoint for eval through
    this target never materializes the optimizer on any device — required
    for ZeRO-1-trained runs whose optimizer state cannot fit un-sharded."""
    import numpy as np

    params = jax.tree_util.tree_map(jnp.copy, variables["params"])
    batch_stats = jax.tree_util.tree_map(jnp.copy, variables.get("batch_stats", {}))
    opt_shapes = jax.eval_shape(tx.init, params)
    opt_state = jax.tree_util.tree_map(
        lambda s: np.zeros(s.shape, s.dtype), opt_shapes
    )
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=opt_state,
        rng=jax.random.PRNGKey(seed),
    )


def _cast_floats(tree: Any, dtype) -> Any:
    """Cast float32 leaves to ``dtype`` (ints/bools untouched)."""
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype)
        if hasattr(x, "dtype") and x.dtype == jnp.float32
        else x,
        tree,
    )


def _train_step_body(
    model: HydraModel,
    tx: optax.GradientTransformation,
    compute_dtype=None,
    remat: bool = False,
) -> Callable[[TrainState, GraphBatch], Tuple[TrainState, jnp.ndarray, jnp.ndarray]]:
    """The un-jitted per-batch training body shared by the jitted
    single-step path and the scan-over-epoch path."""

    def step(state: TrainState, batch: GraphBatch):
        rng, dropout_rng = jax.random.split(state.rng)

        def loss_fn(params):
            if compute_dtype is not None:
                apply_params = _cast_floats(params, compute_dtype)
                apply_batch = _cast_floats(batch, compute_dtype)
            else:
                apply_params, apply_batch = params, batch
            outputs, mutated = model.apply(
                {"params": apply_params, "batch_stats": state.batch_stats},
                apply_batch,
                train=True,
                mutable=["batch_stats"],
                rngs={"dropout": dropout_rng},
            )
            # loss in f32 against the ORIGINAL (uncast) targets
            outputs = [o.astype(jnp.float32) for o in outputs]
            total, tasks = model_loss(model.cfg, outputs, batch)
            return total, (jnp.stack(tasks), mutated)

        lf = jax.checkpoint(loss_fn) if remat else loss_fn
        (loss, (tasks, mutated)), grads = jax.value_and_grad(lf, has_aux=True)(
            state.params
        )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=params,
            batch_stats=mutated["batch_stats"],
            opt_state=opt_state,
            rng=rng,
        )
        return new_state, loss, tasks

    return step


def _guarded_step_body(
    model: HydraModel,
    tx: optax.GradientTransformation,
    compute_dtype=None,
    remat: bool = False,
):
    """Non-finite-guarded training body (the device half of
    ``hydragnn_tpu/resilience/sentry.py``): runs the normal step, then
    a cheap on-device ``isfinite(loss) & isfinite(global_norm(grads))``
    check decides whether the update LANDS. A bad batch leaves params,
    optimizer state, BatchNorm statistics and the step counter at their
    previous values — one fused ``where`` over the state, no host sync.

    Signature: ``(state, batch, consec) -> (state, loss, tasks, consec,
    bad)`` where ``consec`` is the consecutive-bad-step counter
    (int32 device scalar, threaded by the caller across steps) and
    ``bad`` is this step's flag as float32 (0.0/1.0) — reported loss
    and task losses are zeroed on bad steps so the epoch's weighted
    metrics (which also zero the batch's count) stay clean.
    """

    def step(state: TrainState, batch: GraphBatch, consec: jnp.ndarray):
        rng, dropout_rng = jax.random.split(state.rng)

        def loss_fn(params):
            if compute_dtype is not None:
                apply_params = _cast_floats(params, compute_dtype)
                apply_batch = _cast_floats(batch, compute_dtype)
            else:
                apply_params, apply_batch = params, batch
            outputs, mutated = model.apply(
                {"params": apply_params, "batch_stats": state.batch_stats},
                apply_batch,
                train=True,
                mutable=["batch_stats"],
                rngs={"dropout": dropout_rng},
            )
            outputs = [o.astype(jnp.float32) for o in outputs]
            total, tasks = model_loss(model.cfg, outputs, batch)
            return total, (jnp.stack(tasks), mutated)

        lf = jax.checkpoint(loss_fn) if remat else loss_fn
        (loss, (tasks, mutated)), grads = jax.value_and_grad(lf, has_aux=True)(
            state.params
        )
        bad = jnp.logical_not(
            jnp.isfinite(loss) & jnp.isfinite(optax.global_norm(grads))
        )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)

        def keep(new, old):
            return jax.tree_util.tree_map(
                lambda a, b: jnp.where(bad, b, a), new, old
            )

        new_state = state.replace(
            step=state.step + jnp.where(bad, 0, 1).astype(state.step.dtype),
            params=keep(params, state.params),
            batch_stats=keep(mutated["batch_stats"], state.batch_stats),
            opt_state=keep(opt_state, state.opt_state),
            rng=rng,
        )
        badf = bad.astype(jnp.float32)
        new_consec = jnp.where(bad, consec + 1, 0).astype(jnp.int32)
        return (
            new_state,
            jnp.where(bad, 0.0, loss),
            jnp.where(bad, jnp.zeros_like(tasks), tasks),
            new_consec,
            badf,
        )

    return step


def make_train_step(
    model: HydraModel,
    tx: optax.GradientTransformation,
    compute_dtype=None,
    remat: bool = False,
    guard_nonfinite: bool = False,
    diagnostics: bool = False,
) -> Callable[..., Tuple]:
    """Returns jitted ``(state, batch) -> (state, loss, tasks_loss)``.

    ``compute_dtype=jnp.bfloat16`` enables mixed precision: params and
    batch features are cast to bf16 for the forward/backward (MXU-native
    on TPU), while the master params, optimizer state, BatchNorm
    statistics, and the loss stay float32.

    ``remat=True`` (config ``Training.remat``) checkpoints the forward:
    activations are recomputed during the backward pass instead of held in
    HBM — the standard FLOPs-for-memory trade for deep conv stacks or
    large padded graphs. No reference analog (torch would use
    ``torch.utils.checkpoint``; the reference never does).

    ``guard_nonfinite=True`` (config ``Training.nonfinite_guard``)
    returns the GUARDED step instead — signature ``(state, batch,
    consec) -> (state, loss, tasks_loss, consec, bad)`` — which skips
    any batch producing a non-finite loss or gradient norm (see
    :func:`_guarded_step_body`; the host policy lives in
    ``hydragnn_tpu/resilience/sentry.py``). With all-finite inputs it
    computes exactly what the unguarded step computes.

    ``diagnostics=True`` (config ``Training.diagnostics``) additionally
    returns the jitted per-head diagnostics step — ``(train_step,
    diag_step)`` — a SEPARATE executable over the same loss (per-head
    gradient norms, inter-task cosine conflict matrix, update-to-param
    ratio; see ``hydragnn_tpu/obs/introspect.py``) that the train loop
    dispatches only on sampled steps, so the hot path's executable and
    sync discipline are untouched."""
    body = (
        _guarded_step_body(model, tx, compute_dtype=compute_dtype, remat=remat)
        if guard_nonfinite
        else _train_step_body(model, tx, compute_dtype=compute_dtype, remat=remat)
    )
    step = jax.jit(body, donate_argnums=(0,))
    if diagnostics:
        from hydragnn_tpu.obs.introspect import make_diagnostics_step

        return step, make_diagnostics_step(
            model, tx, compute_dtype=compute_dtype, remat=remat
        )
    return step


def make_scan_epoch(
    model: HydraModel,
    tx: optax.GradientTransformation,
    compute_dtype=None,
    remat: bool = False,
    guard_nonfinite: bool = False,
) -> Callable[..., Tuple]:
    """Whole-epoch training as ONE dispatch: ``lax.scan`` of the train
    step over device-resident stacked batches.

    Per-step dispatch costs a host->device round trip (~0.6 ms through a
    tunneled chip — comparable to the flagship's entire step compute);
    scanning the epoch inside one jitted program amortizes it to one
    dispatch per epoch. Requires every batch of the epoch stacked on a
    leading axis and resident in HBM (GraphLoader.stacked_device_batches),
    so it suits datasets that fit on-device. Since the scan-eligibility
    work (train/loop.py:_scan_auto_eligible) this is the DEFAULT
    dispatch mode on a single-device mesh with a stackable loader; the
    streaming per-step path remains for everything else.

    Returns jitted ``(state, stacked_batches, order) -> (state, losses[B],
    tasks[B, H], counts[B])`` where ``order`` is an int32 permutation of
    the batch axis (the per-epoch reshuffle, device-side gather) and
    ``counts`` the real-graph count per batch for weighted averaging.

    ``guard_nonfinite=True`` scans the GUARDED step body instead — the
    same on-device non-finite skip the per-step path gets
    (:func:`_guarded_step_body`), with the consecutive-bad counter
    threaded through the scan carry. Signature then becomes
    ``(state, stacked, order, consec0) -> (state, losses, tasks, counts,
    bads[B], consec_end)`` where bad steps contribute zero loss/count
    (the ``NonFiniteSentry.observe_scan`` contract).
    """
    if guard_nonfinite:
        gbody = _guarded_step_body(
            model, tx, compute_dtype=compute_dtype, remat=remat
        )

        def epoch_guarded(
            state: TrainState, stacked: GraphBatch, order: jnp.ndarray,
            consec: jnp.ndarray,
        ):
            def scan_body(carry, i: jnp.ndarray):
                state, consec = carry
                batch = jax.tree_util.tree_map(lambda x: x[i], stacked)
                state, loss, tasks, consec, bad = gbody(state, batch, consec)
                cnt = batch.graph_mask.sum().astype(jnp.float32) * (1.0 - bad)
                return (state, consec), (loss, tasks, cnt, bad)

            (state, consec), (losses, tasks, counts, bads) = jax.lax.scan(
                scan_body, (state, consec), order
            )
            return state, losses, tasks, counts, bads, consec

        return jax.jit(epoch_guarded, donate_argnums=(0,))

    body = _train_step_body(model, tx, compute_dtype=compute_dtype, remat=remat)

    def epoch(state: TrainState, stacked: GraphBatch, order: jnp.ndarray):
        # Scan over the PERMUTATION, dynamic-indexing one batch out of the
        # closed-over stack per iteration: a full permuted copy of the
        # train split as scan xs would double the feature's HBM footprint.
        def scan_body(state: TrainState, i: jnp.ndarray):
            batch = jax.tree_util.tree_map(lambda x: x[i], stacked)
            new_state, loss, tasks = body(state, batch)
            return new_state, (loss, tasks, batch.graph_mask.sum().astype(jnp.float32))

        state, (losses, tasks, counts) = jax.lax.scan(scan_body, state, order)
        return state, losses, tasks, counts

    return jax.jit(epoch, donate_argnums=(0,))


def make_scan_eval(
    model: HydraModel,
) -> Callable[..., Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]]:
    """Whole-split evaluation as ONE dispatch: ``lax.scan`` of the eval
    step over device-resident stacked batches (the eval-side companion of
    :func:`make_scan_epoch`; same HBM-residency requirement). Returns
    jitted ``(state, stacked) -> (losses[B], tasks[B, H], counts[B])``."""

    def scan_body(state: TrainState, batch: GraphBatch):
        outputs = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            batch,
            train=False,
        )
        loss, tasks = model_loss(model.cfg, outputs, batch)
        return state, (loss, jnp.stack(tasks), batch.graph_mask.sum().astype(jnp.float32))

    def evaluate(state: TrainState, stacked: GraphBatch):
        _, (losses, tasks, counts) = jax.lax.scan(scan_body, state, stacked)
        return losses, tasks, counts

    return jax.jit(evaluate)


def make_stats_step(model: HydraModel) -> Callable[[TrainState, GraphBatch], TrainState]:
    """Jitted BatchNorm-recalibration step: a train-mode forward that
    updates ONLY the running statistics (params untouched, no grads).

    Used after training to re-estimate the running stats at the final
    parameters: the in-training EMA trails the last few noisy batches
    (and BN's train-mode batch-feedback can leave it far from the
    stationary statistics — observed as train-mode metrics converging
    while eval-mode metrics diverge), so a few frozen-parameter passes
    make eval faithful."""

    def step(state: TrainState, batch: GraphBatch):
        # dropout OFF (train=False), BatchNorm in batch-stats mode
        # (bn_train=True): eval statistics must be estimated under the
        # same deterministic forward eval itself uses
        _, mutated = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            batch,
            train=False,
            bn_train=True,
            mutable=["batch_stats"],
        )
        return state.replace(batch_stats=mutated["batch_stats"])

    return jax.jit(step)


def make_eval_step(
    model: HydraModel, with_outputs: bool = False
) -> Callable[..., Any]:
    """Returns jitted ``(state, batch) -> (loss, tasks_loss[, outputs])``
    using running BatchNorm statistics (train=False), the analog of the
    reference's ``model.eval()`` validate/test passes
    (train_validate_test.py:374-443)."""

    def step(state: TrainState, batch: GraphBatch):
        outputs = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            batch,
            train=False,
        )
        loss, tasks = model_loss(model.cfg, outputs, batch)
        if with_outputs:
            return loss, jnp.stack(tasks), outputs
        return loss, jnp.stack(tasks)

    return jax.jit(step)
