"""Epoch driver: train / validate / test with plateau LR and early stop.

TPU-native re-design of the reference epoch loop (reference:
hydragnn/train/train_validate_test.py:37-215). Semantics kept:

  - per-epoch seeded reshuffle (``loader.set_epoch`` = the reference's
    ``sampler.set_epoch``, :113-115);
  - loss accumulation weighted by the real graph count of each batch
    (``data.num_graphs`` weighting, :364-367) — here the count comes from
    ``graph_mask`` so padding never dilutes the average;
  - ``ReduceLROnPlateau(factor=0.5, patience=5, min_lr=1e-5)`` stepped on
    the validation loss (reference constructs it at run_training.py:94-96);
  - ``EarlyStopping(patience=10, min_delta=0)`` gated by config
    ``Training.EarlyStopping`` / ``Training.patience`` (:53-56,103-106,
    utils/model.py:128-143);
  - cross-process metric reduction (mean) replacing the torch.distributed
    all-reduce (:284-289); prediction gathering replacing the padded
    all-gather (:292-330).

Device-sync discipline: per-batch losses are accumulated as device scalars
and materialized once per epoch, so the hot loop never blocks on D2H.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.graph.batch import GraphBatch
from hydragnn_tpu.models.base import HydraModel, ModelConfig
from hydragnn_tpu.train.optimizer import current_learning_rate, set_learning_rate
from hydragnn_tpu.train.state import (
    TrainState,
    make_eval_step,
    make_scan_epoch,
    make_scan_eval,
    make_stats_step,
    make_train_step,
)
from hydragnn_tpu.utils.print_utils import print_distributed, iterate_tqdm
from hydragnn_tpu.utils import knobs
from hydragnn_tpu.utils.time_utils import Timer


class EarlyStopping:
    """Patience counter on validation loss (reference:
    hydragnn/utils/model.py:128-143)."""

    def __init__(self, patience: int = 10, min_delta: float = 0.0):
        self.patience = patience
        self.min_delta = min_delta
        self.count = 0
        self.min_loss = float("inf")

    def __call__(self, val_loss: float) -> bool:
        if val_loss < self.min_loss:
            self.min_loss = val_loss
            self.count = 0
        elif val_loss > self.min_loss + self.min_delta:
            self.count += 1
            if self.count >= self.patience:
                return True
        return False


class ReduceLROnPlateau:
    """Torch-semantics plateau scheduler acting on the injected dynamic
    learning rate (reference uses torch.optim.lr_scheduler.ReduceLROnPlateau
    with factor=0.5, patience=5, min_lr=1e-5, run_training.py:94-96)."""

    def __init__(
        self,
        factor: float = 0.5,
        patience: int = 5,
        min_lr: float = 1e-5,
        threshold: float = 1e-4,
    ):
        self.factor = factor
        self.patience = patience
        self.min_lr = min_lr
        self.threshold = threshold
        self.best = float("inf")
        self.num_bad_epochs = 0

    def step(self, state: TrainState, val_loss: float) -> TrainState:
        if val_loss < self.best * (1.0 - self.threshold):
            self.best = val_loss
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.num_bad_epochs > self.patience:
            self.num_bad_epochs = 0
            lr = max(current_learning_rate(state.opt_state) * self.factor, self.min_lr)
            state = state.replace(opt_state=set_learning_rate(state.opt_state, lr))
        return state


def _reduce_mean_across_processes(values: np.ndarray) -> np.ndarray:
    """Mean across processes (reference reduce_values_ranks,
    train_validate_test.py:284-289); identity in single-process runs."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(values)).mean(axis=0)
    return values


def _finalize_weighted(
    weighted_losses, weighted_tasks, counts
) -> Tuple[float, np.ndarray]:
    """Count-weighted mean of per-batch metrics (already multiplied by
    their counts), mean-reduced across processes — the reference's
    num_graphs weighting + all-reduce
    (train_validate_test.py:284-289,364-367)."""
    total = max(float(jnp.stack(counts).sum()), 1.0)
    avg_loss = float(jnp.stack(weighted_losses).sum()) / total
    avg_tasks = np.asarray(jnp.stack(weighted_tasks).sum(axis=0)) / total
    avg_loss = float(_reduce_mean_across_processes(np.asarray([avg_loss]))[0])
    avg_tasks = _reduce_mean_across_processes(avg_tasks)
    return avg_loss, avg_tasks


def _named_tasks(names: Sequence[str], values) -> Dict[str, float]:
    """Per-task loss array -> {head_name: loss}. Zip-truncating: a
    zero-length array (preempted epoch finalize) yields {}."""
    return {n: float(v) for n, v in zip(names, np.asarray(values).reshape(-1))}


class _MetricAccum:
    """Accumulates per-batch (loss, tasks, graph_mask) as raw device
    arrays; ``finalize`` does ALL the weighting math in one stacked
    computation at the epoch boundary. The hot loop therefore dispatches
    ZERO extra device ops per step — no ``graph_mask.sum()``, no
    ``loss * n`` multiplies — and syncs exactly once per epoch (the
    step-span tracer pins this: no ``block_until_ready`` outside the
    sampled window)."""

    def __init__(self):
        self._losses: List[jnp.ndarray] = []
        self._tasks: List[jnp.ndarray] = []
        self._ns: List[jnp.ndarray] = []
        self._bads: List[Optional[jnp.ndarray]] = []

    def add(
        self,
        loss: jnp.ndarray,
        tasks: jnp.ndarray,
        n: jnp.ndarray,
        bad: Optional[jnp.ndarray] = None,
    ) -> None:
        """``n``: the batch's ``graph_mask`` (preferred — summed in one
        stacked op at finalize) or an already-reduced scalar count.
        ``bad``: the guarded step's 0/1 flag; a bad batch's count is
        zeroed at finalize (its loss/tasks are already zeroed on
        device by the guarded step)."""
        self._losses.append(loss)
        self._tasks.append(tasks)
        self._ns.append(n)
        self._bads.append(bad)

    def finalize(self) -> Tuple[float, np.ndarray]:
        if not self._ns:
            # zero batches ran (e.g. preemption before the first step);
            # the caller's preempt path discards these values
            return 0.0, np.zeros(0, np.float32)
        losses = jnp.stack(self._losses)
        tasks = jnp.stack(self._tasks)
        first = jnp.asarray(self._ns[0])
        if first.ndim:
            # graph masks (any stacked shape): one fused count reduction
            counts = (
                jnp.stack([jnp.asarray(m) for m in self._ns])
                .reshape(len(self._ns), -1)
                .sum(axis=1)
                .astype(jnp.float32)
            )
        else:
            counts = jnp.stack(self._ns).astype(jnp.float32)
        if any(b is not None for b in self._bads):
            bads = jnp.stack(
                [
                    jnp.zeros((), jnp.float32) if b is None else b
                    for b in self._bads
                ]
            )
            counts = counts * (1.0 - bads)
        return _finalize_weighted(
            [(losses * counts).sum()],
            [(tasks * counts[:, None]).sum(axis=0)],
            [counts.sum()],
        )


def train_epoch(
    loader,
    state: TrainState,
    train_step,
    verbosity: int = 0,
    profiler=None,
    spans=None,
    hooks=None,
    diag=None,
    incidents=None,
) -> Tuple[TrainState, float, np.ndarray]:
    """One training epoch; returns (state, avg_loss, avg_tasks_loss[H]).

    ``spans`` (hydragnn_tpu/obs/spans.py:StepSpans) decomposes the
    epoch's wall time into data-wait / host-dispatch / sampled device
    time; the default disabled spans keep the loop's plain async shape
    (identity iterator, direct step call).

    ``hooks`` (hydragnn_tpu/resilience/hooks.py:TrainHooks) adds the
    fault-tolerance hot-loop duties at batch granularity: preemption
    check (graceful mid-epoch stop), watchdog heartbeat, fault
    injection, and — when its non-finite sentry is active — the
    GUARDED step call ``train_step(state, batch, consec)`` whose
    skipped batches contribute zero weight to the epoch metrics.

    ``diag`` (hydragnn_tpu/obs/introspect.py:HeadDiagnostics) samples
    the per-head gradient diagnostics every K steps. It must run
    BEFORE the train step consumes the state: the jitted step donates
    the state's buffers, so the sampled step is the last moment this
    state is usable from Python (the runtime serializes the in-flight
    diagnostics read against the donating write). Non-sampled steps pay
    one counter increment; no host sync happens until the epoch
    boundary."""
    if spans is None:
        from hydragnn_tpu.obs import StepSpans

        spans = StepSpans.disabled()
    sentry = hooks.sentry if hooks is not None else None
    acc = _MetricAccum()
    for batch in spans.timed_iter(iterate_tqdm(loader, verbosity, desc="train")):
        if hooks is not None:
            if hooks.preempted:
                break
            batch = hooks.before_step(batch)
        if diag is not None:
            diag.maybe_sample(state, batch)
        if sentry is not None:
            state, loss, task_losses, consec, bad = spans.step(
                train_step, state, batch, sentry.consec
            )
            sentry.observe(consec, bad)
            acc.add(loss, task_losses, batch.graph_mask, bad=bad)
        else:
            state, loss, task_losses = spans.step(train_step, state, batch)
            # the raw mask, NOT mask.sum(): the accumulator defers every
            # metric reduction to ONE stacked dispatch at epoch end, so
            # the steady-state step is exactly one host->device dispatch
            acc.add(loss, task_losses, batch.graph_mask)
        if profiler is not None:
            profiler.step()
        if incidents is not None:
            # drives any OPEN incident's bounded profiler capture at
            # step granularity (obs/triggers.py:IncidentRecorder.tick);
            # a recorder with no open incident returns immediately
            incidents.tick()
    avg_loss, avg_tasks = acc.finalize()
    return state, avg_loss, avg_tasks


def _finalize_scan(losses, tasks, counts) -> Tuple[float, np.ndarray]:
    """Weighted finalize for per-batch metric arrays coming out of a
    scan ([B], [B, H], [B])."""
    return _finalize_weighted(
        [(losses * counts).sum()],
        [(tasks * counts[:, None]).sum(axis=0)],
        [counts.sum()],
    )


def _landing_checked(cached, fresh, ecache, key, expected_delta, label):
    """Wrap a CACHED (deserialized) donated executable with a one-time
    landing check: the first real execution's output ``state.step`` must
    equal input ``step + expected_delta`` (1 for a per-step executable,
    num_batches for a scan-epoch one). A round-trip that dropped
    donation metadata produces an optimizer update that never lands —
    the exact silent-staleness failure mode the exec-cache donation gate
    exists for (utils/exec_cache.py module docstring) — so a failed
    check EVICTS the entry (``donation_check_failed``) and replays the
    step through the fresh jitted ``fresh`` on a pre-copy of the inputs
    (the cached executable may have consumed the donated originals)."""
    holder = {"fn": cached, "checked": False}

    def _copy(tree):
        return jax.tree_util.tree_map(
            lambda x: x.copy() if hasattr(x, "copy") else x, tree
        )

    def step(*args):
        if holder["checked"]:
            return holder["fn"](*args)
        saved = _copy(args)
        in_step = int(jax.device_get(args[0].step))
        try:
            out = holder["fn"](*args)
            out_step = int(jax.device_get(out[0].step))
            if out_step != in_step + expected_delta:
                raise RuntimeError(
                    f"cached {label} executable landed step {out_step}, "
                    f"expected {in_step + expected_delta}"
                )
            holder["checked"] = True
            return out
        except Exception:
            ecache._evict(key, "donation_check_failed")
            ecache._miss(key, "donation_check_failed", label=label)
            holder["fn"] = fresh
            holder["checked"] = True
            return fresh(*saved)

    return step


def train_epoch_scan(
    loader, state: TrainState, scan_fn, epoch: int, diag=None, sentry=None
) -> Tuple[TrainState, float, np.ndarray]:
    """One training epoch as a single device dispatch (``Training.
    scan_epoch``): lax.scan over the loader's device-resident stacked
    batches, shuffled device-side by an epoch-seeded permutation of the
    batch axis (sample-to-batch membership reshuffles only when the
    loader's ``scan_reshuffle_every`` is set — see
    ``GraphLoader.stacked_device_batches``). Same weighted-metric
    semantics as ``train_epoch``.

    ``diag`` (obs/introspect.py:HeadDiagnostics): sampled ONCE per epoch
    on the first scheduled batch, BEFORE the donating scan consumes the
    state — scan mode has no step granularity, so per-epoch is the
    sampling floor. ``sentry``: when the scan_fn is the GUARDED variant
    (make_scan_epoch(guard_nonfinite=True)), the per-step bad flags and
    the carry's consecutive counter are handed to it, device-resident."""
    stacked = loader.stacked_device_batches(epoch)
    nb = len(loader)
    if loader.shuffle:
        order = np.random.default_rng(loader.seed + epoch).permutation(nb)
    else:
        order = np.arange(nb)
    if diag is not None:
        # DEVICE-scalar index: a Python-int index would bake the batch
        # position into the gather executable and recompile every epoch
        # (the shuffle moves order[0]), tripping the zero-unexpected-
        # recompile contract the compile monitor enforces
        i0 = jnp.asarray(order[0], dtype=jnp.int32)
        first = jax.tree_util.tree_map(lambda x: x[i0], stacked)
        diag.maybe_sample(state, first)
    order_dev = jnp.asarray(order, dtype=jnp.int32)
    if sentry is not None:
        state, losses, tasks, counts, bads, consec = scan_fn(
            state, stacked, order_dev, sentry.consec
        )
        sentry.observe_scan(bads, consec)
    else:
        state, losses, tasks, counts = scan_fn(state, stacked, order_dev)
    avg_loss, avg_tasks = _finalize_scan(losses, tasks, counts)
    return state, avg_loss, avg_tasks


def evaluate_epoch(
    loader, state: TrainState, eval_step, verbosity: int = 0, desc: str = "validate"
) -> Tuple[float, np.ndarray]:
    acc = _MetricAccum()
    for batch in iterate_tqdm(loader, verbosity, desc=desc):
        loss, task_losses = eval_step(state, batch)
        acc.add(loss, task_losses, batch.graph_mask)
    return acc.finalize()


def evaluate_epoch_scan(loader, state: TrainState, scan_eval_fn) -> Tuple[float, np.ndarray]:
    """Whole-split evaluation in one dispatch (``Training.scan_epoch``'s
    eval-side companion); same weighted-metric semantics as
    ``evaluate_epoch``."""
    losses, tasks, counts = scan_eval_fn(state, loader.stacked_device_batches())
    return _finalize_scan(losses, tasks, counts)


def test_epoch(
    loader,
    state: TrainState,
    eval_step_with_outputs,
    cfg: ModelConfig,
    verbosity: int = 0,
    return_samples: bool = True,
) -> Tuple[float, np.ndarray, List[np.ndarray], List[np.ndarray]]:
    """Full test pass; optionally collects per-head (true, predicted) value
    arrays over real (unpadded) entries — the reference ``test()`` contract
    (train_validate_test.py:399-443). Multi-process runs concatenate values
    across processes (the reference's padded all-gather, :292-330)."""
    acc = _MetricAccum()
    true_values: List[List[np.ndarray]] = [[] for _ in range(cfg.num_heads)]
    pred_values: List[List[np.ndarray]] = [[] for _ in range(cfg.num_heads)]
    for batch in iterate_tqdm(loader, verbosity, desc="test"):
        loss, task_losses, outputs = eval_step_with_outputs(state, batch)
        acc.add(loss, task_losses, batch.graph_mask)
        if return_samples:
            # Stacked multi-device batches carry a leading device axis on
            # masks/targets ([D, G]) while sharded eval outputs come back
            # device-concatenated ([D*G, d]); flattening aligns both.
            # ``local_view`` reduces multi-host global arrays to this
            # process's rows (same order as its local sub-batches), so the
            # cross-process concat below sees each sample exactly once.
            from hydragnn_tpu.parallel.mesh import local_view

            gmask = local_view(batch.graph_mask).reshape(-1)
            nmask = local_view(batch.node_mask).reshape(-1)
            for ihead in range(cfg.num_heads):
                name = cfg.output_names[ihead]
                if cfg.output_type[ihead] == "graph":
                    t = local_view(batch.graph_targets[name])
                    tv = t.reshape(-1, t.shape[-1])[gmask]
                    p = local_view(outputs[ihead])
                    pv = p.reshape(-1, p.shape[-1])[gmask]
                else:
                    t = local_view(batch.node_targets[name])
                    tv = t.reshape(-1, t.shape[-1])[nmask]
                    p = local_view(outputs[ihead])
                    pv = p.reshape(-1, p.shape[-1])[nmask]
                true_values[ihead].append(tv)
                pred_values[ihead].append(pv)
    avg_loss, avg_tasks = acc.finalize()

    trues: List[np.ndarray] = []
    preds: List[np.ndarray] = []
    if return_samples:
        for ihead in range(cfg.num_heads):
            tv = np.concatenate(true_values[ihead]) if true_values[ihead] else np.zeros((0, 1))
            pv = np.concatenate(pred_values[ihead]) if pred_values[ihead] else np.zeros((0, 1))
            if jax.process_count() > 1:
                tv = _allgather_varlen(tv)
                pv = _allgather_varlen(pv)
            trues.append(tv)
            preds.append(pv)
    return avg_loss, avg_tasks, trues, preds


def _allgather_varlen(arr: np.ndarray) -> np.ndarray:
    """Cross-process concat of per-process arrays with different row
    counts: exchange sizes, pad to the max, all-gather, trim — the
    reference's padded variable-length all-gather
    (train_validate_test.py:292-330). Row counts differ because each
    process's shard holds different samples (node heads: different atom
    counts)."""
    from jax.experimental import multihost_utils

    n = np.asarray([arr.shape[0]], dtype=np.int64)
    counts = np.asarray(multihost_utils.process_allgather(n)).reshape(-1)
    n_max = int(counts.max())
    padded = np.zeros((n_max,) + arr.shape[1:], dtype=arr.dtype)
    padded[: arr.shape[0]] = arr
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    return np.concatenate([gathered[p, : counts[p]] for p in range(len(counts))])


def _scan_auto_eligible(loader, partitioner=None) -> Tuple[bool, str]:
    """Is the whole-epoch scan dispatch the right DEFAULT here?
    (``Training.scan_epoch`` unset — an explicit true/false always
    wins.) Eligible = single-device mesh + a loader that can stack the
    split device-resident + no feature that inherently needs batch
    granularity (step-indexed fault injection). Returns (eligible,
    human-readable reason) — the reason lands in the flight manifest's
    ``dispatch_mode`` field either way.

    ``partitioner`` (hydragnn_tpu/parallel/partitioner.py) is the
    authoritative topology signal when given: the scan path trusts
    ``partitioner.single_device`` instead of sniffing the loader's
    mesh shape itself."""
    if not hasattr(loader, "stacked_device_batches") or not hasattr(
        loader, "shuffle"
    ):
        return False, "loader cannot stack device-resident batches"
    if partitioner is not None:
        if not partitioner.single_device:
            return False, "partitioner mesh is multi-device"
    elif getattr(loader, "device_stack", 1) != 1:
        return False, "multi-device stacked loader (sharded mesh)"
    if jax.process_count() > 1:
        return False, "multi-process run"
    try:
        if len(loader) < 1:
            return False, "empty loader"
    except TypeError:
        return False, "unsized loader"
    inject = knobs.active_injections(include_serve=False)
    if inject:
        # deterministic fault injection is step-indexed — it needs the
        # per-step path's batch granularity to fire at the right step
        return False, f"fault injection active ({inject[0]})"
    if knobs.get_float("HYDRAGNN_WATCHDOG_S", 0.0) > 0:
        # the watchdog heartbeats at batch granularity; a whole-epoch
        # dispatch would read as a stall
        return False, "hang watchdog active"
    return True, "single-device mesh + device-resident stacked loader"


def train_validate_test(
    model: HydraModel,
    tx,
    state: TrainState,
    train_loader,
    val_loader,
    test_loader,
    config: Dict[str, Any],
    log_name: str = "run",
    verbosity: int = 0,
    create_plots: bool = False,
    plot_init_solution: bool = False,
    plot_hist_solution: bool = False,
    log_dir: str = "./logs/",
    profiler=None,
    train_step=None,
    eval_step=None,
    eval_step_out=None,
    stats_step=None,
    flight=None,
    run_config=None,
    partitioner=None,
    manifest_extra=None,
) -> Tuple[TrainState, Dict[str, Any]]:
    """Train for ``Training.num_epoch`` epochs with validation-driven LR
    plateau + early stopping; returns (final_state, history dict). ``config``
    is the ``NeuralNetwork`` section (reference signature parity,
    train_validate_test.py:37-58). Callers running data-parallel pass the
    sharded step functions (hydragnn_tpu/parallel); defaults are the
    single-device jitted steps.

    Telemetry (hydragnn_tpu/obs, gated by ``HYDRAGNN_TELEMETRY``): the
    run writes a flight record — ``<log_dir>/<log_name>/flight.jsonl``,
    rank 0 — with a start manifest (resolved config, backend, mesh,
    pad plans), per-epoch records carrying the losses plus the
    data-wait / dispatch / device step-time decomposition and compile
    counts, and a final summary. Callers may pass their own ``flight``
    recorder (bench harnesses) and ``run_config`` (the full resolved
    config for the manifest; defaults to the NeuralNetwork section);
    ``manifest_extra`` merges extra caller keys into the run_start
    manifest (the retrain pilot's fine-tune child stamps its
    provenance there — pilot/tune.py).

    ``partitioner`` (hydragnn_tpu/parallel/partitioner.py) is the run's
    sharding authority: the scan-epoch auto-dispatch trusts its
    single-device verdict, and the manifest's ``parallel`` block (mesh
    shape, fsdp factor, per-leaf sharding summary, per-device bytes,
    replicated-leaf fallbacks) comes from it — docs/PARALLELISM.md."""
    training = config["Training"]
    num_epoch = int(training["num_epoch"])
    early_stop = bool(training.get("EarlyStopping", False))
    stopper = EarlyStopping(patience=int(training.get("patience", 10))) if early_stop else None
    scheduler = ReduceLROnPlateau()

    cfg = model.cfg
    # Training.mixed_precision: bf16 forward/backward with f32 master
    # params/optimizer/BN stats (MXU-native; absent from the reference,
    # which has no AMP path — SURVEY §2.2 "explicitly absent")
    compute_dtype = (
        jnp.bfloat16 if training.get("mixed_precision") else None
    )
    # Dispatch-mode resolution. ``Training.scan_epoch`` explicit
    # true/false always wins; UNSET defaults to the whole-epoch lax.scan
    # dispatch when eligible (_scan_auto_eligible: single-device mesh +
    # device-resident stacked loader — it already wins 3x on qm9,
    # BENCH_r04), with automatic fallback to per-step dispatch and the
    # decision recorded in the flight manifest's ``dispatch_mode``.
    scan_fn = scan_eval_fn = None
    loop_owned = train_step is None
    scan_cfg = training.get("scan_epoch")
    scan_auto = scan_cfg is None and loop_owned
    if not loop_owned:
        use_scan, dispatch_reason = False, "caller-supplied train step"
    elif scan_cfg is None:
        use_scan, dispatch_reason = _scan_auto_eligible(
            train_loader, partitioner=partitioner
        )
        if use_scan and (profiler is not None or "Profile" in config):
            use_scan, dispatch_reason = False, "per-step profiler configured"
        if use_scan and float(training.get("watchdog_stall_s", 0) or 0) > 0:
            use_scan, dispatch_reason = False, "hang watchdog active"
        if use_scan:
            # the stack must actually materialize (pad-plan/HBM limits):
            # fall back instead of dying mid-run — the loader caches the
            # stack, so epoch 0 does not pay this twice
            try:
                train_loader.stacked_device_batches(0)
            except Exception as exc:
                use_scan = False
                dispatch_reason = f"stacking failed: {type(exc).__name__}"
    elif scan_cfg:
        use_scan, dispatch_reason = True, "Training.scan_epoch=true"
    else:
        use_scan, dispatch_reason = False, "Training.scan_epoch=false"
    # Non-finite guard (hydragnn_tpu/resilience/sentry.py): folded into
    # the loop-owned step in BOTH dispatch modes — per-step via the
    # guarded jitted step, scan via the guarded scan body threading the
    # consecutive-bad counter through the carry. Sharded callers pass
    # their own step and keep their own policy.
    guard_nonfinite = bool(training.get("nonfinite_guard", True)) and loop_owned
    if use_scan:
        scan_fn = make_scan_epoch(
            model,
            tx,
            compute_dtype=compute_dtype,
            remat=bool(training.get("remat", False)),
            guard_nonfinite=guard_nonfinite,
        )
        if eval_step is None:  # a caller-supplied eval_step keeps priority
            scan_eval_fn = make_scan_eval(model)
            if scan_auto:
                # auto mode must not die on an unstackable VAL split —
                # eval falls back to per-step, training stays scanned
                try:
                    val_loader.stacked_device_batches(0)
                except Exception:
                    scan_eval_fn = None
    # own_step: the loop built the default single-device PER-STEP train
    # step — the only mode with per-batch (state, batch) pairs on the
    # host (the diagnostics sampler's per-step granularity; scan mode
    # samples once per epoch instead).
    own_step = loop_owned and scan_fn is None
    train_step = train_step or make_train_step(
        model,
        tx,
        compute_dtype=compute_dtype,
        remat=bool(training.get("remat", False)),
        guard_nonfinite=guard_nonfinite,
    )
    eval_step = eval_step or make_eval_step(model)
    eval_step_out = eval_step_out or make_eval_step(model, with_outputs=True)
    if stats_step is None and training.get("bn_recalibration", True):
        stats_step = make_stats_step(model)

    # config-driven profiler (reference: Profiler setup from
    # config["Profile"], train_validate_test.py:99-101)
    if profiler is None and "Profile" in config:
        from hydragnn_tpu.utils.profile import Profiler

        profiler = Profiler(prefix=os.path.join(log_dir, log_name, "profile"))
        profiler.setup(config["Profile"])
        if not profiler.enable:
            profiler = None

    history: Dict[str, List] = {
        "train_loss": [],
        "val_loss": [],
        "test_loss": [],
        "train_tasks": [],
        "val_tasks": [],
        "test_tasks": [],
        "lr": [],
    }
    # Per-epoch checkpointing + exact resume (beyond the reference's
    # restore-model-and-start-over: epoch index, plateau scheduler, and
    # early-stop counters survive the restart). The TrainState itself is
    # restored by the caller via Training.continue/startfrom.
    ckpt_every = int(training.get("checkpoint_every", 0))
    ckpt_keep_last = int(training.get("checkpoint_keep_last", 3))
    start_epoch = 0
    resumed_from = None  # set when a continue-run actually loaded meta
    if training.get("continue") == 1:
        from hydragnn_tpu.utils.checkpoint import load_train_meta

        if "startfrom" not in training:
            raise ValueError("Training.continue=1 requires Training.startfrom")
        meta = load_train_meta(training["startfrom"], log_dir)
        if meta is not None:
            # The model file and the meta sidecar are written sequentially
            # (each atomic, the pair not): a crash between them leaves meta
            # one interval older than the weights. The meta carries the
            # optimizer step it described; on mismatch, re-derive the epoch
            # from the restored weights instead of replaying epochs.
            meta_step = meta.get("step")
            state_step = int(jax.device_get(state.step))
            if meta_step is not None and int(meta_step) != state_step:
                steps_per_epoch = max(len(train_loader), 1)
                derived = min(num_epoch, state_step // steps_per_epoch)
                print_distributed(
                    verbosity,
                    f"WARNING: checkpoint meta (step {meta_step}) does not "
                    f"match restored weights (step {state_step}) — the run "
                    "likely crashed between the weight and meta writes; "
                    f"resuming from epoch {derived} derived from the "
                    f"weights, not meta epoch {meta['epoch']}",
                )
                # Repair the whole sidecar, not just the epoch: the stale
                # history would misalign epoch indices for everything
                # appended after it, and the stale scheduler/stopper
                # counters describe an older state than the weights (the
                # weights' own opt_state already carries the live LR).
                hist = meta.get("history", {})
                for k, v in hist.items():
                    v = v[:derived]
                    while v and len(v) < derived:
                        v.append(v[-1])  # unknown epochs: carry the last
                    hist[k] = v
                meta = {
                    "epoch": derived,
                    "step": state_step,
                    "early_stopped": False,
                    "scheduler": {"best": float("inf"), "num_bad_epochs": 0},
                    "stopper": {"count": 0, "min_loss": float("inf")},
                    "history": hist,
                }
                # rewrite once so future resumes see a consistent pair —
                # under the name resume READS from (training["startfrom"]),
                # which may differ from this run's log_name; also under
                # log_name so this run's own sidecar starts consistent
                from hydragnn_tpu.utils.checkpoint import save_train_meta

                save_train_meta(meta, training["startfrom"], log_dir)
                if log_name != training["startfrom"]:
                    save_train_meta(meta, log_name, log_dir)
            # an early-stopped run resumes to a no-op (the stop decision
            # is honored, not replayed into extra epochs); a completed or
            # interrupted run continues from its recorded epoch — which
            # also supports the reference's extend-training workflow
            # (continue with a larger num_epoch)
            start_epoch = num_epoch if meta.get("early_stopped") else int(meta["epoch"])
            resumed_from = start_epoch
            scheduler.best = float(meta["scheduler"]["best"])
            scheduler.num_bad_epochs = int(meta["scheduler"]["num_bad_epochs"])
            if stopper is not None and "stopper" in meta:
                stopper.count = int(meta["stopper"]["count"])
                stopper.min_loss = float(meta["stopper"]["min_loss"])
            history = meta["history"]

    # Unified telemetry (hydragnn_tpu/obs): flight record + step spans +
    # compile monitor, all inert when HYDRAGNN_TELEMETRY=0. Created
    # AFTER resume handling so a config error there cannot leak a
    # registered monitor or an empty flight file. The flight record is
    # rank-0 (like checkpoints/tensorboard); spans and the compile
    # monitor run everywhere but only rank 0 persists them.
    from hydragnn_tpu.obs import (
        CompileMonitor,
        FlightRecorder,
        StepSpans,
        telemetry_enabled,
    )

    telemetry_on = telemetry_enabled()
    # Pod-visibility plane (obs/podview.py, docs/OBSERVABILITY.md "Pod
    # visibility"): when the run spans >1 host (real or simulated via
    # HYDRAGNN_PODVIEW*), every host writes its own flight shard —
    # rank 0 keeps the canonical flight.jsonl, host k writes
    # flight.host<k>.jsonl — instead of non-zero ranks staying silent.
    from hydragnn_tpu.obs import podview as _podview

    pv_host, pv_hosts = _podview.host_identity()
    pv_on = telemetry_on and _podview.podview_enabled()
    pv_run_id = _podview.resolve_run_id(log_name)
    pv_monitor = None
    pv_overhead_s = 0.0
    pv_t_run0 = time.perf_counter()
    own_flight = flight is None
    if flight is None:
        if telemetry_on and (pv_host == 0 or pv_on):
            flight_path = _podview.host_flight_path(
                os.path.join(log_dir, log_name), pv_host
            )
        else:
            flight_path = None
        flight = FlightRecorder(
            flight_path,
            enabled=telemetry_on,
            host=pv_host if pv_on else None,
        )
    if pv_on and pv_host == 0:
        from hydragnn_tpu.obs import get_registry as _get_registry

        pv_monitor = _podview.SkewMonitor(
            os.path.join(log_dir, log_name),
            host=pv_host,
            hosts=pv_hosts,
            run_id=pv_run_id,
            registry=_get_registry(),
        )
    # Pod fault-tolerance plane (resilience/podckpt.py,
    # docs/RESILIENCE.md "Pod recovery"): multi-host runs cut sharded
    # generations with a rank-0 COMMIT marker, exchange heartbeats, and
    # coordinate preemption cuts so every host checkpoints the SAME
    # generation. Single-host runs keep the plain msgpack path only.
    pv_signaler = None
    pod_ckpt_on = False
    if pv_on and pv_hosts > 1:
        from hydragnn_tpu.resilience.podckpt import PodSignaler

        pv_signaler = PodSignaler(
            os.path.join(log_dir, log_name), host=pv_host, hosts=pv_hosts
        )
        pod_ckpt_on = knobs.get_bool("HYDRAGNN_POD_CKPT", True)
    spans = StepSpans() if telemetry_on else StepSpans.disabled()
    cmon = CompileMonitor().start() if telemetry_on else None
    if profiler is not None and getattr(profiler, "on_trace", None) is None:
        profiler.on_trace = lambda path, ep: flight.record(
            "profile_trace", path=path, epoch=ep
        )

    # Incident-grade tracing (obs/trace.py + obs/triggers.py,
    # docs/OBSERVABILITY.md "Tracing and incidents"): sampled sync
    # steps join the request-trace timeline keyed (epoch, step), and —
    # when Training.slo_triggers is on — an SLO trigger engine
    # evaluated at each epoch end (nonfinite burst, loss spike vs
    # rolling median, MFU drop) arms a bounded profiler capture whose
    # evidence lands in an incident bundle under
    # <log_dir>/<log_name>/incidents/<id>/.
    tracer = None
    trig_engine = None
    incidents = None
    if telemetry_on:
        from hydragnn_tpu.obs.trace import Tracer

        tracer = Tracer(flight=flight)
        spans.tracer = tracer
    if telemetry_on and bool(training.get("slo_triggers", False)):
        from hydragnn_tpu.obs import get_registry
        from hydragnn_tpu.obs.triggers import (
            IncidentRecorder,
            TriggerEngine,
            TriggerRule,
        )

        trig_rules = [
            TriggerRule(
                "train_nonfinite_burst",
                "nonfinite_burst",
                "train.nonfinite_skipped",
                float(training.get("slo_nonfinite_burst", 1)),
            ),
            TriggerRule(
                "train_loss_spike",
                "loss_spike",
                "train_loss",
                float(training.get("slo_loss_spike_factor", 3.0)),
            ),
            TriggerRule(
                "train_mfu_drop",
                "mfu_drop",
                "mfu",
                float(training.get("slo_mfu_drop_factor", 0.5)),
            ),
        ]
        if pv_monitor is not None:
            # cross-host skew rules over the gauges the SkewMonitor
            # publishes; the step_skew threshold defaults to the
            # scaling model's skew_tolerance derivation
            trig_rules.append(
                TriggerRule(
                    "podview_step_skew",
                    "step_skew",
                    "podview.skew_frac",
                    float(
                        training.get("podview_skew_threshold")
                        or pv_monitor.threshold
                    ),
                )
            )
            trig_rules.append(
                TriggerRule(
                    "podview_host_stall",
                    "host_stall",
                    "podview.stall_age_s",
                    knobs.get_float("HYDRAGNN_PODVIEW_STALL_S", 120.0),
                )
            )
        if pv_signaler is not None and pv_signaler.lost_after_s > 0:
            # a peer missing HYDRAGNN_POD_LOST_AFTER_S seconds of
            # heartbeats sets podview.lost_hosts > 0 at the epoch
            # boundary; the incident bundles the heartbeat view
            trig_rules.append(
                TriggerRule(
                    "podview_host_lost",
                    "host_lost",
                    "podview.lost_hosts",
                    0.5,
                )
            )
        trig_engine = TriggerEngine(trig_rules, registry=get_registry())
        if jax.process_index() == 0:
            incidents = IncidentRecorder(
                os.path.join(log_dir, log_name, "incidents"),
                registry=get_registry(),
                flight_path=flight.path,
                podview=pv_monitor,
            )

    # Model-level introspection (hydragnn_tpu/obs/introspect.py,
    # docs/OBSERVABILITY.md "Model-level diagnostics"): per-head
    # gradient diagnostics sampled every Training.diag_every steps
    # (default: once per epoch), per-head eval MAE/RMSE off the
    # test_epoch gather path, and the hardware-efficiency ledger
    # (compiled-step FLOPs from the LOWERED module — no second compile
    # — turned into per-epoch achieved TFLOP/s + MFU + memory
    # watermark). All inert when HYDRAGNN_TELEMETRY=0 or
    # Training.diagnostics=false; the gradient sampler additionally
    # requires the loop-owned per-step path (sharded callers and the
    # scan path degrade to heads.available=false, never fail).
    # HYDRAGNN_DIAGNOSTICS=0 force-disables introspection regardless of
    # config (the tier-1 suite sets it: dozens of tiny training tests
    # would each pay the diagnostics executable's compile + the ledger
    # lowering; the dedicated introspection tests and the ci.sh smoke
    # opt back in). Production default stays ON.
    introspect_on = (
        telemetry_on
        and bool(training.get("diagnostics", True))
        and knobs.get_bool("HYDRAGNN_DIAGNOSTICS", True)
    )
    head_names = list(cfg.output_names)
    diag = None
    ledger = None
    if introspect_on:
        from hydragnn_tpu.obs.introspect import (
            HardwareLedger,
            HeadDiagnostics,
            make_diagnostics_step,
        )

        if loop_owned:
            # per-step mode: sample every diag_every steps (default once
            # per epoch). Scan mode calls the sampler once per EPOCH
            # (train_epoch_scan), so diag_every converts to an epoch
            # stride there — the sampling floor one dispatch per epoch
            # allows.
            diag_every = int(training.get("diag_every", 0))
            if scan_fn is not None:
                every = max(1, diag_every // max(len(train_loader), 1))
            else:
                every = diag_every or max(len(train_loader), 1)
            diag = HeadDiagnostics(
                make_diagnostics_step(
                    model,
                    tx,
                    compute_dtype=compute_dtype,
                    remat=bool(training.get("remat", False)),
                ),
                head_names=head_names,
                every=every,
            )
        try:
            example = next(iter(train_loader))
            lower_args = (
                (state, example, jnp.zeros((), jnp.int32))
                if guard_nonfinite
                else (state, example)
            )
            # the scan path runs the SAME step body nb times per
            # dispatch, so the per-step lowered cost prices it too
            ledger = HardwareLedger.from_step(train_step, lower_args)
            # useful-vs-padded byte accounting: the XLA cost model above
            # prices padded shapes; the pad-waste fractions + analytic
            # conv-traffic model say how much of that a bucket-ladder
            # batch actually uses (its own guard: this is telemetry and
            # must never take the ledger down with it)
            try:
                from hydragnn_tpu.obs.introspect import (
                    conv_traffic_model,
                    pad_waste_from_batch,
                )

                waste = pad_waste_from_batch(example)
                ledger.set_conv_traffic(
                    waste,
                    conv_traffic_model(
                        waste["node_pad"],
                        waste["edge_pad"],
                        model.cfg.hidden_dim,
                        model.cfg.num_conv_layers,
                        real_edges=waste["real_edges_mean"],
                    ),
                )
            except Exception:
                pass
        except Exception:
            ledger = HardwareLedger.disabled(reason="example_batch_unavailable")

    # Fault tolerance (hydragnn_tpu/resilience, docs/RESILIENCE.md):
    # preemption handler (SIGTERM/SIGINT -> graceful stop + final
    # checkpoint within Training.preempt_grace_s), non-finite sentry
    # over the guarded loop-owned step (per-step OR the guarded scan
    # body — sharded callers pass their own step and keep their own
    # policy), and the opt-in hang watchdog (Training.watchdog_stall_s
    # or HYDRAGNN_WATCHDOG_S; off by default — it must be sized above
    # the worst expected compile time, and it forces per-step dispatch).
    from hydragnn_tpu.resilience import (
        HangWatchdog,
        NonFiniteSentry,
        PreemptionHandler,
        TrainHooks,
        TrainingPreempted,
    )

    sentry = (
        NonFiniteSentry(
            patience=int(training.get("nonfinite_patience", 16)),
            max_rollbacks=int(training.get("nonfinite_max_rollbacks", 2)),
            lr_factor=float(training.get("nonfinite_rollback_lr_factor", 0.5)),
        )
        if guard_nonfinite
        else None
    )
    preempt = (
        PreemptionHandler(
            grace_s=float(training.get("preempt_grace_s", 30.0))
        ).install()
        if training.get("preempt_handler", True)
        else None
    )
    stall_s = float(
        training.get("watchdog_stall_s", 0)
        or knobs.get_float("HYDRAGNN_WATCHDOG_S", 0.0)
        or 0
    )
    watchdog = HangWatchdog(stall_s, flight=flight).start() if stall_s > 0 else None
    hooks = TrainHooks(preempt=preempt, sentry=sentry, watchdog=watchdog)
    if preempt is not None and pv_signaler is not None:
        # SIGTERM on this host announces the cut generation to the pod
        # (preempt.proposed_gen is kept current at each epoch start)
        preempt.signaler = pv_signaler

    def _abort_telemetry(exc: BaseException, epochs: int) -> None:
        """Record the failure into the flight record before unwinding —
        a crashed run must still leave a parseable artifact (the r05
        'traceback was the only evidence' failure mode)."""
        hooks.teardown()
        if incidents is not None:
            incidents.finalize()
        flight.error(exc)
        flight.end_run(
            status="failed",
            epochs=epochs,
            triggers=(
                trig_engine.summary(incidents.capture_s if incidents else 0.0)
                if trig_engine is not None
                else None
            ),
        )
        if cmon is not None:
            cmon.stop()
        if own_flight:
            flight.close()

    metrics_path = None
    if jax.process_index() == 0:
        out_dir = os.path.join(log_dir, log_name)
        os.makedirs(out_dir, exist_ok=True)
        metrics_path = os.path.join(out_dir, "metrics.jsonl")
    # rank-0 tensorboard scalars (reference: train_validate_test.py:130-137)
    from hydragnn_tpu.utils.tensorboard import get_summary_writer

    writer = get_summary_writer(log_name, log_dir)

    # Flight-record manifest: everything needed to interpret (and rerun)
    # this run without the builder's shell history. Recorded AFTER resume
    # handling so start_epoch reflects what will actually execute.
    def _loader_plan(ld) -> Dict[str, Any]:
        return {
            "num_batches": len(ld),
            "num_samples": getattr(ld, "num_samples", None),
            "batch_size": getattr(ld, "batch_size", None),
            "pad_nodes": getattr(ld, "pad_nodes", None),
            "pad_edges": getattr(ld, "pad_edges", None),
            "pad_graphs": getattr(ld, "pad_graphs", None),
        }

    _dev0 = jax.devices()[0]
    # flight ``parallel`` block (docs/PARALLELISM.md): the partitioner's
    # mesh shape, axis names, fsdp factor, per-leaf param/optimizer
    # sharding summary, per-device bytes, and any replicated-leaf
    # fallbacks — computed from the PLACED state so it reports what is
    # actually committed, not what was intended
    if partitioner is not None:
        parallel_block = partitioner.manifest(state=state)
    else:
        parallel_block = {
            "available": False,
            "reason": "caller passed no partitioner",
        }
    if pv_monitor is not None:
        # the committed layout feeds the SkewMonitor's collective-aware
        # cost attribution (compute vs wire split in podview_report.json)
        pv_monitor.set_parallel(parallel_block)
    # graftcheck contract block (lint/ir.py, docs/LINT.md CC rules): the
    # run's OWN train step, lowered and audited for the static contracts
    # the full checker (tools/graftcheck.py) gates in CI — so every
    # recorded run says which contracts its executable passed. Costs one
    # trace, no compile; HYDRAGNN_GRAFTCHECK=0 skips the lowering, and
    # any failure degrades to an all-not_checked block (stamping is
    # telemetry and must never take the run down).
    from hydragnn_tpu.lint.ir import contract_block

    graftcheck_block = contract_block(None)
    # drift reference window (obs/drift.py): per-channel feature stats +
    # per-head target stats over a bounded subsample of the training
    # set, stamped into the manifest so a later serving run can load
    # this flight record as its HYDRAGNN_DRIFT_REF and compare live
    # traffic against what this model actually trained on. Telemetry:
    # a failure degrades to an absent block, never a dead run.
    stats_block = None
    if telemetry_on:
        try:
            from hydragnn_tpu.obs.drift import build_reference

            stats_block = build_reference(
                list(train_loader.all_samples), head_names=head_names
            )
        except Exception:
            stats_block = None
    if telemetry_on and knobs.get_bool("HYDRAGNN_GRAFTCHECK", True):
        try:
            # peek_batch builds the first batch without counting as an
            # __iter__ draw, so loader wrappers that count epochs
            # (schedulers, fault harnesses) are unperturbed
            _gc_example = (
                train_loader.peek_batch()
                if hasattr(train_loader, "peek_batch")
                else next(iter(train_loader))
            )
            _gc_args = (
                (state, _gc_example, jnp.zeros((), jnp.int32))
                if guard_nonfinite
                else (state, _gc_example)
            )
            _pcfg = partitioner.config if partitioner is not None else None
            graftcheck_block = contract_block(
                train_step.lower(*_gc_args).as_text(),
                donated=True,
                conv_bf16=bool(getattr(cfg, "conv_bf16", False)),
                edge_pad=int(_gc_example.senders.shape[-1]),
                data=int(getattr(_pcfg, "data", 1) or 1),
                fsdp=int(getattr(_pcfg, "fsdp", 1) or 1),
                zero1=bool(getattr(_pcfg, "zero1", False)),
                residency_shapes=(
                    [(int(_gc_example.nodes.shape[-2]), int(cfg.hidden_dim))]
                    if getattr(cfg, "conv_residency", False)
                    else None
                ),
            )
        except Exception:
            pass
    # lineage left behind by a pod-checkpoint restore earlier in this
    # process (utils/checkpoint.load_existing_model → podckpt); consumed
    # once so only the run that actually restored stamps it
    from hydragnn_tpu.resilience import podckpt as _podckpt

    pod_lineage = _podckpt.consume_last_restore_info()
    flight.start_run(
        {
            "run": log_name,
            "log_dir": log_dir,
            "config": run_config if run_config is not None else {"NeuralNetwork": config},
            "device_kind": getattr(_dev0, "device_kind", str(_dev0)),
            "local_device_count": jax.local_device_count(),
            "mesh": {
                "device_stack": getattr(train_loader, "device_stack", 1),
                "process_count": jax.process_count(),
            },
            # pod-visibility identity (obs/podview.py): which host shard
            # this is and the shared run id the merge reader joins on
            "podview": {
                "enabled": pv_on,
                "host": pv_host,
                "hosts": pv_hosts,
                "run_id": pv_run_id,
            },
            "parallel": parallel_block,
            "pad_plans": {
                "train": _loader_plan(train_loader),
                "val": _loader_plan(val_loader),
                "test": _loader_plan(test_loader),
            },
            "num_epoch": num_epoch,
            "start_epoch": start_epoch,
            "mixed_precision": compute_dtype is not None,
            "scan_epoch": scan_fn is not None,
            # which dispatch mode actually ran, whether it was the
            # automatic default, and why — the satellite contract: a
            # flight record always says which mode executed the epochs
            "dispatch_mode": {
                "mode": "scan_epoch" if scan_fn is not None else "per_step",
                "auto": scan_auto,
                "reason": dispatch_reason,
            },
            "compile_monitor_available": bool(cmon and cmon.available),
            "nonfinite_guard": sentry is not None,
            "preempt_handler": bool(preempt and preempt.available),
            "watchdog_stall_s": stall_s or None,
            "head_names": head_names,
            "diagnostics": {
                "enabled": diag is not None,
                "diag_every": diag.every if diag is not None else None,
            },
            # the hardware-efficiency ledger's run-constant half: what
            # one compiled train step costs and what the chip could do
            "hw_cost": ledger.manifest() if ledger is not None else {"available": False},
            # which compiled-IR contracts (docs/LINT.md CC rules) this
            # run's own lowered step passed — the in-run face of
            # tools/graftcheck.py
            "graftcheck": graftcheck_block,
            # the drift reference window serving runs compare live
            # traffic against (obs/drift.py load_reference reads it
            # straight out of this flight record)
            "stats": stats_block,
            # pod-restore lineage (resilience/podckpt.py): set when this
            # process's state came out of a sharded pod checkpoint —
            # which committed generation, the prior pod layout it was
            # cut under, and any generations skipped as torn
            **(
                {
                    "pod_resume": {
                        "resumed_from_gen": pod_lineage.get("gen"),
                        "step": pod_lineage.get("step"),
                        "prior_hosts": pod_lineage.get("hosts"),
                        "prior_layout": pod_lineage.get("layout"),
                        "fallbacks": pod_lineage.get("fallbacks") or [],
                    }
                }
                if pod_lineage is not None
                else {}
            ),
            # caller-stamped provenance (e.g. the retrain pilot's
            # fine-tune child marks which serving run + spool window it
            # trained from — pilot/tune.py)
            **(manifest_extra or {}),
        }
    )
    if resumed_from is not None:
        # a restarted run announces where it picked up — the supervisor
        # story ("one preempted + one resumed") is then readable from
        # the merged flight record alone
        flight.record("resumed", epoch=resumed_from)
    if pod_lineage is not None:
        flight.record(
            "pod_resume",
            gen=int(pod_lineage.get("gen", -1)),
            prior_hosts=pod_lineage.get("hosts"),
            prior_layout=pod_lineage.get("layout"),
            fallbacks=pod_lineage.get("fallbacks") or [],
        )

    # Persistent AOT executable cache (utils/exec_cache.py): with
    # HYDRAGNN_EXEC_CACHE set — an env var strip_injection_env
    # deliberately preserves, so supervisor auto-resume restarts keep it
    # — the loop-owned train executable (per-step OR scan-epoch) is
    # deserialized from disk instead of recompiled. The loop caches a
    # DONATION-FREE twin of the step (a plain jit of the same body): a
    # deserialized donated executable is unsound inside a full training
    # process on this jax/jaxlib (scrambled output pytrees, runtime
    # aborts — utils/exec_cache.py module docstring), and the failure
    # escapes any same-process probe. Warm loads additionally ride a
    # first-execution landing check: the cached step's output
    # ``state.step`` must equal input ``step + delta`` (1 per-step,
    # num_batches for scan), else the entry is evicted with a
    # ``donation_check_failed`` miss and the fresh jitted step takes
    # over on a saved copy of the inputs.
    # Placed AFTER start_run (the --require-complete validator demands
    # run_start first) and after the ledger lowered the RAW jitted step.
    if loop_owned and start_epoch < num_epoch:
        try:
            from hydragnn_tpu.utils.exec_cache import (
                ExecCache,
                abstract_fingerprint,
                compat_manifest,
                fingerprint,
            )

            _ecache = ExecCache.from_env(flight=flight, consumer="train")
        except Exception:
            _ecache = None
        if _ecache is not None and _ecache.enabled:
            try:
                _pc = partitioner.config if partitioner is not None else None
                _compat = compat_manifest(
                    layout=(_pc.data, _pc.fsdp, _pc.edge) if _pc is not None else (1, 1, 1),
                    compute_dtype=compute_dtype,
                )
                # resume bookkeeping (auto_resume_config flips
                # Training.continue/startfrom on a supervisor restart)
                # selects WHICH checkpoint restores, not what compiles —
                # it must not change the key or no resume ever hits
                _cfg_key = dict(config)
                _tr_parent = _cfg_key
                if "Training" not in _tr_parent and isinstance(
                    _cfg_key.get("NeuralNetwork"), dict
                ):
                    _nn_key = dict(_cfg_key["NeuralNetwork"])
                    _cfg_key["NeuralNetwork"] = _nn_key
                    _tr_parent = _nn_key
                if isinstance(_tr_parent.get("Training"), dict):
                    _tr_key = dict(_tr_parent["Training"])
                    for _vol in ("continue", "startfrom"):
                        _tr_key.pop(_vol, None)
                    _tr_parent["Training"] = _tr_key
                _arch = fingerprint(_cfg_key, abstract_fingerprint(state))
                _is_scan = scan_fn is not None
                if _is_scan:
                    _stacked0 = train_loader.stacked_device_batches(0)
                    _order0 = jnp.arange(len(train_loader), dtype=jnp.int32)
                    _cargs = (
                        (state, _stacked0, _order0, jnp.zeros((), jnp.int32))
                        if guard_nonfinite
                        else (state, _stacked0, _order0)
                    )
                    _label, _delta, _raw = (
                        "scan_epoch", int(_order0.shape[0]), scan_fn,
                    )
                else:
                    _example0 = next(iter(train_loader))
                    _cargs = (
                        (state, _example0, jnp.zeros((), jnp.int32))
                        if guard_nonfinite
                        else (state, _example0)
                    )
                    _label, _delta, _raw = "train_step", 1, train_step
                # the donation-free twin: jit of the same body without
                # donate_argnums. Costs one extra state-sized buffer
                # while the cache is enabled; buys executables that
                # survive the serialize round trip. Donation-ness is
                # part of the key — the two programs are not the same
                # executable.
                _body = getattr(_raw, "__wrapped__", None)
                _cache_fn = jax.jit(_body) if _body is not None else _raw
                _donated = _body is None
                _ckey = fingerprint(
                    _label, _arch, abstract_fingerprint(_cargs), _donated
                )
                # marked AFTER arg construction: the eager jnp.arange
                # / jnp.zeros scalars above cost one tiny compile each
                # per process and would pollute the zero-compile number
                if cmon is not None:
                    cmon.mark("exec_cache_build")
                _exe, _hit, _build_s = _ecache.get_or_compile(
                    _ckey, _cache_fn, _cargs, _compat,
                    donated=_donated, label=_label,
                )
                if _hit:
                    _exe = _landing_checked(
                        _exe, _cache_fn, _ecache, _ckey,
                        expected_delta=_delta, label=_label,
                    )
                if _is_scan:
                    scan_fn = _exe
                else:
                    train_step = _exe
                # the scoped zero-compile evidence the fault-injection
                # smoke pins: how many XLA compiles the build took (0 on
                # a warm hit) and how long restart-to-ready cost
                flight.record(
                    "exec_cache",
                    event="train_ready",
                    hit=_hit,
                    compiles=(
                        cmon.count_since("exec_cache_build")
                        if cmon is not None
                        else None
                    ),
                    build_s=round(_build_s, 3),
                    mode="scan_epoch" if scan_fn is not None else "per_step",
                )
            except Exception as exc:
                # cache wiring must never take training down: fall back
                # to the live jitted path and say so in the record
                flight.record(
                    "exec_cache", event="wiring_failed",
                    error=str(exc)[-200:],
                )

    # Visualization (reference: Visualizer wiring, train_validate_test.py:
    # 71-97,90-96: initial-solution scatter, per-epoch histograms, final
    # plots). Plots are rank-0 only.
    visualizer = None
    if create_plots and jax.process_index() == 0:
        from hydragnn_tpu.postprocess.visualizer import Visualizer

        visualizer = Visualizer(
            log_name,
            num_heads=cfg.num_heads,
            head_names=cfg.output_names,
            log_dir=log_dir,
        )
    # all_samples = the full split, not this process's shard; also reused
    # by the final per-node plot dispatch
    viz_nodes_per_graph = (
        [s.num_nodes for s in test_loader.all_samples]
        if visualizer is not None and hasattr(test_loader, "all_samples")
        else None
    )
    if viz_nodes_per_graph is not None:
        # test-set node-count histogram at setup (reference: Visualizer
        # num_nodes_plot wiring, train_validate_test.py:71-97)
        visualizer.num_nodes_plot(viz_nodes_per_graph)
    if visualizer is not None and plot_init_solution:
        try:
            _, _, tv, pv = test_epoch(
                test_loader, state, eval_step_out, cfg, verbosity, return_samples=True
            )
            visualizer.create_scatter_plots(tv, pv, iepoch=-1)
        except BaseException as exc:
            _abort_telemetry(exc, 0)
            raise

    def _declare_lost(lost, epoch_now: int) -> None:
        """Record each newly-lost peer exactly once: one ``host_lost``
        flight event per host plus the ``podview.lost_host(s)`` gauges
        the podview_host_lost trigger rule reads."""
        fresh = pv_signaler.mark_declared(lost)
        if not fresh:
            return
        from hydragnn_tpu.obs import get_registry

        reg = get_registry()
        reg.gauge("podview.lost_hosts").set(
            float(len(set(pv_signaler.lost_hosts()) | set(lost)))
        )
        for h in fresh:
            reg.gauge("podview.lost_host").set(float(h))
            flight.record(
                "host_lost",
                host=int(h),
                epoch=int(epoch_now),
                lost_after_s=pv_signaler.lost_after_s,
            )

    def _pod_checkpoint(ckpt_state, gen: int) -> None:
        """One sharded generation cut (resilience/podckpt.py): every
        host writes its shard + sha sidecar + manifest; rank 0
        bounded-waits for the peers' manifests, validates them, and
        writes ``gen<N>.COMMIT`` LAST. Runs BEFORE save_train_meta so a
        commit that dies on a lost peer leaves the meta sidecar
        describing the last COMMITTED generation, not this torn one."""
        from hydragnn_tpu.resilience import podckpt
        from hydragnn_tpu.resilience.preempt import PodHostLost

        run_dir = os.path.join(log_dir, log_name)
        pv_signaler.heartbeat(epoch=gen, force=True)
        podckpt.save_pod_shard(
            ckpt_state,
            run_dir,
            gen=gen,
            host=pv_host,
            hosts=pv_hosts,
            step=int(jax.device_get(ckpt_state.step)),
            layout=(
                parallel_block.get("layout")
                if isinstance(parallel_block, dict)
                else None
            ),
        )
        if pv_host != 0:
            # only rank 0 waits at the commit point: the simulated-host
            # CI mode runs hosts sequentially, and a non-zero host
            # blocking here would deadlock it
            return
        commit = podckpt.commit_generation(
            run_dir, gen, pv_hosts, signaler=pv_signaler
        )
        if commit.get("committed"):
            podckpt.prune_generations(run_dir)
            return
        # proceed-and-record: the failed commit is itself flight
        # evidence; a LOST peer additionally raises the typed exit so
        # the supervisor restarts from the last committed generation
        flight.record(
            "error",
            error=(
                f"pod generation {gen} failed to commit: "
                f"lost={commit.get('lost')} bad={commit.get('bad')} "
                f"timeout={commit.get('timeout')}"
            ),
            error_type="PodCommitFailed",
        )
        lost = commit.get("lost") or []
        if lost:
            _declare_lost(lost, gen)
            raise PodHostLost(lost, gen)

    def _write_checkpoint(ckpt_state, epoch_next: int, early_stopped: bool) -> None:
        from hydragnn_tpu.utils.checkpoint import save_model, save_train_meta

        save_model(ckpt_state, log_name, log_dir, verbosity, keep_last=ckpt_keep_last)
        if pod_ckpt_on:
            _pod_checkpoint(ckpt_state, epoch_next)
        save_train_meta(
            {
                "epoch": epoch_next,
                # the optimizer step ties this sidecar to the weight file
                # it was written with (resume verifies the pair matches)
                "step": int(jax.device_get(ckpt_state.step)),
                "early_stopped": early_stopped,
                "scheduler": {
                    "best": scheduler.best,
                    "num_bad_epochs": scheduler.num_bad_epochs,
                },
                "stopper": {
                    "count": stopper.count if stopper else 0,
                    "min_loss": stopper.min_loss if stopper else float("inf"),
                },
                "history": history,
            },
            log_name,
            log_dir,
        )

    def _preempt_exit(ckpt_state, epoch: int, coordinated_from=None):
        """Graceful preemption: checkpoint + meta pair for this epoch,
        ``preempt`` + ``run_end{status:"preempted"}`` flight events,
        telemetry closed — all inside the grace window the handler's
        hard-exit timer enforces — then the typed exception the driver's
        run_guard maps to EXIT_PREEMPTED. ``coordinated_from`` marks a
        cut taken on a PEER's announcement rather than our own signal."""
        signum = preempt.signum if preempt is not None else 0
        if signum is None:
            signum = 0
        _write_checkpoint(ckpt_state, epoch, early_stopped=False)
        flight.record(
            "preempt",
            signal=signum,
            epoch=epoch,
            step=int(jax.device_get(ckpt_state.step)),
            **(
                {"coordinated_from": int(coordinated_from)}
                if coordinated_from is not None
                else {}
            ),
        )
        if incidents is not None:
            incidents.finalize()
        flight.end_run(status="preempted", epochs=epoch - start_epoch)
        if cmon is not None:
            cmon.stop()
        if own_flight:
            flight.close()
        try:
            writer.flush()
            writer.close()
        except Exception:
            pass
        hooks.teardown()
        raise TrainingPreempted(signum, epoch)

    def _sentry_rollback(cur_state, epoch: int, consec_end: int):
        """K consecutive non-finite steps at the epoch's tail: restore
        the last good checkpoint with a reduced LR instead of
        continuing; give up (typed, fail-fast exit) when the rollback
        budget is spent or there is nothing to roll back to."""
        from hydragnn_tpu.resilience import NonFiniteRollbackExhausted
        from hydragnn_tpu.utils.checkpoint import (
            checkpoint_exists,
            load_existing_model,
        )

        if sentry.exhausted or not checkpoint_exists(log_name, log_dir):
            raise NonFiniteRollbackExhausted(
                f"epoch {epoch} ended with {consec_end} consecutive "
                f"non-finite steps; rollbacks used {sentry.rollbacks}/"
                f"{sentry.max_rollbacks}"
                + (
                    ""
                    if checkpoint_exists(log_name, log_dir)
                    else " and no checkpoint exists to roll back to"
                )
            )
        restored = load_existing_model(cur_state, log_name, log_dir)
        lr = max(
            current_learning_rate(restored.opt_state) * sentry.lr_factor, 1e-8
        )
        restored = restored.replace(
            opt_state=set_learning_rate(restored.opt_state, lr)
        )
        sentry.on_rollback()
        flight.record(
            "rollback",
            epoch=epoch,
            consec=consec_end,
            rollbacks=sentry.rollbacks,
            lr=lr,
        )
        print_distributed(
            verbosity,
            f"non-finite sentry: epoch {epoch} ended with {consec_end} "
            f"consecutive bad steps — rolled back to the last good "
            f"checkpoint (lr -> {lr:g})",
        )
        return restored

    timer = Timer("train_validate_test")
    timer.start()
    epochs_done = start_epoch
    try:
      for epoch in range(start_epoch, num_epoch):
        hooks.epoch_start(epoch)
        if hooks.preempted:
            _preempt_exit(state, epoch)
        if pv_signaler is not None:
            # a SIGTERM landing anywhere in this epoch announces the
            # cut at its END boundary, so every host checkpoints the
            # same generation (epoch + 1)
            if preempt is not None:
                preempt.proposed_gen = epoch + 1
            pv_signaler.heartbeat(epoch=epoch, force=True)
        for loader in (train_loader, val_loader, test_loader):
            if hasattr(loader, "set_epoch"):
                loader.set_epoch(epoch)
        if profiler is not None:
            profiler.set_current_epoch(epoch)
        if cmon is not None:
            cmon.mark("epoch_start")
        spans.epoch_start(epoch)

        # the profiler context closes an in-flight trace at epoch end even
        # when the epoch has fewer steps than its schedule expects
        t_train0 = time.perf_counter()
        with (profiler if profiler is not None else contextlib.nullcontext()):
            if scan_fn is not None:
                if incidents is not None:
                    # scan mode is one dispatch per epoch: a single tick
                    # here spans the whole epoch's capture window
                    incidents.tick()
                state, train_loss, train_tasks = train_epoch_scan(
                    train_loader, state, scan_fn, epoch, diag=diag,
                    sentry=sentry,
                )
            else:
                state, train_loss, train_tasks = train_epoch(
                    train_loader,
                    state,
                    train_step,
                    verbosity,
                    profiler=profiler,
                    spans=spans,
                    hooks=hooks,
                    diag=diag,
                    incidents=incidents,
                )
        # the epoch metrics above already synced at finalize, so this
        # wall time covers every dispatched train step's execution —
        # the denominator of the epoch's achieved-TFLOP/s and MFU
        train_wall_s = time.perf_counter() - t_train0
        if hooks.preempted and pv_signaler is None:
            # mid-epoch graceful stop: this epoch is incomplete, resume
            # re-runs it (the meta pair written here says so). Pod mode
            # instead defers to the epoch's END boundary — the
            # generation the SIGTERM handler announced to the peers —
            # racing the handler's hard-exit grace timer
            _preempt_exit(state, epoch)
        nonfinite = None
        if sentry is not None:
            skipped, consec_end = sentry.epoch_finalize()
            if skipped:
                from hydragnn_tpu.obs import get_registry

                get_registry().counter("train.nonfinite_skipped").inc(skipped)
                nonfinite = {"skipped": skipped, "consec_end": consec_end}
            if sentry.needs_rollback(consec_end):
                state = _sentry_rollback(state, epoch, consec_end)
                epochs_done = epoch + 1
                continue  # the rolled-back epoch consumed its slot
        if scan_eval_fn is not None:
            val_loss, val_tasks = evaluate_epoch_scan(val_loader, state, scan_eval_fn)
        else:
            val_loss, val_tasks = evaluate_epoch(val_loader, state, eval_step, verbosity)
        collect = plot_hist_solution and visualizer is not None
        # introspection reuses the test() gather path for per-head
        # MAE/RMSE — same eval executable, extra host-side gathering
        test_loss, test_tasks, true_values, predicted_values = test_epoch(
            test_loader,
            state,
            eval_step_out,
            cfg,
            verbosity,
            return_samples=collect or introspect_on,
        )
        head_quality = None
        if introspect_on and true_values:
            from hydragnn_tpu.obs.introspect import per_head_error_metrics

            head_quality = per_head_error_metrics(
                true_values, predicted_values, head_names
            )
        if collect:
            visualizer.create_error_histograms(
                true_values, predicted_values, iepoch=epoch
            )
        state = scheduler.step(state, val_loss)

        lr = current_learning_rate(state.opt_state)
        history["train_loss"].append(train_loss)
        history["val_loss"].append(val_loss)
        history["test_loss"].append(test_loss)
        history["train_tasks"].append(train_tasks.tolist())
        history["val_tasks"].append(val_tasks.tolist())
        history["test_tasks"].append(test_tasks.tolist())
        history["lr"].append(lr)

        print_distributed(
            verbosity,
            f"Epoch: {epoch:02d}, Train Loss: {train_loss:.8f}, "
            f"Val Loss: {val_loss:.8f}, Test Loss: {test_loss:.8f}",
        )
        if epoch == 0:
            # post-first-epoch peak = steady-state footprint (weights +
            # activations + optimizer state); the reference prints peak
            # GPU memory around the train step (distributed.py:236-243)
            from hydragnn_tpu.utils.print_utils import print_peak_memory

            print_peak_memory(verbosity, prefix=f"epoch {epoch}")
        # per-task metrics are keyed by head name everywhere (flight,
        # tensorboard, metrics.jsonl) — a multi-head record is readable
        # without cross-referencing the config's output order
        train_tasks_named = _named_tasks(head_names, train_tasks)
        val_tasks_named = _named_tasks(head_names, val_tasks)
        test_tasks_named = _named_tasks(head_names, test_tasks)
        diag_snap = diag.epoch_snapshot() if diag is not None else None
        hw = (
            ledger.epoch_record(steps=len(train_loader), wall_s=train_wall_s)
            if ledger is not None
            else None
        )

        writer.add_scalar("train error", train_loss, epoch)
        writer.add_scalar("validate error", val_loss, epoch)
        writer.add_scalar("test error", test_loss, epoch)
        for name in head_names:
            if name in train_tasks_named:
                writer.add_scalar(
                    f"heads/{name}/train_loss", train_tasks_named[name], epoch
                )
            if name in val_tasks_named:
                writer.add_scalar(
                    f"heads/{name}/val_loss", val_tasks_named[name], epoch
                )
        if metrics_path is not None:
            with open(metrics_path, "a") as f:
                f.write(
                    json.dumps(
                        {
                            "epoch": epoch,
                            "train_loss": train_loss,
                            "val_loss": val_loss,
                            "test_loss": test_loss,
                            "lr": lr,
                            "train_tasks": train_tasks_named,
                            "val_tasks": val_tasks_named,
                        }
                    )
                    + "\n"
                )

        # per-epoch flight record: losses + the step-time decomposition
        # + compile counts. After the first executed epoch every train
        # step function is compiled; further compiles are the silent
        # recompile class this exists to surface.
        span_snap = None if scan_fn is not None else spans.epoch_snapshot()
        step_time = (
            dict(span_snap, mode="per_step")
            if span_snap is not None
            # scan mode is ONE device dispatch per epoch — there are no
            # host-side per-step spans to decompose
            else {"mode": "scan_epoch" if scan_fn is not None else "disabled"}
        )
        compiles: Dict[str, Any] = {"available": bool(cmon and cmon.available)}
        if cmon is not None:
            n_compiles = cmon.count_since("epoch_start")
            compiles["count"] = n_compiles
            compiles["unexpected"] = bool(
                cmon.available and epoch > start_epoch and n_compiles > 0
            )
        # heads: the model-level half of the epoch record — per-head
        # losses always; sampled gradient diagnostics and eval MAE/RMSE
        # when introspection produced them this epoch
        heads: Dict[str, Any] = {"names": head_names, "available": False}
        if diag_snap is not None:
            heads.update(diag_snap)
        if head_quality is not None:
            heads["available"] = True
            heads["mae"] = {n: m["mae"] for n, m in head_quality.items()}
            heads["rmse"] = {n: m["rmse"] for n, m in head_quality.items()}
        extra: Dict[str, Any] = {}
        if nonfinite:
            extra["nonfinite"] = nonfinite
        if introspect_on:
            extra["heads"] = heads
            extra["hw"] = hw if hw is not None else {"available": False}
        flight.epoch(
            epoch,
            train_loss=train_loss,
            val_loss=val_loss,
            test_loss=test_loss,
            lr=lr,
            train_tasks=train_tasks_named,
            val_tasks=val_tasks_named,
            test_tasks=test_tasks_named,
            step_time=step_time,
            compiles=compiles,
            **extra,
        )

        # pod-visibility (obs/podview.py): append this host's epoch
        # summary to its shard — the lightweight cross-host exchange
        # unit — and, on rank 0, fold every host's summaries into the
        # podview.* skew gauges. Runs BEFORE trigger evaluation so the
        # step_skew / host_stall rules see THIS epoch's skew.
        if pv_on:
            _t_pv0 = time.perf_counter()
            pv_summary = {
                "hosts": pv_hosts,
                "epoch_s": round(train_wall_s, 6),
                "data_wait_s": (span_snap or {}).get("data_wait_s"),
                "dispatch_s": (span_snap or {}).get("dispatch_s"),
                "steps": (span_snap or {}).get("steps", len(train_loader)),
                "nonfinite_skipped": (nonfinite or {}).get("skipped", 0),
                "mfu": hw.get("mfu") if hw is not None else None,
            }
            flight.record(
                "host_epoch",
                epoch=epoch,
                host=pv_host,
                run_id=pv_run_id,
                **pv_summary,
            )
            if pv_monitor is not None:
                pv_skew = pv_monitor.observe_epoch(
                    epoch, dict(pv_summary, epoch=epoch)
                )
                if pv_skew is not None:
                    flight.record("podview", **pv_skew)
            pv_overhead_s += time.perf_counter() - _t_pv0

        # pod liveness at the epoch boundary (resilience/podckpt.py):
        # refresh this host's beat, then declare any peer whose beats
        # lapsed past HYDRAGNN_POD_LOST_AFTER_S — one host_lost flight
        # event per host, plus the podview.lost_hosts gauge the
        # podview_host_lost trigger rule (evaluated just below) reads
        if pv_signaler is not None:
            pv_signaler.heartbeat(epoch=epoch + 1, force=True)
            lost_now = pv_signaler.lost_hosts()
            if lost_now:
                # _declare_lost dedupes, so polling every epoch still
                # yields exactly one event per lost host
                _declare_lost(lost_now, epoch + 1)

        # SLO trigger evaluation at the epoch boundary: feed the rolling
        # series the rules watch, then let at most one verdict open an
        # incident whose profiler capture runs during the NEXT epoch's
        # ticks (docs/OBSERVABILITY.md "SLO triggers and incidents").
        if trig_engine is not None:
            trig_engine.observe("train_loss", train_loss)
            trig_engine.observe("val_loss", val_loss)
            if hw is not None and hw.get("mfu") is not None:
                trig_engine.observe("mfu", hw["mfu"])
            for verdict in trig_engine.evaluate():
                # the bundle's trigger.json carries the full verdict;
                # open_incident records the flight "incident" pointer
                if incidents is not None:
                    incidents.open_incident(verdict, flight=flight)
        from hydragnn_tpu.utils.tensorboard import write_scalar_dict

        if span_snap is not None:
            write_scalar_dict(writer, span_snap, epoch, prefix="obs/step_time")
            if compiles.get("count") is not None:
                writer.add_scalar("obs/compiles", compiles["count"], epoch)
        if diag_snap is not None:
            for name in head_names:
                if name in diag_snap.get("grad_norm", {}):
                    writer.add_scalar(
                        f"heads/{name}/grad_norm",
                        diag_snap["grad_norm"][name],
                        epoch,
                    )
            writer.add_scalar("obs/update_ratio", diag_snap["update_ratio"], epoch)
        if head_quality is not None:
            for name, m in head_quality.items():
                if m["mae"] is not None:
                    writer.add_scalar(f"heads/{name}/mae", m["mae"], epoch)
                    writer.add_scalar(f"heads/{name}/rmse", m["rmse"], epoch)
        if hw is not None and hw.get("mfu") is not None:
            writer.add_scalar("obs/hw/mfu", hw["mfu"], epoch)
        if hw is not None and hw.get("achieved_tflops") is not None:
            writer.add_scalar(
                "obs/hw/achieved_tflops", hw["achieved_tflops"], epoch
            )

        # Prometheus textfile export for training (serve already has
        # one): one atomic train.prom snapshot per epoch, gated by
        # Training.prometheus_dir (docs/OBSERVABILITY.md)
        # rank 0 keeps the legacy train.prom name; any other host (real
        # process or simulated podview host) writes train.host<k>.prom
        # so a second host never clobbers the first
        prom_dir = training.get("prometheus_dir")
        if prom_dir and telemetry_on and (jax.process_index() == 0 or pv_on):
            from hydragnn_tpu.obs import get_registry
            from hydragnn_tpu.obs.export import registry_to_prometheus

            reg = get_registry()
            reg.gauge("train.epoch").set(epoch)
            reg.gauge("train.loss").set(train_loss)
            reg.gauge("train.val_loss").set(val_loss)
            reg.gauge("train.lr").set(lr)
            for name, v in train_tasks_named.items():
                reg.gauge(f"train.head.{name}.loss").set(v)
            if diag_snap is not None:
                for name, v in diag_snap.get("grad_norm", {}).items():
                    reg.gauge(f"train.head.{name}.grad_norm").set(v)
            if hw is not None and hw.get("mfu") is not None:
                reg.gauge("train.mfu").set(hw["mfu"])
            registry_to_prometheus(
                reg,
                _podview.host_artifact_path(
                    os.path.join(prom_dir, "train.prom"), pv_host
                ),
            )

        stop = stopper is not None and stopper(val_loss)
        epochs_done = epoch + 1

        if ckpt_every and (epoch + 1) % ckpt_every == 0:
            _write_checkpoint(state, epoch + 1, early_stopped=False)

        if hooks.preempted:
            # SIGTERM landed during val/test/plots (or, pod mode,
            # anywhere in the epoch): this epoch is complete and
            # recorded, resume continues from the next
            _preempt_exit(state, epoch + 1)

        if pv_signaler is not None:
            req = pv_signaler.preempt_request()
            if (
                req is not None
                and int(req.get("host", -1)) != pv_host
                and epoch + 1 >= int(req.get("gen", 0))
            ):
                # a PEER announced preemption: cut the same generation
                # at this boundary so the pod's shards agree and the
                # supervisor restarts everyone from one COMMIT
                _preempt_exit(
                    state,
                    epoch + 1,
                    coordinated_from=int(req.get("host", -1)),
                )

        if stop:
            print_distributed(verbosity, f"Early stopping at epoch {epoch}")
            break
    except TrainingPreempted:
        # _preempt_exit already wrote the checkpoint, the flight
        # events, and tore telemetry down — only the process-global
        # timer still needs closing before the exception unwinds
        timer.stop_if_running()
        raise
    except BaseException as exc:
        # the registry timer is process-global: close its interval or
        # every later train_validate_test in this process raises
        # "Timer already running" (same discipline as run_training's
        # try/finally around its total_training timer)
        timer.stop_if_running()
        _abort_telemetry(exc, epochs_done - start_epoch)
        raise
    timer.stop()

    # A resume that trained zero epochs (e.g. continuing an early-stopped
    # or completed run) must be a pure no-op: re-running BN recalibration
    # would mutate batch_stats and rewriting the checkpoint would change
    # the saved model file without any training having happened.
    ran_epochs = epochs_done > start_epoch
    resumed_noop = training.get("continue") == 1 and not ran_epochs

    try:
        # BatchNorm recalibration: the in-training running-stat EMA trails
        # the last few (noisy, small) batches; with frozen final parameters,
        # two passes over the train set re-estimate faithful eval statistics.
        if (
            stats_step is not None
            and training.get("bn_recalibration", True)
            and not resumed_noop
        ):
            for _ in range(2):
                for b in train_loader:
                    hooks.beat()  # recalibration batches count as liveness
                    state = stats_step(state, b)

        # Final checkpoint+meta pair AFTER BN recalibration: the model file
        # and the loop-state sidecar must describe the same state (a mid-run
        # meta against the final recalibrated weights would make a later
        # continue run replay epochs on the wrong state); an early-stopped
        # run is marked so resume honors the stop instead of training on.
        if ckpt_every and not resumed_noop:
            _write_checkpoint(
                state, epochs_done, early_stopped=bool(stopper and stopper.count >= stopper.patience)
            )

        writer.flush()
        writer.close()

        # Final plots (reference: train_validate_test.py:173-215 rank-0 plots).
        if visualizer is not None:
            _, _, tv, pv = test_epoch(
                test_loader, state, eval_step_out, cfg, verbosity, return_samples=True
            )
            visualizer.create_scatter_plots(tv, pv)
            visualizer.create_plot_global(tv, pv)
            # vector parity grids, per-node diagnostics (fixed-size graphs),
            # and the scalar/vector global-analysis figures (reference:
            # visualizer.py:134-280, 387-613)
            visualizer.create_reference_plot_suite(
                tv, pv, cfg.output_type, viz_nodes_per_graph
            )
            visualizer.plot_history(history)
    except BaseException as exc:
        _abort_telemetry(exc, epochs_done - start_epoch)
        raise

    # run_end summary: the flight record's terminal event — per-process
    # timers, whatever landed in the global metrics registry (loader
    # prefetch accounting, ...), and the whole-run compile count.
    if cmon is not None:
        cmon.stop()
    if incidents is not None:
        # an incident still capturing at run end closes as "truncated"
        incidents.finalize()
    from hydragnn_tpu.obs import get_registry
    from hydragnn_tpu.utils.time_utils import timers_snapshot

    flight.end_run(
        status="completed",
        epochs=epochs_done - start_epoch,
        epochs_total=epochs_done,
        early_stopped=bool(stopper and stopper.count >= stopper.patience),
        best_val_loss=min(history["val_loss"]) if history["val_loss"] else None,
        final_lr=history["lr"][-1] if history["lr"] else None,
        compiles=cmon.snapshot() if cmon is not None else None,
        timers=timers_snapshot(),
        metrics=get_registry().snapshot(),
        # hardware-efficiency rollup: mean/max MFU across epochs and
        # the run's device-memory high-water mark
        hw=ledger.run_summary() if ledger is not None else None,
        triggers=(
            trig_engine.summary(incidents.capture_s if incidents else 0.0)
            if trig_engine is not None
            else None
        ),
        # measured cost of the pod-visibility plane: shard writes +
        # rank-0 skew folds as a fraction of run wall time (the <1%
        # clean-path acceptance gate ci.sh asserts)
        podview=(
            {
                "enabled": True,
                "host": pv_host,
                "hosts": pv_hosts,
                "run_id": pv_run_id,
                "overhead_s": round(pv_overhead_s, 6),
                "overhead_frac": round(
                    pv_overhead_s
                    / max(time.perf_counter() - pv_t_run0, 1e-9),
                    8,
                ),
            }
            if pv_on
            else None
        ),
    )
    if own_flight:
        flight.close()
    hooks.teardown()

    return state, history
