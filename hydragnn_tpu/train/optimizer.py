"""Optimizer selection — the optax analog of the reference's 8-way factory.

Reference: hydragnn/utils/optimizer.py:12-113 selects one of
{SGD, Adam, Adadelta, Adagrad, Adamax, AdamW, RMSprop, FusedLAMB} and
optionally wraps it in ZeroRedundancyOptimizer (ZeRO stage 1). On TPU:

  - every optimizer maps to its optax counterpart (FusedLAMB -> optax.lamb;
    no custom kernel is needed, XLA fuses the update);
  - ZeRO-1 is not an optimizer wrapper but a *sharding rule*: optimizer
    state is sharded over the data axis by the parallel layer
    (hydragnn_tpu/parallel), so ``use_zero_redundancy`` is accepted and
    recorded but changes nothing here;
  - the learning rate is injected as a dynamic hyperparameter so the
    host-side ReduceLROnPlateau controller can change it between steps
    without recompiling (reference: torch scheduler mutates param groups,
    hydragnn/run_training.py:94-96).

``freeze_conv_layers`` (reference: Base._freeze_conv Base.py:117-121 via
requires_grad=False on the conv stack) is honored by zeroing the final
updates for every parameter subtree named ``conv_*``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import optax


OPTIMIZERS = ("SGD", "Adam", "Adadelta", "Adagrad", "Adamax", "AdamW", "RMSprop", "FusedLAMB")


def _base_optimizer(opt_type: str, learning_rate) -> optax.GradientTransformation:
    if opt_type == "SGD":
        return optax.sgd(learning_rate)
    if opt_type == "Adam":
        return optax.adam(learning_rate)
    if opt_type == "Adadelta":
        return optax.adadelta(learning_rate)
    if opt_type == "Adagrad":
        return optax.adagrad(learning_rate)
    if opt_type == "Adamax":
        return optax.adamax(learning_rate)
    if opt_type == "AdamW":
        return optax.adamw(learning_rate)
    if opt_type == "RMSprop":
        return optax.rmsprop(learning_rate)
    if opt_type == "FusedLAMB":
        return optax.lamb(learning_rate)
    raise NameError(f"The string used to identify the optimizer is not recognized: {opt_type}")


def _frozen_conv_mask(params) -> Any:
    """True (frozen) for every top-level ``conv_*`` subtree."""
    return {k: jax.tree_util.tree_map(lambda _: k.startswith("conv_"), v) for k, v in params.items()}


def select_optimizer(
    training_config: Dict[str, Any],
    freeze_conv: bool = False,
    params: Optional[Any] = None,
) -> optax.GradientTransformation:
    """Build the optimizer from the ``Training`` config section.

    ``training_config["Optimizer"]`` carries ``type`` and ``learning_rate``
    (reference config schema, hydragnn/utils/optimizer.py:43-113).
    Returns an ``inject_hyperparams`` transformation whose state exposes
    ``.hyperparams["learning_rate"]`` for the plateau scheduler.
    """
    opt_cfg = training_config.get("Optimizer", {})
    opt_type = opt_cfg.get("type", "AdamW")
    lr = float(opt_cfg.get("learning_rate", training_config.get("learning_rate", 1e-3)))

    def make(learning_rate):
        tx = _base_optimizer(opt_type, learning_rate)
        if freeze_conv:
            tx = optax.chain(tx, optax.masked(optax.set_to_zero(), _frozen_conv_mask))
        return tx

    tx = optax.inject_hyperparams(make)(learning_rate=lr)

    # Training.grad_accum_steps: average gradients over k micro-batches
    # before each parameter update (effective batch = k x batch_size) —
    # a memory lever for large padded graphs. Absent from the reference
    # (SURVEY §2.2 "explicitly absent: gradient accumulation").
    accum = int(training_config.get("grad_accum_steps", 1))
    if accum > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=accum).gradient_transformation()
    return tx


def _hyperparam_state(opt_state):
    """Walk wrapper states (e.g. MultiSteps) down to the
    inject_hyperparams state that owns the dynamic learning rate."""
    s = opt_state
    while not hasattr(s, "hyperparams"):
        if hasattr(s, "inner_opt_state"):
            s = s.inner_opt_state
        else:
            raise AttributeError(
                f"no hyperparams state found in {type(opt_state).__name__}"
            )
    return s


def current_learning_rate(opt_state) -> float:
    """Read the dynamic learning rate out of an inject_hyperparams state
    (possibly nested under gradient-accumulation wrappers)."""
    return float(_hyperparam_state(opt_state).hyperparams["learning_rate"])


def set_learning_rate(opt_state, lr: float):
    """Return a new opt_state with the learning rate replaced (host-side;
    the next jitted step picks it up as a donated input, no recompile)."""
    import jax.numpy as jnp

    if hasattr(opt_state, "hyperparams"):
        hyper = dict(opt_state.hyperparams)
        hyper["learning_rate"] = jnp.asarray(
            lr, dtype=jnp.asarray(hyper["learning_rate"]).dtype
        )
        return opt_state._replace(hyperparams=hyper)
    if hasattr(opt_state, "inner_opt_state"):
        return opt_state._replace(
            inner_opt_state=set_learning_rate(opt_state.inner_opt_state, lr)
        )
    raise AttributeError(f"no hyperparams state found in {type(opt_state).__name__}")
