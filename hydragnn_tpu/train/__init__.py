from hydragnn_tpu.train.optimizer import (
    select_optimizer,
    current_learning_rate,
    set_learning_rate,
)
from hydragnn_tpu.train.state import (
    TrainState,
    create_eval_state,
    create_train_state,
    make_scan_epoch,
    make_scan_eval,
    make_train_step,
    make_eval_step,
    make_stats_step,
)
from hydragnn_tpu.train.loop import (
    EarlyStopping,
    ReduceLROnPlateau,
    train_epoch,
    evaluate_epoch,
    test_epoch,
    train_validate_test,
)
