"""Data-parallel (ZeRO-1 / FSDP-capable) train/eval steps over a device mesh.

TPU-native replacement for DDP (reference: hydragnn/utils/distributed.py:
220-233 wraps the model; gradient all-reduce happens inside torch's
backward). Here the structure is explicit and compiler-friendly:

  - the loader yields batches with a leading device axis [D, ...] whose
    edge indices are LOCAL to each sub-batch (no cross-device gathers in
    the segment ops — the analog of each DDP rank owning its own graphs);
  - ``shard_map`` runs the per-device forward+backward; gradients are
    ``pmean``-ed over the batch axes (DDP's all-reduce, riding ICI);
  - BatchNorm running stats are ``pmean``-ed so the replicated state stays
    consistent (plain DDP keeps per-rank stats and saves rank 0's; the
    in-forward statistics stay per-device unless ``SyncBatchNorm`` sets
    ``bn_axis_name``, matching reference semantics);
  - the optimizer update runs under ``jit`` outside shard_map; the
    state layout is pinned by a sharding constraint: replicated by
    default, optimizer-state leaves sharded over the data axis with
    ``zero1=True`` (ZeRO stage 1 — XLA inserts the reduce-scatter /
    all-gather pattern; reference: ZeroRedundancyOptimizer,
    hydragnn/utils/optimizer.py:43-113), or an arbitrary caller-supplied
    layout via ``state_sharding_fn`` — how the ``Partitioner``
    (parallel/partitioner.py) threads its FSDP parameter+optimizer
    sharding through the SAME step.

The ``batch_axes`` parameter generalizes every step to composed meshes:
the batch's leading device axis shards over that tuple of mesh axes
(``("data",)`` classic DP; ``("data", "fsdp")`` under the Partitioner's
FSDP layout) and gradients/metrics reduce over all of them.
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hydragnn_tpu.graph.batch import GraphBatch
from hydragnn_tpu.models.base import HydraModel, model_loss
from hydragnn_tpu.parallel.mesh import DATA_AXIS
from hydragnn_tpu.train.state import TrainState

from hydragnn_tpu.utils.jax_compat import shard_map

# graftsync: thread-safe=GIL-atomic one-way False->True latch; a race costs one duplicate warning
_warned_zero1_replicated = False


def _lead_spec(batch_axes: Sequence[str]):
    """PartitionSpec entry for the batch leading axis."""
    if not batch_axes:
        return None
    return batch_axes[0] if len(batch_axes) == 1 else tuple(batch_axes)


def _axes_arg(batch_axes: Sequence[str]):
    """axis_name argument for pmean/psum over the batch axes."""
    return batch_axes[0] if len(batch_axes) == 1 else tuple(batch_axes)


def _device_index(batch_axes: Sequence[str], mesh: Mesh) -> jnp.ndarray:
    """Flat per-device index over the batch axes (dropout decorrelation)."""
    idx = jnp.zeros((), jnp.int32)
    for a in batch_axes:
        idx = idx * mesh.shape[a] + jax.lax.axis_index(a)
    return idx


def _zero1_leaf_shardings(
    mesh: Mesh, opt_state, report: Optional[List[str]] = None
):
    """ZeRO-1 layout for the optimizer tree: leaves sharded on their
    first axis when it divides the data-axis size, else replicated —
    recording each non-scalar replicated fallback's path into ``report``
    (the silent-replication fix: the fallback is now observable)."""
    n = mesh.shape[DATA_AXIS]
    rep = NamedSharding(mesh, P())

    def leaf(path, x):
        if (
            hasattr(x, "ndim")
            and x.ndim >= 1
            and x.shape[0] > 0
            and x.shape[0] % n == 0
        ):
            return NamedSharding(mesh, P(DATA_AXIS))
        if report is not None and getattr(x, "ndim", 0) >= 1:
            report.append("opt_state" + jax.tree_util.keystr(path))
        return rep

    return jax.tree_util.tree_map_with_path(leaf, opt_state)


def _zero1_sharding(
    mesh: Mesh, state: TrainState, warn: bool = False
) -> TrainState:
    """Per-leaf shardings for the TrainState: params/batch_stats/rng
    replicated, optimizer-state leaves sharded on their first axis when it
    divides the data-axis size (ZeRO-1), else replicated. With
    ``warn=True`` (placement time, never inside a trace) a replicated
    fallback logs ONE loud rank-0 warning naming the leaf paths."""
    global _warned_zero1_replicated
    rep = NamedSharding(mesh, P())
    report: List[str] = []
    opt = _zero1_leaf_shardings(mesh, state.opt_state, report)
    if (
        warn
        and report
        and not _warned_zero1_replicated
        and jax.process_index() == 0
    ):
        _warned_zero1_replicated = True
        shown = ", ".join(report[:8]) + (", ..." if len(report) > 8 else "")
        warnings.warn(
            f"ZeRO-1: {len(report)} optimizer leaf(ves) have a first axis "
            f"not divisible by the data-axis size {mesh.shape[DATA_AXIS]} "
            f"and stay fully REPLICATED on every device: {shown}. Recorded "
            "in the flight manifest as parallel.replicated_leaves.",
            RuntimeWarning,
            stacklevel=3,
        )
    return TrainState(
        step=rep,
        params=jax.tree_util.tree_map(lambda _: rep, state.params),
        batch_stats=jax.tree_util.tree_map(lambda _: rep, state.batch_stats),
        opt_state=opt,
        rng=rep,
    )


def _replicated_state_sharding(mesh: Mesh, state: TrainState) -> TrainState:
    rep = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda _: rep, state)


def _state_sharding(
    mesh: Mesh,
    state: TrainState,
    zero1: bool,
    state_sharding_fn: Optional[Callable[[TrainState], TrainState]] = None,
    warn: bool = False,
) -> TrainState:
    """The run's state layout — single source of truth shared by initial
    placement and the per-step output constraint. ``state_sharding_fn``
    (the Partitioner's FSDP layout) overrides the built-in rules."""
    if state_sharding_fn is not None:
        return state_sharding_fn(state)
    if zero1:
        return _zero1_sharding(mesh, state, warn=warn)
    return _replicated_state_sharding(mesh, state)


def place_state(
    mesh: Mesh,
    state: TrainState,
    zero1: bool = False,
    state_sharding_fn: Optional[Callable[[TrainState], TrainState]] = None,
) -> TrainState:
    """Place a host-built TrainState onto the mesh with the chosen layout."""
    sh = _state_sharding(mesh, state, zero1, state_sharding_fn, warn=True)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), state, sh
    )


def make_sharded_train_step(
    model: HydraModel,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    zero1: bool = False,
    compute_dtype=None,
    remat: bool = False,
    batch_axes: Tuple[str, ...] = (DATA_AXIS,),
    state_sharding_fn: Optional[Callable[[TrainState], TrainState]] = None,
) -> Callable[[TrainState, GraphBatch], Tuple[TrainState, jnp.ndarray, jnp.ndarray]]:
    """Jitted ``(state, batch[D-leading]) -> (state, loss, tasks)``.

    ``batch`` leaves carry a leading device axis equal to the product of
    the ``batch_axes`` mesh sizes (GraphLoader(device_stack=D) output).
    ``compute_dtype=jnp.bfloat16`` enables mixed precision exactly like
    the single-device step: bf16 forward/backward, f32 master params /
    grads / BN stats / loss. ``remat=True`` checkpoints the per-device
    forward (see train.state.make_train_step). ``state_sharding_fn``
    pins a caller-owned state layout (the Partitioner's FSDP sharding:
    params + optimizer leaves over the ``fsdp`` axis — XLA turns the
    replicated-in / sharded-out constraint pair into the all-gather /
    reduce-scatter FSDP pattern)."""
    from hydragnn_tpu.train.state import _cast_floats

    axes = _axes_arg(batch_axes)
    lead = _lead_spec(batch_axes)

    def per_device_grads(params, batch_stats, dropout_rng, batch: GraphBatch):
        # Each device sees its own sub-batch (leading axis stripped by
        # shard_map's lead-axis in_spec).
        batch = jax.tree_util.tree_map(lambda x: x[0], batch)
        dropout_rng = jax.random.fold_in(
            dropout_rng, _device_index(batch_axes, mesh)
        )

        def loss_fn(p):
            if compute_dtype is not None:
                ap = _cast_floats(p, compute_dtype)
                ab = _cast_floats(batch, compute_dtype)
            else:
                ap, ab = p, batch
            outputs, mutated = model.apply(
                {"params": ap, "batch_stats": batch_stats},
                ab,
                train=True,
                mutable=["batch_stats"],
                rngs={"dropout": dropout_rng},
            )
            # loss in f32 against the original (uncast) targets
            outputs = [o.astype(jnp.float32) for o in outputs]
            total, tasks = model_loss(model.cfg, outputs, batch)
            return total, (jnp.stack(tasks), mutated)

        lf = jax.checkpoint(loss_fn) if remat else loss_fn
        (loss, (tasks, mutated)), grads = jax.value_and_grad(lf, has_aux=True)(
            params
        )
        # DDP-equivalent gradient mean over the batch axes (ICI collective).
        grads = jax.lax.pmean(grads, axes)
        new_stats = jax.lax.pmean(mutated["batch_stats"], axes)
        # Real-graph-weighted global loss for reporting.
        n = batch.graph_mask.sum().astype(jnp.float32)
        n_tot = jnp.maximum(jax.lax.psum(n, axes), 1.0)
        loss_g = jax.lax.psum(loss * n, axes) / n_tot
        tasks_g = jax.lax.psum(tasks * n, axes) / n_tot
        return grads, new_stats, loss_g, tasks_g

    sharded_grads = shard_map(
        per_device_grads,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(lead)),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )

    def step(state: TrainState, batch: GraphBatch):
        rng, dropout_rng = jax.random.split(state.rng)
        grads, new_stats, loss, tasks = sharded_grads(
            state.params, state.batch_stats, dropout_rng, batch
        )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=params,
            batch_stats=new_stats,
            opt_state=opt_state,
            rng=rng,
        )
        # Pin the documented layout (replicated, ZeRO-1, or the
        # Partitioner's FSDP sharding): without the constraint XLA may
        # propagate an input sharding into the updated params, which
        # both changes layout across steps (recompile + donation churn)
        # and leaves params unreadable from host code.
        new_state = jax.lax.with_sharding_constraint(
            new_state, _state_sharding(mesh, new_state, zero1, state_sharding_fn)
        )
        return new_state, loss, tasks

    return jax.jit(step, donate_argnums=(0,))


def make_sharded_stats_step(
    model: HydraModel, mesh: Mesh, batch_axes: Tuple[str, ...] = (DATA_AXIS,)
) -> Callable[[TrainState, GraphBatch], TrainState]:
    """Sharded BatchNorm recalibration (see train.state.make_stats_step):
    train-mode forward over the device mesh updating only the running
    statistics (psum-synchronized by the BN layer's axis_name)."""
    axes = _axes_arg(batch_axes)
    lead = _lead_spec(batch_axes)

    def per_device(params, batch_stats, batch: GraphBatch):
        batch = jax.tree_util.tree_map(lambda x: x[0], batch)
        # dropout off, BN in batch-stats mode (see make_stats_step)
        _, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch,
            train=False,
            bn_train=True,
            mutable=["batch_stats"],
        )
        return jax.lax.pmean(mutated["batch_stats"], axes)

    fn = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), P(), P(lead)),
        out_specs=P(),
        check_vma=False,
    )

    def step(state: TrainState, batch: GraphBatch):
        new_stats = fn(state.params, state.batch_stats, batch)
        return state.replace(batch_stats=new_stats)

    return jax.jit(step)


def make_sharded_eval_step(
    model: HydraModel,
    mesh: Mesh,
    with_outputs: bool = False,
    batch_axes: Tuple[str, ...] = (DATA_AXIS,),
) -> Callable[..., Any]:
    """Jitted sharded eval. With ``with_outputs`` the per-head outputs come
    back concatenated over devices ([D*G, d] / [D*N, d]) so the host-side
    ``test_epoch`` collection can flatten masks to match."""
    axes = _axes_arg(batch_axes)
    lead = _lead_spec(batch_axes)

    def per_device(params, batch_stats, batch: GraphBatch):
        batch = jax.tree_util.tree_map(lambda x: x[0], batch)
        outputs = model.apply(
            {"params": params, "batch_stats": batch_stats}, batch, train=False
        )
        loss, tasks = model_loss(model.cfg, outputs, batch)
        tasks = jnp.stack(tasks)
        n = batch.graph_mask.sum().astype(jnp.float32)
        n_tot = jnp.maximum(jax.lax.psum(n, axes), 1.0)
        loss_g = jax.lax.psum(loss * n, axes) / n_tot
        tasks_g = jax.lax.psum(tasks * n, axes) / n_tot
        if with_outputs:
            return loss_g, tasks_g, tuple(outputs)
        return loss_g, tasks_g

    out_specs: Any = (P(), P())
    if with_outputs:
        out_specs = (P(), P(), tuple(P(lead) for _ in range(model.cfg.num_heads)))

    fn = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), P(), P(lead)),
        out_specs=out_specs,
        check_vma=False,
    )

    def step(state: TrainState, batch: GraphBatch):
        res = fn(state.params, state.batch_stats, batch)
        if with_outputs:
            loss, tasks, outputs = res
            return loss, tasks, list(outputs)
        return res

    return jax.jit(step)
