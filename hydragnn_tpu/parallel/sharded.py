"""Data-parallel (and ZeRO-1) train/eval steps over a device mesh.

TPU-native replacement for DDP (reference: hydragnn/utils/distributed.py:
220-233 wraps the model; gradient all-reduce happens inside torch's
backward). Here the structure is explicit and compiler-friendly:

  - the loader yields batches with a leading device axis [D, ...] whose
    edge indices are LOCAL to each sub-batch (no cross-device gathers in
    the segment ops — the analog of each DDP rank owning its own graphs);
  - ``shard_map`` runs the per-device forward+backward; gradients are
    ``pmean``-ed over the ``data`` axis (DDP's all-reduce, riding ICI);
  - BatchNorm running stats are ``pmean``-ed so the replicated state stays
    consistent (plain DDP keeps per-rank stats and saves rank 0's; the
    in-forward statistics stay per-device unless ``SyncBatchNorm`` sets
    ``bn_axis_name``, matching reference semantics);
  - the optimizer update runs under ``jit`` outside shard_map; with
    ``zero1=True`` optimizer-state leaves are sharded over the data axis
    via NamedSharding constraints — XLA inserts the reduce-scatter /
    all-gather pattern, which IS ZeRO stage 1 (reference:
    ZeroRedundancyOptimizer, hydragnn/utils/optimizer.py:43-113).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hydragnn_tpu.graph.batch import GraphBatch
from hydragnn_tpu.models.base import HydraModel, model_loss
from hydragnn_tpu.parallel.mesh import DATA_AXIS
from hydragnn_tpu.train.state import TrainState

from hydragnn_tpu.utils.jax_compat import shard_map


def _zero1_sharding(mesh: Mesh, state: TrainState) -> TrainState:
    """Per-leaf shardings for the TrainState: params/batch_stats/rng
    replicated, optimizer-state leaves sharded on their first axis when it
    divides the data-axis size (ZeRO-1), else replicated."""
    n = mesh.shape[DATA_AXIS]
    rep = NamedSharding(mesh, P())

    def opt_leaf(x):
        if hasattr(x, "ndim") and x.ndim >= 1 and x.shape[0] % n == 0 and x.shape[0] > 0:
            return NamedSharding(mesh, P(DATA_AXIS))
        return rep

    return TrainState(
        step=rep,
        params=jax.tree_util.tree_map(lambda _: rep, state.params),
        batch_stats=jax.tree_util.tree_map(lambda _: rep, state.batch_stats),
        opt_state=jax.tree_util.tree_map(opt_leaf, state.opt_state),
        rng=rep,
    )


def _replicated_state_sharding(mesh: Mesh, state: TrainState) -> TrainState:
    rep = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda _: rep, state)


def _state_sharding(mesh: Mesh, state: TrainState, zero1: bool) -> TrainState:
    """The run's state layout — single source of truth shared by initial
    placement and the per-step output constraint."""
    return _zero1_sharding(mesh, state) if zero1 else _replicated_state_sharding(mesh, state)


def place_state(mesh: Mesh, state: TrainState, zero1: bool = False) -> TrainState:
    """Place a host-built TrainState onto the mesh with the chosen layout."""
    sh = _state_sharding(mesh, state, zero1)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), state, sh
    )


def make_sharded_train_step(
    model: HydraModel,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    zero1: bool = False,
    compute_dtype=None,
    remat: bool = False,
) -> Callable[[TrainState, GraphBatch], Tuple[TrainState, jnp.ndarray, jnp.ndarray]]:
    """Jitted ``(state, batch[D-leading]) -> (state, loss, tasks)``.

    ``batch`` leaves carry a leading device axis of size mesh['data']
    (GraphLoader(device_stack=D) output). ``compute_dtype=jnp.bfloat16``
    enables mixed precision exactly like the single-device step: bf16
    forward/backward, f32 master params / grads / BN stats / loss.
    ``remat=True`` checkpoints the per-device forward (see
    train.state.make_train_step)."""
    from hydragnn_tpu.train.state import _cast_floats

    def per_device_grads(params, batch_stats, dropout_rng, batch: GraphBatch):
        # Each device sees its own sub-batch (leading axis stripped by
        # shard_map's P(DATA_AXIS) in_spec).
        batch = jax.tree_util.tree_map(lambda x: x[0], batch)
        dropout_rng = jax.random.fold_in(dropout_rng, jax.lax.axis_index(DATA_AXIS))

        def loss_fn(p):
            if compute_dtype is not None:
                ap = _cast_floats(p, compute_dtype)
                ab = _cast_floats(batch, compute_dtype)
            else:
                ap, ab = p, batch
            outputs, mutated = model.apply(
                {"params": ap, "batch_stats": batch_stats},
                ab,
                train=True,
                mutable=["batch_stats"],
                rngs={"dropout": dropout_rng},
            )
            # loss in f32 against the original (uncast) targets
            outputs = [o.astype(jnp.float32) for o in outputs]
            total, tasks = model_loss(model.cfg, outputs, batch)
            return total, (jnp.stack(tasks), mutated)

        lf = jax.checkpoint(loss_fn) if remat else loss_fn
        (loss, (tasks, mutated)), grads = jax.value_and_grad(lf, has_aux=True)(
            params
        )
        # DDP-equivalent gradient mean over the data axis (ICI collective).
        grads = jax.lax.pmean(grads, DATA_AXIS)
        new_stats = jax.lax.pmean(mutated["batch_stats"], DATA_AXIS)
        # Real-graph-weighted global loss for reporting.
        n = batch.graph_mask.sum().astype(jnp.float32)
        n_tot = jnp.maximum(jax.lax.psum(n, DATA_AXIS), 1.0)
        loss_g = jax.lax.psum(loss * n, DATA_AXIS) / n_tot
        tasks_g = jax.lax.psum(tasks * n, DATA_AXIS) / n_tot
        return grads, new_stats, loss_g, tasks_g

    sharded_grads = shard_map(
        per_device_grads,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(DATA_AXIS)),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )

    def step(state: TrainState, batch: GraphBatch):
        rng, dropout_rng = jax.random.split(state.rng)
        grads, new_stats, loss, tasks = sharded_grads(
            state.params, state.batch_stats, dropout_rng, batch
        )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=params,
            batch_stats=new_stats,
            opt_state=opt_state,
            rng=rng,
        )
        # Pin the documented layout (params/stats replicated, optimizer
        # state data-sharded under ZeRO-1): without the constraint XLA may
        # propagate the opt-state sharding into the updated params, which
        # both changes layout across steps (recompile + donation churn)
        # and leaves params unreadable from host code.
        new_state = jax.lax.with_sharding_constraint(
            new_state, _state_sharding(mesh, new_state, zero1)
        )
        return new_state, loss, tasks

    return jax.jit(step, donate_argnums=(0,))


def make_sharded_stats_step(
    model: HydraModel, mesh: Mesh
) -> Callable[[TrainState, GraphBatch], TrainState]:
    """Sharded BatchNorm recalibration (see train.state.make_stats_step):
    train-mode forward over the device mesh updating only the running
    statistics (psum-synchronized by the BN layer's axis_name)."""

    def per_device(params, batch_stats, batch: GraphBatch):
        batch = jax.tree_util.tree_map(lambda x: x[0], batch)
        # dropout off, BN in batch-stats mode (see make_stats_step)
        _, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch,
            train=False,
            bn_train=True,
            mutable=["batch_stats"],
        )
        return jax.lax.pmean(mutated["batch_stats"], DATA_AXIS)

    fn = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), P(), P(DATA_AXIS)),
        out_specs=P(),
        check_vma=False,
    )

    def step(state: TrainState, batch: GraphBatch):
        new_stats = fn(state.params, state.batch_stats, batch)
        return state.replace(batch_stats=new_stats)

    return jax.jit(step)


def make_sharded_eval_step(
    model: HydraModel, mesh: Mesh, with_outputs: bool = False
) -> Callable[..., Any]:
    """Jitted sharded eval. With ``with_outputs`` the per-head outputs come
    back concatenated over devices ([D*G, d] / [D*N, d]) so the host-side
    ``test_epoch`` collection can flatten masks to match."""

    def per_device(params, batch_stats, batch: GraphBatch):
        batch = jax.tree_util.tree_map(lambda x: x[0], batch)
        outputs = model.apply(
            {"params": params, "batch_stats": batch_stats}, batch, train=False
        )
        loss, tasks = model_loss(model.cfg, outputs, batch)
        tasks = jnp.stack(tasks)
        n = batch.graph_mask.sum().astype(jnp.float32)
        n_tot = jnp.maximum(jax.lax.psum(n, DATA_AXIS), 1.0)
        loss_g = jax.lax.psum(loss * n, DATA_AXIS) / n_tot
        tasks_g = jax.lax.psum(tasks * n, DATA_AXIS) / n_tot
        if with_outputs:
            return loss_g, tasks_g, tuple(outputs)
        return loss_g, tasks_g

    out_specs: Any = (P(), P())
    if with_outputs:
        out_specs = (P(), P(), tuple(P(DATA_AXIS) for _ in range(model.cfg.num_heads)))

    fn = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), P(), P(DATA_AXIS)),
        out_specs=out_specs,
        check_vma=False,
    )

    def step(state: TrainState, batch: GraphBatch):
        res = fn(state.params, state.batch_stats, batch)
        if with_outputs:
            loss, tasks, outputs = res
            return loss, tasks, list(outputs)
        return res

    return jax.jit(step)
