"""Edge-sharded message passing: one giant graph split across chips.

The reference cannot partition a single graph across ranks — a graph
must fit one device, and large-graph scaling is handled purely on the
data side (SURVEY §5: out-of-core reads, DDStore fetches). This module
is the TPU-native headroom beyond that parity point: the EDGE set of one
huge graph is sharded over the ``data`` mesh axis, every device computes
messages for its edge shard against replicated node features, reduces
them into per-node partials with a local segment-sum, and one ``psum``
over ICI combines the partials — the GNN analog of sequence-parallel
attention (partition the quadratic axis, all-reduce the contraction).

Memory per chip: O(E/D + N) instead of O(E + N); compute per chip:
O(E/D) message FLOPs. Works under ``jit`` with static shapes: pad the
edge list to a multiple of the mesh size and mask.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hydragnn_tpu.parallel.mesh import DATA_AXIS

shard_map = jax.shard_map


def shard_edges(
    senders: np.ndarray,
    receivers: np.ndarray,
    edge_data: Optional[np.ndarray],
    num_devices: int,
) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray], np.ndarray]:
    """Host-side: pad the edge list to a multiple of ``num_devices`` and
    return (senders, receivers, edge_data, edge_mask) ready to place with
    a ``P(DATA_AXIS)`` sharding. Padding edges point at node 0 and are
    masked out."""
    e = senders.shape[0]
    e_pad = ((e + num_devices - 1) // num_devices) * num_devices
    pad = e_pad - e
    mask = np.concatenate([np.ones(e, bool), np.zeros(pad, bool)])
    senders = np.concatenate([senders, np.zeros(pad, senders.dtype)])
    receivers = np.concatenate([receivers, np.zeros(pad, receivers.dtype)])
    if edge_data is not None:
        edge_data = np.concatenate(
            [edge_data, np.zeros((pad,) + edge_data.shape[1:], edge_data.dtype)]
        )
    return senders, receivers, edge_data, mask


def edge_sharded_aggregate(
    mesh: Mesh,
    message_fn: Callable[..., jnp.ndarray],
    nodes: jnp.ndarray,
    senders: jnp.ndarray,
    receivers: jnp.ndarray,
    edge_mask: jnp.ndarray,
    edge_data: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Aggregated messages [N, H] for one edge-sharded graph.

    ``message_fn(x_i, x_j[, edge_data]) -> [e_local, H]`` computes the
    per-edge messages on each device's shard; the result is the masked
    segment-sum over receivers, psum-combined across the mesh. ``nodes``
    is replicated; ``senders``/``receivers``/``edge_mask``/``edge_data``
    are sharded on their leading axis.
    """
    num_nodes = nodes.shape[0]
    has_edge_data = edge_data is not None

    def local(nodes, snd, rcv, msk, *ed):
        x_i = nodes[rcv]
        x_j = nodes[snd]
        msg = message_fn(x_i, x_j, *ed)
        msg = jnp.where(msk[:, None], msg, 0)
        part = jax.ops.segment_sum(msg, rcv, num_nodes)
        return jax.lax.psum(part, DATA_AXIS)

    in_specs = [P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)]
    args = [nodes, senders, receivers, edge_mask]
    if has_edge_data:
        in_specs.append(P(DATA_AXIS))
        args.append(edge_data)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=P(),
        check_vma=False,
    )
    return fn(*args)


def place_edge_shards(mesh: Mesh, *arrays):
    """Device-put edge arrays with leading-axis sharding over the mesh."""
    sh = NamedSharding(mesh, P(DATA_AXIS))
    return tuple(jax.device_put(a, sh) if a is not None else None for a in arrays)


def edge_axis_shardings(mesh: Mesh, batch):
    """Per-leaf shardings for a GraphBatch holding ONE giant graph:
    every leaf whose leading axis is the edge axis (senders, receivers,
    edge_attr, edge_mask) is sharded ``P(data)``; node/graph leaves stay
    replicated. Matching is a heuristic on the leading dim: node and edge
    pads MAY coincide, in which case node arrays get edge-style sharding
    too — that only changes layout (XLA inserts the gathers), never
    results."""
    e = batch.senders.shape[0]
    rep = NamedSharding(mesh, P())
    edge = NamedSharding(mesh, P(DATA_AXIS))

    def pick(x):
        if hasattr(x, "shape") and getattr(x, "ndim", 0) >= 1 and x.shape[0] == e:
            return edge
        return rep

    return jax.tree_util.tree_map(pick, batch)


def place_giant_batch(mesh: Mesh, batch):
    """Place one giant-graph batch with its edge arrays sharded over the
    mesh and everything else replicated. A plain jitted train/eval step
    over inputs placed this way is partitioned by XLA's SPMD pass: each
    device computes messages for its edge shard, the partial-aggregate
    all-reduce rides ICI, and gradients get the matching collectives
    automatically — the full-model generalization of
    :func:`edge_sharded_aggregate`, with no hand-written comm. Memory per
    chip: O(E/D) edge buffers + O(N) node buffers.

    The edge pad is rounded up to a mesh multiple first (a ``P(data)``
    placement requires divisibility); the extra slots are masked padding."""
    d = int(mesh.shape[DATA_AXIS])
    e = batch.senders.shape[0]
    if e % d:
        from hydragnn_tpu.graph.batch import pad_batch

        batch = pad_batch(
            batch,
            n_node=batch.nodes.shape[0],
            n_edge=((e + d - 1) // d) * d,
            n_graph=batch.graph_mask.shape[0],
        )
    return jax.device_put(batch, edge_axis_shardings(mesh, batch))


def edge_sharded_gin_layer(
    mesh: Mesh,
    nodes: jnp.ndarray,
    senders: jnp.ndarray,
    receivers: jnp.ndarray,
    edge_mask: jnp.ndarray,
    w1: jnp.ndarray,
    b1: jnp.ndarray,
    w2: jnp.ndarray,
    b2: jnp.ndarray,
    eps: float = 100.0,
) -> jnp.ndarray:
    """One GIN conv over an edge-sharded giant graph: the neighbor sum is
    computed edge-parallel; the (1+eps)x + sum MLP stays node-replicated
    (node count is the small axis by assumption). Demonstrates how a full
    conv composes with :func:`edge_sharded_aggregate`."""
    agg = edge_sharded_aggregate(
        mesh,
        lambda x_i, x_j: x_j,
        nodes,
        senders,
        receivers,
        edge_mask,
    )
    h = (1.0 + eps) * nodes + agg
    h = jax.nn.relu(h @ w1 + b1)
    return h @ w2 + b2
