"""Edge-sharded message passing: one giant graph split across chips.

The reference cannot partition a single graph across ranks — a graph
must fit one device, and large-graph scaling is handled purely on the
data side (SURVEY §5: out-of-core reads, DDStore fetches). This module
is the TPU-native headroom beyond that parity point: the EDGE set of one
huge graph is sharded over the ``data`` mesh axis, every device computes
messages for its edge shard against replicated node features, reduces
them into per-node partials with a local segment-sum, and one ``psum``
over ICI combines the partials — the GNN analog of sequence-parallel
attention (partition the quadratic axis, all-reduce the contraction).

Memory per chip: O(E/D + N) instead of O(E + N); compute per chip:
O(E/D) message FLOPs. Works under ``jit`` with static shapes: pad the
edge list to a multiple of the mesh size and mask.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hydragnn_tpu.parallel.mesh import DATA_AXIS

from hydragnn_tpu.utils.jax_compat import shard_map


def shard_edges(
    senders: np.ndarray,
    receivers: np.ndarray,
    edge_data: Optional[np.ndarray],
    num_devices: int,
) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray], np.ndarray]:
    """Host-side: pad the edge list to a multiple of ``num_devices`` and
    return (senders, receivers, edge_data, edge_mask) ready to place with
    a ``P(DATA_AXIS)`` sharding. Padding edges point at node 0 and are
    masked out."""
    e = senders.shape[0]
    e_pad = ((e + num_devices - 1) // num_devices) * num_devices
    pad = e_pad - e
    mask = np.concatenate([np.ones(e, bool), np.zeros(pad, bool)])
    senders = np.concatenate([senders, np.zeros(pad, senders.dtype)])
    receivers = np.concatenate([receivers, np.zeros(pad, receivers.dtype)])
    if edge_data is not None:
        edge_data = np.concatenate(
            [edge_data, np.zeros((pad,) + edge_data.shape[1:], edge_data.dtype)]
        )
    return senders, receivers, edge_data, mask


def edge_sharded_aggregate(
    mesh: Mesh,
    message_fn: Callable[..., jnp.ndarray],
    nodes: jnp.ndarray,
    senders: jnp.ndarray,
    receivers: jnp.ndarray,
    edge_mask: jnp.ndarray,
    edge_data: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Aggregated messages [N, H] for one edge-sharded graph.

    ``message_fn(x_i, x_j[, edge_data]) -> [e_local, H]`` computes the
    per-edge messages on each device's shard; the result is the masked
    segment-sum over receivers, psum-combined across the mesh. ``nodes``
    is replicated; ``senders``/``receivers``/``edge_mask``/``edge_data``
    are sharded on their leading axis.
    """
    num_nodes = nodes.shape[0]
    has_edge_data = edge_data is not None

    def local(nodes, snd, rcv, msk, *ed):
        x_i = nodes[rcv]
        x_j = nodes[snd]
        msg = message_fn(x_i, x_j, *ed)
        msg = jnp.where(msk[:, None], msg, 0)
        part = jax.ops.segment_sum(msg, rcv, num_nodes)
        return jax.lax.psum(part, DATA_AXIS)

    in_specs = [P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)]
    args = [nodes, senders, receivers, edge_mask]
    if has_edge_data:
        in_specs.append(P(DATA_AXIS))
        args.append(edge_data)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=P(),
        check_vma=False,
    )
    return fn(*args)


def place_edge_shards(mesh: Mesh, *arrays):
    """Device-put edge arrays with leading-axis sharding over the mesh."""
    sh = NamedSharding(mesh, P(DATA_AXIS))
    return tuple(jax.device_put(a, sh) if a is not None else None for a in arrays)


def edge_axis_shardings(mesh: Mesh, batch):
    """Per-leaf shardings for a GraphBatch holding ONE giant graph:
    every leaf whose leading axis is the edge axis (senders, receivers,
    edge_attr, edge_mask) is sharded ``P(data)``; node/graph leaves stay
    replicated. Matching is a heuristic on the leading dim: node and edge
    pads MAY coincide, in which case node arrays get edge-style sharding
    too — that only changes layout (XLA inserts the gathers), never
    results."""
    e = batch.senders.shape[0]
    rep = NamedSharding(mesh, P())
    edge = NamedSharding(mesh, P(DATA_AXIS))

    def pick(x):
        if hasattr(x, "shape") and getattr(x, "ndim", 0) >= 1 and x.shape[0] == e:
            return edge
        return rep

    return jax.tree_util.tree_map(pick, batch)


def place_giant_batch(mesh: Mesh, batch):
    """Place one giant-graph batch with its edge arrays sharded over the
    mesh and everything else replicated. A plain jitted train/eval step
    over inputs placed this way is partitioned by XLA's SPMD pass: each
    device computes messages for its edge shard, the partial-aggregate
    all-reduce rides ICI, and gradients get the matching collectives
    automatically — the full-model generalization of
    :func:`edge_sharded_aggregate`, with no hand-written comm. Memory per
    chip: O(E/D) edge buffers + O(N) node buffers.

    The edge pad is rounded up to a mesh multiple first (a ``P(data)``
    placement requires divisibility); the extra slots are masked padding.

    The loader's local-window plans (``sender_win``/``dense_sender_win``)
    are stripped: they index GLOBAL edge positions, and the local-window
    kernel has no partitioning rule — the model then falls back to the
    sorted-permute path, whose ops all partition."""
    batch = batch.replace(sender_win=None, dense_sender_win=None)
    d = int(mesh.shape[DATA_AXIS])
    e = batch.senders.shape[0]
    if e % d:
        from hydragnn_tpu.graph.batch import pad_batch

        batch = pad_batch(
            batch,
            n_node=batch.nodes.shape[0],
            n_edge=((e + d - 1) // d) * d,
            n_graph=batch.graph_mask.shape[0],
        )
    return jax.device_put(batch, edge_axis_shardings(mesh, batch))


def _lead_entry(batch_axes):
    """PartitionSpec entry for the stacked batch's leading device axis."""
    if not batch_axes:
        return None
    return batch_axes[0] if len(batch_axes) == 1 else tuple(batch_axes)


def place_dp_edge_batch(mesh: Mesh, batch, batch_axes=(DATA_AXIS,)):
    """Place a device-stacked batch ([D_data, ...] leaves from
    ``GraphLoader(device_stack=D_data)``) on a composed mesh carrying an
    ``edge`` axis: axis 0 shards over the batch axes (``data``, or
    ``data × fsdp`` under the Partitioner); leaves whose SECOND axis is
    the edge axis additionally shard it over ``edge``. Companion of
    :func:`make_dp_edge_train_step`."""
    d_edge = int(mesh.shape["edge"])
    e = batch.senders.shape[1]
    if e % d_edge:
        raise ValueError(
            f"the edge-axis size ({d_edge}) must divide the stacked edge "
            f"pad ({e}); build the loader with edge_multiple={d_edge} "
            "(or a multiple of it)"
        )

    lead = _lead_entry(batch_axes)
    dp = NamedSharding(mesh, P(lead))
    dp_edge = NamedSharding(mesh, P(lead, "edge"))

    # Edge leaves are selected by GraphBatch field NAME, not by shape:
    # a node- or graph-axis leaf whose pad coincidentally equals the edge
    # pad must stay data-sharded only.
    import dataclasses as _dc

    edge_fields = {"senders", "receivers", "edge_mask", "edge_attr", "sender_perm"}
    shardings = {}
    for f in _dc.fields(batch):
        v = getattr(batch, f.name)
        if f.metadata.get("static"):
            # static pytree meta (run_align): pass the value through —
            # it is part of the treedef, not a shardable leaf
            shardings[f.name] = v
            continue
        sh = dp_edge if f.name in edge_fields else dp
        shardings[f.name] = jax.tree_util.tree_map(lambda _: sh, v)
    return jax.device_put(batch, type(batch)(**shardings))


def make_dp_edge_train_step(
    model, tx, mesh: Mesh, batch_axes=(DATA_AXIS,), state_sharding_fn=None
):
    """Data-parallel x edge-sharded training on a composed mesh carrying
    an ``edge`` axis: sub-batches vmap over the leading batch axis (each
    holding its own graphs) while every sub-batch's edge arrays shard
    over the edge axis — GSPMD partitions both (the giant-graph analog of
    composing DP with sequence parallelism). Parameters stay replicated
    by default; ``state_sharding_fn`` (the Partitioner's FSDP layout)
    pins an fsdp-sharded parameter/optimizer layout instead — GSPMD then
    all-gathers parameters into the vmapped forward and reduce-scatters
    the state update, composing edge sharding with FSDP.

    Returns jitted ``(state, batch[D_data-leading]) -> (state, loss,
    tasks)`` matching ``make_sharded_train_step``'s contract."""
    import optax

    from hydragnn_tpu.models.base import model_loss
    from hydragnn_tpu.ops.segment_pallas import xla_segment_ops

    from hydragnn_tpu.parallel.sharded import _state_sharding

    def step(state, batch):
        # this step vmaps the model over the data axis; the Pallas
        # segment ops' custom_partitioning wrapper has no vmap batching
        # rule, so trace the whole body on the XLA segment path (the
        # GSPMD giant-graph path — plain jit, no vmap — keeps the
        # kernel via its partitioning rule; see ops/segment_pallas.py)
        with xla_segment_ops():
            return _body(state, batch)

    def _body(state, batch):
        rng, dropout_rng = jax.random.split(state.rng)
        d_data = batch.graph_mask.shape[0]

        def loss_fn(params):
            def per_shard(batch_d, rng_d):
                outputs, mutated = model.apply(
                    {"params": params, "batch_stats": state.batch_stats},
                    batch_d,
                    train=True,
                    mutable=["batch_stats"],
                    rngs={"dropout": rng_d},
                )
                total, tasks = model_loss(model.cfg, outputs, batch_d)
                n = batch_d.graph_mask.sum().astype(jnp.float32)
                return total, jnp.stack(tasks), mutated["batch_stats"], n

            rngs = jax.random.split(dropout_rng, d_data)
            # axis_name binds SyncBatchNorm's psum, like shard_map's mesh
            losses, tasks, stats, ns = jax.vmap(
                per_shard, axis_name=DATA_AXIS
            )(batch, rngs)
            # Gradient target is the UNWEIGHTED mean over shards — the
            # shard_map step pmean's per-device grads (DDP semantics,
            # sharded.py); reported metrics stay real-graph-weighted.
            loss_grad = losses.mean()
            w = ns / jnp.maximum(ns.sum(), 1.0)
            loss_rep = (losses * w).sum()
            tasks_rep = (tasks * w[:, None]).sum(axis=0)
            new_stats = jax.tree_util.tree_map(lambda s: s.mean(axis=0), stats)
            return loss_grad, (loss_rep, tasks_rep, new_stats)

        (_, (loss, tasks, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=params,
            batch_stats=new_stats,
            opt_state=opt_state,
            rng=rng,
        )
        # pin the state layout (see sharded.py: without it the batch's
        # (data, edge) sharding can propagate into params, churning
        # layouts across donated steps); a caller-supplied layout (the
        # Partitioner's FSDP sharding) wins over the replicated default
        new_state = jax.lax.with_sharding_constraint(
            new_state,
            _state_sharding(
                mesh, new_state, zero1=False, state_sharding_fn=state_sharding_fn
            ),
        )
        return new_state, loss, tasks

    return jax.jit(step, donate_argnums=(0,))


def make_dp_edge_eval_step(model, mesh: Mesh, with_outputs: bool = False):
    """Eval companion of :func:`make_dp_edge_train_step`: the vmapped
    eval forward over the stacked batch axis, edge arrays sharded over
    the mesh's ``edge`` axis by the batch placement. With
    ``with_outputs`` the per-head outputs come back flattened over the
    device axis ([D*G, d] / [D*N, d]) so ``test_epoch``'s mask
    flattening aligns — the same contract as ``make_sharded_eval_step``."""
    import jax.numpy as _jnp

    from hydragnn_tpu.models.base import model_loss as _model_loss
    from hydragnn_tpu.ops.segment_pallas import xla_segment_ops

    def step(state, batch):
        with xla_segment_ops():
            return _body(state, batch)

    def _body(state, batch):
        def per_shard(batch_d):
            outputs = model.apply(
                {"params": state.params, "batch_stats": state.batch_stats},
                batch_d,
                train=False,
            )
            loss, tasks = _model_loss(model.cfg, outputs, batch_d)
            n = batch_d.graph_mask.sum().astype(_jnp.float32)
            return loss, _jnp.stack(tasks), n, tuple(outputs)

        losses, tasks, ns, outputs = jax.vmap(per_shard, axis_name=DATA_AXIS)(
            batch
        )
        w = ns / _jnp.maximum(ns.sum(), 1.0)
        loss = (losses * w).sum()
        tasks = (tasks * w[:, None]).sum(axis=0)
        if with_outputs:
            flat = [o.reshape((-1,) + o.shape[2:]) for o in outputs]
            return loss, tasks, flat
        return loss, tasks

    return jax.jit(step)


def make_dp_edge_stats_step(model, mesh: Mesh):
    """BatchNorm-recalibration companion of
    :func:`make_dp_edge_train_step` (see train.state.make_stats_step):
    vmapped train-mode forward updating only the running statistics,
    averaged over the stacked sub-batches."""
    from hydragnn_tpu.ops.segment_pallas import xla_segment_ops

    def step(state, batch):
        with xla_segment_ops():
            def per_shard(batch_d):
                _, mutated = model.apply(
                    {"params": state.params, "batch_stats": state.batch_stats},
                    batch_d,
                    train=False,
                    bn_train=True,
                    mutable=["batch_stats"],
                )
                return mutated["batch_stats"]

            stats = jax.vmap(per_shard, axis_name=DATA_AXIS)(batch)
            new_stats = jax.tree_util.tree_map(lambda s: s.mean(axis=0), stats)
            return state.replace(batch_stats=new_stats)

    return jax.jit(step)


def edge_sharded_gin_layer(
    mesh: Mesh,
    nodes: jnp.ndarray,
    senders: jnp.ndarray,
    receivers: jnp.ndarray,
    edge_mask: jnp.ndarray,
    w1: jnp.ndarray,
    b1: jnp.ndarray,
    w2: jnp.ndarray,
    b2: jnp.ndarray,
    eps: float = 100.0,
) -> jnp.ndarray:
    """One GIN conv over an edge-sharded giant graph: the neighbor sum is
    computed edge-parallel; the (1+eps)x + sum MLP stays node-replicated
    (node count is the small axis by assumption). Demonstrates how a full
    conv composes with :func:`edge_sharded_aggregate`."""
    agg = edge_sharded_aggregate(
        mesh,
        lambda x_i, x_j: x_j,
        nodes,
        senders,
        receivers,
        edge_mask,
    )
    h = (1.0 + eps) * nodes + agg
    h = jax.nn.relu(h @ w1 + b1)
    return h @ w2 + b2
