from hydragnn_tpu.parallel.mesh import (
    DATA_AXIS,
    barrier,
    batch_sharding,
    get_comm_size_and_rank,
    globalize_batch,
    local_device_count,
    local_view,
    make_mesh,
    make_multihost_mesh,
    nsplit,
    replicated_sharding,
    setup_distributed,
)
from hydragnn_tpu.parallel.edge_sharded import (
    make_dp_edge_eval_step,
    make_dp_edge_stats_step,
    make_dp_edge_train_step,
    place_dp_edge_batch,
    place_giant_batch,
)
from hydragnn_tpu.parallel.partitioner import (
    AXIS_ORDER,
    EDGE_AXIS,
    FSDP_AXIS,
    ParallelConfig,
    Partitioner,
    parallel_manifest_summary,
)
from hydragnn_tpu.parallel.sharded import (
    make_sharded_eval_step,
    make_sharded_stats_step,
    make_sharded_train_step,
    place_state,
)
