"""Device mesh setup and distributed runtime bootstrap.

TPU-native replacement for the reference's DDP bootstrap (reference:
hydragnn/utils/distributed.py:110-162): where the reference sniffs
LSF/SLURM env vars, picks NCCL/Gloo, and calls
``dist.init_process_group``, here multi-host rendezvous is
``jax.distributed.initialize()`` (coordinator-based; reads cluster env
automatically on TPU pods and SLURM) and the "process group" is a
``jax.sharding.Mesh`` over all global devices. Collectives are XLA ops
over ICI/DCN inserted by the compiler — there is no hand-written comm
layer to configure.

The single parallel axis is ``data`` (the reference's only model-parallel
axis is DP, SURVEY §2.2); the mesh helper accepts extra axes for headroom
(e.g. a future edge-sharded aggregation axis).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


# graftsync: thread-safe=written during single-threaded startup (setup_distributed must precede any other jax call, hence any worker thread)
_DISTRIBUTED_INITIALIZED = False


def nsplit(seq, n: int):
    """Split ``seq`` into ``n`` near-even contiguous chunks (the
    reference's work-sharding helper, hydragnn/utils/distributed.py:246-248)
    — used to shard file lists / generation work across processes."""
    k, m = divmod(len(seq), n)
    return (seq[i * k + min(i, m) : (i + 1) * k + min(i + 1, m)] for i in range(n))


def barrier(tag: str = "barrier") -> None:
    """Cross-process sync point (the reference's ``comm.Barrier()``
    pattern in the example drivers); no-op single-process."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def _multiprocess_env_configured() -> bool:
    """Pure env sniffing — MUST NOT touch any jax API that would
    initialize the XLA backend (``jax.distributed.initialize`` has to run
    first). The env set mirrors the reference's rendezvous discovery
    (distributed.py:77-94: OMPI_COMM_WORLD_*, SLURM_NPROCS)."""
    if os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    ):
        return True
    for var in ("SLURM_NPROCS", "OMPI_COMM_WORLD_SIZE"):
        if os.environ.get(var, "1") not in ("", "1"):
            return True
    return False


def setup_distributed() -> Tuple[int, int]:
    """Initialize the multi-host runtime when launched as one process per
    host (the analog of ``setup_ddp``, distributed.py:110-162). Call this
    BEFORE any other jax API — backend initialization (even
    ``jax.devices()``/``jax.process_count()``) forecloses
    ``jax.distributed.initialize``.

    Returns (world_size, rank) as (process_count, process_index).
    """
    global _DISTRIBUTED_INITIALIZED
    if _DISTRIBUTED_INITIALIZED or not _multiprocess_env_configured():
        return jax.process_count(), jax.process_index()
    # A mis-ordered call (backend already up) or bad coordinator config is
    # a real error: swallowing it would silently train unsynced replicas.
    jax.distributed.initialize()
    _DISTRIBUTED_INITIALIZED = True
    return jax.process_count(), jax.process_index()


def get_comm_size_and_rank() -> Tuple[int, int]:
    """Reference-parity name (distributed.py:95-107)."""
    return jax.process_count(), jax.process_index()


def make_mesh(
    n_devices: Optional[int] = None, axis_names: Sequence[str] = (DATA_AXIS,)
) -> Mesh:
    """A 1-D (default) mesh over the first ``n_devices`` global devices."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(f"requested {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    return Mesh(np.array(devices).reshape(shape), axis_names)


def make_multihost_mesh(
    per_process: int = 0, axis_names: Sequence[str] = (DATA_AXIS,)
) -> Mesh:
    """A 1-D mesh spanning every process: ``per_process`` devices from
    EACH process (0 = all of them), ordered by (process, device id) so a
    process's shards are contiguous on the data axis. This is the
    multi-host "process group" — every process must contribute devices or
    ``make_array_from_process_local_data`` has nowhere to place that
    process's batch shard."""
    by_proc: dict = {}
    for d in jax.devices():
        by_proc.setdefault(d.process_index, []).append(d)
    picked = []
    for proc in sorted(by_proc):
        devs = sorted(by_proc[proc], key=lambda d: d.id)
        n = per_process if per_process > 0 else len(devs)
        if n > len(devs):
            raise ValueError(
                f"process {proc} has {len(devs)} devices, need {n}"
            )
        picked.extend(devs[:n])
    shape = (len(picked),) + (1,) * (len(axis_names) - 1)
    return Mesh(np.array(picked).reshape(shape), axis_names)


def globalize_batch(mesh: Mesh, batch, axes=DATA_AXIS):
    """Assemble per-process local ``[D_local, ...]`` batch leaves into
    global ``jax.Array``s sharded over ``axes`` (default the data axis;
    the Partitioner passes its composed ``(data, fsdp)`` lead axes) on a
    multi-process mesh (global leading axis = D_local × process_count).
    This is the moment a multi-host batch becomes one logical array — the
    analog of the reference's implicit "each DDP rank owns its own
    sub-batch" contract (hydragnn/preprocess/load_data.py:229-231),
    expressed as a sharding instead of per-rank processes."""
    sh = NamedSharding(mesh, P(axes))
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(sh, np.asarray(x)), batch
    )


def local_view(arr) -> np.ndarray:
    """Host-local rows of an array whose leading axis is (possibly)
    sharded across processes: for a non-fully-addressable ``jax.Array``
    this concatenates the process's addressable shards in global index
    order; numpy / fully-addressable arrays pass through. Used to align
    sharded eval outputs with this process's slice of the batch."""
    if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
        shards = sorted(
            arr.addressable_shards, key=lambda s: s.index[0].start or 0
        )
        return np.concatenate([np.asarray(s.data) for s in shards], axis=0)
    return np.asarray(arr)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for loader output with a leading device axis [D, ...]."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def local_device_count() -> int:
    return jax.local_device_count()
