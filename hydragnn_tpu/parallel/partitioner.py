"""Unified ``Partitioner``: one sharding story for train, serve, and bench.

Before this module the repo carried three divergent sharding stories —
``parallel/sharded.py`` (data mesh + ZeRO-1 special case),
``parallel/edge_sharded.py`` (giant-graph edge axis), and ``serve/``'s
implicit single-device — and parameters/optimizer state always lived
fully replicated on every chip. The ``Partitioner`` owns all of it:

  - **mesh construction** over the composed ``(data, fsdp, edge)`` axis
    set, with auto-collapse of size-1 axes (a pure-DP run gets the same
    1-D ``("data",)`` mesh ``make_mesh`` built, so nothing recompiles);
  - **input sharding**: the loader's leading device axis ``[D, ...]``
    shards over ``data × fsdp`` (each device owns one sub-batch — the
    openpi ``(batch, fsdp)`` shape), edge-sharded CSR leaves additionally
    shard over ``edge``, pad-plan aware through the existing
    ``place_dp_edge_batch`` arithmetic;
  - **state sharding**: with ``fsdp > 1`` every parameter AND optimizer
    leaf shards its largest ``fsdp``-divisible dimension over the
    ``fsdp`` axis — XLA inserts the all-gather(params) /
    reduce-scatter(grads) pattern around the data-parallel step, which
    IS FSDP/ZeRO-style sharding, unlocking models whose parameters +
    optimizer state exceed one chip's HBM. The legacy ZeRO-1 mode
    (optimizer leaves over ``data``) is the ``fsdp == 1, zero1=True``
    special case of the same layout machinery. Leaves that cannot shard
    are replicated LOUDLY: one rank-0 warning with the leaf paths, and
    ``parallel.replicated_leaves`` in the flight manifest;
  - **step partitioning**: ``shard_init`` / ``shard_train_step`` /
    ``shard_eval_step`` / ``shard_stats_step`` used identically by
    ``train/loop.py``, ``serve/`` (registry warmup + bucket-ladder AOT
    compiles run under this mesh via :meth:`shard_variables` /
    :meth:`shard_inference_batch`), and ``bench_scaling.py`` /
    ``tools/scaling_estimate.py``.

Numerics: the fsdp axis only changes WHERE state bytes live, not what is
computed — the batch still splits over all ``data × fsdp`` devices and
gradients still ``pmean`` over all of them, so an ``(data=2, fsdp=4)``
run computes what the ``data=8`` run computes (modulo collective
reduction order). Correctness is pinned on a forced multi-device CPU
host mesh (``XLA_FLAGS=--xla_force_host_platform_device_count=8``) in
``tests/test_partitioner.py``. The collective set this layout implies —
all-reduce over ``data``/``data×fsdp``, all-gather/reduce-scatter only
over ``fsdp`` (or ``data`` for ZeRO-1), nothing else — is machine-checked
from the compiled step's HLO by graftcheck contract CC003
(docs/LINT.md), so a change here that leaks a new collective fails CI
before it costs wire time. See docs/PARALLELISM.md.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hydragnn_tpu.parallel.mesh import DATA_AXIS

FSDP_AXIS = "fsdp"
EDGE_AXIS = "edge"
# canonical axis order: data outermost (rows of sub-batches), fsdp inside
# it (state shards stay intra-host on multi-host meshes), edge innermost
AXIS_ORDER = (DATA_AXIS, FSDP_AXIS, EDGE_AXIS)


def _leaf_size(x) -> int:
    shape = getattr(x, "shape", None)
    if shape is None:
        return 0
    return int(np.prod(shape)) if len(shape) else 1


def _leaf_bytes(x) -> int:
    if not hasattr(x, "dtype"):
        return 0
    return _leaf_size(x) * int(np.dtype(x.dtype).itemsize)


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """Global axis widths of the composed ``(data, fsdp, edge)`` mesh.

    ``data``: sub-batches processed in parallel (DDP width). ``fsdp``:
    parameter/optimizer-state sharding width — the batch ALSO splits over
    this axis, so total sub-batches per step = ``data * fsdp``. ``edge``:
    per-sub-batch edge-array sharding width (giant graphs). ``zero1``:
    the legacy optimizer-state-over-``data`` layout; subsumed by (and
    ignored under) ``fsdp > 1``.
    """

    data: int = 1
    fsdp: int = 1
    edge: int = 1
    zero1: bool = False

    def __post_init__(self):
        for name in ("data", "fsdp", "edge"):
            v = getattr(self, name)
            if not isinstance(v, int) or v < 1:
                raise ValueError(
                    f"Parallel.{name} must be a positive integer, got {v!r}"
                )

    @property
    def num_devices(self) -> int:
        return self.data * self.fsdp * self.edge


class Partitioner:
    """Owns the mesh and every sharding decision of a run.

    Construct directly (``Partitioner(data=8)``,
    ``Partitioner(data=2, fsdp=4)``) or from a completed config via
    :meth:`from_config` (the ``NeuralNetwork.Parallel`` section). A
    config whose axes are all 1 yields the SINGLE-DEVICE partitioner:
    ``mesh is None``, every ``shard_*`` method degrades to the plain
    jitted single-device behavior, and callers need no special-casing —
    the "partitioner says single-device" signal the scan-epoch
    eligibility check consumes.
    """

    def __init__(
        self,
        config: Optional[ParallelConfig] = None,
        *,
        data: int = 1,
        fsdp: int = 1,
        edge: int = 1,
        zero1: bool = False,
        devices: Optional[Sequence[Any]] = None,
        multihost: bool = False,
    ):
        if config is None:
            config = ParallelConfig(data=data, fsdp=fsdp, edge=edge, zero1=zero1)
        self.config = config
        self.multihost = bool(multihost)
        self._warned_replicated = False
        self._replicated_leaves: List[str] = []
        self.mesh, self.axis_names = self._build_mesh(devices)

    # -- construction ------------------------------------------------------

    @classmethod
    def from_config(
        cls,
        nn_config: Dict[str, Any],
        device_stack: int = 1,
        multihost: bool = False,
        devices: Optional[Sequence[Any]] = None,
    ) -> "Partitioner":
        """Build from a (completed) ``NeuralNetwork`` config section.

        ``device_stack`` is the PER-PROCESS batch device axis the loaders
        were built with (``data_local * fsdp``); ``Parallel.fsdp`` must
        divide it so fsdp groups never span sub-batch boundaries — on
        multi-host meshes this also keeps every fsdp all-gather
        intra-host. ``Training.Optimizer.use_zero_redundancy`` maps to
        the legacy ZeRO-1 layout and is subsumed when ``fsdp > 1``."""
        par = dict(nn_config.get("Parallel") or {})
        fsdp = int(par.get("fsdp", 1) or 1)
        edge = int(par.get("edge", 1) or 1)
        zero1 = bool(
            nn_config.get("Training", {})
            .get("Optimizer", {})
            .get("use_zero_redundancy", False)
        )
        if device_stack % fsdp:
            raise ValueError(
                f"Parallel.fsdp={fsdp} must divide the batch device axis "
                f"(device_stack={device_stack}); pick an fsdp width that "
                "divides the local data-parallel width"
            )
        nproc = jax.process_count() if multihost else 1
        data = (device_stack // fsdp) * nproc
        if fsdp > 1 and zero1:
            # fsdp shards the optimizer state (and the parameters) over
            # its own axis — the ZeRO-1 special case is subsumed
            zero1 = False
        return cls(
            ParallelConfig(data=data, fsdp=fsdp, edge=edge, zero1=zero1),
            devices=devices,
            multihost=multihost,
        )

    def _ordered_devices(self, per_process: Optional[int] = None) -> List[Any]:
        """Process-major device list; in multihost mode each process
        contributes exactly ``per_process`` devices (its lowest-id ones),
        so every process owns a contiguous block of mesh rows and can
        feed its shard via ``make_array_from_process_local_data``."""
        if not self.multihost:
            return list(jax.devices())
        by_proc: Dict[int, list] = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, []).append(d)
        out: List[Any] = []
        for p in sorted(by_proc):
            devs = sorted(by_proc[p], key=lambda d: d.id)
            n = per_process if per_process is not None else len(devs)
            if n > len(devs):
                raise ValueError(
                    f"process {p} has {len(devs)} devices, the mesh needs "
                    f"{n} from each process"
                )
            out.extend(devs[:n])
        return out

    def _build_mesh(self, devices) -> Tuple[Optional[Mesh], Tuple[str, ...]]:
        c = self.config
        total = c.num_devices
        if total == 1 and not self.multihost:
            return None, ()
        if devices is None:
            per_proc = None
            if self.multihost:
                nproc = jax.process_count()
                if total % nproc:
                    raise ValueError(
                        f"{total} mesh devices do not divide evenly over "
                        f"{nproc} processes"
                    )
                per_proc = total // nproc
                if per_proc % (c.fsdp * c.edge):
                    raise ValueError(
                        f"fsdp*edge={c.fsdp * c.edge} must divide the "
                        f"per-process device count {per_proc} so no "
                        "fsdp/edge group spans hosts"
                    )
            devices = self._ordered_devices(per_proc)
        if total > len(devices):
            raise ValueError(
                f"parallel config (data={c.data}, fsdp={c.fsdp}, "
                f"edge={c.edge}) needs {total} devices, have {len(devices)}"
            )
        sizes = [(DATA_AXIS, c.data), (FSDP_AXIS, c.fsdp), (EDGE_AXIS, c.edge)]
        # auto-collapse size-1 axes: the spec/axis machinery only ever
        # names axes that exist, so a pure-DP mesh is exactly the 1-D
        # ("data",) mesh the pre-partitioner code built
        axes = [(n, s) for n, s in sizes if s > 1]
        if not axes:
            axes = [(DATA_AXIS, 1)]  # degenerate multihost: keep one axis
        shape = tuple(s for _, s in axes)
        names = tuple(n for n, _ in axes)
        mesh = Mesh(np.asarray(devices[:total]).reshape(shape), names)
        return mesh, names

    # -- topology ----------------------------------------------------------

    @property
    def single_device(self) -> bool:
        """True when this partitioner describes a plain single-device run
        — the signal scan-epoch eligibility and serve's fast path use
        instead of sniffing meshes themselves."""
        return self.mesh is None or self.mesh.size == 1

    @property
    def num_devices(self) -> int:
        return 1 if self.mesh is None else int(self.mesh.size)

    @property
    def lead_axes(self) -> Tuple[str, ...]:
        """Mesh axes the batch's leading device axis shards over."""
        return tuple(a for a in (DATA_AXIS, FSDP_AXIS) if a in self.axis_names)

    @property
    def lead_spec(self):
        """The PartitionSpec entry for the batch leading axis (a bare
        name, a tuple of names, or None when the batch is unsharded)."""
        ax = self.lead_axes
        if not ax:
            return None
        return ax[0] if len(ax) == 1 else ax

    @property
    def fsdp_factor(self) -> int:
        return self.config.fsdp

    @property
    def device_stack(self) -> int:
        """Sub-batches per PROCESS batch — what ``GraphLoader`` needs."""
        st = self.config.data * self.config.fsdp
        if self.multihost:
            st //= jax.process_count()
        return max(st, 1)

    @property
    def bn_axis_name(self):
        """Axis name(s) SyncBatchNorm reduces over under this mesh: the
        shard_map lead axes for the DP/FSDP step, the vmap's logical
        ``data`` axis for the edge-sharded step, None single-device."""
        if self.mesh is None:
            return None
        if self.config.edge > 1:
            return DATA_AXIS
        ax = self.lead_axes
        if not ax:
            return None
        return ax[0] if len(ax) == 1 else ax

    # -- input sharding ----------------------------------------------------

    def batch_sharding(self) -> Optional[NamedSharding]:
        """Sharding for loader output with a leading device axis [D, ...]."""
        if self.mesh is None:
            return None
        lead = self.lead_spec
        return NamedSharding(self.mesh, P(lead) if lead is not None else P())

    def replicated_sharding(self) -> Optional[NamedSharding]:
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, P())

    def shard_batch(self, batch):
        """Place one loader batch with this mesh's input layout (edge
        leaves additionally over ``edge`` when that axis exists)."""
        if self.mesh is None:
            return batch
        if self.config.edge > 1:
            from hydragnn_tpu.parallel.edge_sharded import place_dp_edge_batch

            if self.config.data * self.config.fsdp == 1:
                # edge-only mesh over an unstacked loader: the vmapped
                # edge step still wants a leading device axis [1, ...]
                batch = jax.tree_util.tree_map(
                    lambda x: np.asarray(x)[None], batch
                )
            return place_dp_edge_batch(self.mesh, batch, batch_axes=self.lead_axes)
        return jax.device_put(batch, self.batch_sharding())

    def shard_inference_batch(self, batch):
        """Serving-side batch placement: request batches are not
        data-sharded (one coalesced batch at a time) — they replicate on
        the mesh so the fsdp-sharded forward's executable sees one
        committed, deterministic input layout."""
        if self.mesh is None:
            return batch
        return jax.device_put(batch, self.replicated_sharding())

    def attach_loader(self, loader) -> None:
        """Point a ``GraphLoader`` at this mesh: multi-host loaders
        assemble global arrays over the lead axes, single-host loaders
        device_put with the batch sharding (or the per-field edge placer
        when the edge axis exists). Single-device: no-op."""
        if self.mesh is None:
            return
        if self.multihost:
            loader.set_global_mesh(self.mesh, axes=self.lead_spec)
        elif self.config.edge > 1:
            loader.set_placer(self.shard_batch)
        else:
            loader.set_sharding(self.batch_sharding())

    # -- state sharding ----------------------------------------------------

    def _fsdp_dim(self, shape) -> Optional[int]:
        """The dimension an fsdp-sharded leaf splits: the LARGEST one
        divisible by the fsdp width (largest → the biggest per-device
        byte saving; ties → lowest index for determinism)."""
        n = self.config.fsdp
        best = None
        for i, d in enumerate(shape):
            if d > 0 and d % n == 0:
                if best is None or d > shape[best]:
                    best = i
        return best

    def param_spec(self, x) -> P:
        """fsdp PartitionSpec for one parameter/optimizer leaf (``P()``
        when the leaf cannot shard: scalars, no divisible dimension, or
        ``fsdp == 1``)."""
        if self.config.fsdp <= 1 or getattr(x, "ndim", 0) == 0:
            return P()
        dim = self._fsdp_dim(x.shape)
        if dim is None:
            return P()
        return P(*([None] * dim + [FSDP_AXIS]))

    def _map_section(self, prefix: str, tree, report: List[str]):
        """Per-leaf NamedShardings for one state section under the fsdp
        rule, recording un-shardable non-scalar leaves into ``report``."""
        mesh = self.mesh
        rep = NamedSharding(mesh, P())

        def leaf(path, x):
            spec = self.param_spec(x)
            if len(spec) == 0:
                if getattr(x, "ndim", 0) >= 1 and _leaf_size(x) > 1:
                    report.append(prefix + jax.tree_util.keystr(path))
                return rep
            return NamedSharding(mesh, spec)

        return jax.tree_util.tree_map_with_path(leaf, tree)

    def state_sharding(self, state):
        """Per-leaf shardings for a ``TrainState`` — the single source of
        truth shared by initial placement (:meth:`shard_init`) and the
        per-step output constraint inside the partitioned train step."""
        shardings, _ = self._state_sharding_with_report(state)
        return shardings

    def _state_sharding_with_report(self, state):
        mesh = self.mesh
        if mesh is None:
            return None, []
        rep = NamedSharding(mesh, P())
        rep_tree = lambda tree: jax.tree_util.tree_map(lambda _: rep, tree)
        replicated: List[str] = []
        if self.config.fsdp > 1:
            params = self._map_section("params", state.params, replicated)
            opt = self._map_section("opt_state", state.opt_state, replicated)
        elif self.config.zero1 and DATA_AXIS in self.axis_names:
            from hydragnn_tpu.parallel.sharded import _zero1_leaf_shardings

            params = rep_tree(state.params)
            opt = _zero1_leaf_shardings(mesh, state.opt_state, replicated)
        else:
            params = rep_tree(state.params)
            opt = rep_tree(state.opt_state)
        return (
            type(state)(
                step=rep,
                params=params,
                batch_stats=rep_tree(state.batch_stats),
                opt_state=opt,
                rng=rep,
            ),
            replicated,
        )

    def _warn_replicated(self, paths: List[str]) -> None:
        if not paths or self._warned_replicated or jax.process_index() != 0:
            return
        self._warned_replicated = True
        axis = FSDP_AXIS if self.config.fsdp > 1 else DATA_AXIS
        width = self.config.fsdp if self.config.fsdp > 1 else (
            self.mesh.shape[DATA_AXIS] if self.mesh is not None else 1
        )
        shown = ", ".join(paths[:8]) + (", ..." if len(paths) > 8 else "")
        warnings.warn(
            f"Partitioner: {len(paths)} state leaf(ves) have no dimension "
            f"divisible by the {axis!r} axis width {width} and stay fully "
            f"REPLICATED on every device: {shown}. Recorded in the flight "
            "manifest as parallel.replicated_leaves.",
            RuntimeWarning,
            stacklevel=3,
        )

    def shard_init(self, state):
        """Place a host-built ``TrainState`` onto the mesh with this
        partitioner's layout (no-op single-device). Replicated-leaf
        fallbacks warn once, loudly, on rank 0."""
        if self.mesh is None:
            return state
        sh, replicated = self._state_sharding_with_report(state)
        self._replicated_leaves = replicated
        self._warn_replicated(replicated)
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s), state, sh
        )

    def shard_variables(self, variables: Dict[str, Any]) -> Dict[str, Any]:
        """Serving-side state placement: ``params`` shard over ``fsdp``
        (a served model bigger than one chip's HBM), everything else
        (batch_stats) replicates. No-op single-device."""
        if self.mesh is None:
            return variables
        rep = self.replicated_sharding()
        replicated: List[str] = []
        out: Dict[str, Any] = {}
        for section, tree in variables.items():
            if section == "params" and self.config.fsdp > 1:
                sh = self._map_section("params", tree, replicated)
            else:
                sh = jax.tree_util.tree_map(lambda _: rep, tree)
            out[section] = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, s), tree, sh
            )
        self._replicated_leaves = replicated
        self._warn_replicated(replicated)
        return out

    # -- step partitioning -------------------------------------------------

    def shard_train_step(self, model, tx, compute_dtype=None, remat: bool = False):
        """Jitted ``(state, batch[D-leading]) -> (state, loss, tasks)``
        partitioned for this mesh; the plain single-device jitted step
        when the partitioner is single-device."""
        if self.mesh is None:
            from hydragnn_tpu.train.state import make_train_step

            return make_train_step(
                model, tx, compute_dtype=compute_dtype, remat=remat
            )
        if self.config.edge > 1:
            if compute_dtype is not None:
                raise ValueError(
                    "the edge-sharded train step has no mixed-precision "
                    "path; drop Training.mixed_precision or Parallel.edge"
                )
            from hydragnn_tpu.parallel.edge_sharded import make_dp_edge_train_step

            return make_dp_edge_train_step(
                model,
                tx,
                self.mesh,
                batch_axes=self.lead_axes,
                state_sharding_fn=self.state_sharding,
            )
        from hydragnn_tpu.parallel.sharded import make_sharded_train_step

        return make_sharded_train_step(
            model,
            tx,
            self.mesh,
            zero1=self.config.zero1,
            compute_dtype=compute_dtype,
            remat=remat,
            batch_axes=self.lead_axes,
            state_sharding_fn=self.state_sharding if self.config.fsdp > 1 else None,
        )

    def shard_eval_step(self, model, with_outputs: bool = False):
        if self.mesh is None:
            from hydragnn_tpu.train.state import make_eval_step

            return make_eval_step(model, with_outputs=with_outputs)
        if self.config.edge > 1:
            from hydragnn_tpu.parallel.edge_sharded import make_dp_edge_eval_step

            return make_dp_edge_eval_step(
                model, self.mesh, with_outputs=with_outputs
            )
        from hydragnn_tpu.parallel.sharded import make_sharded_eval_step

        return make_sharded_eval_step(
            model,
            self.mesh,
            with_outputs=with_outputs,
            batch_axes=self.lead_axes,
        )

    def shard_stats_step(self, model):
        if self.mesh is None:
            from hydragnn_tpu.train.state import make_stats_step

            return make_stats_step(model)
        if self.config.edge > 1:
            from hydragnn_tpu.parallel.edge_sharded import make_dp_edge_stats_step

            return make_dp_edge_stats_step(model, self.mesh)
        from hydragnn_tpu.parallel.sharded import make_sharded_stats_step

        return make_sharded_stats_step(
            model, self.mesh, batch_axes=self.lead_axes
        )

    # -- introspection -----------------------------------------------------

    def _shard_factor(self, sharding) -> int:
        """How many ways a leaf under ``sharding`` splits across devices."""
        if self.mesh is None or not isinstance(sharding, NamedSharding):
            return 1
        f = 1
        for entry in sharding.spec:
            if entry is None:
                continue
            for a in entry if isinstance(entry, tuple) else (entry,):
                f *= int(self.mesh.shape[a])
        return f

    def _section_summary(self, tree, sh_tree) -> Dict[str, Any]:
        leaves = jax.tree_util.tree_leaves(tree)
        shs = (
            jax.tree_util.tree_leaves(
                sh_tree, is_leaf=lambda x: isinstance(x, NamedSharding)
            )
            if sh_tree is not None
            else [None] * len(leaves)
        )
        total = per_dev = 0
        sharded = 0
        for x, s in zip(leaves, shs):
            b = _leaf_bytes(x)
            f = self._shard_factor(s)
            total += b
            per_dev += -(-b // f) if f > 1 else b  # ceil-divide real shards
            if f > 1:
                sharded += 1
        return {
            "leaves": len(leaves),
            "sharded": sharded,
            "bytes_global": int(total),
            "bytes_per_device": int(per_dev),
        }

    def layout_fingerprint(self) -> Dict[str, Any]:
        """Compact, JSON-stable identity of the committed layout — what
        the pod checkpoint protocol (resilience/podckpt.py) stamps into
        every shard manifest and COMMIT marker so a restore can tell
        "same layout, place shards directly" from "different layout,
        reassemble leaves elastically", and lineage events can name the
        PRIOR layout a resumed run came from."""
        c = self.config
        fp: Dict[str, Any] = {
            "data": int(c.data),
            "fsdp": int(c.fsdp),
            "edge": int(c.edge),
            "zero1": bool(c.zero1),
            "devices": None if self.mesh is None else int(self.mesh.size),
        }
        try:
            from hydragnn_tpu.obs.podview import host_identity

            _, fp["hosts"] = host_identity()
        except Exception:
            fp["hosts"] = 1
        return fp

    def manifest(self, state=None, variables=None) -> Dict[str, Any]:
        """The flight-record ``parallel`` block: mesh shape and axis
        names, axis widths, and (given a ``state`` or served
        ``variables``) the per-leaf parameter/optimizer sharding summary,
        per-device bytes, and the replicated-leaf fallback list —
        surfaced by ``tools/obs_report.py`` (docs/PARALLELISM.md)."""
        c = self.config
        info: Dict[str, Any] = {
            "available": True,
            "single_device": self.single_device,
            "mesh": None
            if self.mesh is None
            else {
                "shape": {str(k): int(v) for k, v in self.mesh.shape.items()},
                "axis_names": list(self.axis_names),
                "devices": int(self.mesh.size),
            },
            "data": c.data,
            "fsdp": c.fsdp,
            "edge": c.edge,
            "zero1": bool(c.zero1),
            "multihost": self.multihost,
            "device_stack": self.device_stack,
        }
        # pod-visibility identity (obs/podview.py): which host committed
        # this layout and how many peers it expects — the inputs the
        # SkewMonitor's collective-aware cost attribution joins on
        try:
            from hydragnn_tpu.obs.podview import host_identity

            info["process_index"], info["process_count"] = host_identity()
        except Exception:
            pass
        info["layout"] = self.layout_fingerprint()
        if state is not None:
            sh, replicated = self._state_sharding_with_report(state)
            info["params"] = self._section_summary(
                state.params, sh.params if sh is not None else None
            )
            info["opt"] = self._section_summary(
                state.opt_state, sh.opt_state if sh is not None else None
            )
            info["replicated_leaves"] = list(replicated)
        elif variables is not None:
            replicated: List[str] = []
            params = variables.get("params", {})
            sh = (
                self._map_section("params", params, replicated)
                if self.mesh is not None and c.fsdp > 1
                else None
            )
            info["params"] = self._section_summary(params, sh)
            info["replicated_leaves"] = list(replicated)
        return info


def parallel_manifest_summary(par: Dict[str, Any]) -> str:
    """One-line human rendering of a flight ``parallel`` block (used by
    ``tools/obs_report.py``)."""
    mesh = par.get("mesh")
    if not mesh:
        shape = "single-device"
    else:
        shape = "×".join(
            f"{k}{v}" for k, v in (mesh.get("shape") or {}).items()
        )
    parts = [f"mesh={shape}", f"fsdp={par.get('fsdp', 1)}"]
    p = par.get("params")
    if p:
        parts.append(
            f"params {p['sharded']}/{p['leaves']} leaves sharded, "
            f"{p['bytes_per_device']}/{p['bytes_global']} B/device"
        )
    o = par.get("opt")
    if o:
        parts.append(
            f"opt {o['sharded']}/{o['leaves']} sharded, "
            f"{o['bytes_per_device']}/{o['bytes_global']} B/device"
        )
    reps = par.get("replicated_leaves")
    if reps:
        parts.append(f"replicated_leaves={len(reps)}")
    return " ".join(parts)
