"""ModelServer: the batched online-inference front end.

Wiring: requests (single prepared graphs) -> bucket router
(``serve/buckets.py``) -> deadline micro-batcher (``serve/batcher.py``)
-> one executor thread that pads the coalesced batch to the bucket's
plan, runs the AOT-compiled forward, and slices per-request results out
of the padded outputs. Degradation is graceful by construction:

  - a graph over every routing cap but under the LARGEST bucket's pad
    plan dispatches immediately as a batch-of-1 on that bucket (no new
    compile, just wasted padding);
  - a graph over even the largest plan takes the eager path — its own
    natural pad, compiled on first sight (counted as a compile-cache
    MISS: the operator signal that the ladder no longer covers traffic);
  - a full queue rejects with :class:`~hydragnn_tpu.serve.batcher.
    Overloaded` instead of buffering unboundedly.

Requests carry NO targets (there is nothing to supervise at inference
time); the builder strips them so request batches and warmup batches
share one pytree structure — an AOT executable is shape-exact.

Resilience (docs/RESILIENCE.md "Serving resilience"): a request whose
forward raises or returns non-finite values fails ONLY its own future
with the typed :class:`RequestFailed` (multi-request batches are
re-run once as singles to localize the poison; confirmed poisons are
quarantined); the dispatch thread runs under an in-process restart
supervisor with a re-armed hang watchdog (``serve/supervise.py``);
:meth:`ModelServer.health` is the liveness/readiness probe surface
(exported to the Prometheus textfile, read by ``tools/serve_probe.py``);
and :meth:`ModelServer.reload` swaps in new weights with zero downtime
— canary-validated against the existing bucket executables, rolled
back on any failure.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from hydragnn_tpu.resilience import inject
from hydragnn_tpu.serve.batcher import (
    MicroBatchQueue,
    Overloaded,
    PendingRequest,
    ServerClosed,
)
from hydragnn_tpu.serve.buckets import Bucket, BucketCompileCache, build_bucket_ladder, route
from hydragnn_tpu.serve.metrics import ServeMetrics
from hydragnn_tpu.utils import knobs, syncdebug
from hydragnn_tpu.serve.registry import ServedModel


class Oversize(RuntimeError):
    """Request exceeds every bucket and the eager fallback is disabled."""


class RequestFailed(RuntimeError):
    """One request's forward raised or produced non-finite outputs.

    Only the offending request's future carries this — co-batched
    requests and the dispatch loop are unaffected. ``seq`` is the
    request's admission sequence number, ``reason`` is ``"exception"``
    or ``"nonfinite"`` (``"dispatch"`` when the dispatch thread itself
    died with the batch in hand)."""

    def __init__(self, message: str, seq: int = -1, reason: str = "exception"):
        super().__init__(message)
        self.seq = seq
        self.reason = reason


class ReloadFailed(RuntimeError):
    """A hot reload's candidate weights failed to load or failed the
    canary; the previous weights are still serving (rollback)."""


def _result_finite(result: Dict[str, np.ndarray]) -> bool:
    return all(np.all(np.isfinite(v)) for v in result.values())


def _corrupt_variables(variables: Dict[str, Any]) -> Dict[str, Any]:
    """Torn-reload injection: NaN every float leaf — the canary must
    reject this candidate and the old weights must keep serving."""
    import jax

    def nan_like(a):
        arr = np.asarray(a)
        if np.issubdtype(arr.dtype, np.floating):
            return np.full_like(arr, np.nan)
        return a

    return jax.tree_util.tree_map(nan_like, variables)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Knobs of the serving path.

    max_batch: graphs coalesced per device dispatch (bucket batch size).
    num_buckets: pad-plan ladder size (before dedup of identical plans).
    max_delay_ms: deadline before a partial batch flushes — the latency
      budget a request can pay waiting for co-batched traffic.
    max_pending: bounded queue across all buckets; beyond it ``submit``
      raises Overloaded (explicit backpressure).
    eager_fallback: compile-on-demand natural-pad path for graphs larger
      than every bucket plan; off -> such requests raise Oversize.
    check_finite: scan each request's sliced outputs on the host and
      fail non-finite ones with RequestFailed (poison isolation) — a
      NaN answer is a corruption served as truth; off only if the
      output-scan cost ever matters more than that.
    dispatch_stall_s: watchdog threshold for a wedged forward (liveness
      flips false after this long with a batch in flight and no beat).
    max_dispatch_restarts / dispatch_backoff_*: the in-process restart
      policy for a dead dispatch thread (SupervisorPolicy semantics,
      serving-scale defaults — requests are waiting, so backoff starts
      at 50 ms, not seconds).
    ready_queue_highwater: readiness flips false when the queue holds
      more than this fraction of max_pending (the orchestrator should
      steer traffic away BEFORE submit starts raising Overloaded).
    prometheus_path: when set, the supervisor's monitor thread writes
      the health + metrics textfile there every prometheus_every_s —
      the file ``tools/serve_probe.py`` probes.
    exec_cache_dir: persistent AOT executable cache directory
      (``utils/exec_cache.py``) — the bucket ladder deserializes from
      here instead of compiling when a previous process already paid
      (warm cold-start: a second replica or post-restart server starts
      with 0 compiles). Default: the ``HYDRAGNN_EXEC_CACHE`` env var;
      unset -> the cache is inert and startup compiles as before.
    """

    max_batch: int = 8
    num_buckets: int = 3
    max_delay_ms: float = 5.0
    max_pending: int = 256
    node_multiple: int = 16
    edge_multiple: int = 8
    eager_fallback: bool = True
    latency_window: int = 2048
    check_finite: bool = True
    dispatch_stall_s: float = 30.0
    max_dispatch_restarts: int = 5
    dispatch_backoff_base_s: float = 0.05
    dispatch_backoff_factor: float = 2.0
    dispatch_backoff_max_s: float = 2.0
    ready_queue_highwater: float = 0.9
    prometheus_path: Optional[str] = None
    prometheus_every_s: float = 5.0
    exec_cache_dir: Optional[str] = None
    # SLO trigger rules (obs/triggers.py) — None disables a rule, so a
    # default-config server runs exactly as before. When any rule is
    # set, the dispatch loop evaluates the engine every
    # trigger_eval_every_s and a firing rule opens an incident bundle
    # (bounded profiler capture + evidence sidecars) under
    # incident_dir (default: <log_dir>/serve/incidents).
    slo_p99_ms: Optional[float] = None
    slo_queue_depth: Optional[int] = None
    slo_queue_age_s: Optional[float] = None
    trigger_eval_every_s: float = 1.0
    incident_dir: Optional[str] = None
    # Served-traffic spool + drift observability (obs/spool.py /
    # obs/drift.py; docs/OBSERVABILITY.md "Drift detection").
    # Spool: every spool_sample'th answered request (inputs +
    # per-head predictions + trace/tenant/fingerprint) appended to
    # rotating HGC shards under spool_dir (default
    # <log_dir>/serve/spool), disk-bounded to spool_max_mb. Enabled by
    # spool=True / spool_sample>0 / HYDRAGNN_SPOOL=1; the 0-defaults
    # resolve through HYDRAGNN_SPOOL_SAMPLE / HYDRAGNN_SPOOL_MAX_MB.
    # Drift: drift_ref (or HYDRAGNN_DRIFT_REF) names the training
    # reference window; arming it builds a DriftMonitor and, per
    # non-None threshold, a feature_drift / pred_drift / error_drift
    # trigger rule on the same engine cadence as the SLO rules.
    spool: bool = False
    spool_sample: int = 0
    spool_max_mb: float = 0.0
    spool_shard_mb: float = 1.0
    spool_dir: Optional[str] = None
    drift_ref: Optional[str] = None
    # pred drift is self-baselined on the session's own early window
    # (obs/drift.py:_HeadSketch), so its clean-traffic noise floor is a
    # two-sample PSI — the threshold sits higher than feature drift's.
    drift_feature_psi: Optional[float] = 0.25
    drift_pred_psi: Optional[float] = 0.5
    drift_error_score: Optional[float] = 3.0
    drift_min_count: int = 64


def request_to_dict(sample: Any) -> Dict[str, Any]:
    """Normalize a request (GraphSample or graph dict) to the dict form
    ``graph/batch.py:batch_graphs`` consumes, WITHOUT targets."""
    if isinstance(sample, dict):
        g = dict(sample)
        if "senders" not in g:
            ei = g.pop("edge_index", None)
            if ei is None:
                raise ValueError("request dict needs 'senders'/'receivers' or 'edge_index'")
            ei = np.asarray(ei)
            g["senders"], g["receivers"] = ei[0], ei[1]
    else:
        if getattr(sample, "edge_index", None) is None:
            raise ValueError("request sample has no edge_index (no edges built)")
        g = {
            "x": sample.x,
            "senders": sample.edge_index[0],
            "receivers": sample.edge_index[1],
        }
        if getattr(sample, "pos", None) is not None:
            g["pos"] = sample.pos
        if getattr(sample, "edge_attr", None) is not None:
            g["edge_attr"] = sample.edge_attr
    g.pop("graph_targets", None)
    g.pop("node_targets", None)
    return g


def _dict_sizes(g: Dict[str, Any]) -> tuple:
    return int(np.asarray(g["x"]).shape[0]), int(np.asarray(g["senders"]).shape[0])


class ModelServer:
    """Batched online inference over one :class:`ServedModel`.

    ``reference_samples`` size the bucket ladder and fix the request
    FIELD SPEC (feature width, pos/edge_attr presence) every request
    must match — use the prepared dataset the model was trained on.
    """

    def __init__(
        self,
        served: ServedModel,
        reference_samples: Sequence,
        config: Optional[ServeConfig] = None,
        metrics: Optional[ServeMetrics] = None,
        flight=None,
    ):
        if not reference_samples:
            raise ValueError("reference_samples must be non-empty (sizes the buckets)")
        self.served = served
        self.config = config or ServeConfig()
        # ONE sharding story with training (docs/PARALLELISM.md): the
        # served model's Partitioner owns the serving mesh — fsdp-sharded
        # variables, request/warmup batches placed replicated on the same
        # mesh so every AOT executable sees one committed layout. The
        # default Partitioner is the single-device story: every placement
        # below is a no-op.
        if served.partitioner is not None:
            self.partitioner = served.partitioner
        else:
            from hydragnn_tpu.parallel import Partitioner

            self.partitioner = Partitioner()
        self.buckets: List[Bucket] = build_bucket_ladder(
            reference_samples,
            self.config.max_batch,
            num_buckets=self.config.num_buckets,
            node_multiple=self.config.node_multiple,
            edge_multiple=self.config.edge_multiple,
        )
        self.metrics = metrics or ServeMetrics(
            len(self.buckets), latency_window=self.config.latency_window
        )
        ref = request_to_dict(reference_samples[0])
        ref_x = np.asarray(ref["x"])
        ref_ea = np.asarray(ref["edge_attr"]) if "edge_attr" in ref else None
        self._spec = {
            "feat_dim": int(ref_x.shape[1]) if ref_x.ndim > 1 else 1,
            "has_pos": "pos" in ref,
            "pos_dim": int(np.asarray(ref["pos"]).shape[-1]) if "pos" in ref else 0,
            "has_edge_attr": ref_ea is not None,
            "edge_dim": (
                int(ref_ea.shape[-1]) if ref_ea is not None and ref_ea.ndim > 1 else (1 if ref_ea is not None else 0)
            ),
        }
        # optional run flight recorder (hydragnn_tpu/obs/flight.py):
        # start() logs a serving manifest (bucket ladder, request spec),
        # stop() the final metrics snapshot — bench_serve.py passes one
        # so a serving bench leaves the same evidence artifact training
        # runs do. None -> an inert recorder; no call site needs a gate.
        # (Built BEFORE the compile cache so exec-cache events land in it.)
        if flight is None:
            from hydragnn_tpu.obs import FlightRecorder

            flight = FlightRecorder(None, enabled=False)
        self.flight = flight
        # persistent AOT executable cache (utils/exec_cache.py): keyed
        # by architecture + bucket pad plan, validated against versions /
        # device_kind / the partitioner layout. Serving forwards used
        # here are donation-free on CPU and value-independent, so they
        # cache unconditionally.
        from hydragnn_tpu.utils.exec_cache import (
            ExecCache,
            abstract_fingerprint,
            compat_manifest,
        )

        pcfg = self.partitioner.config
        self._exec_cache = ExecCache(
            self.config.exec_cache_dir or knobs.raw("HYDRAGNN_EXEC_CACHE"),
            flight=self.flight,
            metrics=self.metrics,
            consumer="serve",
        )
        self._cache = BucketCompileCache(
            served.forward,
            served.variables,
            self._build_warm_batch,
            metrics=self.metrics,
            exec_cache=self._exec_cache,
            identity=(
                served.nn_config
                if getattr(served, "nn_config", None) is not None
                else repr(served.cfg),
                abstract_fingerprint(served.variables),
                dict(self._spec),
            ),
            compat=compat_manifest(layout=(pcfg.data, pcfg.fsdp, pcfg.edge)),
        )
        # graftsync: thread-safe=MicroBatchQueue is internally synchronized (its own Condition); the reference itself is set once here
        self._queue = MicroBatchQueue(
            len(self.buckets),
            self.config.max_batch,
            self.config.max_delay_ms / 1e3,
            self.config.max_pending,
        )
        # graftsync: guarded-by=server.ModelServer._eager_lock
        self._eager_shapes: set = set()
        self._eager_lock = syncdebug.maybe_wrap(
            threading.Lock(), "server.ModelServer._eager_lock"
        )
        # graftsync: thread-safe=GIL-atomic bool lifecycle flags written by the owning thread in start()/stop(); a racing submit sees at worst one stale admit, which the closed queue then rejects
        self._started = False
        # graftsync: thread-safe=GIL-atomic one-way False->True latch set by the owning thread in stop()
        self._stopped = False
        self._seq = itertools.count()  # admission sequence (injection anchor)
        # graftsync: thread-safe=only the single dispatch thread increments (in _run)
        self._dispatched_batches = 0
        self._reload_lock = syncdebug.maybe_wrap(
            threading.Lock(), "server.ModelServer._reload_lock"
        )
        # graftsync: thread-safe=written by the owning thread in start()/stop() before/after the dispatch threads exist; others read the reference
        self._supervisor = None  # built in start()
        self.log_dir = "./logs/"  # reload()'s default checkpoint root
        # per-request tracing + SLO triggers, built in start() (the
        # incident root defaults under log_dir, which api.serve_model
        # stamps after construction)
        # graftsync: thread-safe=written once in start() before the dispatch thread spawns; Tracer is internally synchronized
        self._tracer = None
        # graftsync: thread-safe=written once in start() before the dispatch thread spawns
        self._triggers = None
        # graftsync: thread-safe=written once in start() before the dispatch thread spawns; IncidentRecorder is internally synchronized
        self._incidents = None
        # graftsync: thread-safe=only the dispatch thread writes (_maybe_trigger runs on the dispatch loop)
        self._last_trigger_eval = 0.0
        # served-traffic spool + drift monitor (obs/spool.py /
        # obs/drift.py), built in start() when configured/armed
        # graftsync: thread-safe=written once in start() before the dispatch thread spawns (and disarmed only by the dispatch thread); RequestSpool is internally synchronized
        self._spool = None
        # graftsync: thread-safe=written once in start() before the dispatch thread spawns (and disarmed only by the dispatch thread); only the dispatch thread feeds it
        self._drift = None
        # the spool/drift arming blocks start() stamped into run_start —
        # public so benches can carry them in their committed records
        # graftsync: thread-safe=written once in start() before the dispatch thread spawns
        self.obs_arming = {"spool": {"enabled": False}, "drift": {"armed": False}}
        # graftsync: thread-safe=written once in start() before the dispatch thread spawns
        self._t_started = 0.0
        # retrain pilot (pilot/pilot.py), attached via attach_pilot()
        # graftsync: thread-safe=written once by attach_pilot() before traffic flows; the dispatch thread only reads the reference
        self._pilot = None
        self._pin_lock = syncdebug.maybe_wrap(
            threading.Lock(), "server.ModelServer._pin_lock"
        )
        # spool shards pinned per open incident id (released by the
        # recorder's on_close hook) — no incident bundle may point at
        # traffic the spool has already evicted
        # graftsync: guarded-by=server.ModelServer._pin_lock
        self._incident_pins: Dict[str, List[str]] = {}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ModelServer":
        """AOT-compile the whole bucket ladder, then start the executor
        thread under its supervisor. Returns self
        (``serve_model(...).start()`` chains)."""
        if self._started:
            return self
        if self._stopped:
            raise ServerClosed("server was stopped; build a new one")
        t0 = time.monotonic()
        self._cache.warmup(self.buckets)
        # graftcheck contract block (lint/ir.py, docs/LINT.md CC rules):
        # audit the serve forward's OWN lowered module (smallest bucket)
        # so the serving manifest says which compiled-IR contracts its
        # ladder passed. One trace, no compile; HYDRAGNN_GRAFTCHECK=0
        # skips it and any failure degrades to not_checked.
        from hydragnn_tpu.lint.ir import contract_block

        graftcheck_block = contract_block(None)
        if knobs.get_bool("HYDRAGNN_GRAFTCHECK", True):
            try:
                _b0 = self.buckets[0]
                _pcfg = self.partitioner.config
                graftcheck_block = contract_block(
                    self.served.forward.lower(
                        self.served.variables, self._build_warm_batch(_b0)
                    ).as_text(),
                    donated=False,  # serve forwards are donation-free
                    conv_bf16=bool(getattr(self.served.cfg, "conv_bf16", False)),
                    edge_pad=int(_b0.edge_pad),
                    data=int(_pcfg.data),
                    fsdp=int(_pcfg.fsdp),
                    zero1=bool(getattr(_pcfg, "zero1", False)),
                )
            except Exception:
                pass
        # served-traffic spool + drift monitor — built BEFORE start_run
        # so the manifest records whether they were armed (obs_report
        # --validate surfaces un-armed drift monitoring on bench runs)
        spool_block, drift_block = self._build_spool_drift()
        self.obs_arming = {"spool": spool_block, "drift": drift_block}
        self.flight.start_run(
            {
                "mode": "serve",
                "serve_config": dataclasses.asdict(self.config),
                "request_spec": dict(self._spec),
                "buckets": [
                    {
                        "cap_nodes": b.cap_nodes,
                        "cap_edges": b.cap_edges,
                        "node_pad": b.node_pad,
                        "edge_pad": b.edge_pad,
                        "graph_pad": b.graph_pad,
                    }
                    for b in self.buckets
                ],
                "warmup_compile_s": round(time.monotonic() - t0, 3),
                # persistent-executable-cache outcome of this warmup: a
                # warm start shows hits == len(buckets) and 0 live
                # compiles (compile_warmup in the metrics snapshot)
                "exec_cache": self._exec_cache.manifest(),
                # which mesh the ladder compiled under + the served
                # parameter sharding summary (fsdp serving)
                "parallel": self.partitioner.manifest(
                    variables=self.served.variables
                ),
                # which compiled-IR contracts (docs/LINT.md CC rules)
                # the serve forward's lowered module passed
                "graftcheck": graftcheck_block,
                # served-traffic spool + drift observability arming
                # (docs/OBSERVABILITY.md "Drift detection")
                "spool": spool_block,
                "drift": drift_block,
            }
        )
        self._t_started = t0
        from hydragnn_tpu.resilience.supervisor import SupervisorPolicy
        from hydragnn_tpu.serve.supervise import DispatchSupervisor

        cfg = self.config
        # per-request tracing (obs/trace.py): every admitted request
        # gets a trace ID + span list; every Nth finished trace lands
        # in the flight record as a trace_capture event
        from hydragnn_tpu.obs.trace import Tracer

        self._tracer = Tracer(flight=self.flight)
        # declarative SLO rules -> trigger engine + incident recorder
        # (obs/triggers.py); no rules configured -> both stay None and
        # the dispatch loop pays one attribute check per batch
        rules = []
        mp = self.metrics.prefix
        if cfg.slo_p99_ms is not None:
            from hydragnn_tpu.obs.triggers import TriggerRule

            rules.append(
                TriggerRule(
                    "serve_p99", "latency_p99", f"{mp}.latency_s",
                    cfg.slo_p99_ms / 1e3,
                )
            )
        if cfg.slo_queue_depth is not None:
            from hydragnn_tpu.obs.triggers import TriggerRule

            rules.append(
                TriggerRule(
                    "serve_queue_depth", "queue_depth", f"{mp}.queue_depth",
                    float(cfg.slo_queue_depth),
                )
            )
        if cfg.slo_queue_age_s is not None:
            from hydragnn_tpu.obs.triggers import TriggerRule

            rules.append(
                TriggerRule(
                    "serve_queue_age", "queue_age",
                    f"{mp}.queue_oldest_age_s", float(cfg.slo_queue_age_s),
                )
            )
        if self._drift is not None:
            # drift rules read the DriftMonitor's gauges on the same
            # engine cadence as the SLO rules; a breach opens an
            # incident whose bundle carries the full drift report and
            # the offending spool window (_attach_drift_evidence)
            from hydragnn_tpu.obs.triggers import TriggerRule

            for name, kind, gauge, thresh in (
                ("serve_feature_drift", "feature_drift",
                 "drift.feature_psi", cfg.drift_feature_psi),
                ("serve_pred_drift", "pred_drift",
                 "drift.pred_psi", cfg.drift_pred_psi),
                ("serve_error_drift", "error_drift",
                 "drift.error_score", cfg.drift_error_score),
            ):
                if thresh is not None:
                    rules.append(
                        TriggerRule(name, kind, f"{mp}.{gauge}", float(thresh))
                    )
        if rules:
            from hydragnn_tpu.obs.triggers import IncidentRecorder, TriggerEngine

            self._triggers = TriggerEngine(rules, registry=self.metrics.registry)
            self._incidents = IncidentRecorder(
                cfg.incident_dir
                or os.path.join(self.log_dir, "serve", "incidents"),
                registry=self.metrics.registry,
                flight_path=self.flight.path,
                on_close=self._on_incident_close,
            )
        self._supervisor = DispatchSupervisor(
            self._run,
            policy=SupervisorPolicy(
                max_restarts=cfg.max_dispatch_restarts,
                backoff_base_s=cfg.dispatch_backoff_base_s,
                backoff_factor=cfg.dispatch_backoff_factor,
                backoff_max_s=cfg.dispatch_backoff_max_s,
            ),
            stall_s=cfg.dispatch_stall_s,
            flight=self.flight,
            metrics=self.metrics,
            on_giveup=self._on_dispatch_giveup,
            on_tick=self._export_tick if cfg.prometheus_path else None,
            tick_every_s=cfg.prometheus_every_s,
        )
        self._started = True
        self._supervisor.start()
        return self

    def stop(self, timeout: Optional[float] = 10.0) -> None:
        """Stop admitting, drain what is queued, join the executor."""
        was_started = self._started
        self._stopped = True
        self._queue.close()
        if self._supervisor is not None:
            self._supervisor.stop(timeout)
        self._started = False
        if was_started:
            # close any open incident (capture stopped, manifest
            # written) BEFORE the final snapshot so the run_end trigger
            # block counts it
            if self._incidents is not None:
                self._incidents.finalize()
            extra = {}
            if self._triggers is not None:
                extra["triggers"] = self._triggers.summary(
                    self._incidents.capture_s if self._incidents else 0.0
                )
            if self._spool is not None:
                # flush the tail shard + stamp the measured spool cost
                # as a fraction of serve wall time (the CI overhead gate)
                spool_summary = self._spool.finalize()
                wall = max(time.monotonic() - self._t_started, 1e-9)
                spool_summary["overhead_frac"] = round(
                    spool_summary["overhead_s"] / wall, 6
                )
                extra["spool"] = spool_summary
            if self._drift is not None:
                extra["drift"] = self._drift.summary()
            self.flight.end_run(
                status="stopped", metrics=self.metrics_snapshot(), **extra
            )

    def _build_spool_drift(self) -> tuple:
        """Resolve spool/drift config (explicit ServeConfig fields win
        over the HYDRAGNN_SPOOL* / HYDRAGNN_DRIFT_REF knobs), build the
        enabled pieces, and return the two manifest blocks. A drift_ref
        that fails to load is a loud start() failure — silently serving
        unmonitored when monitoring was requested is the one outcome
        this plane exists to prevent."""
        cfg = self.config
        spool_block: Dict[str, Any] = {"enabled": False}
        spool_on = (
            cfg.spool
            or cfg.spool_sample > 0
            or knobs.get_bool("HYDRAGNN_SPOOL", False)
        )
        if spool_on:
            from hydragnn_tpu.obs.spool import RequestSpool
            from hydragnn_tpu.utils.exec_cache import abstract_fingerprint

            sample = cfg.spool_sample or knobs.get_int(
                "HYDRAGNN_SPOOL_SAMPLE", 8
            )
            max_mb = cfg.spool_max_mb or knobs.get_float(
                "HYDRAGNN_SPOOL_MAX_MB", 64.0
            )
            mcfg = self.served.cfg
            self._spool = RequestSpool(
                cfg.spool_dir or os.path.join(self.log_dir, "serve", "spool"),
                sample_every=int(sample),
                max_mb=float(max_mb),
                shard_mb=cfg.spool_shard_mb,
                model_fingerprint=abstract_fingerprint(self.served.variables),
                head_kinds={
                    mcfg.output_names[i]: mcfg.output_type[i]
                    for i in range(mcfg.num_heads)
                },
                flight=self.flight,
            )
            spool_block = {
                "enabled": True,
                "dir": self._spool.root,
                "sample_every": int(sample),
                "max_mb": float(max_mb),
            }
        drift_block: Dict[str, Any] = {"armed": False}
        ref_path = cfg.drift_ref or knobs.raw("HYDRAGNN_DRIFT_REF")
        if ref_path:
            from hydragnn_tpu.obs.drift import DriftMonitor, load_reference

            self._drift = DriftMonitor(
                load_reference(ref_path),
                self.metrics.registry,
                prefix=self.metrics.prefix,
                min_count=cfg.drift_min_count,
            )
            drift_block = {
                "armed": True,
                "ref": ref_path,
                "channels": self._drift.num_channels,
                "min_count": cfg.drift_min_count,
                "thresholds": {
                    "feature_psi": cfg.drift_feature_psi,
                    "pred_psi": cfg.drift_pred_psi,
                    "error_score": cfg.drift_error_score,
                },
            }
        return spool_block, drift_block

    def _on_dispatch_giveup(self, exc: BaseException) -> None:
        """Restart budget exhausted: a loudly dead server. Close
        admission (submit raises ServerClosed) and fail everything
        queued with the typed error — zero silently wedged futures."""
        self._queue.close()
        self._queue.cancel_pending(
            RequestFailed(
                f"dispatch supervisor gave up after "
                f"{self.config.max_dispatch_restarts} restarts: {exc!r}",
                reason="dispatch",
            )
        )
        self.flight.error(exc, where="dispatch_giveup")

    def __enter__(self) -> "ModelServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- request path ------------------------------------------------------

    def submit(self, sample: Any, tenant: str = "default") -> Future:
        """Admit one graph; returns a Future resolving to
        ``{head_name: np.ndarray}`` (graph heads: [d]; node heads:
        [n_nodes, d], this graph's rows only). Raises Overloaded on
        backpressure, Oversize when nothing can take the graph, and
        ServerClosed after stop() — typed and immediate, never a future
        that can no longer resolve. ``tenant`` rides along for spool
        attribution (the fleet router stamps the admitting tenant)."""
        if self._stopped or (self._supervisor is not None and self._supervisor.failed):
            raise ServerClosed("server is stopped; submissions are rejected")
        if not self._started:
            raise RuntimeError("server not started (call start())")
        g = self._validated(request_to_dict(sample))
        # deterministic covariate-shift injection (drift self-test):
        # applied at admission so the sketches AND the model see it
        g["x"] = inject.maybe_drift_shift(g["x"])
        n, e = _dict_sizes(g)
        seq = next(self._seq)
        trace = self._tracer.begin(seq=seq) if self._tracer is not None else None
        bucket = route(self.buckets, n, e)
        if bucket is not None:
            if trace is not None:
                trace.mark("serve.route", bucket=bucket.index)
            self.metrics.record_request(bucket.index)
            try:
                fut = self._queue.put(
                    bucket.index, g, seq=seq, trace=trace, tenant=tenant
                )
            except Overloaded:
                self.metrics.record_reject()
                raise
            self.metrics.set_queue_depth(
                self._queue.depth(), self._queue.oldest_age_s()
            )
            return fut
        return self._submit_oversize(g, n, e, seq, trace, tenant)

    def predict(self, sample: Any, timeout: Optional[float] = None) -> Dict[str, np.ndarray]:
        """Blocking single-request convenience around :meth:`submit`."""
        return self.submit(sample).result(timeout)

    def predict_many(
        self, samples: Sequence[Any], timeout: Optional[float] = None
    ) -> List[Dict[str, np.ndarray]]:
        futures = [self.submit(s) for s in samples]
        return [f.result(timeout) for f in futures]

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    def queue_depth(self) -> int:
        """Requests currently queued (all buckets) — the load signal the
        fleet router's least-queue-depth placement reads per admission,
        kept public so callers never reach into the batcher."""
        return self._queue.depth()

    # -- health / probes ---------------------------------------------------

    def health(self) -> Dict[str, Any]:
        """Liveness/readiness probe surface (exported to Prometheus as
        ``serve.live`` / ``serve.ready`` gauges; ``tools/serve_probe.py``
        turns the textfile into an exit code for an orchestrator).

        Liveness = the dispatch loop exists and is beating (a wedged
        forward past ``dispatch_stall_s`` flips it false; a supervisor
        give-up keeps it false). Readiness = live AND every bucket's
        executable is warm AND the queue is below the high-water mark —
        "send me traffic", not just "don't kill me"."""
        sup = self._supervisor
        started = self._started and not self._stopped
        alive = bool(sup is not None and sup.alive)
        stalled = bool(sup is not None and sup.stalled)
        failed = bool(sup is not None and sup.failed)
        hb_age = sup.heartbeat_age() if sup is not None else None
        live = started and alive and not stalled and not failed
        warm = len(self._cache)
        depth = self._queue.depth()
        highwater = max(1, int(self.config.ready_queue_highwater * self.config.max_pending))
        ready = live and warm >= len(self.buckets) and depth < highwater
        reasons = []
        if not started:
            reasons.append("not started" if not self._stopped else "stopped")
        if started and not alive:
            reasons.append("dispatch thread down")
        if stalled:
            reasons.append(f"dispatch stalled (heartbeat {hb_age:.1f}s)")
        if failed:
            reasons.append("dispatch supervisor gave up")
        if warm < len(self.buckets):
            reasons.append(f"buckets warming ({warm}/{len(self.buckets)})")
        if depth >= highwater:
            reasons.append(f"queue over high-water ({depth}/{highwater})")
        self.metrics.set_health(live, ready, hb_age, warm)
        # keep the queue gauges fresh even when the dispatch loop is
        # idle/wedged — the oldest-request age is exactly the signal
        # that matters then (satellite of the trigger engine AND the
        # external Prometheus probe)
        self.metrics.set_queue_depth(depth, self._queue.oldest_age_s())
        return {
            "live": live,
            "ready": ready,
            "dispatch_alive": alive,
            "dispatch_stalled": stalled,
            "dispatch_failed": failed,
            "heartbeat_age_s": round(hb_age, 3) if hb_age is not None else None,
            "warm_buckets": warm,
            "num_buckets": len(self.buckets),
            "queue_depth": depth,
            "queue_highwater": highwater,
            "dispatch_restarts": sup.restarts if sup is not None else 0,
            "reasons": reasons,
        }

    def export_prometheus(self, path: str) -> None:
        """Write this server's metrics as a Prometheus textfile snapshot
        (atomic rename; point a node-exporter textfile collector at it
        and scrape — no HTTP server in-process). Refreshes the health
        gauges first so the probe signals are current. On a non-zero
        host (real process or podview simulated host) the path is
        suffixed ``<name>.host<k><ext>`` so a second host's probe file
        never clobbers the first's (obs/podview.py)."""
        from hydragnn_tpu.obs.export import registry_to_prometheus
        from hydragnn_tpu.obs.podview import host_artifact_path

        self.health()
        registry_to_prometheus(self.metrics.registry, host_artifact_path(path))

    def _export_tick(self) -> None:
        """Periodic textfile export from the supervisor's monitor thread
        (``ServeConfig.prometheus_path`` / ``prometheus_every_s``)."""
        self.export_prometheus(self.config.prometheus_path)

    # -- zero-downtime reload ----------------------------------------------

    def reload(
        self,
        checkpoint: Optional[str] = None,
        *,
        variables: Optional[Dict[str, Any]] = None,
        log_dir: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Swap in new weights without dropping traffic or recompiling.

        ``checkpoint`` is a run name restored through the VALIDATING
        loader (sha256 sidecars, torn-pointer fallback — the PR 3
        integrity path) under ``log_dir`` (default: the server's
        ``log_dir``, set by ``api.serve_model``); or pass ``variables``
        directly (same pytree/shapes — benches, tests, an in-process
        trainer). The candidate runs a CANARY first: every bucket's
        already-compiled executable is invoked with the new weights on
        its warmup batch and must return all-finite outputs — a shape
        mismatch or NaN weights fail HERE, never on live traffic. Only
        then is the forward swapped (one atomic reference store; the old
        weights keep answering everything dispatched before the swap).
        Any failure rolls back: the old weights are untouched,
        ``reload_failed`` is recorded, and :class:`ReloadFailed` raises.

        Zero-downtime by construction: the server stays READY throughout
        — no queue pause, no executable rebuild (an AOT executable is
        specialized to shapes, not values, so same-architecture weights
        reuse the whole warm ladder: 0 compile misses)."""
        if (checkpoint is None) == (variables is None):
            raise ValueError("pass exactly one of checkpoint= or variables=")
        source = checkpoint if checkpoint is not None else "<variables>"
        with self._reload_lock:
            t0 = time.monotonic()
            try:
                if checkpoint is not None:
                    from hydragnn_tpu.serve.registry import load_served_variables

                    new_vars = load_served_variables(
                        self.served, checkpoint, log_dir or self.log_dir
                    )
                else:
                    new_vars = dict(variables)
                if inject.serve_torn_reload():
                    new_vars = _corrupt_variables(new_vars)
                # same committed layout as the running weights: the warm
                # executables are sharding-exact, so the candidate must
                # land on the mesh BEFORE the canary invokes them
                new_vars = self.partitioner.shard_variables(new_vars)
                self._canary(new_vars)
            except Exception as exc:
                self.metrics.record_reload(ok=False)
                self.flight.record(
                    "reload_failed",
                    source=source,
                    error=repr(exc)[-300:],
                    rolled_back=True,
                )
                raise ReloadFailed(
                    f"reload from {source!r} failed ({exc!r}); previous "
                    "weights still serving"
                ) from exc
            # the swap: one reference store the dispatch thread picks up
            # on its next batch (in-flight batches finish on old weights)
            self.served.variables = new_vars
            # require_canary: buckets compiled on demand AFTER this
            # reload must pass the same all-finite gate the canary just
            # applied to the warm ladder (serve/buckets.py)
            self._cache.rebind(new_vars, require_canary=True)
            self.metrics.record_reload(ok=True)
            info = {
                "source": source,
                "canary_buckets": len(self.buckets),
                "swap_s": round(time.monotonic() - t0, 3),
            }
            self.flight.record("reload", **info)
            return info

    def _canary(self, new_vars: Dict[str, Any]) -> None:
        """Candidate-weight gate: every bucket's compiled executable on
        its warmup batch, all outputs finite, or the reload fails."""
        for b in self.buckets:
            exe = self._cache.executable(b)
            outs = exe(new_vars, self._build_warm_batch(b))
            for i, o in enumerate(outs):
                if not np.all(np.isfinite(np.asarray(o))):
                    raise ReloadFailed(
                        f"canary produced non-finite outputs (bucket "
                        f"{b.index}, head {i}) — candidate weights rejected"
                    )

    # -- oversize fallbacks ------------------------------------------------

    def _submit_oversize(
        self,
        g: Dict[str, Any],
        n: int,
        e: int,
        seq: int,
        trace: Any = None,
        tenant: str = "default",
    ) -> Future:
        self.metrics.record_request(None)
        fut: Future = Future()
        largest = self.buckets[-1]
        if largest.fits_totals(n, e, 1):
            # over the per-graph routing caps (sized for max_batch
            # co-tenants) but within the biggest plan alone: dispatch
            # unbatched on the ALREADY-COMPILED largest bucket
            self.metrics.record_oversize("largest_bucket")
            if trace is not None:
                trace.mark("serve.route", oversize="largest_bucket")
            t0 = time.monotonic()
            reqs = [PendingRequest(g, fut, t0, largest.index, seq, trace, tenant)]
            self._execute_bucket(largest.index, reqs, reason="oversize")
            return fut
        if not self.config.eager_fallback:
            self.metrics.record_error()
            fut.set_exception(
                Oversize(
                    f"graph ({n} nodes, {e} edges) exceeds the largest bucket "
                    f"plan {largest.node_pad}/{largest.edge_pad} and "
                    "eager_fallback is disabled"
                )
            )
            return fut
        self.metrics.record_oversize("eager")
        t0 = time.monotonic()
        try:
            result = self._execute_eager(g, seq)
            if not _result_finite(result) and self.config.check_finite:
                self._quarantine(
                    PendingRequest(g, fut, t0, -1, seq, trace), None,
                    "nonfinite", None,
                )
                return fut
            fut.set_result(result)
            self.metrics.observe_latency(time.monotonic() - t0)
            if self._drift is not None or self._spool is not None:
                self._observe_answered(g, result, trace, tenant, seq)
            if trace is not None:
                trace.mark("serve.eager_execute")
                self._tracer.finish(trace)
        except Oversize as exc:
            self.metrics.record_error()
            fut.set_exception(exc)
        except BaseException as exc:
            self._quarantine(
                PendingRequest(g, fut, t0, -1, seq, trace), None,
                "exception", exc,
            )
        return fut

    def _execute_eager(self, g: Dict[str, Any], seq: int) -> Dict[str, np.ndarray]:
        """Natural-pad unbatched call through the plain jit cache. Each
        NEW padded shape is a fresh XLA compile — recorded as a
        compile-cache miss; repeats of a shape hit jit's own cache."""
        from hydragnn_tpu.graph.batch import batch_graphs

        inject.maybe_serve_raise([seq])
        batch = self.partitioner.shard_inference_batch(
            batch_graphs(
                [g],
                node_multiple=self.config.node_multiple,
                edge_multiple=self.config.edge_multiple,
            )
        )
        shape_key = (batch.num_nodes, batch.num_edges, batch.num_graphs)
        with self._eager_lock:
            seen = shape_key in self._eager_shapes
            self._eager_shapes.add(shape_key)
        self.metrics.record_compile(hit=seen)
        outputs = self.served.forward(self.served.variables, batch)
        outputs = inject.maybe_serve_nan([np.asarray(o) for o in outputs], [seq])
        n, _ = _dict_sizes(g)
        return self._slice_result(outputs, graph_index=0, node_offset=0, num_nodes=n)

    # -- executor ----------------------------------------------------------

    def _run(self) -> None:
        sup = self._supervisor
        while True:
            sup.beat()
            got = self._queue.take_batch()
            if got is None:
                return
            bucket_index, requests, reason = got
            self.metrics.set_queue_depth(
                self._queue.depth(), self._queue.oldest_age_s()
            )
            self._dispatched_batches += 1
            sup.busy(True)
            sup.beat()
            try:
                # thread-death injection fires OUTSIDE request isolation
                inject.maybe_serve_kill_dispatch(self._dispatched_batches)
                self._execute_bucket(bucket_index, requests, reason)
                self._maybe_trigger()
            except BaseException as exc:
                # anything escaping here is dispatch-level (request
                # failures were isolated below): resolve the in-hand
                # futures with the typed error, then die loudly so the
                # supervisor restarts the loop
                self.metrics.record_error(len(requests))
                for r in requests:
                    if not r.future.done():
                        r.future.set_exception(
                            RequestFailed(
                                f"dispatch thread died with this batch in "
                                f"hand: {exc!r}",
                                seq=r.seq,
                                reason="dispatch",
                            )
                        )
                raise
            finally:
                sup.busy(False)
                sup.beat()

    def _execute_bucket(
        self,
        bucket_index: int,
        requests: List[PendingRequest],
        reason: str,
        singles_retry: bool = True,
    ) -> None:
        """Run one coalesced batch with poison isolation: a failure
        (exception or non-finite outputs) fails only the offending
        requests' futures, never the caller. Multi-request batches are
        re-run once as singles to localize the poison; confirmed
        single-request failures are quarantined."""
        from hydragnn_tpu.graph.batch import batch_graphs

        bucket = self.buckets[bucket_index]
        seqs = [r.seq for r in requests]
        for r in requests:
            if r.trace is not None:
                # coalescing wait ends the moment the batch is in hand
                r.trace.mark(
                    "serve.queue_wait", reason=reason, bucket=bucket_index
                )
        try:
            inject.maybe_serve_wedge(seqs)
            inject.maybe_serve_raise(seqs)
            t_build0 = time.time()
            batch = self.partitioner.shard_inference_batch(
                batch_graphs(
                    [r.item for r in requests],
                    n_node_pad=bucket.node_pad,
                    n_edge_pad=bucket.edge_pad,
                    n_graph_pad=bucket.graph_pad,
                )
            )
            t_exec0 = time.time()
            exe = self._cache.executable(bucket)
            outputs = [np.asarray(o) for o in exe(self.served.variables, batch)]
            outputs = inject.maybe_serve_nan(outputs, seqs)
            t_exec1 = time.time()
        except Exception as exc:
            self._isolate_failure(
                bucket_index, requests, "exception", exc, singles_retry
            )
            return
        # batch-level spans are shared by every co-batched trace
        for r in requests:
            if r.trace is not None:
                r.trace.add_span(
                    "serve.batch_build", t_build0, t_exec0,
                    occupancy=len(requests),
                )
                r.trace.add_span("serve.device_execute", t_exec0, t_exec1)
        self.metrics.record_batch(
            bucket_index, len(requests), bucket.max_batch, reason
        )
        t_done = time.monotonic()
        node_offset = 0
        poisoned: List[PendingRequest] = []
        for gi, r in enumerate(requests):
            n, _ = _dict_sizes(r.item)
            result = self._slice_result(
                outputs, graph_index=gi, node_offset=node_offset, num_nodes=n
            )
            node_offset += n
            if self.config.check_finite and not _result_finite(result):
                poisoned.append(r)
                continue
            if not r.future.done():
                r.future.set_result(result)
                self.metrics.observe_latency(t_done - r.t_enqueue)
                # spool/drift hook: everything in hand (inputs, sliced
                # result) is already host-side numpy — zero device syncs
                if self._drift is not None or self._spool is not None:
                    self._observe_answered(
                        r.item, result, r.trace, r.tenant, r.seq
                    )
                if r.trace is not None:
                    r.trace.add_span("serve.postprocess", t_exec1, time.time())
                    self._tracer.finish(r.trace)
                    r.trace = None
        if poisoned:
            self._isolate_failure(
                bucket_index, poisoned, "nonfinite", None, singles_retry
            )

    def _isolate_failure(
        self,
        bucket_index: int,
        requests: List[PendingRequest],
        kind: str,
        exc: Optional[BaseException],
        singles_retry: bool,
    ) -> None:
        if len(requests) > 1 and singles_retry:
            # a co-batched failure cannot be attributed: re-run each
            # request alone on the same (already compiled) bucket — the
            # poison fails again and is quarantined, innocents succeed
            self.metrics.record_poison_retry(len(requests))
            for r in requests:
                self._execute_bucket(
                    bucket_index, [r], "retry_single", singles_retry=False
                )
            return
        for r in requests:
            self._quarantine(r, bucket_index, kind, exc)

    def _quarantine(
        self,
        r: PendingRequest,
        bucket_index: Optional[int],
        kind: str,
        exc: Optional[BaseException],
    ) -> None:
        """Fail ONE request's future with the typed error + evidence:
        the ``serve.quarantined`` counter and a ``quarantine`` flight
        event (docs/RESILIENCE.md failure matrix)."""
        self.metrics.record_quarantine()
        self.metrics.record_error()
        detail = repr(exc) if exc is not None else "non-finite outputs"
        self.flight.record(
            "quarantine",
            seq=r.seq,
            reason=kind,
            bucket=bucket_index,
            error=detail[-300:],
        )
        if not r.future.done():
            r.future.set_exception(
                RequestFailed(
                    f"request seq={r.seq} quarantined ({kind}): {detail}",
                    seq=r.seq,
                    reason=kind,
                )
            )
        if r.trace is not None and self._tracer is not None:
            r.trace.mark("serve.quarantine", reason=kind)
            self._tracer.finish(r.trace)
            r.trace = None

    def _observe_answered(
        self,
        g: Dict[str, Any],
        result: Dict[str, np.ndarray],
        trace: Any,
        tenant: str,
        seq: int,
    ) -> None:
        """Post-answer spool/drift ingest. Observability must never
        fail a request: exception-contained, and a failing plane
        disarms itself after recording the error (one flight event, not
        one per request)."""
        try:
            if self._drift is not None:
                self._drift.observe(np.asarray(g["x"]), result)
            if self._spool is not None:
                self._spool.offer(
                    g,
                    result,
                    trace=trace.trace_id if trace is not None else None,
                    tenant=tenant,
                    seq=seq,
                )
        except Exception as exc:
            self.flight.error(exc, where="spool_drift")
            self._drift = None
            self._spool = None

    def _maybe_trigger(self) -> None:
        """Post-batch trigger hook: drive any open incident's bounded
        capture, then (rate-limited to ``trigger_eval_every_s``)
        evaluate the SLO rules. Observability must never take the
        dispatch thread down, so everything is exception-contained."""
        trig, inc = self._triggers, self._incidents
        if trig is None or inc is None:
            return
        try:
            inc.tick()
            now = time.monotonic()
            if now - self._last_trigger_eval < self.config.trigger_eval_every_s:
                return
            self._last_trigger_eval = now
            for verdict in trig.evaluate():
                opened = inc.open_incident(verdict, flight=self.flight)
                if opened is not None:
                    if verdict.kind in (
                        "feature_drift", "pred_drift", "error_drift"
                    ):
                        self._attach_drift_evidence(opened, verdict)
                        if self._pilot is not None:
                            # the pilot must never take the dispatch
                            # thread down — its own state machine owns
                            # failure handling past this handoff
                            try:
                                self._pilot.on_drift_incident(opened, verdict)
                            except Exception as exc:
                                self.flight.error(exc, where="pilot_notify")
                    opened.tick()  # start the capture on this batch
        except Exception as exc:
            self.flight.error(exc, where="trigger_engine")

    def _attach_drift_evidence(self, opened, verdict) -> None:
        """A drift breach must be self-diagnosing: write the full drift
        report + the offending spool window into the incident bundle as
        ``drift_report.json`` and narrate the breach as a ``drift``
        flight event. The window's shards are PINNED against spool
        eviction until the incident closes (released in
        ``_on_incident_close``) and each pinned shard's
        ``spool_manifest.json`` is copied into the bundle under
        ``spool_manifests/`` — the evidence stands on its own even after
        the spool eventually reclaims the data."""
        from hydragnn_tpu.obs.triggers import _atomic_json

        report = self._drift.report() if self._drift is not None else {}
        window: Dict[str, Any] = {}
        if self._spool is not None:
            # the traffic that tripped the rule is mostly still in the
            # OPEN pending shard; cut it now so the window (and the pins
            # below) cover the offending samples, not just older shards
            self._spool.flush_pending()
            window = self._spool.window()
        pinned: List[str] = []
        if self._spool is not None and window.get("shards"):
            pinned = self._spool.pin(window["shards"])
            with self._pin_lock:
                self._incident_pins[opened.id] = list(pinned)
            if pinned:
                from hydragnn_tpu.obs.spool import read_shard_manifest

                mdir = os.path.join(opened.dir, "spool_manifests")
                os.makedirs(mdir, exist_ok=True)
                for name in pinned:
                    try:
                        man = read_shard_manifest(
                            os.path.join(window["dir"], name)
                        )
                    except Exception:
                        continue  # unreadable manifest; the pin still
                        # holds the shard itself for the capture window
                    _atomic_json(os.path.join(mdir, f"{name}.json"), man)
                    opened.files[f"spool_manifest/{name}"] = os.path.join(
                        "spool_manifests", f"{name}.json"
                    )
        report["spool_window"] = window
        report["pinned_shards"] = pinned
        report["trigger"] = verdict.to_dict()
        _atomic_json(os.path.join(opened.dir, "drift_report.json"), report)
        opened.files["drift_report"] = "drift_report.json"
        self.flight.record(
            "drift",
            rule=verdict.rule,
            rule_kind=verdict.kind,
            metric=verdict.metric,
            observed=verdict.observed,
            threshold=verdict.threshold,
            spool_window=window,
            pinned_shards=pinned,
        )

    def _on_incident_close(self, inc, status: str) -> None:
        """IncidentRecorder close hook: release the spool pins taken for
        the incident's drift evidence. A retrain pilot holds its OWN
        pins across a fine-tune cycle, so an incident closing mid-tune
        cannot evict the training window out from under it."""
        with self._pin_lock:
            pinned = self._incident_pins.pop(inc.id, None)
        if pinned and self._spool is not None:
            self._spool.unpin(pinned)

    # -- retrain pilot seam ------------------------------------------------

    def attach_pilot(self, pilot) -> None:
        """Attach a retrain pilot (``pilot/pilot.py``): every drift
        incident the trigger engine opens is forwarded to
        ``pilot.on_drift_incident(incident, verdict)`` right after its
        evidence bundle (drift report + pinned spool window) lands."""
        self._pilot = pilot

    def pin_spool(self, shards) -> List[str]:
        """Ref-count-pin spool shards against eviction; returns the
        names actually pinned (``[]`` when no spool is armed)."""
        if self._spool is None:
            return []
        return self._spool.pin(shards)

    def unpin_spool(self, shards) -> None:
        if self._spool is not None:
            self._spool.unpin(shards)

    def spool_dir(self) -> Optional[str]:
        return self._spool.root if self._spool is not None else None

    def reset_drift(self) -> None:
        """Drop the drift monitor's accumulated sketches (reference
        intact). The pilot calls this after a successful hot reload so
        the drift rules re-arm against the NEW weights' behaviour
        instead of re-firing on pre-reload sketch mass."""
        if self._drift is not None:
            self._drift.reset()

    def open_pilot_incident(self, verdict):
        """Best-effort escalation bundle for a terminal pilot state.
        The recorder keeps one incident open at a time — when a capture
        is already running this returns None and the pilot's flight
        event is the escalation record."""
        if self._incidents is None:
            return None
        # the dispatch loop's inc.tick() drives the bundle's bounded
        # capture and close exactly like any rule-fired incident
        return self._incidents.open_incident(verdict, flight=self.flight)

    def export_trace(self, path: str) -> Optional[str]:
        """Dump the tracer's recent-request ring as Chrome/Perfetto
        trace JSON; returns the path (None when tracing is off)."""
        if self._tracer is None or not self._tracer.enabled:
            return None
        return self._tracer.export_chrome(path)

    def _slice_result(
        self, outputs, graph_index: int, node_offset: int, num_nodes: int
    ) -> Dict[str, np.ndarray]:
        cfg = self.served.cfg
        result: Dict[str, np.ndarray] = {}
        for ihead in range(cfg.num_heads):
            out = np.asarray(outputs[ihead])
            if cfg.output_type[ihead] == "graph":
                result[cfg.output_names[ihead]] = out[graph_index]
            else:
                result[cfg.output_names[ihead]] = out[
                    node_offset : node_offset + num_nodes
                ]
        return result

    # -- batch construction ------------------------------------------------

    def _validated(self, g: Dict[str, Any]) -> Dict[str, Any]:
        """Enforce the field spec: AOT executables are pytree-exact, so a
        request whose optional fields differ from the reference spec must
        fail loudly at admission, not as an opaque structure error inside
        the executor."""
        spec = self._spec
        x = np.asarray(g["x"])
        feat = x.shape[1] if x.ndim > 1 else 1
        if feat != spec["feat_dim"]:
            raise ValueError(
                f"request feature width {feat} != model's {spec['feat_dim']}"
            )
        if ("pos" in g) != spec["has_pos"]:
            raise ValueError(
                "request 'pos' presence does not match the serving spec "
                f"(expected {'present' if spec['has_pos'] else 'absent'})"
            )
        if ("edge_attr" in g) != spec["has_edge_attr"]:
            raise ValueError(
                "request 'edge_attr' presence does not match the serving spec "
                f"(expected {'present' if spec['has_edge_attr'] else 'absent'})"
            )
        return g

    def _build_warm_batch(self, bucket: Bucket):
        """A structurally representative batch at ``bucket``'s plan for
        AOT lowering: one minimal graph matching the field spec, padded
        to the plan — the same builder and options as request batches,
        so the traced structure is exact."""
        from hydragnn_tpu.graph.batch import batch_graphs

        spec = self._spec
        g: Dict[str, Any] = {
            "x": np.zeros((2, spec["feat_dim"]), dtype=np.float32),
            "senders": np.zeros((1,), dtype=np.int32),
            "receivers": np.ones((1,), dtype=np.int32),
        }
        if spec["has_pos"]:
            g["pos"] = np.zeros((2, spec["pos_dim"]), dtype=np.float32)
        if spec["has_edge_attr"]:
            g["edge_attr"] = np.zeros((1, spec["edge_dim"]), dtype=np.float32)
        # placed through the partitioner so the AOT lowering sees the
        # exact committed layout request batches will arrive with
        return self.partitioner.shard_inference_batch(
            batch_graphs(
                [g],
                n_node_pad=bucket.node_pad,
                n_edge_pad=bucket.edge_pad,
                n_graph_pad=bucket.graph_pad,
            )
        )
