"""ModelServer: the batched online-inference front end.

Wiring: requests (single prepared graphs) -> bucket router
(``serve/buckets.py``) -> deadline micro-batcher (``serve/batcher.py``)
-> one executor thread that pads the coalesced batch to the bucket's
plan, runs the AOT-compiled forward, and slices per-request results out
of the padded outputs. Degradation is graceful by construction:

  - a graph over every routing cap but under the LARGEST bucket's pad
    plan dispatches immediately as a batch-of-1 on that bucket (no new
    compile, just wasted padding);
  - a graph over even the largest plan takes the eager path — its own
    natural pad, compiled on first sight (counted as a compile-cache
    MISS: the operator signal that the ladder no longer covers traffic);
  - a full queue rejects with :class:`~hydragnn_tpu.serve.batcher.
    Overloaded` instead of buffering unboundedly.

Requests carry NO targets (there is nothing to supervise at inference
time); the builder strips them so request batches and warmup batches
share one pytree structure — an AOT executable is shape-exact.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from hydragnn_tpu.serve.batcher import MicroBatchQueue, Overloaded, PendingRequest
from hydragnn_tpu.serve.buckets import Bucket, BucketCompileCache, build_bucket_ladder, route
from hydragnn_tpu.serve.metrics import ServeMetrics
from hydragnn_tpu.serve.registry import ServedModel


class Oversize(RuntimeError):
    """Request exceeds every bucket and the eager fallback is disabled."""


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Knobs of the serving path.

    max_batch: graphs coalesced per device dispatch (bucket batch size).
    num_buckets: pad-plan ladder size (before dedup of identical plans).
    max_delay_ms: deadline before a partial batch flushes — the latency
      budget a request can pay waiting for co-batched traffic.
    max_pending: bounded queue across all buckets; beyond it ``submit``
      raises Overloaded (explicit backpressure).
    eager_fallback: compile-on-demand natural-pad path for graphs larger
      than every bucket plan; off -> such requests raise Oversize.
    """

    max_batch: int = 8
    num_buckets: int = 3
    max_delay_ms: float = 5.0
    max_pending: int = 256
    node_multiple: int = 16
    edge_multiple: int = 8
    eager_fallback: bool = True
    latency_window: int = 2048


def request_to_dict(sample: Any) -> Dict[str, Any]:
    """Normalize a request (GraphSample or graph dict) to the dict form
    ``graph/batch.py:batch_graphs`` consumes, WITHOUT targets."""
    if isinstance(sample, dict):
        g = dict(sample)
        if "senders" not in g:
            ei = g.pop("edge_index", None)
            if ei is None:
                raise ValueError("request dict needs 'senders'/'receivers' or 'edge_index'")
            ei = np.asarray(ei)
            g["senders"], g["receivers"] = ei[0], ei[1]
    else:
        if getattr(sample, "edge_index", None) is None:
            raise ValueError("request sample has no edge_index (no edges built)")
        g = {
            "x": sample.x,
            "senders": sample.edge_index[0],
            "receivers": sample.edge_index[1],
        }
        if getattr(sample, "pos", None) is not None:
            g["pos"] = sample.pos
        if getattr(sample, "edge_attr", None) is not None:
            g["edge_attr"] = sample.edge_attr
    g.pop("graph_targets", None)
    g.pop("node_targets", None)
    return g


def _dict_sizes(g: Dict[str, Any]) -> tuple:
    return int(np.asarray(g["x"]).shape[0]), int(np.asarray(g["senders"]).shape[0])


class ModelServer:
    """Batched online inference over one :class:`ServedModel`.

    ``reference_samples`` size the bucket ladder and fix the request
    FIELD SPEC (feature width, pos/edge_attr presence) every request
    must match — use the prepared dataset the model was trained on.
    """

    def __init__(
        self,
        served: ServedModel,
        reference_samples: Sequence,
        config: Optional[ServeConfig] = None,
        metrics: Optional[ServeMetrics] = None,
        flight=None,
    ):
        if not reference_samples:
            raise ValueError("reference_samples must be non-empty (sizes the buckets)")
        self.served = served
        self.config = config or ServeConfig()
        self.buckets: List[Bucket] = build_bucket_ladder(
            reference_samples,
            self.config.max_batch,
            num_buckets=self.config.num_buckets,
            node_multiple=self.config.node_multiple,
            edge_multiple=self.config.edge_multiple,
        )
        self.metrics = metrics or ServeMetrics(
            len(self.buckets), latency_window=self.config.latency_window
        )
        ref = request_to_dict(reference_samples[0])
        ref_x = np.asarray(ref["x"])
        ref_ea = np.asarray(ref["edge_attr"]) if "edge_attr" in ref else None
        self._spec = {
            "feat_dim": int(ref_x.shape[1]) if ref_x.ndim > 1 else 1,
            "has_pos": "pos" in ref,
            "pos_dim": int(np.asarray(ref["pos"]).shape[-1]) if "pos" in ref else 0,
            "has_edge_attr": ref_ea is not None,
            "edge_dim": (
                int(ref_ea.shape[-1]) if ref_ea is not None and ref_ea.ndim > 1 else (1 if ref_ea is not None else 0)
            ),
        }
        self._cache = BucketCompileCache(
            served.forward,
            served.variables,
            self._build_warm_batch,
            metrics=self.metrics,
        )
        self._queue = MicroBatchQueue(
            len(self.buckets),
            self.config.max_batch,
            self.config.max_delay_ms / 1e3,
            self.config.max_pending,
        )
        self._eager_shapes: set = set()
        self._eager_lock = threading.Lock()
        self._worker: Optional[threading.Thread] = None
        self._started = False
        # optional run flight recorder (hydragnn_tpu/obs/flight.py):
        # start() logs a serving manifest (bucket ladder, request spec),
        # stop() the final metrics snapshot — bench_serve.py passes one
        # so a serving bench leaves the same evidence artifact training
        # runs do. None -> an inert recorder; no call site needs a gate.
        if flight is None:
            from hydragnn_tpu.obs import FlightRecorder

            flight = FlightRecorder(None, enabled=False)
        self.flight = flight

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ModelServer":
        """AOT-compile the whole bucket ladder, then start the executor
        thread. Returns self (``serve_model(...).start()`` chains)."""
        if self._started:
            return self
        t0 = time.monotonic()
        self._cache.warmup(self.buckets)
        self.flight.start_run(
            {
                "mode": "serve",
                "serve_config": dataclasses.asdict(self.config),
                "request_spec": dict(self._spec),
                "buckets": [
                    {
                        "cap_nodes": b.cap_nodes,
                        "cap_edges": b.cap_edges,
                        "node_pad": b.node_pad,
                        "edge_pad": b.edge_pad,
                        "graph_pad": b.graph_pad,
                    }
                    for b in self.buckets
                ],
                "warmup_compile_s": round(time.monotonic() - t0, 3),
            }
        )
        self._worker = threading.Thread(
            target=self._run, name="hydragnn-serve-executor", daemon=True
        )
        self._worker.start()
        self._started = True
        return self

    def stop(self, timeout: Optional[float] = 10.0) -> None:
        """Stop admitting, drain what is queued, join the executor."""
        was_started = self._started
        self._queue.close()
        if self._worker is not None:
            self._worker.join(timeout)
            self._worker = None
        self._started = False
        if was_started:
            self.flight.end_run(status="stopped", metrics=self.metrics_snapshot())

    def __enter__(self) -> "ModelServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- request path ------------------------------------------------------

    def submit(self, sample: Any) -> Future:
        """Admit one graph; returns a Future resolving to
        ``{head_name: np.ndarray}`` (graph heads: [d]; node heads:
        [n_nodes, d], this graph's rows only). Raises Overloaded on
        backpressure and Oversize when nothing can take the graph."""
        if not self._started:
            raise RuntimeError("server not started (call start())")
        g = self._validated(request_to_dict(sample))
        n, e = _dict_sizes(g)
        bucket = route(self.buckets, n, e)
        if bucket is not None:
            self.metrics.record_request(bucket.index)
            try:
                fut = self._queue.put(bucket.index, g)
            except Overloaded:
                self.metrics.record_reject()
                raise
            self.metrics.set_queue_depth(self._queue.depth())
            return fut
        return self._submit_oversize(g, n, e)

    def predict(self, sample: Any, timeout: Optional[float] = None) -> Dict[str, np.ndarray]:
        """Blocking single-request convenience around :meth:`submit`."""
        return self.submit(sample).result(timeout)

    def predict_many(
        self, samples: Sequence[Any], timeout: Optional[float] = None
    ) -> List[Dict[str, np.ndarray]]:
        futures = [self.submit(s) for s in samples]
        return [f.result(timeout) for f in futures]

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    def export_prometheus(self, path: str) -> None:
        """Write this server's metrics as a Prometheus textfile snapshot
        (atomic rename; point a node-exporter textfile collector at it
        and scrape — no HTTP server in-process)."""
        from hydragnn_tpu.obs.export import registry_to_prometheus

        registry_to_prometheus(self.metrics.registry, path)

    # -- oversize fallbacks ------------------------------------------------

    def _submit_oversize(self, g: Dict[str, Any], n: int, e: int) -> Future:
        self.metrics.record_request(None)
        fut: Future = Future()
        largest = self.buckets[-1]
        if largest.fits_totals(n, e, 1):
            # over the per-graph routing caps (sized for max_batch
            # co-tenants) but within the biggest plan alone: dispatch
            # unbatched on the ALREADY-COMPILED largest bucket
            self.metrics.record_oversize("largest_bucket")
            t0 = time.monotonic()
            reqs = [PendingRequest(g, fut, t0, largest.index)]
            try:
                self._execute_bucket(largest.index, reqs, reason="oversize")
            except BaseException as exc:
                self.metrics.record_error()
                if not fut.done():
                    fut.set_exception(exc)
            return fut
        if not self.config.eager_fallback:
            self.metrics.record_error()
            fut.set_exception(
                Oversize(
                    f"graph ({n} nodes, {e} edges) exceeds the largest bucket "
                    f"plan {largest.node_pad}/{largest.edge_pad} and "
                    "eager_fallback is disabled"
                )
            )
            return fut
        self.metrics.record_oversize("eager")
        t0 = time.monotonic()
        try:
            result = self._execute_eager(g)
            fut.set_result(result)
            self.metrics.observe_latency(time.monotonic() - t0)
        except BaseException as exc:
            self.metrics.record_error()
            fut.set_exception(exc)
        return fut

    def _execute_eager(self, g: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Natural-pad unbatched call through the plain jit cache. Each
        NEW padded shape is a fresh XLA compile — recorded as a
        compile-cache miss; repeats of a shape hit jit's own cache."""
        from hydragnn_tpu.graph.batch import batch_graphs

        batch = batch_graphs(
            [g],
            node_multiple=self.config.node_multiple,
            edge_multiple=self.config.edge_multiple,
        )
        shape_key = (batch.num_nodes, batch.num_edges, batch.num_graphs)
        with self._eager_lock:
            seen = shape_key in self._eager_shapes
            self._eager_shapes.add(shape_key)
        self.metrics.record_compile(hit=seen)
        outputs = self.served.forward(self.served.variables, batch)
        n, _ = _dict_sizes(g)
        return self._slice_result(outputs, graph_index=0, node_offset=0, num_nodes=n)

    # -- executor ----------------------------------------------------------

    def _run(self) -> None:
        while True:
            got = self._queue.take_batch()
            if got is None:
                return
            bucket_index, requests, reason = got
            self.metrics.set_queue_depth(self._queue.depth())
            try:
                self._execute_bucket(bucket_index, requests, reason)
            except BaseException as exc:  # surface to every caller, keep serving
                self.metrics.record_error(len(requests))
                for r in requests:
                    if not r.future.done():
                        r.future.set_exception(exc)

    def _execute_bucket(
        self, bucket_index: int, requests: List[PendingRequest], reason: str
    ) -> None:
        from hydragnn_tpu.graph.batch import batch_graphs

        bucket = self.buckets[bucket_index]
        dicts = [r.item for r in requests]
        batch = batch_graphs(
            dicts,
            n_node_pad=bucket.node_pad,
            n_edge_pad=bucket.edge_pad,
            n_graph_pad=bucket.graph_pad,
        )
        exe = self._cache.executable(bucket)
        outputs = [np.asarray(o) for o in exe(self.served.variables, batch)]
        self.metrics.record_batch(
            bucket_index, len(requests), bucket.max_batch, reason
        )
        t_done = time.monotonic()
        node_offset = 0
        for gi, r in enumerate(requests):
            n, _ = _dict_sizes(r.item)
            result = self._slice_result(
                outputs, graph_index=gi, node_offset=node_offset, num_nodes=n
            )
            node_offset += n
            r.future.set_result(result)
            self.metrics.observe_latency(t_done - r.t_enqueue)

    def _slice_result(
        self, outputs, graph_index: int, node_offset: int, num_nodes: int
    ) -> Dict[str, np.ndarray]:
        cfg = self.served.cfg
        result: Dict[str, np.ndarray] = {}
        for ihead in range(cfg.num_heads):
            out = np.asarray(outputs[ihead])
            if cfg.output_type[ihead] == "graph":
                result[cfg.output_names[ihead]] = out[graph_index]
            else:
                result[cfg.output_names[ihead]] = out[
                    node_offset : node_offset + num_nodes
                ]
        return result

    # -- batch construction ------------------------------------------------

    def _validated(self, g: Dict[str, Any]) -> Dict[str, Any]:
        """Enforce the field spec: AOT executables are pytree-exact, so a
        request whose optional fields differ from the reference spec must
        fail loudly at admission, not as an opaque structure error inside
        the executor."""
        spec = self._spec
        x = np.asarray(g["x"])
        feat = x.shape[1] if x.ndim > 1 else 1
        if feat != spec["feat_dim"]:
            raise ValueError(
                f"request feature width {feat} != model's {spec['feat_dim']}"
            )
        if ("pos" in g) != spec["has_pos"]:
            raise ValueError(
                "request 'pos' presence does not match the serving spec "
                f"(expected {'present' if spec['has_pos'] else 'absent'})"
            )
        if ("edge_attr" in g) != spec["has_edge_attr"]:
            raise ValueError(
                "request 'edge_attr' presence does not match the serving spec "
                f"(expected {'present' if spec['has_edge_attr'] else 'absent'})"
            )
        return g

    def _build_warm_batch(self, bucket: Bucket):
        """A structurally representative batch at ``bucket``'s plan for
        AOT lowering: one minimal graph matching the field spec, padded
        to the plan — the same builder and options as request batches,
        so the traced structure is exact."""
        from hydragnn_tpu.graph.batch import batch_graphs

        spec = self._spec
        g: Dict[str, Any] = {
            "x": np.zeros((2, spec["feat_dim"]), dtype=np.float32),
            "senders": np.zeros((1,), dtype=np.int32),
            "receivers": np.ones((1,), dtype=np.int32),
        }
        if spec["has_pos"]:
            g["pos"] = np.zeros((2, spec["pos_dim"]), dtype=np.float32)
        if spec["has_edge_attr"]:
            g["edge_attr"] = np.zeros((1, spec["edge_dim"]), dtype=np.float32)
        return batch_graphs(
            [g],
            n_node_pad=bucket.node_pad,
            n_edge_pad=bucket.edge_pad,
            n_graph_pad=bucket.graph_pad,
        )
