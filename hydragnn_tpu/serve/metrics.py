"""Serving metrics: the observable surface of the online-inference path.

Counters answer the questions an operator of a bucketed server actually
asks: is traffic landing in the right buckets (per-bucket request count,
batch occupancy), is the deadline batcher coalescing or just timing out
(flush reasons), is the server keeping up (queue depth, overload
rejections), and — the TPU-specific one — is anything recompiling in
steady state (compile hits/misses; a miss on the serving path is a
multi-second latency cliff, which is the whole reason the bucket ladder
exists).

Since the unified-telemetry refactor, :class:`ServeMetrics` is a facade
over the shared metrics registry (``hydragnn_tpu/obs/registry.py``) —
the same counter/gauge/histogram store train, loader, and bench record
into — but its public surface is unchanged: the ``record_*`` methods
the server calls and the exact ``snapshot()`` key set operators and
tests already depend on. Tensorboard export rides the existing rank-0
writer plumbing (``utils/tensorboard.py:write_scalar_dict``);
Prometheus textfile export comes free from the registry
(``hydragnn_tpu/obs/export.py``).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from hydragnn_tpu.obs.registry import MetricsRegistry

_FLUSH_REASONS = ("full", "deadline", "drain")


def latency_percentiles(values_s) -> Dict[str, float]:
    """p50/p95/p99 over a sequence of second-latencies, in milliseconds.
    Nearest-rank on the sorted sample — exact for the small windows kept
    here, no interpolation surprises at the tail."""
    vals: List[float] = sorted(values_s)
    if not vals:
        return {"p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0}
    n = len(vals)

    def rank(q: float) -> float:
        i = min(n - 1, max(0, int(round(q * (n - 1)))))
        return vals[i] * 1e3

    return {"p50_ms": rank(0.50), "p95_ms": rank(0.95), "p99_ms": rank(0.99)}


class ServeMetrics:
    """Thread-safe serving counters for one :class:`~hydragnn_tpu.serve.
    server.ModelServer`, stored in a metrics registry.

    ``latency_window`` bounds the per-request latency sample the
    percentiles are computed over (a rolling window, not all-time — a
    serving process lives for days and early warmup latencies must age
    out of the tail stats).

    ``registry`` defaults to a private :class:`MetricsRegistry` so two
    servers in one process never alias counters; pass a shared registry
    (e.g. ``hydragnn_tpu.obs.get_registry()``) to co-locate serve
    metrics with everything else a process records — with a distinct
    ``prefix`` per server if more than one shares it.
    """

    def __init__(
        self,
        num_buckets: int,
        latency_window: int = 2048,
        registry: Optional[MetricsRegistry] = None,
        prefix: str = "serve",
    ):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.prefix = prefix
        self.num_buckets = num_buckets
        r = self.registry
        p = prefix
        self._requests = r.counter(f"{p}.requests_total")
        self._results = r.counter(f"{p}.results_total")
        self._rejected = r.counter(f"{p}.rejected_overload")
        self._oversize_largest = r.counter(f"{p}.oversize_largest_bucket")
        self._oversize_eager = r.counter(f"{p}.oversize_eager")
        self._errors = r.counter(f"{p}.errors")
        self._queue_depth = r.gauge(f"{p}.queue_depth")
        # age of the oldest queued request — the SLO trigger engine's
        # queue_age signal, and (via the registry's Prometheus export)
        # the same gauge external probes scrape
        self._queue_oldest_age = r.gauge(f"{p}.queue_oldest_age_s")
        # compile-cache accounting: warmup compiles are the startup AOT
        # ladder (expected, paid once); a MISS is a post-warmup dispatch
        # that required a fresh XLA compile — the thing steady-state
        # serving must never do.
        self._compile_warmup = r.counter(f"{p}.compile_warmup")
        self._compile_hits = r.counter(f"{p}.compile_hits")
        self._compile_misses = r.counter(f"{p}.compile_misses")
        # persistent AOT executable cache (utils/exec_cache.py): a disk
        # HIT replaces an XLA compile with a deserialize — the warm
        # cold-start. Miss REASONS are kept per-class because an
        # `absent` (first boot) and a `version_skew` (silent fleet
        # drift) demand different operator responses.
        self._exec_cache_hits = r.counter(f"{p}.exec_cache_hits")
        self._exec_cache_misses = r.counter(f"{p}.exec_cache_misses")
        self._exec_cache_miss_reasons: Dict[str, object] = {}
        # resilience surface (docs/RESILIENCE.md "Serving resilience"):
        # quarantined = requests failed with the typed RequestFailed
        # (poison isolation), poison_retries = multi-request batches
        # re-run as singles to localize a poison, dispatch_restarts =
        # supervisor restarts of a dead dispatch thread, reloads /
        # reload_failed = hot weight swaps (rolled back on failure)
        self._quarantined = r.counter(f"{p}.quarantined")
        self._poison_retries = r.counter(f"{p}.poison_retries")
        self._dispatch_restarts = r.counter(f"{p}.dispatch_restarts")
        self._reloads = r.counter(f"{p}.reloads")
        self._reload_failed = r.counter(f"{p}.reload_failed")
        # health gauges, refreshed by ModelServer.health() so the
        # Prometheus textfile carries the probe signals
        self._live = r.gauge(f"{p}.live")
        self._ready = r.gauge(f"{p}.ready")
        self._heartbeat_age = r.gauge(f"{p}.heartbeat_age_s")
        self._warm_buckets = r.gauge(f"{p}.warm_buckets")
        self._latency = r.histogram(f"{p}.latency_s", window=latency_window)
        self._buckets = []
        for i in range(num_buckets):
            bp = f"{p}.bucket_{i}"
            self._buckets.append(
                {
                    "requests": r.counter(f"{bp}.requests"),
                    "batches": r.counter(f"{bp}.batches"),
                    "graphs": r.counter(f"{bp}.graphs"),
                    "occupancy_sum": r.counter(f"{bp}.occupancy_sum"),
                    "flush": {
                        reason: r.counter(f"{bp}.flush_{reason}")
                        for reason in _FLUSH_REASONS
                    },
                    "capacity": r.gauge(f"{bp}.capacity"),
                    "capacity_set": False,
                }
            )

    # -- recording ---------------------------------------------------------

    def record_request(self, bucket: Optional[int]) -> None:
        self._requests.inc()
        if bucket is not None:
            self._buckets[bucket]["requests"].inc()

    def record_batch(self, bucket: int, occupancy: int, capacity: int, reason: str) -> None:
        b = self._buckets[bucket]
        b["batches"].inc()
        b["graphs"].inc(occupancy)
        b["occupancy_sum"].inc(occupancy)
        flush = b["flush"].get(reason)
        if flush is None:
            flush = self.registry.counter(
                f"{self.prefix}.bucket_{bucket}.flush_{reason}"
            )
            b["flush"][reason] = flush
        flush.inc()
        b["capacity"].set(capacity)
        b["capacity_set"] = True

    def record_reject(self) -> None:
        self._rejected.inc()

    def record_oversize(self, kind: str) -> None:
        if kind == "largest_bucket":
            self._oversize_largest.inc()
        else:
            self._oversize_eager.inc()

    def record_compile(self, *, hit: bool, warmup: bool = False) -> None:
        if warmup:
            self._compile_warmup.inc()
        elif hit:
            self._compile_hits.inc()
        else:
            self._compile_misses.inc()

    def record_exec_cache(self, *, hit: bool, reason: Optional[str] = None) -> None:
        """One persistent-executable-cache interaction: a hit (disk
        deserialize instead of compile) or a classified miss."""
        if hit:
            self._exec_cache_hits.inc()
            return
        self._exec_cache_misses.inc()
        reason = reason or "absent"
        c = self._exec_cache_miss_reasons.get(reason)
        if c is None:
            c = self.registry.counter(f"{self.prefix}.exec_cache_miss_{reason}")
            self._exec_cache_miss_reasons[reason] = c
        c.inc()

    def record_error(self, n: int = 1) -> None:
        self._errors.inc(n)

    def record_quarantine(self, n: int = 1) -> None:
        self._quarantined.inc(n)

    def record_poison_retry(self, n: int = 1) -> None:
        self._poison_retries.inc(n)

    def record_dispatch_restart(self) -> None:
        self._dispatch_restarts.inc()

    def record_reload(self, ok: bool) -> None:
        (self._reloads if ok else self._reload_failed).inc()

    def set_health(
        self,
        live: bool,
        ready: bool,
        heartbeat_age_s: Optional[float],
        warm_buckets: int,
    ) -> None:
        self._live.set(1.0 if live else 0.0)
        self._ready.set(1.0 if ready else 0.0)
        if heartbeat_age_s is not None:
            self._heartbeat_age.set(round(float(heartbeat_age_s), 3))
        self._warm_buckets.set(warm_buckets)

    def observe_latency(self, seconds: float, n_results: int = 1) -> None:
        self._latency.observe(seconds)
        self._results.inc(n_results)

    def set_queue_depth(
        self, depth: int, oldest_age_s: Optional[float] = None
    ) -> None:
        self._queue_depth.set(depth)
        if oldest_age_s is not None:
            self._queue_oldest_age.set(round(float(oldest_age_s), 4))

    # -- export ------------------------------------------------------------

    def snapshot(self) -> dict:
        """One dict of every counter plus derived stats (mean occupancy
        per bucket, latency percentiles). Key set is the pre-registry
        contract — bench_serve.py and test_serve.py parse it."""
        buckets = {}
        for i, b in enumerate(self._buckets):
            batches = b["batches"].snapshot()
            occupancy_sum = b["occupancy_sum"].snapshot()
            d = {
                "requests": b["requests"].snapshot(),
                "batches": batches,
                "graphs": b["graphs"].snapshot(),
            }
            for reason, c in b["flush"].items():
                d[f"flush_{reason}"] = c.snapshot()
            if b["capacity_set"]:
                d["capacity"] = b["capacity"].snapshot()
            d["occupancy_mean"] = occupancy_sum / batches if batches else 0.0
            buckets[f"bucket_{i}"] = d
        return {
            "requests_total": self._requests.snapshot(),
            "results_total": self._results.snapshot(),
            "rejected_overload": self._rejected.snapshot(),
            "oversize_largest_bucket": self._oversize_largest.snapshot(),
            "oversize_eager": self._oversize_eager.snapshot(),
            "errors": self._errors.snapshot(),
            "quarantined": self._quarantined.snapshot(),
            "poison_retries": self._poison_retries.snapshot(),
            "dispatch_restarts": self._dispatch_restarts.snapshot(),
            "reloads": self._reloads.snapshot(),
            "reload_failed": self._reload_failed.snapshot(),
            "live": self._live.snapshot(),
            "ready": self._ready.snapshot(),
            "queue_depth": self._queue_depth.snapshot(),
            "queue_depth_peak": int(self._queue_depth.peak),
            "queue_oldest_age_s": self._queue_oldest_age.snapshot(),
            "compile_warmup": self._compile_warmup.snapshot(),
            "compile_hits": self._compile_hits.snapshot(),
            "compile_misses": self._compile_misses.snapshot(),
            # additive keys (the pre-existing key set above is a parse
            # contract): the persistent executable cache's counters
            "exec_cache_hits": self._exec_cache_hits.snapshot(),
            "exec_cache_misses": self._exec_cache_misses.snapshot(),
            "exec_cache_miss_reasons": {
                reason: c.snapshot()
                for reason, c in sorted(self._exec_cache_miss_reasons.items())
            },
            "latency": latency_percentiles(self._latency.values()),
            "buckets": buckets,
        }

    def to_tensorboard(self, writer, step: int, prefix: str = "serve") -> int:
        """Flush a snapshot to a (rank-0) SummaryWriter from
        ``utils/tensorboard.py:get_summary_writer``; returns the number of
        scalars written."""
        from hydragnn_tpu.utils.tensorboard import write_scalar_dict

        return write_scalar_dict(writer, self.snapshot(), step, prefix=prefix)

    def to_prometheus_text(self) -> str:
        """Prometheus exposition snapshot of this server's registry
        (``hydragnn_tpu/obs/export.py:registry_to_prometheus_text``)."""
        from hydragnn_tpu.obs.export import registry_to_prometheus_text

        return registry_to_prometheus_text(self.registry)
