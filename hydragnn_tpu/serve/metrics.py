"""Serving metrics: the observable surface of the online-inference path.

Counters answer the questions an operator of a bucketed server actually
asks: is traffic landing in the right buckets (per-bucket request count,
batch occupancy), is the deadline batcher coalescing or just timing out
(flush reasons), is the server keeping up (queue depth, overload
rejections), and — the TPU-specific one — is anything recompiling in
steady state (compile hits/misses; a miss on the serving path is a
multi-second latency cliff, which is the whole reason the bucket ladder
exists).

Everything is a plain thread-safe in-process aggregate exported as a
dict (:meth:`ServeMetrics.snapshot`); tensorboard export rides the
existing rank-0 writer plumbing (``utils/tensorboard.py:
write_scalar_dict``).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional


def latency_percentiles(values_s) -> Dict[str, float]:
    """p50/p95/p99 over a sequence of second-latencies, in milliseconds.
    Nearest-rank on the sorted sample — exact for the small windows kept
    here, no interpolation surprises at the tail."""
    vals: List[float] = sorted(values_s)
    if not vals:
        return {"p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0}
    n = len(vals)

    def rank(q: float) -> float:
        i = min(n - 1, max(0, int(round(q * (n - 1)))))
        return vals[i] * 1e3

    return {"p50_ms": rank(0.50), "p95_ms": rank(0.95), "p99_ms": rank(0.99)}


class ServeMetrics:
    """Thread-safe serving counters for one :class:`~hydragnn_tpu.serve.
    server.ModelServer`.

    ``latency_window`` bounds the per-request latency sample the
    percentiles are computed over (a rolling window, not all-time — a
    serving process lives for days and early warmup latencies must age
    out of the tail stats).
    """

    def __init__(self, num_buckets: int, latency_window: int = 2048):
        self._lock = threading.Lock()
        self._latencies: deque = deque(maxlen=latency_window)
        self.requests_total = 0
        self.results_total = 0
        self.rejected_overload = 0
        self.oversize_largest_bucket = 0
        self.oversize_eager = 0
        self.errors = 0
        self.queue_depth = 0
        self.queue_depth_peak = 0
        # compile-cache accounting: warmup compiles are the startup AOT
        # ladder (expected, paid once); a MISS is a post-warmup dispatch
        # that required a fresh XLA compile — the thing steady-state
        # serving must never do.
        self.compile_warmup = 0
        self.compile_hits = 0
        self.compile_misses = 0
        self._buckets = [
            {
                "requests": 0,
                "batches": 0,
                "graphs": 0,
                "occupancy_sum": 0,
                "flush_full": 0,
                "flush_deadline": 0,
                "flush_drain": 0,
            }
            for _ in range(num_buckets)
        ]

    # -- recording ---------------------------------------------------------

    def record_request(self, bucket: Optional[int]) -> None:
        with self._lock:
            self.requests_total += 1
            if bucket is not None:
                self._buckets[bucket]["requests"] += 1

    def record_batch(self, bucket: int, occupancy: int, capacity: int, reason: str) -> None:
        with self._lock:
            b = self._buckets[bucket]
            b["batches"] += 1
            b["graphs"] += occupancy
            b["occupancy_sum"] += occupancy
            b[f"flush_{reason}"] = b.get(f"flush_{reason}", 0) + 1
            b["capacity"] = capacity

    def record_reject(self) -> None:
        with self._lock:
            self.rejected_overload += 1

    def record_oversize(self, kind: str) -> None:
        with self._lock:
            if kind == "largest_bucket":
                self.oversize_largest_bucket += 1
            else:
                self.oversize_eager += 1

    def record_compile(self, *, hit: bool, warmup: bool = False) -> None:
        with self._lock:
            if warmup:
                self.compile_warmup += 1
            elif hit:
                self.compile_hits += 1
            else:
                self.compile_misses += 1

    def record_error(self, n: int = 1) -> None:
        with self._lock:
            self.errors += n

    def observe_latency(self, seconds: float, n_results: int = 1) -> None:
        with self._lock:
            self._latencies.append(seconds)
            self.results_total += n_results

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = depth
            self.queue_depth_peak = max(self.queue_depth_peak, depth)

    # -- export ------------------------------------------------------------

    def snapshot(self) -> dict:
        """One consistent dict of every counter plus derived stats
        (mean occupancy per bucket, latency percentiles)."""
        with self._lock:
            buckets = []
            for b in self._buckets:
                d = dict(b)
                d["occupancy_mean"] = (
                    b["occupancy_sum"] / b["batches"] if b["batches"] else 0.0
                )
                d.pop("occupancy_sum")
                buckets.append(d)
            out = {
                "requests_total": self.requests_total,
                "results_total": self.results_total,
                "rejected_overload": self.rejected_overload,
                "oversize_largest_bucket": self.oversize_largest_bucket,
                "oversize_eager": self.oversize_eager,
                "errors": self.errors,
                "queue_depth": self.queue_depth,
                "queue_depth_peak": self.queue_depth_peak,
                "compile_warmup": self.compile_warmup,
                "compile_hits": self.compile_hits,
                "compile_misses": self.compile_misses,
                "latency": latency_percentiles(self._latencies),
                "buckets": {f"bucket_{i}": b for i, b in enumerate(buckets)},
            }
        return out

    def to_tensorboard(self, writer, step: int, prefix: str = "serve") -> int:
        """Flush a snapshot to a (rank-0) SummaryWriter from
        ``utils/tensorboard.py:get_summary_writer``; returns the number of
        scalars written."""
        from hydragnn_tpu.utils.tensorboard import write_scalar_dict

        return write_scalar_dict(writer, self.snapshot(), step, prefix=prefix)
