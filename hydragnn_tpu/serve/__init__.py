"""hydragnn_tpu.serve — batched online inference for trained models.

The training side of this tree compiles ONE worst-case batch shape and
streams epochs through it; serving inverts the problem: requests arrive
one graph at a time, at unpredictable sizes, and a fresh XLA compile on
the request path is a multi-second latency cliff. This package answers
with four pieces:

  - :mod:`~hydragnn_tpu.serve.registry` — named checkpoints loaded once
    and held warm (restored variables + jitted forward);
  - :mod:`~hydragnn_tpu.serve.buckets` — a ladder of pad plans, each
    AOT-compiled at startup, with smallest-fitting-bucket routing;
  - :mod:`~hydragnn_tpu.serve.batcher` — a bounded deadline queue that
    coalesces single-graph requests into bucket batches;
  - :mod:`~hydragnn_tpu.serve.metrics` — the operator surface (per-
    bucket traffic, occupancy, latency percentiles, compile hits/misses);
  - :mod:`~hydragnn_tpu.serve.supervise` — the in-process dispatch
    supervisor (bounded restart + re-armed hang watchdog) behind the
    self-healing guarantees in docs/RESILIENCE.md "Serving resilience":
    poison isolation (:class:`RequestFailed`), health/readiness probes
    (``ModelServer.health``, ``tools/serve_probe.py``), and
    zero-downtime reload (``ModelServer.reload``).

Entry points: ``hydragnn_tpu.api.serve_model`` stands a server up from a
trained run; :class:`ModelServer` composes the pieces for in-memory
models (benches, tests).
"""

from hydragnn_tpu.serve.batcher import (  # noqa: F401
    MicroBatchQueue,
    Overloaded,
    ServerClosed,
)
from hydragnn_tpu.serve.buckets import (  # noqa: F401
    Bucket,
    BucketCompileCache,
    build_bucket_ladder,
    route,
)
from hydragnn_tpu.serve.metrics import ServeMetrics, latency_percentiles  # noqa: F401
from hydragnn_tpu.serve.registry import (  # noqa: F401
    ModelRegistry,
    ServedModel,
    load_served_variables,
)
from hydragnn_tpu.serve.server import (  # noqa: F401
    ModelServer,
    Oversize,
    ReloadFailed,
    RequestFailed,
    ServeConfig,
    request_to_dict,
)
from hydragnn_tpu.serve.supervise import DispatchSupervisor  # noqa: F401
