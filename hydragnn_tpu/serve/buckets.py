"""Bucketed pad-plan ladder: the serving-side compile cache.

Training amortizes one worst-case pad plan over an epoch; serving cannot
— a single-graph request padded to the dataset worst case wastes compute
proportional to the size spread, while padding each request to its own
shape recompiles per shape (seconds on XLA:TPU — a latency cliff no
online path can absorb). The middle ground is a small LADDER of padded
shapes ("buckets"), each AOT-compiled once at startup: every request
routes to the smallest bucket whose per-graph caps fit it, so
steady-state traffic never sees a fresh compile and small graphs never
pay the big-graph pad.

The plans themselves come from ``data/loader.py:bucket_pad_plans`` (the
same ``pad_plan_for`` arithmetic every GraphLoader uses), so a bucket
batch obeys exactly the invariants the model chassis assumes of loader
batches.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One rung of the ladder.

    ``cap_nodes``/``cap_edges`` are PER-GRAPH routing caps; the pad plan
    (``node_pad``, ``edge_pad``, ``graph_pad``) covers any batch of up to
    ``max_batch`` graphs each within the caps, by construction
    (bucket_pad_plans builds it from a synthetic worst-case batch of
    cap-sized graphs)."""

    index: int
    cap_nodes: int
    cap_edges: int
    node_pad: int
    edge_pad: int
    graph_pad: int
    max_batch: int

    def fits_graph(self, num_nodes: int, num_edges: int) -> bool:
        return num_nodes <= self.cap_nodes and num_edges <= self.cap_edges

    def fits_totals(self, tot_nodes: int, tot_edges: int, n_graphs: int) -> bool:
        """Whether a concrete batch fits the PAD PLAN (batch_graphs needs
        one spare node slot and one spare graph slot for padding)."""
        return (
            tot_nodes < self.node_pad
            and tot_edges <= self.edge_pad
            and n_graphs < self.graph_pad
        )


def build_bucket_ladder(
    reference_samples: Sequence,
    max_batch: int,
    num_buckets: int = 3,
    node_multiple: int = 16,
    edge_multiple: int = 8,
) -> List[Bucket]:
    """Size a ladder from a reference sample set (typically the prepared
    dataset the model was trained on — serving traffic is assumed to be
    drawn from a similar size distribution; graphs beyond the top rung
    take the server's oversize fallback path).

    Ascending, deduplicated by pad plan: quantile spacing on a tight size
    distribution can collapse adjacent rungs into one."""
    from hydragnn_tpu.data.loader import bucket_pad_plans

    plans = bucket_pad_plans(
        reference_samples,
        max_batch,
        num_buckets=num_buckets,
        node_multiple=node_multiple,
        edge_multiple=edge_multiple,
    )
    return [
        Bucket(
            index=i,
            cap_nodes=cap_n,
            cap_edges=cap_e,
            node_pad=plan[0],
            edge_pad=plan[1],
            graph_pad=plan[2],
            max_batch=max_batch,
        )
        for i, ((cap_n, cap_e), plan) in enumerate(plans)
    ]


def route(
    buckets: Sequence[Bucket], num_nodes: int, num_edges: int
) -> Optional[Bucket]:
    """Smallest bucket whose per-graph caps fit, or None (oversize —
    the server's fallback path decides what happens next). Buckets are
    ascending, so the first fit is the smallest."""
    for b in buckets:
        if b.fits_graph(num_nodes, num_edges):
            return b
    return None


class BucketCompileCache:
    """AOT-compiled forward executable per bucket.

    ``warmup`` materializes the whole ladder up front; after that,
    :meth:`executable` is a dict lookup — a serving dispatch can only
    recompile by going through the eager fallback, which the server
    counts as a miss.

    With an :class:`~hydragnn_tpu.utils.exec_cache.ExecCache` attached,
    warmup first tries the persistent on-disk executable cache: a disk
    hit deserializes in milliseconds with ZERO XLA compiles (a second
    replica or a post-restart server starts warm), and every live
    compile is stored back so the NEXT process hits. ``compile_warmup``
    counts only LIVE compiles — a fully warm start reports
    ``compile_warmup == 0``, which bench_serve.py and the ci.sh warm
    stage pin."""

    def __init__(
        self,
        forward,
        variables,
        build_warm_batch,
        metrics=None,
        exec_cache=None,
        identity=None,
        compat=None,
    ):
        """``forward`` is the jitted forward fn (variables, batch) ->
        outputs; ``build_warm_batch(bucket)`` builds a structurally
        representative all-padding batch at the bucket's plan.
        ``identity`` is the model-architecture half of the disk-cache
        key (the bucket pad plan is mixed in per bucket); ``compat`` is
        the environment manifest (versions, device_kind, layout) the
        disk cache validates entries against."""
        self._forward = forward
        self._variables = variables
        self._build_warm_batch = build_warm_batch
        self._metrics = metrics
        self._exec_cache = exec_cache
        self._identity = identity
        self._compat = compat or {}
        self._compiled = {}
        # armed by rebind(require_canary=True) after a hot reload: an
        # on-demand compile against the NEW variables must pass the same
        # all-finite gate the reload canary applied to the warm ladder
        self._post_rebind_gate = False

    def _key(self, b: Bucket) -> Optional[str]:
        if self._exec_cache is None or not self._exec_cache.enabled:
            return None
        from hydragnn_tpu.utils.exec_cache import fingerprint

        return fingerprint(
            "serve_bucket",
            self._identity,
            (b.node_pad, b.edge_pad, b.graph_pad, b.max_batch),
        )

    def _load_disk(self, b: Bucket):
        key = self._key(b)
        if key is None:
            return None
        return self._exec_cache.load(key, self._compat, label=f"bucket_{b.index}")

    def _store_disk(self, b: Bucket, exe) -> None:
        key = self._key(b)
        if key is not None:
            self._exec_cache.store(key, exe, self._compat, label=f"bucket_{b.index}")

    def warmup(self, buckets: Sequence[Bucket]) -> None:
        for b in buckets:
            if b.index in self._compiled:
                continue
            exe = self._load_disk(b)
            if exe is not None:
                # disk hit: no XLA compile happened, so compile_warmup
                # stays untouched (the exec-cache hit counter carries it)
                self._compiled[b.index] = exe
                continue
            warm = self._build_warm_batch(b)
            exe = self._forward.lower(self._variables, warm).compile()
            self._compiled[b.index] = exe
            self._store_disk(b, exe)
            if self._metrics is not None:
                self._metrics.record_compile(hit=False, warmup=True)

    def rebind(self, variables, require_canary: bool = False) -> None:
        """Point future on-demand compiles at new weights (hot reload).
        Existing executables are shape-specialized, not value-
        specialized — they serve the new variables unchanged.
        ``require_canary=True`` additionally routes every FUTURE
        on-demand :meth:`executable` materialization through the
        all-finite gate the reload canary applied to the warm ladder —
        without it, a bucket first compiled after a reload would serve
        the new weights unvetted."""
        self._variables = variables
        if require_canary:
            self._post_rebind_gate = True

    def executable(self, bucket: Bucket):
        """The pre-built executable for ``bucket``; materializes on
        demand — disk cache first, else a live compile (recorded as a
        MISS: this only happens if warmup was skipped)."""
        exe = self._compiled.get(bucket.index)
        if exe is None:
            exe = self._load_disk(bucket)
            hit_disk = exe is not None
            if exe is None:
                warm = self._build_warm_batch(bucket)
                exe = self._forward.lower(self._variables, warm).compile()
            if self._post_rebind_gate:
                self._canary_gate(exe, bucket)
            self._compiled[bucket.index] = exe
            if not hit_disk:
                self._store_disk(bucket, exe)
                if self._metrics is not None:
                    self._metrics.record_compile(hit=False)
        elif self._metrics is not None:
            self._metrics.record_compile(hit=True)
        return exe

    def _canary_gate(self, exe, bucket: Bucket) -> None:
        """The reload canary's all-finite check, applied to an
        executable materialized AFTER a hot reload: run it on the
        bucket's warm batch against the current (post-reload) variables
        and reject non-finite outputs before it ever serves traffic."""
        import numpy as np

        outs = exe(self._variables, self._build_warm_batch(bucket))
        for i, o in enumerate(outs):
            if not np.all(np.isfinite(np.asarray(o))):
                raise RuntimeError(
                    f"post-reload canary gate: on-demand executable for "
                    f"bucket {bucket.index} produced non-finite outputs "
                    f"(head {i}) against the reloaded weights"
                )

    def __len__(self) -> int:
        return len(self._compiled)
