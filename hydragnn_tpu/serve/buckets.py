"""Bucketed pad-plan ladder: the serving-side compile cache.

Training amortizes one worst-case pad plan over an epoch; serving cannot
— a single-graph request padded to the dataset worst case wastes compute
proportional to the size spread, while padding each request to its own
shape recompiles per shape (seconds on XLA:TPU — a latency cliff no
online path can absorb). The middle ground is a small LADDER of padded
shapes ("buckets"), each AOT-compiled once at startup: every request
routes to the smallest bucket whose per-graph caps fit it, so
steady-state traffic never sees a fresh compile and small graphs never
pay the big-graph pad.

The plans themselves come from ``data/loader.py:bucket_pad_plans`` (the
same ``pad_plan_for`` arithmetic every GraphLoader uses), so a bucket
batch obeys exactly the invariants the model chassis assumes of loader
batches.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One rung of the ladder.

    ``cap_nodes``/``cap_edges`` are PER-GRAPH routing caps; the pad plan
    (``node_pad``, ``edge_pad``, ``graph_pad``) covers any batch of up to
    ``max_batch`` graphs each within the caps, by construction
    (bucket_pad_plans builds it from a synthetic worst-case batch of
    cap-sized graphs)."""

    index: int
    cap_nodes: int
    cap_edges: int
    node_pad: int
    edge_pad: int
    graph_pad: int
    max_batch: int

    def fits_graph(self, num_nodes: int, num_edges: int) -> bool:
        return num_nodes <= self.cap_nodes and num_edges <= self.cap_edges

    def fits_totals(self, tot_nodes: int, tot_edges: int, n_graphs: int) -> bool:
        """Whether a concrete batch fits the PAD PLAN (batch_graphs needs
        one spare node slot and one spare graph slot for padding)."""
        return (
            tot_nodes < self.node_pad
            and tot_edges <= self.edge_pad
            and n_graphs < self.graph_pad
        )


def build_bucket_ladder(
    reference_samples: Sequence,
    max_batch: int,
    num_buckets: int = 3,
    node_multiple: int = 16,
    edge_multiple: int = 8,
) -> List[Bucket]:
    """Size a ladder from a reference sample set (typically the prepared
    dataset the model was trained on — serving traffic is assumed to be
    drawn from a similar size distribution; graphs beyond the top rung
    take the server's oversize fallback path).

    Ascending, deduplicated by pad plan: quantile spacing on a tight size
    distribution can collapse adjacent rungs into one."""
    from hydragnn_tpu.data.loader import bucket_pad_plans

    plans = bucket_pad_plans(
        reference_samples,
        max_batch,
        num_buckets=num_buckets,
        node_multiple=node_multiple,
        edge_multiple=edge_multiple,
    )
    return [
        Bucket(
            index=i,
            cap_nodes=cap_n,
            cap_edges=cap_e,
            node_pad=plan[0],
            edge_pad=plan[1],
            graph_pad=plan[2],
            max_batch=max_batch,
        )
        for i, ((cap_n, cap_e), plan) in enumerate(plans)
    ]


def route(
    buckets: Sequence[Bucket], num_nodes: int, num_edges: int
) -> Optional[Bucket]:
    """Smallest bucket whose per-graph caps fit, or None (oversize —
    the server's fallback path decides what happens next). Buckets are
    ascending, so the first fit is the smallest."""
    for b in buckets:
        if b.fits_graph(num_nodes, num_edges):
            return b
    return None


class BucketCompileCache:
    """AOT-compiled forward executable per bucket.

    ``warmup`` compiles the whole ladder up front (startup cost, recorded
    as ``compile_warmup`` in the metrics); after that, :meth:`executable`
    is a dict lookup — a serving dispatch can only recompile by going
    through the eager fallback, which the server counts as a miss."""

    def __init__(self, forward, variables, build_warm_batch, metrics=None):
        """``forward`` is the jitted forward fn (variables, batch) ->
        outputs; ``build_warm_batch(bucket)`` builds a structurally
        representative all-padding batch at the bucket's plan."""
        self._forward = forward
        self._variables = variables
        self._build_warm_batch = build_warm_batch
        self._metrics = metrics
        self._compiled = {}

    def warmup(self, buckets: Sequence[Bucket]) -> None:
        for b in buckets:
            if b.index in self._compiled:
                continue
            warm = self._build_warm_batch(b)
            self._compiled[b.index] = self._forward.lower(
                self._variables, warm
            ).compile()
            if self._metrics is not None:
                self._metrics.record_compile(hit=False, warmup=True)

    def rebind(self, variables) -> None:
        """Point future on-demand compiles at new weights (hot reload).
        Existing executables are shape-specialized, not value-
        specialized — they serve the new variables unchanged."""
        self._variables = variables

    def executable(self, bucket: Bucket):
        """The pre-built executable for ``bucket``; compiles on demand
        (recorded as a MISS — this only happens if warmup was skipped)."""
        exe = self._compiled.get(bucket.index)
        if exe is None:
            warm = self._build_warm_batch(bucket)
            exe = self._forward.lower(self._variables, warm).compile()
            self._compiled[bucket.index] = exe
            if self._metrics is not None:
                self._metrics.record_compile(hit=False)
        elif self._metrics is not None:
            self._metrics.record_compile(hit=True)
        return exe

    def __len__(self) -> int:
        return len(self._compiled)
