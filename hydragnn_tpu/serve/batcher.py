"""Async micro-batcher: coalesce single-graph requests into bucket batches.

Online traffic arrives one graph at a time; the TPU wants batches. The
queue here buys batch occupancy with a bounded latency budget: a bucket
flushes the moment it holds ``max_batch`` requests (occupancy win) or
when its OLDEST request has waited ``max_delay_s`` (latency bound) —
whichever comes first. Under light load every request pays at most the
deadline; under heavy load batches fill before the deadline and the
deadline never fires.

Backpressure is explicit: the queue is bounded across all buckets and
``put`` raises :class:`Overloaded` instead of buffering unboundedly —
the caller (or a fronting load balancer) decides whether to retry,
shed, or route elsewhere. An overloaded server that queues silently
just converts overload into timeout storms downstream.

This module is deliberately jax-free: it moves (item, Future) pairs
between threads. The server owns execution.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, List, Optional, Tuple

from hydragnn_tpu.utils import syncdebug


class Overloaded(RuntimeError):
    """The request queue is full — explicit load-shedding signal."""


class ServerClosed(RuntimeError):
    """Submission after close()/stop(): the typed immediate rejection —
    never an enqueued future that can no longer resolve."""


@dataclasses.dataclass
class PendingRequest:
    item: Any
    future: Future
    t_enqueue: float  # time.monotonic() at admission
    bucket: int
    seq: int = -1  # server-wide admission sequence number
    trace: Any = None  # obs/trace.py RequestTrace (None when tracing off)
    tenant: str = "default"  # admitting tenant (fleet router; spool attribution)


class MicroBatchQueue:
    """Thread-safe bounded multi-bucket queue with deadline coalescing.

    Producers call :meth:`put` (any thread); a single consumer thread
    loops on :meth:`take_batch`, which blocks until some bucket is
    flushable and returns ``(bucket_index, requests, reason)`` with
    reason one of ``"full"`` / ``"deadline"`` / ``"drain"`` (close-time
    flush), or ``None`` once closed and drained.
    """

    def __init__(self, num_buckets: int, max_batch: int, max_delay_s: float, max_pending: int):
        if num_buckets < 1:
            raise ValueError("num_buckets must be >= 1")
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        self._max_batch = max_batch
        self._max_delay_s = float(max_delay_s)
        self._max_pending = max_pending
        self._cv = syncdebug.maybe_wrap(
            threading.Condition(), "batcher.MicroBatchQueue._cv"
        )
        # graftsync: guarded-by=batcher.MicroBatchQueue._cv
        self._pending: List[deque] = [deque() for _ in range(num_buckets)]
        self._count = 0  # graftsync: guarded-by=batcher.MicroBatchQueue._cv
        self._closed = False  # graftsync: guarded-by=batcher.MicroBatchQueue._cv

    def put(
        self,
        bucket: int,
        item: Any,
        seq: int = -1,
        trace: Any = None,
        tenant: str = "default",
    ) -> Future:
        """Admit one request into ``bucket``'s lane; returns its Future.
        Raises :class:`Overloaded` when the queue is at capacity and
        :class:`ServerClosed` after :meth:`close` — a closed queue must
        reject immediately, never mint a future no consumer will ever
        resolve."""
        fut: Future = Future()
        with self._cv:
            if self._closed:
                raise ServerClosed("serving queue is closed")
            if self._count >= self._max_pending:
                raise Overloaded(
                    f"serving queue full ({self._count}/{self._max_pending} pending)"
                )
            self._pending[bucket].append(
                PendingRequest(
                    item, fut, time.monotonic(), bucket, seq, trace, tenant
                )
            )
            self._count += 1
            self._cv.notify_all()
        return fut

    def depth(self) -> int:
        with self._cv:
            return self._count

    def oldest_age_s(self) -> float:
        """Age (seconds) of the oldest queued request across all
        buckets; 0.0 when the queue is empty. The head of each bucket's
        deque is its oldest admit, so this is O(buckets)."""
        with self._cv:
            oldest = None
            for dq in self._pending:
                if dq and (oldest is None or dq[0].t_enqueue < oldest):
                    oldest = dq[0].t_enqueue
        if oldest is None:
            return 0.0
        return max(time.monotonic() - oldest, 0.0)

    def take_batch(self) -> Optional[Tuple[int, List[PendingRequest], str]]:
        with self._cv:
            while True:
                # full buckets flush immediately, fullest first — under
                # sustained load the deadline never gates throughput
                best_full = None
                for i, dq in enumerate(self._pending):
                    if len(dq) >= self._max_batch and (
                        best_full is None
                        or len(dq) > len(self._pending[best_full])
                    ):
                        best_full = i
                if best_full is not None:
                    reason = "full" if not self._closed else "drain"
                    return best_full, self._pop(best_full), reason

                if self._closed:
                    for i, dq in enumerate(self._pending):
                        if dq:
                            return i, self._pop(i), "drain"
                    return None

                # earliest-deadline bucket next
                now = time.monotonic()
                soonest, soonest_t = None, None
                for i, dq in enumerate(self._pending):
                    if dq:
                        t = dq[0].t_enqueue + self._max_delay_s
                        if soonest_t is None or t < soonest_t:
                            soonest, soonest_t = i, t
                if soonest is not None and soonest_t <= now:
                    return soonest, self._pop(soonest), "deadline"
                self._cv.wait(
                    timeout=None if soonest_t is None else max(soonest_t - now, 0.0)
                )

    # graftsync: holds=batcher.MicroBatchQueue._cv
    def _pop(self, bucket: int) -> List[PendingRequest]:
        dq = self._pending[bucket]
        out = [dq.popleft() for _ in range(min(len(dq), self._max_batch))]
        self._count -= len(out)
        self._cv.notify_all()
        return out

    def close(self) -> None:
        """Stop admitting; take_batch drains what is queued then returns
        None. Idempotent."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def cancel_pending(self, exc: Optional[BaseException] = None) -> int:
        """Fail every queued request (server teardown without drain);
        returns how many were cancelled."""
        # drain under the lock, resolve futures OUTSIDE it: resolving a
        # future runs its done-callbacks synchronously on this thread,
        # and a callback that touches the queue (depth(), a retry
        # re-put) would deadlock on the non-reentrant Condition
        drained: List[PendingRequest] = []
        with self._cv:
            for dq in self._pending:
                while dq:
                    drained.append(dq.popleft())
            self._count = 0
            self._cv.notify_all()
        for req in drained:
            if exc is not None:
                req.future.set_exception(exc)
            else:
                req.future.cancel()
        return len(drained)
