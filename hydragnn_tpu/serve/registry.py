"""Model registry: named checkpoints -> warm, jitted forward functions.

One serving process answers requests for one or more trained runs (the
multi-headed design makes a single loaded model already serve N property
endpoints; the registry adds the run dimension). Loading goes through
the exact training-side machinery — ``models/create.py`` for the
factory, ``train.create_eval_state`` for the checkpoint schema,
``utils/checkpoint.py:load_existing_model`` for the restore — so a
served model is bit-identical to what ``api.run_prediction`` would
evaluate, and a ZeRO-1-trained checkpoint restores without ever
materializing optimizer state on device.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional

from hydragnn_tpu.utils import syncdebug


@dataclasses.dataclass
class ServedModel:
    """A loaded model held warm for inference: flax module + restored
    variables + the jitted forward. ``forward`` donates the batch buffers
    on accelerator backends (each dispatch consumes a freshly-built
    request batch, so its device memory can be recycled into outputs);
    CPU skips donation — XLA:CPU cannot use donated buffers and would
    warn per dispatch.

    ``partitioner`` (hydragnn_tpu/parallel/partitioner.py) carries the
    serving mesh: with ``fsdp > 1`` the variables are sharded over it
    (set by the registry's admission paths) and the server places every
    request/warmup batch replicated on the same mesh so the AOT
    executables see one committed layout. None/default = the
    single-device story, unchanged."""

    name: str
    model: Any  # HydraModel
    variables: Dict[str, Any]  # {'params': ..., 'batch_stats': ...}
    nn_config: Optional[Dict[str, Any]] = None
    partitioner: Any = None
    _forward: Any = dataclasses.field(default=None, repr=False)

    @property
    def cfg(self):
        return self.model.cfg

    @property
    def forward(self):
        """Jitted ``(variables, batch) -> [outputs per head]`` eval
        forward (train=False: dropout off, running BatchNorm stats —
        identical semantics to ``train.make_eval_step``)."""
        if self._forward is None:
            import jax

            model = self.model

            def fwd(variables, batch):
                return model.apply(variables, batch, train=False)

            donate = () if jax.default_backend() == "cpu" else (1,)
            self._forward = jax.jit(fwd, donate_argnums=donate)
        return self._forward

    def head_names(self) -> List[str]:
        return list(self.cfg.output_names)


class ModelRegistry:
    """Thread-safe name -> :class:`ServedModel` map.

    Two admission paths:
      - :meth:`load`: restore a named checkpoint from a run directory
        (the ``log_name`` convention ``api.run_training`` saves under);
      - :meth:`register`: adopt an in-memory (model, variables) pair —
        benches and tests serve random-init models without a checkpoint
        round-trip.
    """

    def __init__(self, log_dir: str = "./logs/"):
        self.log_dir = log_dir
        self._lock = syncdebug.maybe_wrap(
            threading.Lock(), "registry.ModelRegistry._lock"
        )
        # graftsync: guarded-by=registry.ModelRegistry._lock
        self._models: Dict[str, ServedModel] = {}

    def register(
        self,
        name: str,
        model: Any,
        variables: Dict[str, Any],
        nn_config: Optional[Dict[str, Any]] = None,
        partitioner: Any = None,
    ) -> ServedModel:
        variables = dict(variables)
        if partitioner is not None:
            variables = partitioner.shard_variables(variables)
        served = ServedModel(
            name=name,
            model=model,
            variables=variables,
            nn_config=nn_config,
            partitioner=partitioner,
        )
        with self._lock:
            self._models[name] = served
        return served

    def load(
        self,
        log_name: str,
        nn_config: Dict[str, Any],
        example_graph: Any,
        seed: int = 0,
        partitioner: Any = None,
    ) -> ServedModel:
        """Build the model from its (completed) ``NeuralNetwork`` config,
        then overwrite the fresh init with the checkpoint under
        ``<log_dir>/<log_name>/``. ``example_graph`` is one prepared
        sample (GraphSample or graph dict) — init only needs its feature
        shapes, not the serving pad plan. Idempotent per name: a second
        load replaces the entry (checkpoint refresh).

        The restore goes through the VALIDATING loader
        (``load_existing_model``: sha256 sidecars, parse validation,
        fallback down the retained ``.step<N>.mp`` versions with a loud
        warning) — a torn/corrupt checkpoint pointer serves the newest
        intact version instead of deserializing garbage into a warm
        forward (pinned by tests/test_serve_resilience.py)."""
        from hydragnn_tpu.graph.batch import batch_graphs
        from hydragnn_tpu.models.create import create_model_config
        from hydragnn_tpu.serve.server import request_to_dict
        from hydragnn_tpu.train import create_eval_state, select_optimizer
        from hydragnn_tpu.utils.checkpoint import load_existing_model

        example_batch = batch_graphs([request_to_dict(example_graph)])
        model, variables = create_model_config(nn_config, example_batch, seed=seed)
        # The optimizer chain defines the checkpoint's opt_state SCHEMA
        # (freeze_conv changes the pytree structure); eval never reads it
        # and create_eval_state keeps the restore target host-side.
        tx = select_optimizer(
            nn_config["Training"],
            freeze_conv=bool(nn_config["Architecture"].get("freeze_conv_layers")),
        )
        state = create_eval_state(variables, tx)
        state = load_existing_model(state, log_name, self.log_dir)
        served_vars = {"params": state.params, "batch_stats": state.batch_stats}
        if partitioner is not None:
            # fsdp-shard the served parameters over the partitioner's
            # mesh (a model beyond one chip's HBM serves from N chips)
            served_vars = partitioner.shard_variables(served_vars)
        served = ServedModel(
            name=log_name,
            model=model,
            variables=served_vars,
            nn_config=nn_config,
            partitioner=partitioner,
        )
        with self._lock:
            self._models[log_name] = served
        return served

    def get(self, name: str) -> ServedModel:
        with self._lock:
            if name not in self._models:
                raise KeyError(
                    f"model {name!r} not in registry (loaded: {sorted(self._models)})"
                )
            return self._models[name]

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)


def load_served_variables(
    served: ServedModel, log_name: str, log_dir: str = "./logs/"
) -> Dict[str, Any]:
    """Fresh ``{'params', 'batch_stats'}`` for an ALREADY-served model,
    restored through the validating checkpoint loader
    (``utils/checkpoint.py:load_existing_model``: sha256 sidecars,
    torn-pointer fallback down the retained versions, loud rejection
    warnings) — the path :meth:`ModelServer.reload` uses so a corrupt
    checkpoint pointer can never deserialize garbage into a warm
    forward. The served model supplies the schema (its current
    variables' pytree) and the optimizer chain (``nn_config``)."""
    from hydragnn_tpu.train import create_eval_state, select_optimizer
    from hydragnn_tpu.utils.checkpoint import load_existing_model

    nn_config = served.nn_config
    if nn_config is None:
        raise ValueError(
            f"served model {served.name!r} has no nn_config (registered "
            "in-memory); reload it with explicit variables= instead"
        )
    tx = select_optimizer(
        nn_config["Training"],
        freeze_conv=bool(nn_config["Architecture"].get("freeze_conv_layers")),
    )
    state = create_eval_state(served.variables, tx)
    state = load_existing_model(state, log_name, log_dir)
    return {"params": state.params, "batch_stats": state.batch_stats}
