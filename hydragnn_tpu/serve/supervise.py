"""In-process dispatch supervisor: the serving analog of the restart
supervisor that keeps training alive (``resilience/supervisor.py``).

One thread executes every batch the micro-batcher coalesces; if that
thread dies, every queued future wedges silently and the server looks
healthy from the outside — the exact failure PR 3 taught training to
survive. The supervisor closes that gap with the same two mechanisms,
scoped to a thread instead of a process:

  - **bounded restart with backoff** — the dispatch target runs under a
    wrapper that captures any escaping exception; a monitor thread
    notices the death, records a ``dispatch_restart`` flight event,
    waits out the :class:`~hydragnn_tpu.resilience.supervisor.
    SupervisorPolicy` backoff (the training policy's arithmetic,
    serving-scale defaults), and starts a fresh thread. Past
    ``max_restarts`` it gives up: the ``on_giveup`` callback fails every
    pending future with a typed error and closes admission — a loudly
    dead server, not a silently wedged one.
  - **re-armed hang watchdog** — the PR 3
    :class:`~hydragnn_tpu.resilience.watchdog.HangWatchdog` fed a
    heartbeat from the dispatch loop, gated on the loop being BUSY (an
    idle server blocked on the queue is not hung) and re-arming after a
    stall clears (a wedged forward that eventually returns resumes
    service; the stall is evidence in the flight record, not a death
    sentence). While stalled, liveness is False — the orchestrator's
    probe sees a wedged server even though the process is fine.

The monitor doubles as the health-export ticker: ``on_tick`` runs every
``tick_every_s`` (ModelServer points it at the Prometheus textfile
writer so ``tools/serve_probe.py`` always reads a fresh snapshot).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from hydragnn_tpu.resilience.supervisor import SupervisorPolicy
from hydragnn_tpu.resilience.watchdog import HangWatchdog


class DispatchSupervisor:
    """Supervise one dispatch-loop thread.

    ``target`` is the dispatch loop; a normal return is a clean
    shutdown (queue closed + drained) and is never restarted. The loop
    must call :meth:`beat` once per iteration and bracket device work
    with ``busy(True)`` / ``busy(False)`` so the watchdog only counts a
    stall while a forward is actually in flight.
    """

    def __init__(
        self,
        target: Callable[[], None],
        policy: Optional[SupervisorPolicy] = None,
        stall_s: float = 30.0,
        flight=None,
        metrics=None,
        on_giveup: Optional[Callable[[BaseException], None]] = None,
        on_stall_change: Optional[Callable[[bool], None]] = None,
        on_tick: Optional[Callable[[], None]] = None,
        tick_every_s: float = 5.0,
        poll_s: float = 0.05,
        thread_name: str = "hydragnn-serve-executor",
    ):
        self._target = target
        self.policy = policy or SupervisorPolicy()
        self.flight = flight
        self.metrics = metrics
        self.on_giveup = on_giveup
        self.on_stall_change = on_stall_change
        self.on_tick = on_tick
        self.tick_every_s = float(tick_every_s)
        self.poll_s = float(poll_s)
        self.thread_name = thread_name
        # graftsync: thread-safe=only the single monitor thread increments; health readers tolerate staleness
        self.restarts = 0
        # graftsync: thread-safe=GIL-atomic one-way False->True latch set by the monitor thread
        self.failed = False
        # graftsync: thread-safe=GIL-atomic reference store from the worker thread; the monitor reads it once after join
        self.last_error: Optional[BaseException] = None
        # graftsync: thread-safe=GIL-atomic bool; dispatch thread writes, watchdog gate reads — a stale read shifts stall attribution by one poll
        self._busy = False
        # graftsync: thread-safe=only the single monitor thread touches it
        self._was_stalled = False
        # graftsync: thread-safe=GIL-atomic bool; worker sets it as its last act, the monitor reads it only after is_alive() is False
        self._clean_exit = False
        # graftsync: thread-safe=GIL-atomic one-way False->True latch set by stop()
        self._stopping = False
        # graftsync: thread-safe=written by the owning thread (start/stop) and the monitor's crash path; GIL-atomic reference store
        self._worker: Optional[threading.Thread] = None
        # graftsync: thread-safe=start()/stop() run on the owning thread only
        self._monitor: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.watchdog = HangWatchdog(
            stall_s,
            flight=flight,
            action=lambda: None,  # fired state IS the signal; health reads it
            gate=lambda: self._busy,
            rearm=True,
            end_run_on_fire=False,
            warmup_beats=0,
        )

    # -- signals from the dispatch loop ------------------------------------

    def beat(self) -> None:
        self.watchdog.beat()

    def busy(self, flag: bool) -> None:
        self._busy = bool(flag)

    # -- health surface ----------------------------------------------------

    @property
    def alive(self) -> bool:
        return self._worker is not None and self._worker.is_alive()

    @property
    def stalled(self) -> bool:
        return bool(self.watchdog.fired)

    def heartbeat_age(self) -> float:
        return self.watchdog.heartbeat_age()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "DispatchSupervisor":
        if self._monitor is not None:
            return self
        self.watchdog.beat()
        self._spawn_worker()
        self.watchdog.start()
        self._monitor = threading.Thread(
            target=self._run_monitor, name=f"{self.thread_name}-supervisor",
            daemon=True,
        )
        self._monitor.start()
        return self

    def stop(self, timeout: Optional[float] = 10.0) -> None:
        """Join the worker (the caller closes the queue first so it
        exits its loop), then stop the monitor and watchdog."""
        self._stopping = True
        if self._worker is not None:
            self._worker.join(timeout)
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
            self._monitor = None
        self.watchdog.stop()
        self._worker = None

    # -- internals ---------------------------------------------------------

    def _spawn_worker(self) -> None:
        self._clean_exit = False
        self._worker = threading.Thread(
            target=self._wrapped, name=self.thread_name, daemon=True
        )
        self._worker.start()

    # graftsync: thread-root
    def _wrapped(self) -> None:
        try:
            self._target()
            self._clean_exit = True
        except BaseException as exc:  # noqa: BLE001 - monitor classifies
            self.last_error = exc
        finally:
            self._busy = False

    # graftsync: thread-root
    def _run_monitor(self) -> None:
        last_tick = time.monotonic()
        while not self._stop.wait(self.poll_s):
            now = time.monotonic()
            if self.on_tick is not None and now - last_tick >= self.tick_every_s:
                last_tick = now
                try:
                    self.on_tick()
                except Exception:
                    pass  # an export failure must never stop supervision
            stalled = self.stalled
            if stalled != self._was_stalled:
                self._was_stalled = stalled
                if self.on_stall_change is not None:
                    self.on_stall_change(stalled)
            if self._stopping or self.failed:
                continue
            worker = self._worker
            if worker is not None and not worker.is_alive() and not self._clean_exit:
                self._handle_crash()

    def _handle_crash(self) -> None:
        exc = self.last_error or RuntimeError("dispatch thread died")
        self.restarts += 1
        if self.metrics is not None:
            self.metrics.record_dispatch_restart()
        if self.restarts > self.policy.max_restarts:
            self.failed = True
            if self.flight is not None:
                self.flight.record(
                    "dispatch_restart",
                    attempt=self.restarts,
                    cause="gave_up",
                    error=str(exc)[-300:],
                )
            if self.on_giveup is not None:
                self.on_giveup(exc)
            return
        delay = self.policy.backoff(self.restarts)
        if self.flight is not None:
            self.flight.record(
                "dispatch_restart",
                attempt=self.restarts,
                cause="crash",
                error=str(exc)[-300:],
                delay_s=delay,
            )
        # bounded backoff sleep, abandoned promptly if the server stops
        deadline = time.monotonic() + delay
        while time.monotonic() < deadline:
            if self._stop.wait(min(self.poll_s, 0.05)):
                return
            if self._stopping:
                return
        self.watchdog.beat()  # a fresh thread starts with a fresh heartbeat
        self._spawn_worker()
