"""Importer for the reference's ADIOS2 dataset format.

Existing large-scale HydraGNN deployments write their preprocessed
datasets as ADIOS2 BP files (reference: hydragnn/utils/adiosdataset.py
AdiosWriter.save :79-179; examples/ising_model/train_ising.py:232-238).
The schema is simple and fully self-describing — per split ``label``:

  attributes
    ``{label}/ndata``              sample count (int)
    ``{label}/keys``               string list of per-sample field names
    ``{label}/{k}/variable_dim``   the RAGGED axis of field ``k``
    ``minmax_node_feature`` / ``minmax_graph_feature``  (optional, flat)
    ``total_ndata``                sum over labels
  variables
    ``{label}/{k}``                all samples' ``k`` arrays concatenated
                                   along ``variable_dim``
    ``{label}/{k}/variable_count`` per-sample extent along that axis
    ``{label}/{k}/variable_offset`` per-sample start along that axis

Reading the BP container itself requires the ``adios2`` library (the
binary BP4/BP5 metadata layout is not worth re-implementing, and this
image does not ship it) — so this module offers TWO migration paths:

1. **Direct** (environments with ``adios2``, e.g. the reference's own):
   :class:`ReferenceAdiosReader` / :func:`import_adios_dataset` read the
   BP file through whichever adios2 Python API generation is installed
   (legacy ``adios2.open`` or the 2.9+ ``FileReader``) and convert
   straight to an HGC container. ``python -m
   hydragnn_tpu.data.import_reference <file.bp> <label> <out.hgc>``
   dispatches here automatically; the package is pure-Python, so
   installing it next to the reference is a checkout + PYTHONPATH.
2. **Two-step** (no shared environment): run
   ``tools/export_adios_to_pickle.py`` — a STANDALONE script (needs only
   adios2 + numpy) — inside the reference environment to emit the
   sharded-pickle layout, then import that here with the pickle path.

Both paths land in the same :class:`GraphSample` conversion
(:func:`adios_fields_to_sample`), which is what the tests pin against a
fixture that mirrors ``AdiosWriter.save`` byte-for-byte in layout.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from hydragnn_tpu.data.dataset import GraphSample


def looks_like_adios(path: str) -> bool:
    """True when ``path`` is plausibly an ADIOS2 BP file/dir (the writer
    produces a ``<name>.bp`` directory holding md.idx/data.N for BP4/5,
    or a single ``.bp`` file for older engines). A nonexistent path is
    never "ADIOS" — dispatching it here would replace the truthful
    file-not-found with a misleading 'install adios2' error."""
    if not os.path.exists(path):
        return False
    if path.rstrip("/").endswith(".bp"):
        return True
    if os.path.isdir(path):
        names = set(os.listdir(path))
        return bool({"md.idx", "md.0"} & names)
    return False


class _AdiosFile:
    """Thin adapter over the installed adios2 Python API generation.

    The reference codes against the legacy high-level API
    (``adios2.open(filename, "r")`` + ``read``/``read_attribute``/
    ``read_attribute_string``; adiosdataset.py:239-262). adios2 >= 2.9
    renamed that surface to ``FileReader`` with near-identical methods.
    """

    def __init__(self, filename: str):
        try:
            import adios2  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "reading ADIOS2 BP files needs the 'adios2' library, which "
                "is not installed here. Either run this importer inside the "
                "reference environment (the package is pure Python), or run "
                "tools/export_adios_to_pickle.py there to emit the "
                "sharded-pickle layout and import that instead."
            ) from e
        self._adios2 = adios2
        if hasattr(adios2, "FileReader"):  # 2.9+ API
            self._f = adios2.FileReader(filename)
            self._legacy = False
        else:
            self._f = adios2.open(filename, "r")
            self._legacy = True

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "_AdiosFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def available_attributes(self) -> Dict[str, Any]:
        return self._f.available_attributes()

    def read(self, name: str) -> np.ndarray:
        return np.asarray(self._f.read(name))

    def read_attribute(self, name: str) -> np.ndarray:
        return np.asarray(self._f.read_attribute(name))

    def read_attribute_string(self, name: str) -> List[str]:
        out = self._f.read_attribute_string(name)
        if isinstance(out, str):
            return [out]
        return list(out)


def _ragged_slice(arr: np.ndarray, vdim: int, start: int, count: int) -> np.ndarray:
    """Slice one sample out of the concatenated global array along its
    ragged axis (reference get(): adiosdataset.py:345-358)."""
    sl = [slice(None)] * arr.ndim
    sl[vdim] = slice(start, start + count)
    return arr[tuple(sl)]


def adios_fields_to_sample(
    fields: Dict[str, np.ndarray],
    head_types: Optional[Sequence[str]] = None,
    head_names: Optional[Sequence[str]] = None,
) -> GraphSample:
    """One sample's ``{key: ndarray}`` mapping -> :class:`GraphSample`.

    Same field semantics as the pickle path (x/pos/edge_index/edge_attr
    plus the packed y/y_loc head table) — delegated to the shared
    converter so both importers stay in lockstep."""
    from hydragnn_tpu.data.import_reference import data_object_to_sample

    return data_object_to_sample(dict(fields), head_types, head_names)


class ReferenceAdiosReader:
    """Reader for one split (``label``) of a reference ADIOS2 dataset.

    Preloads each field's global array once (the reference's
    ``preload=True`` default) and slices per sample via the
    count/offset index — identical math to AdiosDataset.get."""

    def __init__(self, filename: str, label: str):
        self.filename = filename
        self.label = label
        with _AdiosFile(filename) as f:
            attrs = set(f.available_attributes())
            ndata_name = f"{label}/ndata"
            if ndata_name not in attrs:
                labels = sorted(
                    a[: -len("/ndata")]
                    for a in attrs
                    if a.endswith("/ndata") and a != "total_ndata"
                )
                raise KeyError(
                    f"label {label!r} not found in {filename!r}; "
                    f"available labels: {labels}"
                )
            self.ndata = int(f.read_attribute(ndata_name).reshape(-1)[0])
            self.keys = f.read_attribute_string(f"{label}/keys")
            self.minmax_node_feature = (
                f.read_attribute("minmax_node_feature").reshape(2, -1)
                if "minmax_node_feature" in attrs
                else None
            )
            self.minmax_graph_feature = (
                f.read_attribute("minmax_graph_feature").reshape(2, -1)
                if "minmax_graph_feature" in attrs
                else None
            )
            self._data: Dict[str, np.ndarray] = {}
            self._count: Dict[str, np.ndarray] = {}
            self._offset: Dict[str, np.ndarray] = {}
            self._vdim: Dict[str, int] = {}
            for k in self.keys:
                self._data[k] = f.read(f"{label}/{k}")
                self._count[k] = (
                    f.read(f"{label}/{k}/variable_count").reshape(-1).astype(np.int64)
                )
                self._offset[k] = (
                    f.read(f"{label}/{k}/variable_offset").reshape(-1).astype(np.int64)
                )
                self._vdim[k] = int(
                    f.read_attribute(f"{label}/{k}/variable_dim").reshape(-1)[0]
                )

    def __len__(self) -> int:
        return self.ndata

    def fields(self, idx: int) -> Dict[str, np.ndarray]:
        if not 0 <= idx < self.ndata:
            raise IndexError(idx)
        return {
            k: _ragged_slice(
                self._data[k],
                self._vdim[k],
                int(self._offset[k][idx]),
                int(self._count[k][idx]),
            )
            for k in self.keys
        }

    def read(
        self,
        idx: int,
        head_types: Optional[Sequence[str]] = None,
        head_names: Optional[Sequence[str]] = None,
    ) -> GraphSample:
        return adios_fields_to_sample(self.fields(idx), head_types, head_names)

    def samples(
        self,
        head_types: Optional[Sequence[str]] = None,
        head_names: Optional[Sequence[str]] = None,
    ) -> List[GraphSample]:
        return [self.read(i, head_types, head_names) for i in range(self.ndata)]


def import_adios_dataset(
    filename: str,
    label: str,
    out_path: str,
    head_types: Optional[Sequence[str]] = None,
    head_names: Optional[Sequence[str]] = None,
) -> int:
    """Convert one split of a reference ADIOS2 dataset into an HGC
    container at ``out_path``. Returns the sample count. The reference's
    minmax metadata rides along as container globals (same contract as
    the pickle importer)."""
    from hydragnn_tpu.data.container import ContainerWriter

    reader = ReferenceAdiosReader(filename, label)
    writer = ContainerWriter(out_path)
    writer.add(reader.samples(head_types, head_names))
    for name, val in (
        ("minmax_node_feature", reader.minmax_node_feature),
        ("minmax_graph_feature", reader.minmax_graph_feature),
    ):
        if val is not None:
            writer.add_global(name, np.asarray(val))
    writer.save()
    return len(reader)
