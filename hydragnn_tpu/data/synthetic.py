"""Deterministic synthetic graph dataset with closed-form targets.

Re-implementation of the reference's keystone test fixture (reference:
tests/deterministic_graph_data.py:20-180): BCC supercells with random unit
cell counts, node feature = random type id, nodal outputs = kNN-smoothed
feature x, x^2 + feature, x^3, graph output = sum of all three nodal
outputs. Because the learned function is known in closed form, end-to-end
accuracy thresholds are meaningful.

Two outputs:
  - ``deterministic_graph_data`` -> in-memory ``GraphSample`` list whose
    feature packing matches what the reference's LSMS reader produces for
    these files — including the charge-density correction ``x[:,1] -= x[:,0]``
    (reference: hydragnn/preprocess/lsms_raw_dataset_loader.py:91-108), so
    effective node features are [type, knn_x^2, knn_x^3] and the raw
    graph feature is the pre-correction total sum.
  - ``write_lsms_files`` -> the same configurations in the LSMS text format
    so the raw-ingestion path can be tested against identical data.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from hydragnn_tpu.data.dataset import GraphSample

# Column layout of one LSMS text row written by the reference generator:
# feature, node index, x, y, z, out_x, out_x2, out_x3
#   (reference: tests/deterministic_graph_data.py:133-145)


def _bcc_positions(uc_x: int, uc_y: int, uc_z: int) -> np.ndarray:
    n = 2 * uc_x * uc_y * uc_z
    pos = np.zeros((n, 3), dtype=np.float64)
    i = 0
    for x in range(uc_x):
        for y in range(uc_y):
            for z in range(uc_z):
                pos[i] = (x, y, z)
                pos[i + 1] = (x + 0.5, y + 0.5, z + 0.5)
                i += 2
    return pos


def _knn_average(pos: np.ndarray, values: np.ndarray, k: int) -> np.ndarray:
    """Uniform k-nearest-neighbor regression evaluated at the training
    points (sklearn KNeighborsRegressor semantics: the query point itself
    is among the candidates at distance 0)."""
    diff = pos[:, None, :] - pos[None, :, :]
    dist = np.sqrt((diff * diff).sum(-1))
    order = np.argsort(dist, axis=1, kind="stable")[:, :k]
    return values[order].mean(axis=1)


def _one_configuration(
    rng: np.random.Generator,
    uc: Tuple[int, int, int],
    types: Sequence[int],
    number_neighbors: int,
    linear_only: bool,
):
    pos = _bcc_positions(*uc)
    n = pos.shape[0]
    feature = rng.integers(min(types), max(types) + 1, size=(n,)).astype(np.float64)
    if linear_only:
        out_x = feature.copy()
    else:
        out_x = _knn_average(pos, feature, number_neighbors)
    out_x2 = out_x**2 + feature
    out_x3 = out_x**3
    if linear_only:
        total = out_x.sum()
        totals = (total,)
    else:
        totals = (out_x.sum() + out_x2.sum() + out_x3.sum(), out_x.sum())
    return pos, feature, out_x, out_x2, out_x3, totals


def deterministic_graph_data(
    number_configurations: int = 500,
    unit_cell_x_range: Tuple[int, int] = (1, 3),
    unit_cell_y_range: Tuple[int, int] = (1, 3),
    unit_cell_z_range: Tuple[int, int] = (1, 2),
    number_types: int = 3,
    types: Optional[Sequence[int]] = None,
    number_neighbors: int = 2,
    linear_only: bool = False,
    seed: int = 0,
) -> List[GraphSample]:
    """Generate the dataset in memory.

    Each sample's raw (pre-normalization) packing mirrors the LSMS-reader
    output for the reference files:
      x columns: [feature(type), out_x2 - feature, out_x3]   (3 features)
      graph_y:   [total] where total = sum(out_x)+sum(out_x2)+sum(out_x3)
    Ranges are exclusive on the high end (torch.randint semantics,
    reference: tests/deterministic_graph_data.py:36-49).
    """
    if types is None:
        types = list(range(number_types))
    rng = np.random.default_rng(seed)
    ucx = rng.integers(unit_cell_x_range[0], unit_cell_x_range[1], number_configurations)
    ucy = rng.integers(unit_cell_y_range[0], unit_cell_y_range[1], number_configurations)
    ucz = rng.integers(unit_cell_z_range[0], unit_cell_z_range[1], number_configurations)

    samples: List[GraphSample] = []
    for c in range(number_configurations):
        pos, feature, out_x, out_x2, out_x3, totals = _one_configuration(
            rng, (int(ucx[c]), int(ucy[c]), int(ucz[c])), types, number_neighbors, linear_only
        )
        # LSMS charge-density correction: selected feature col 1 minus col 0
        # (lsms_raw_dataset_loader.py:91-108). With ci.json's column_index
        # [0, 6, 7] that yields [type, out_x2 - type, out_x3].
        if linear_only:
            x = np.stack([feature, out_x - feature], axis=1)
        else:
            x = np.stack([feature, out_x2 - feature, out_x3], axis=1)
        samples.append(
            GraphSample(
                x=np.asarray(x, dtype=np.float64),
                pos=np.asarray(pos, dtype=np.float32),
                graph_y=np.asarray([totals[0]], dtype=np.float64),
            )
        )
    return samples


def write_lsms_files(
    path: str,
    number_configurations: int = 500,
    configuration_start: int = 0,
    seed: int = 0,
    **kwargs,
) -> None:
    """Write the same configurations in the reference's LSMS text format
    (reference: tests/deterministic_graph_data.py:83-180) so the raw text
    ingestion path can round-trip them."""
    types = kwargs.pop("types", None) or list(range(kwargs.pop("number_types", 3)))
    number_neighbors = kwargs.pop("number_neighbors", 2)
    linear_only = kwargs.pop("linear_only", False)
    ucx_r = kwargs.pop("unit_cell_x_range", (1, 3))
    ucy_r = kwargs.pop("unit_cell_y_range", (1, 3))
    ucz_r = kwargs.pop("unit_cell_z_range", (1, 2))
    if kwargs:
        raise TypeError(f"unexpected kwargs: {sorted(kwargs)}")

    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(seed)
    ucx = rng.integers(ucx_r[0], ucx_r[1], number_configurations)
    ucy = rng.integers(ucy_r[0], ucy_r[1], number_configurations)
    ucz = rng.integers(ucz_r[0], ucz_r[1], number_configurations)
    for c in range(number_configurations):
        pos, feature, out_x, out_x2, out_x3, totals = _one_configuration(
            rng, (int(ucx[c]), int(ucy[c]), int(ucz[c])), types, number_neighbors, linear_only
        )
        n = pos.shape[0]
        lines = ["\t".join(f"{t:.10g}" for t in totals)]
        for i in range(n):
            row = [
                feature[i],
                float(i),
                pos[i, 0],
                pos[i, 1],
                pos[i, 2],
                out_x[i],
                out_x2[i],
                out_x3[i],
            ]
            lines.append("\t".join(f"{v:.10g}" for v in row))
        fname = os.path.join(path, f"output{c + configuration_start}.txt")
        with open(fname, "w") as f:
            f.write("\n".join(lines))
