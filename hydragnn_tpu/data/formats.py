"""Native readers for XYZ and AtomEye CFG atomistic formats.

The reference reads both through ase (reference:
hydragnn/utils/xyzdataset.py:13-71 uses ase.io.read + a ``<name>_energy.txt``
sidecar; hydragnn/utils/cfgdataset.py:12-84 uses ase.io.cfg.read_cfg + a
``<name>.bulk`` sidecar). ase is not a dependency here, so the parsers are
native and produce the same GraphSample content:

  XYZ:  x = [Z] proton numbers, pos, meta['cell'] from an extended-XYZ
        ``Lattice="..."`` comment when present, graph_y from the
        ``_energy.txt`` sidecar columns selected by the dataset config.
  CFG:  x = [Z, mass, c_peratom, fx, fy, fz] (the reference's column
        order, cfgdataset.py:57-66), pos = H0 @ s (reduced -> cartesian),
        meta['cell'] = H0, graph_y from the ``.bulk`` sidecar.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from hydragnn_tpu.data.dataset import GraphSample

# fmt: off
ELEMENT_SYMBOLS = [
    "X", "H", "He", "Li", "Be", "B", "C", "N", "O", "F", "Ne", "Na", "Mg",
    "Al", "Si", "P", "S", "Cl", "Ar", "K", "Ca", "Sc", "Ti", "V", "Cr",
    "Mn", "Fe", "Co", "Ni", "Cu", "Zn", "Ga", "Ge", "As", "Se", "Br",
    "Kr", "Rb", "Sr", "Y", "Zr", "Nb", "Mo", "Tc", "Ru", "Rh", "Pd",
    "Ag", "Cd", "In", "Sn", "Sb", "Te", "I", "Xe", "Cs", "Ba", "La",
    "Ce", "Pr", "Nd", "Pm", "Sm", "Eu", "Gd", "Tb", "Dy", "Ho", "Er",
    "Tm", "Yb", "Lu", "Hf", "Ta", "W", "Re", "Os", "Ir", "Pt", "Au",
    "Hg", "Tl", "Pb", "Bi", "Po", "At", "Rn", "Fr", "Ra", "Ac", "Th",
    "Pa", "U", "Np", "Pu", "Am", "Cm", "Bk", "Cf", "Es", "Fm", "Md",
    "No", "Lr",
]
# fmt: on
SYMBOL_TO_Z = {s: z for z, s in enumerate(ELEMENT_SYMBOLS)}

# standard atomic weights, Z-indexed (0 pad); enough elements for the
# CFG mass->Z inference fallback
ATOMIC_MASSES = np.array(
    [0.0, 1.008, 4.0026, 6.94, 9.0122, 10.81, 12.011, 14.007, 15.999, 18.998,
     20.180, 22.990, 24.305, 26.982, 28.085, 30.974, 32.06, 35.45, 39.948,
     39.098, 40.078, 44.956, 47.867, 50.942, 51.996, 54.938, 55.845, 58.933,
     58.693, 63.546, 65.38, 69.723, 72.630, 74.922, 78.971, 79.904, 83.798,
     85.468, 87.62, 88.906, 91.224, 92.906, 95.95, 97.0, 101.07, 102.91,
     106.42, 107.87, 112.41, 114.82, 118.71, 121.76, 127.60, 126.90, 131.29,
     132.91, 137.33, 138.91, 140.12, 140.91, 144.24, 145.0, 150.36, 151.96,
     157.25, 158.93, 162.50, 164.93, 167.26, 168.93, 173.05, 174.97, 178.49,
     180.95, 183.84, 186.21, 190.23, 192.22, 195.08, 196.97, 200.59, 204.38,
     207.2, 208.98, 209.0, 210.0, 222.0]
)


def _sidecar_graph_features(
    path: str, graph_feature_dims: Sequence[int], graph_feature_cols: Sequence[int]
) -> np.ndarray:
    """Read the single-line sidecar and select the configured columns
    (reference: xyzdataset.py:58-70 / cfgdataset.py:69-82)."""
    with open(path, "r", encoding="utf-8") as f:
        tokens = f.readlines()[0].split()
    g_feature: List[float] = []
    for item in range(len(graph_feature_dims)):
        for icomp in range(graph_feature_dims[item]):
            g_feature.append(float(tokens[graph_feature_cols[item] + icomp]))
    return np.asarray(g_feature, dtype=np.float64)


# ---------------------------------------------------------------- XYZ ----


def read_xyz_file(path: str) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
    """Parse one (extended) XYZ file -> (Z [n], pos [n,3], cell [3,3]|None)."""
    with open(path, "r", encoding="utf-8") as f:
        lines = f.read().splitlines()
    n = int(lines[0].split()[0])
    comment = lines[1] if len(lines) > 1 else ""
    cell = None
    m = re.search(r'Lattice="([^"]+)"', comment)
    if m:
        vals = np.asarray([float(v) for v in m.group(1).split()], dtype=np.float64)
        if vals.size == 9:
            cell = vals.reshape(3, 3)
    zs = np.zeros(n, dtype=np.int64)
    pos = np.zeros((n, 3), dtype=np.float64)
    for i in range(n):
        parts = lines[2 + i].split()
        sym = parts[0]
        if sym not in SYMBOL_TO_Z:
            try:
                zs[i] = int(sym)
            except ValueError:
                raise ValueError(f"unknown element symbol {sym!r} in {path}")
        else:
            zs[i] = SYMBOL_TO_Z[sym]
        pos[i] = [float(parts[1]), float(parts[2]), float(parts[3])]
    return zs, pos, cell


def read_xyz_sample(
    path: str,
    graph_feature_dims: Sequence[int],
    graph_feature_cols: Sequence[int],
) -> GraphSample:
    """XYZ + ``<name>_energy.txt`` sidecar -> GraphSample
    (x = proton numbers, reference xyzdataset.py:50-71)."""
    zs, pos, cell = read_xyz_file(path)
    energy_path = os.path.splitext(path)[0] + "_energy.txt"
    graph_y = _sidecar_graph_features(energy_path, graph_feature_dims, graph_feature_cols)
    meta = {"cell": cell} if cell is not None else {}
    return GraphSample(
        x=zs[:, None].astype(np.float64),
        pos=pos.astype(np.float32),
        graph_y=graph_y,
        meta=meta,
    )


# ---------------------------------------------------------------- CFG ----


def read_cfg_file(path: str) -> Dict[str, np.ndarray]:
    """Parse an AtomEye extended CFG file.

    Returns dict with ``numbers`` [n], ``masses`` [n], ``pos`` [n,3]
    (cartesian, H0 @ s), ``cell`` [3,3], plus one [n] array per auxiliary
    property (e.g. ``c_peratom``, ``fx``, ``fy``, ``fz``).
    """
    with open(path, "r", encoding="utf-8") as f:
        raw_lines = f.read().splitlines()

    n = None
    scale = 1.0
    h0 = np.zeros((3, 3), dtype=np.float64)
    aux_names: Dict[int, str] = {}
    entry_count = None
    body_start = None
    for li, line in enumerate(raw_lines):
        s = line.strip()
        if not s or s.startswith("#"):
            continue
        if s.startswith("Number of particles"):
            n = int(s.split("=")[1].split()[0])
        elif s.startswith("A ="):
            scale = float(s.split("=")[1].split()[0])
        elif s.startswith("H0("):
            m = re.match(r"H0\((\d),(\d)\)\s*=\s*([-\d.eE+]+)", s)
            if m:
                h0[int(m.group(1)) - 1, int(m.group(2)) - 1] = float(m.group(3))
        elif s.startswith("entry_count"):
            entry_count = int(s.split("=")[1].split()[0])
        elif s.startswith("auxiliary["):
            m = re.match(r"auxiliary\[(\d+)\]\s*=\s*(\S+)", s)
            if m:
                aux_names[int(m.group(1))] = m.group(2)
        elif s == ".NO_VELOCITY.":
            pass
        else:
            # first body line: either a bare mass (extended per-species
            # blocks) or a full position row (legacy single-block)
            if n is not None and entry_count is not None:
                body_start = li
                break
    if n is None or body_start is None:
        raise ValueError(f"malformed CFG file {path}")

    cell = h0 * scale
    numbers = np.zeros(n, dtype=np.int64)
    masses = np.zeros(n, dtype=np.float64)
    pos = np.zeros((n, 3), dtype=np.float64)
    n_aux = entry_count - 3
    aux = {aux_names.get(k, f"aux{k}"): np.zeros(n, dtype=np.float64) for k in range(n_aux)}

    i = 0
    cur_mass = 0.0
    cur_z = 0
    li = body_start
    while li < len(raw_lines) and i < n:
        s = raw_lines[li].strip()
        li += 1
        if not s:
            continue
        parts = s.split()
        if len(parts) == 1:
            # species block header: mass line, then symbol line
            cur_mass = float(parts[0])
            sym = raw_lines[li].strip()
            li += 1
            cur_z = SYMBOL_TO_Z.get(
                sym, int(np.abs(ATOMIC_MASSES - cur_mass).argmin())
            )
            continue
        svec = np.asarray([float(parts[0]), float(parts[1]), float(parts[2])])
        pos[i] = svec @ cell
        numbers[i] = cur_z
        masses[i] = cur_mass
        for k in range(n_aux):
            aux[aux_names.get(k, f"aux{k}")][i] = float(parts[3 + k])
        i += 1
    if i != n:
        raise ValueError(f"CFG file {path}: expected {n} atoms, parsed {i}")
    out = {"numbers": numbers, "masses": masses, "pos": pos, "cell": cell}
    out.update(aux)
    return out


def read_cfg_sample(
    path: str,
    graph_feature_dims: Sequence[int],
    graph_feature_cols: Sequence[int],
) -> GraphSample:
    """CFG + optional ``<name>.bulk`` sidecar -> GraphSample with the
    reference's node-feature packing [Z, mass, c_peratom, fx, fy, fz]
    (reference cfgdataset.py:50-84)."""
    parsed = read_cfg_file(path)
    cols = [
        parsed["numbers"].astype(np.float64),
        parsed["masses"],
        parsed.get("c_peratom", np.zeros(len(parsed["numbers"]))),
        parsed.get("fx", np.zeros(len(parsed["numbers"]))),
        parsed.get("fy", np.zeros(len(parsed["numbers"]))),
        parsed.get("fz", np.zeros(len(parsed["numbers"]))),
    ]
    x = np.stack(cols, axis=1)
    graph_y = None
    bulk_path = os.path.splitext(path)[0] + ".bulk"
    if os.path.exists(bulk_path) and sum(graph_feature_dims) > 0:
        graph_y = _sidecar_graph_features(bulk_path, graph_feature_dims, graph_feature_cols)
    return GraphSample(
        x=x,
        pos=parsed["pos"].astype(np.float32),
        graph_y=graph_y,
        meta={"cell": parsed["cell"]},
    )


# ------------------------------------------------------- dir readers ----


def _dataset_cols(dataset_config: Dict) -> Tuple[Sequence[int], Sequence[int]]:
    gf = dataset_config["graph_features"]
    return gf["dim"], gf["column_index"]


def read_xyz_dir(path: str, dataset_config: Dict) -> List[GraphSample]:
    dims, cols = _dataset_cols(dataset_config)
    samples = []
    for fname in sorted(os.listdir(path)):
        if fname.endswith(".xyz"):
            samples.append(read_xyz_sample(os.path.join(path, fname), dims, cols))
    return samples


def read_cfg_dir(path: str, dataset_config: Dict) -> List[GraphSample]:
    dims, cols = _dataset_cols(dataset_config)
    samples = []
    for fname in sorted(os.listdir(path)):
        if fname.endswith(".cfg"):
            samples.append(read_cfg_sample(os.path.join(path, fname), dims, cols))
    return samples
