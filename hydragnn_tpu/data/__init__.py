from hydragnn_tpu.data.radius_graph import radius_graph, radius_graph_pbc
from hydragnn_tpu.data.dataset import (
    GraphSample,
    normalize_dataset,
    scale_features_by_num_nodes,
    update_predicted_values,
    select_input_features,
    samples_to_graph_dicts,
)
from hydragnn_tpu.data.splitting import split_dataset, compositional_stratified_splitting
from hydragnn_tpu.data.loader import GraphLoader, pad_plan_for
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.data.smiles import (
    generate_graphdata_from_smilestr,
    get_node_attribute_name,
    mol_from_smiles,
    parse_smiles,
)
from hydragnn_tpu.data.atomic_descriptors import atomicdescriptors
from hydragnn_tpu.data.import_reference import (
    ReferenceMonolithicReader,
    ReferencePickleReader,
    import_monolithic_dataset,
    import_pickle_dataset,
)
from hydragnn_tpu.data.adios_reference import (
    ReferenceAdiosReader,
    import_adios_dataset,
)

__all__ = [
    "radius_graph",
    "radius_graph_pbc",
    "GraphSample",
    "normalize_dataset",
    "scale_features_by_num_nodes",
    "update_predicted_values",
    "select_input_features",
    "samples_to_graph_dicts",
    "split_dataset",
    "compositional_stratified_splitting",
    "GraphLoader",
    "pad_plan_for",
    "deterministic_graph_data",
    "generate_graphdata_from_smilestr",
    "get_node_attribute_name",
    "mol_from_smiles",
    "parse_smiles",
    "atomicdescriptors",
    "ReferencePickleReader",
    "import_pickle_dataset",
    "ReferenceMonolithicReader",
    "import_monolithic_dataset",
    "ReferenceAdiosReader",
    "import_adios_dataset",
]
