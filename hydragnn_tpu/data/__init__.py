from hydragnn_tpu.data.radius_graph import radius_graph, radius_graph_pbc
from hydragnn_tpu.data.dataset import (
    GraphSample,
    normalize_dataset,
    scale_features_by_num_nodes,
    update_predicted_values,
    select_input_features,
    samples_to_graph_dicts,
)
from hydragnn_tpu.data.splitting import split_dataset, compositional_stratified_splitting
from hydragnn_tpu.data.loader import GraphLoader, pad_plan_for
from hydragnn_tpu.data.synthetic import deterministic_graph_data

__all__ = [
    "radius_graph",
    "radius_graph_pbc",
    "GraphSample",
    "normalize_dataset",
    "scale_features_by_num_nodes",
    "update_predicted_values",
    "select_input_features",
    "samples_to_graph_dicts",
    "split_dataset",
    "compositional_stratified_splitting",
    "GraphLoader",
    "pad_plan_for",
    "deterministic_graph_data",
]
