"""LSMS text-format reader.

Parses the per-configuration text files the reference consumes (reference:
hydragnn/preprocess/lsms_raw_dataset_loader.py:39-108): line 0 = graph
features, remaining lines = per-node rows
``feature index x y z out...``; graph/node features are picked by the
config's column indices, and the LSMS charge-density correction
``x[:, 1] -= x[:, 0]`` is applied (lsms_raw_dataset_loader.py:91-108).
"""

from __future__ import annotations

import os
from typing import Dict, List, Sequence

import numpy as np

from hydragnn_tpu.data.dataset import GraphSample


def read_lsms_file(
    filepath: str,
    graph_feature_dim: Sequence[int],
    graph_feature_col: Sequence[int],
    node_feature_dim: Sequence[int],
    node_feature_col: Sequence[int],
) -> GraphSample:
    with open(filepath, "r", encoding="utf-8") as f:
        lines = f.readlines()
    graph_feat = lines[0].split()
    g = []
    for item in range(len(graph_feature_dim)):
        for icomp in range(graph_feature_dim[item]):
            g.append(float(graph_feat[graph_feature_col[item] + icomp]))

    pos_rows: List[List[float]] = []
    feat_rows: List[List[float]] = []
    for line in lines[1:]:
        if not line.strip():
            continue
        cols = line.split()
        pos_rows.append([float(cols[2]), float(cols[3]), float(cols[4])])
        row = []
        for item in range(len(node_feature_dim)):
            for icomp in range(node_feature_dim[item]):
                row.append(float(cols[node_feature_col[item] + icomp]))
        feat_rows.append(row)

    x = np.asarray(feat_rows, dtype=np.float64)
    # charge-density correction (always applied by the reference LSMS path)
    if x.shape[1] >= 2:
        x[:, 1] = x[:, 1] - x[:, 0]
    return GraphSample(
        x=x,
        pos=np.asarray(pos_rows, dtype=np.float32),
        graph_y=np.asarray(g, dtype=np.float64),
    )


def read_lsms_dir(path: str, dataset_config: Dict) -> List[GraphSample]:
    """Read every file in a directory (sorted, matching the reference's
    sorted(os.listdir), raw_dataset_loader.py:110)."""
    nf = dataset_config["node_features"]
    gf = dataset_config["graph_features"]
    samples = []
    for name in sorted(os.listdir(path)):
        full = os.path.join(path, name)
        if not os.path.isfile(full) or name == ".DS_Store":
            continue
        samples.append(
            read_lsms_file(full, gf["dim"], gf["column_index"], nf["dim"], nf["column_index"])
        )
    return samples
