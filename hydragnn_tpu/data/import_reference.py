"""One-shot importer for existing HydraGNN datasets.

Existing HydraGNN deployments hold their preprocessed datasets in one of
two on-disk formats (reference: hydragnn/utils/pickledataset.py:12-146
sharded-pickle layout; hydragnn/utils/adiosdataset.py:79-179 ADIOS2
schema). This module reads the sharded-pickle layout WITHOUT torch or
torch_geometric being importable as packages in their reference form —
the pickles contain torch_geometric ``Data`` objects, which are
reconstructed through a tolerant unpickler that stubs every
``torch_geometric.*`` class and then walks the captured state for the
tensor payload — and converts it into an HGC container
(:mod:`hydragnn_tpu.data.container`), the native dataset format here.

Layout read (pickledataset.py):
  <basedir>/<label>-meta.pkl   5 sequential pickles: minmax_node_feature,
                               minmax_graph_feature, ntotal, use_subdir,
                               nmax_persubdir
  <basedir>/<label>-<k>.pkl    one pickled PyG Data per sample
                               (under <k // nmax_persubdir>/ subdirs when
                               use_subdir)

The ADIOS2 format (group arrays + per-variable concatenated payloads
with ragged offsets) is handled by the sibling module
:mod:`hydragnn_tpu.data.adios_reference` — the CLI below dispatches to
it automatically for ``.bp`` inputs. Reading the BP container needs the
``adios2`` library (present in reference environments; this package is
pure Python, so running the importer THERE is a checkout away); without
it, ``tools/export_adios_to_pickle.py`` is a standalone adios2+numpy
script that emits the sharded-pickle layout this module consumes.

The reference's ragged ``data.y`` + ``y_loc`` offset table (written by
serialized_dataset_loader.py:262-303) is unpacked into the dict-of-heads
``GraphSample`` layout when present; otherwise ``y`` is kept as the
graph-level target vector.
"""

from __future__ import annotations

import io
import os
import pickle
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from hydragnn_tpu.data.dataset import GraphSample


class _Stub:
    """Stand-in for any unimportable class found in a reference pickle:
    captures constructor args and state without executing any foreign
    code (also a safety property — reference pickles are untrusted, and
    the allowlist below means no arbitrary class is ever instantiated)."""

    _args: tuple = ()
    _state: Any = None

    def __init__(self, *args, **kwargs):
        self._args = args

    def __setstate__(self, state):
        self._state = state

    # PyG BaseStorage pickles may invoke __setitem__-style protocols on
    # append-capable reductions; accept and record them.
    def append(self, item):
        self._args = self._args + (item,)

    def extend(self, items):
        self._args = self._args + tuple(items)


def _safe_storage_from_bytes(b):
    """Replacement for ``torch.storage._load_from_bytes``, whose stock
    implementation calls ``torch.load(weights_only=False)`` — a full
    unrestricted unpickle of attacker-controlled bytes. Storage payloads
    load fine under the restricted loader."""
    import torch

    return torch.load(io.BytesIO(b), weights_only=True)


# Exact (module, name) pairs a reference pickle legitimately needs to
# rebuild tensor/array payloads. Everything else — including builtins
# (builtins.eval/exec resolve through find_class!) and the rest of the
# torch/numpy module trees — maps to _Stub. Names resolved lazily so a
# pickle can't force-import anything beyond torch/numpy themselves.
_SAFE_TORCH_NAMES = frozenset(
    # dtypes (pickled as torch.<name> attribute lookups)
    """float16 float32 float64 bfloat16 complex64 complex128
       int8 int16 int32 int64 uint8 uint16 uint32 uint64 bool""".split()
) | frozenset(
    # shape + legacy typed-storage holders (plain data containers)
    """Size FloatStorage DoubleStorage HalfStorage BFloat16Storage
       LongStorage IntStorage ShortStorage CharStorage ByteStorage
       BoolStorage""".split()
)

_SAFE_GLOBALS = {
    ("torch._utils", "_rebuild_tensor_v2"): None,
    ("torch._utils", "_rebuild_tensor"): None,
    ("torch.storage", "_load_from_bytes"): lambda: _safe_storage_from_bytes,
    ("numpy", "ndarray"): None,
    ("numpy", "dtype"): None,
    ("numpy.core.multiarray", "_reconstruct"): None,
    ("numpy._core.multiarray", "_reconstruct"): None,
    ("numpy.core.multiarray", "scalar"): None,
    ("numpy._core.multiarray", "scalar"): None,
    ("numpy.core.numeric", "_frombuffer"): None,
    ("numpy._core.numeric", "_frombuffer"): None,
    ("_codecs", "encode"): None,  # numpy latin-1 buffer round-trip
    ("collections", "OrderedDict"): None,
}


class _TolerantUnpickler(pickle.Unpickler):
    """Unpickler that rebuilds tensor/array payloads through an exact
    (module, name) allowlist and maps every other global
    (torch_geometric.*, mpi4py leftovers, builtins, ...) to _Stub.

    Nothing outside the allowlist is ever resolved, let alone executed —
    foreign state is captured structurally; torch storage bytes load via
    ``weights_only=True``. That makes loading a foreign pickle no more
    dangerous than parsing it."""

    def find_class(self, module: str, name: str):
        if module == "torch" and name in _SAFE_TORCH_NAMES:
            return super().find_class(module, name)
        hit = _SAFE_GLOBALS.get((module, name), _Stub)
        if hit is None:
            return super().find_class(module, name)
        if hit is _Stub:
            return _Stub
        return hit()


def _load_pickle_stream(path: str, count: int) -> list:
    out = []
    with open(path, "rb") as f:
        for _ in range(count):
            out.append(_TolerantUnpickler(f).load())
    return out


def _to_numpy(v) -> Optional[np.ndarray]:
    """torch.Tensor / ndarray / scalar -> ndarray, else None."""
    if v is None:
        return None
    if isinstance(v, np.ndarray):
        return v
    if hasattr(v, "detach") and hasattr(v, "numpy"):  # torch.Tensor
        try:
            return v.detach().cpu().numpy()
        except Exception:
            return None
    if isinstance(v, (int, float)):
        return np.asarray([v], dtype=np.float32)
    return None


def _tensor_mapping(obj, depth: int = 0) -> Dict[str, np.ndarray]:
    """Walk a stubbed object graph for the innermost dict holding the
    tensor payload (PyG Data stores it at Data.__dict__['_store']
    ._mapping across 2.x versions; older versions keep tensors directly
    in __dict__). Returns {key: ndarray}."""
    if depth > 6:
        return {}
    found: Dict[str, np.ndarray] = {}
    state = None
    if isinstance(obj, dict):
        state = obj
    elif isinstance(obj, _Stub):
        state = obj._state if isinstance(obj._state, dict) else None
        if state is None and obj._args and isinstance(obj._args[-1], dict):
            state = obj._args[-1]
    if state is None:
        return {}
    for k, v in state.items():
        if not isinstance(k, str):
            continue
        arr = _to_numpy(v)
        if arr is not None:
            found[k.lstrip("_")] = arr
        elif isinstance(v, (dict, _Stub)):
            inner = _tensor_mapping(v, depth + 1)
            # deeper mappings win only for keys not already present
            for ik, iv in inner.items():
                found.setdefault(ik, iv)
    return found


def _unpack_y(
    fields: Dict[str, np.ndarray],
    head_types: Optional[Sequence[str]] = None,
    head_names: Optional[Sequence[str]] = None,
) -> Dict[str, Any]:
    """Split the reference's packed ``y`` + ``y_loc`` into the
    dict-of-heads layout (update_predicted_values packing:
    serialized_dataset_loader.py:262-303 — head h occupies rows
    [y_loc[h], y_loc[h+1]), node heads store num_nodes x dim
    row-major)."""
    y = fields.get("y")
    y_loc = fields.get("y_loc")
    n_nodes = fields["x"].shape[0]
    out: Dict[str, Any] = {"graph_targets": {}, "node_targets": {}, "graph_y": None}
    if y is None:
        return out
    y = y.reshape(-1).astype(np.float32)
    if y_loc is None:
        out["graph_y"] = y
        return out
    y_loc = y_loc.reshape(-1).astype(np.int64)
    n_heads = y_loc.shape[0] - 1
    for h in range(n_heads):
        seg = y[y_loc[h] : y_loc[h + 1]]
        name = (
            head_names[h]
            if head_names is not None and h < len(head_names)
            else f"head{h}"
        )
        if head_types is not None and h < len(head_types):
            htype = head_types[h]
        elif seg.shape[0] % n_nodes == 0 and seg.shape[0] >= n_nodes:
            # A graph head whose dim happens to be a multiple of
            # num_nodes is indistinguishable from a node head here, and
            # silent misinference reshapes (= corrupts) targets. This
            # used to be a warning; an importer that keeps going on a
            # coin-flip classification writes a permanently wrong
            # container, so it is a hard error with an escape hatch.
            raise ValueError(
                f"head {h} ({name!r}): length {seg.shape[0]} divides "
                f"num_nodes={n_nodes}, so it could be a node head "
                f"([{n_nodes}, {seg.shape[0] // n_nodes}]) or a "
                f"graph-level head of dim {seg.shape[0]} — ambiguous. "
                "Pass head_types=['graph'|'node', ...] (CLI: repeat "
                "--head-type in y_loc order) to pin every head "
                "explicitly."
            )
        else:
            htype = "graph"
        if htype == "node":
            out["node_targets"][name] = seg.reshape(n_nodes, -1)
        else:
            out["graph_targets"][name] = seg
    return out


def data_object_to_sample(
    obj,
    head_types: Optional[Sequence[str]] = None,
    head_names: Optional[Sequence[str]] = None,
) -> GraphSample:
    """Stubbed PyG ``Data`` -> :class:`GraphSample`."""
    fields = _tensor_mapping(obj)
    if "x" not in fields:
        raise ValueError(
            f"no 'x' tensor found in pickled object (keys: {sorted(fields)})"
        )
    x = fields["x"].astype(np.float32)
    x = x[:, None] if x.ndim == 1 else x
    ei = fields.get("edge_index")
    heads = _unpack_y(fields, head_types, head_names)
    ea = fields.get("edge_attr")
    if ea is not None:
        ea = ea.astype(np.float32)
        ea = ea[:, None] if ea.ndim == 1 else ea
    return GraphSample(
        x=x,
        pos=None if fields.get("pos") is None else fields["pos"].astype(np.float32),
        edge_index=None if ei is None else ei.astype(np.int32),
        edge_attr=ea,
        graph_y=heads["graph_y"],
        graph_targets=heads["graph_targets"],
        node_targets=heads["node_targets"],
    )


class ReferencePickleReader:
    """Reader for the reference sharded-pickle layout."""

    def __init__(self, basedir: str, label: str):
        meta_path = os.path.join(basedir, f"{label}-meta.pkl")
        if not os.path.exists(meta_path):
            raise FileNotFoundError(
                f"{meta_path} not found — expected the reference layout "
                "written by hydragnn/utils/pickledataset.py:SimplePickleWriter"
            )
        (
            self.minmax_node_feature,
            self.minmax_graph_feature,
            self.ntotal,
            self.use_subdir,
            self.nmax_persubdir,
        ) = _load_pickle_stream(meta_path, 5)
        self.basedir = basedir
        self.label = label

    def __len__(self) -> int:
        return int(self.ntotal)

    def _path(self, k: int) -> str:
        fname = f"{self.label}-{k}.pkl"
        if self.use_subdir:
            return os.path.join(self.basedir, str(k // self.nmax_persubdir), fname)
        return os.path.join(self.basedir, fname)

    def read(
        self,
        k: int,
        head_types: Optional[Sequence[str]] = None,
        head_names: Optional[Sequence[str]] = None,
    ) -> GraphSample:
        with open(self._path(k), "rb") as f:
            obj = _TolerantUnpickler(f).load()
        return data_object_to_sample(obj, head_types, head_names)

    def samples(
        self,
        head_types: Optional[Sequence[str]] = None,
        head_names: Optional[Sequence[str]] = None,
    ) -> List[GraphSample]:
        return [self.read(k, head_types, head_names) for k in range(len(self))]


class ReferenceMonolithicReader:
    """Reader for the reference's MONOLITHIC pickle layouts — one file
    holding 3 sequential pickles (minmax_node_feature,
    minmax_graph_feature, list-of-Data):

    - ``SerializedDataset`` (hydragnn/utils/serializeddataset.py:10-87):
      ``<basedir>/<datasetname>-<label>.pkl``, or per-rank
      ``<datasetname>-<label>-<rank>.pkl`` when written distributed;
    - the legacy ``run_training`` path's
      ``serialized_dataset/<name>[_split].pkl`` files
      (hydragnn/preprocess/raw_dataset_loader.py) — same 3-object body.

    Given one ``.pkl`` path, rank-sharded siblings
    (``<stem>-<rank>.pkl``) are discovered and concatenated in rank
    order automatically."""

    def __init__(self, path: str):
        stem = path[: -len(".pkl")] if path.endswith(".pkl") else path
        if os.path.isfile(path):
            self.paths = [path]
        else:
            # a dist write leaves only <stem>-0.pkl, <stem>-1.pkl, ...;
            # accept the base name and concatenate the rank set
            shards: List[str] = []
            r = 0
            while os.path.exists(f"{stem}-{r}.pkl"):
                shards.append(f"{stem}-{r}.pkl")
                r += 1
            if not shards:
                raise FileNotFoundError(path)
            self.paths = shards
        self.minmax_node_feature = None
        self.minmax_graph_feature = None
        self._objects: List[Any] = []
        for p in self.paths:
            mm_node, mm_graph, objs = _load_pickle_stream(p, 3)
            if self.minmax_node_feature is None:
                self.minmax_node_feature = mm_node
                self.minmax_graph_feature = mm_graph
            if isinstance(objs, _Stub):
                # list subclasses pickle their items through append/extend
                objs = list(objs._args)
            if not isinstance(objs, (list, tuple)):
                raise ValueError(
                    f"{p}: third pickle object is {type(objs).__name__}, "
                    "expected the list of Data samples"
                )
            self._objects.extend(objs)

    def __len__(self) -> int:
        return len(self._objects)

    def samples(
        self,
        head_types: Optional[Sequence[str]] = None,
        head_names: Optional[Sequence[str]] = None,
    ) -> List[GraphSample]:
        return [
            data_object_to_sample(o, head_types, head_names)
            for o in self._objects
        ]


def import_monolithic_dataset(
    path: str,
    out_path: str,
    head_types: Optional[Sequence[str]] = None,
    head_names: Optional[Sequence[str]] = None,
) -> int:
    """Convert one reference monolithic-pickle dataset (single file or
    rank-sharded set) into an HGC container. Returns the sample count."""
    from hydragnn_tpu.data.container import ContainerWriter

    reader = ReferenceMonolithicReader(path)
    writer = ContainerWriter(out_path)
    writer.add(reader.samples(head_types, head_names))
    for name, val in (
        ("minmax_node_feature", reader.minmax_node_feature),
        ("minmax_graph_feature", reader.minmax_graph_feature),
    ):
        arr = _to_numpy(val)
        if arr is not None:
            writer.add_global(name, arr)
    writer.save()
    return len(reader)


def import_pickle_dataset(
    basedir: str,
    label: str,
    out_path: str,
    head_types: Optional[Sequence[str]] = None,
    head_names: Optional[Sequence[str]] = None,
) -> int:
    """Convert one reference pickle dataset (``<basedir>/<label>-*.pkl``)
    into an HGC container at ``out_path``. Returns the sample count.

    The reference minmax metadata rides along as container globals so
    downstream normalization (data/ingest.py) can reuse it."""
    from hydragnn_tpu.data.container import ContainerWriter

    reader = ReferencePickleReader(basedir, label)
    writer = ContainerWriter(out_path)
    writer.add(reader.samples(head_types, head_names))
    for name, val in (
        ("minmax_node_feature", reader.minmax_node_feature),
        ("minmax_graph_feature", reader.minmax_graph_feature),
    ):
        arr = _to_numpy(val)
        if arr is not None:
            writer.add_global(name, arr)
    writer.save()
    return len(reader)


def main(argv: Optional[Sequence[str]] = None) -> None:
    import argparse

    p = argparse.ArgumentParser(
        description="Convert a reference HydraGNN dataset (sharded-pickle "
        "directory or ADIOS2 .bp file) into an HGC container."
    )
    p.add_argument(
        "source",
        help="sharded-pickle directory holding <label>-meta.pkl, a "
        "monolithic SerializedDataset .pkl file (rank-sharded sets: "
        "pass the base name), or an ADIOS2 .bp file/dir (needs the "
        "adios2 library)",
    )
    p.add_argument(
        "label",
        nargs="?",
        default="total",
        help="dataset label (e.g. 'trainset', 'total'); unused for "
        "monolithic .pkl inputs (the file IS the split)",
    )
    p.add_argument("out", help="output .hgc container path")
    p.add_argument(
        "--head-type",
        action="append",
        choices=["graph", "node"],
        help="per-head type, in y_loc order (repeat; inferred if omitted)",
    )
    p.add_argument(
        "--head-name", action="append", help="per-head name, in y_loc order"
    )
    args = p.parse_args(argv)
    from hydragnn_tpu.data.adios_reference import (
        import_adios_dataset,
        looks_like_adios,
    )

    if looks_like_adios(args.source):
        n = import_adios_dataset(
            args.source, args.label, args.out, args.head_type, args.head_name
        )
    elif args.source.endswith(".pkl") or os.path.isfile(args.source):
        n = import_monolithic_dataset(
            args.source, args.out, args.head_type, args.head_name
        )
    else:
        n = import_pickle_dataset(
            args.source, args.label, args.out, args.head_type, args.head_name
        )
    print(f"imported {n} samples -> {args.out}")


if __name__ == "__main__":
    main()
