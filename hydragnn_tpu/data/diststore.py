"""Distributed in-memory sample store — the DDStore equivalent.

The reference's ``DistDataset`` registers each process's shard of samples
in pyddstore (an MPI one-sided distributed array); ``get(global_idx)``
fetches any sample from whichever rank owns it (reference:
hydragnn/utils/distdataset.py:17-111, DDStore C++/MPI — SURVEY.md §2.9).

TPU-native design: JAX has no host-side one-sided comm, so ownership +
fetch runs over plain TCP on the data plane (the training plane's ICI/DCN
collectives are untouched): every process packs its shard per-field
(concatenated rows + offset index — the same layout as the HGC container)
and serves byte ranges from a background thread. Addresses are exchanged
once through ``multihost_utils.process_allgather``. Remote fetches are
LRU-cached. Single-process runs short-circuit to local lookups.

Wire protocol (little-endian): request = int64 sample index; response =
int64 payload length + pickled field dict. Pickle is safe here: peers are
the training job's own processes.
"""

from __future__ import annotations

import io
import pickle
import socket
import struct
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import numpy as np

from hydragnn_tpu.data.dataset import GraphSample
from hydragnn_tpu.utils import syncdebug


def _pack_sample(s: GraphSample) -> bytes:
    fields = {
        "x": s.x,
        "pos": s.pos,
        "edge_index": s.edge_index,
        "edge_attr": s.edge_attr,
        "graph_y": s.graph_y,
        "graph_targets": s.graph_targets,
        "node_targets": s.node_targets,
        "meta": s.meta,
    }
    buf = io.BytesIO()
    pickle.dump(fields, buf, protocol=pickle.HIGHEST_PROTOCOL)
    return buf.getvalue()


def _unpack_sample(data: bytes) -> GraphSample:
    fields = pickle.loads(data)
    return GraphSample(
        x=fields["x"],
        pos=fields.get("pos"),
        edge_index=fields.get("edge_index"),
        edge_attr=fields.get("edge_attr"),
        graph_y=fields.get("graph_y"),
        graph_targets=fields.get("graph_targets") or {},
        node_targets=fields.get("node_targets") or {},
        meta=fields.get("meta") or {},
    )


def _egress_ip() -> str:
    """The IP other hosts can reach us on. gethostbyname(hostname) often
    resolves to loopback (Debian-style /etc/hosts), so prefer the kernel's
    route choice toward the coordinator (or a public address) via a
    connected UDP socket — no packet is actually sent."""
    import os

    targets = []
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    )
    if coord:
        targets.append((coord.split(":")[0], 1))
    targets.append(("8.8.8.8", 1))
    for host, port in targets:
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.connect((host, port))
            ip = s.getsockname()[0]
            s.close()
            if not ip.startswith("127."):
                return ip
        except OSError:
            continue
    return socket.gethostbyname(socket.gethostname())


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        c = sock.recv(min(n, 1 << 20))
        if not c:
            raise ConnectionError("peer closed")
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


class DistSampleStore:
    """Own a shard, serve it, fetch anyone's.

    Args:
      local_samples: this process's shard.
      global_counts: per-process shard sizes (position p = process p's
        count). None => single-process (all samples local).
      cache_size: LRU capacity for remote fetches (the reference's
        per-item cache, adiosdataset.py:339-368).
    """

    def __init__(
        self,
        local_samples: Sequence[GraphSample],
        global_counts: Optional[Sequence[int]] = None,
        cache_size: int = 4096,
    ):
        import jax

        self.rank = jax.process_index()
        self.nproc = jax.process_count()
        self._local_samples = list(local_samples)
        # Serving (and thus pre-pickling the shard) only matters with
        # peers; single-process runs answer from _local_samples directly.
        self._local = (
            [_pack_sample(s) for s in local_samples] if self.nproc > 1 else []
        )

        if global_counts is None:
            if self.nproc > 1:
                from jax.experimental import multihost_utils

                mine = np.asarray([len(local_samples)], dtype=np.int64)
                global_counts = (
                    np.asarray(multihost_utils.process_allgather(mine))
                    .reshape(-1)
                    .tolist()
                )
            else:
                global_counts = [len(local_samples)]
        self.counts = np.asarray(global_counts, dtype=np.int64)
        self.starts = np.concatenate([[0], np.cumsum(self.counts)])
        self.total = int(self.counts.sum())

        # graftsync: guarded-by=diststore.DistSampleStore._lock
        self._cache: "OrderedDict[int, bytes]" = OrderedDict()
        self._cache_size = cache_size
        # graftsync: thread-safe=set once in __init__ before the accept thread spawns; close() only closes the socket (never reassigns), unblocking accept()
        self._server: Optional[socket.socket] = None
        # graftsync: thread-safe=populated once in __init__ (before any fetch) and read-only afterwards
        self._peers: List[tuple] = []
        # graftsync: guarded-by=diststore.DistSampleStore._lock
        self._conns: Dict[int, socket.socket] = {}
        self._lock = syncdebug.maybe_wrap(
            threading.Lock(), "diststore.DistSampleStore._lock"
        )
        if self.nproc > 1:
            self._start_server()
            self._exchange_addresses()

    # ---- serving ----

    def _start_server(self) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", 0))
        srv.listen(64)
        self._server = srv
        t = threading.Thread(target=self._serve_loop, daemon=True)
        t.start()

    # graftsync: thread-root
    def _serve_loop(self) -> None:
        while True:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    # graftsync: thread-root
    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                req = _recv_exact(conn, 8)
                (local_idx,) = struct.unpack("<q", req)
                if local_idx < 0 or local_idx >= len(self._local):
                    conn.sendall(struct.pack("<q", -1))
                    continue
                payload = self._local[local_idx]
                conn.sendall(struct.pack("<q", len(payload)) + payload)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def _exchange_addresses(self) -> None:
        from jax.experimental import multihost_utils

        host = _egress_ip()
        port = self._server.getsockname()[1]
        packed = np.frombuffer(
            socket.inet_aton(host) + struct.pack("<I", port), dtype=np.uint8
        )
        all_addr = np.asarray(multihost_utils.process_allgather(packed))
        for p in range(self.nproc):
            ip = socket.inet_ntoa(all_addr[p, :4].tobytes())
            (prt,) = struct.unpack("<I", all_addr[p, 4:8].tobytes())
            self._peers.append((ip, int(prt)))

    # ---- fetching ----

    def owner_of(self, global_idx: int) -> int:
        return int(np.searchsorted(self.starts, global_idx, side="right") - 1)

    def __len__(self) -> int:
        return self.total

    def get(self, global_idx: int) -> GraphSample:
        if not 0 <= global_idx < self.total:
            raise IndexError(global_idx)
        owner = self.owner_of(global_idx)
        local_idx = global_idx - int(self.starts[owner])
        if owner == self.rank:
            return self._local_samples[local_idx]
        with self._lock:
            if global_idx in self._cache:
                self._cache.move_to_end(global_idx)
                return _unpack_sample(self._cache[global_idx])
        data = self._fetch_remote(owner, local_idx)
        with self._lock:
            self._cache[global_idx] = data
            while len(self._cache) > self._cache_size:
                self._cache.popitem(last=False)
        return _unpack_sample(data)

    def __getitem__(self, idx: int) -> GraphSample:
        return self.get(idx)

    def _fetch_remote(self, owner: int, local_idx: int) -> bytes:
        with self._lock:
            conn = self._conns.get(owner)
        if conn is None:
            conn = socket.create_connection(self._peers[owner], timeout=60)
            with self._lock:
                self._conns[owner] = conn
        with self._lock:
            conn.sendall(struct.pack("<q", local_idx))
            (length,) = struct.unpack("<q", _recv_exact(conn, 8))
            if length < 0:
                raise IndexError(f"remote index {local_idx} rejected by rank {owner}")
            return _recv_exact(conn, length)

    def close(self) -> None:
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        # swap the connection map out under the lock, close outside it: a
        # concurrent _fetch_remote either kept its conn (gets a
        # ConnectionError it already handles) or re-caches a fresh one
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
