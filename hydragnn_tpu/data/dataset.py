"""Sample representation, normalization, and target packing.

The reference's data layer carries PyG ``Data`` objects whose ``x`` holds
*all* node features column-packed and whose ``y`` is a ragged concatenation
of the selected targets plus a ``y_loc`` offset table (reference:
hydragnn/preprocess/serialized_dataset_loader.py:262-303). The TPU-native
design replaces the ragged contract with explicit dicts:

  GraphSample.x        [n, sum(node_feature_dims)]  — all raw node features
  GraphSample.graph_y  [sum(graph_feature_dims)]    — all raw graph features
  graph_targets / node_targets: {head_name: array}  — selected, packed

Normalization mirrors AbstractRawDataLoader.normalize_dataset (reference:
hydragnn/preprocess/raw_dataset_loader.py:194-279): global min-max per
*feature* (not per column), divide-by-zero-safe.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class GraphSample:
    """One graph, host-side numpy. ``edge_index`` is [2, e] (senders row 0)."""

    x: np.ndarray
    pos: Optional[np.ndarray] = None
    edge_index: Optional[np.ndarray] = None
    edge_attr: Optional[np.ndarray] = None
    graph_y: Optional[np.ndarray] = None
    graph_targets: Dict[str, np.ndarray] = dataclasses.field(default_factory=dict)
    node_targets: Dict[str, np.ndarray] = dataclasses.field(default_factory=dict)
    # free-form extras (e.g. supercell size, composition id)
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def num_nodes(self) -> int:
        return int(self.x.shape[0])

    @property
    def num_edges(self) -> int:
        return 0 if self.edge_index is None else int(self.edge_index.shape[1])


def scale_features_by_num_nodes(
    samples: Sequence[GraphSample],
    graph_feature_names: Sequence[str],
    node_feature_names: Sequence[str],
    graph_feature_dims: Sequence[int],
    node_feature_dims: Sequence[int],
) -> None:
    """Divide ``*_scaled_num_nodes`` features by the node count, in place
    (reference: raw_dataset_loader.py:169-192)."""
    g_cols = _feature_columns(graph_feature_names, graph_feature_dims, "_scaled_num_nodes")
    n_cols = _feature_columns(node_feature_names, node_feature_dims, "_scaled_num_nodes")
    for s in samples:
        if s.graph_y is not None and g_cols:
            s.graph_y[g_cols] = s.graph_y[g_cols] / s.num_nodes
        if n_cols:
            s.x[:, n_cols] = s.x[:, n_cols] / s.num_nodes


def _feature_columns(names, dims, suffix) -> List[int]:
    cols: List[int] = []
    start = 0
    for name, dim in zip(names, dims):
        if suffix in name:
            cols.extend(range(start, start + dim))
        start += dim
    return cols


def compute_minmax(
    samples: Sequence[GraphSample],
    graph_feature_dims: Sequence[int],
    node_feature_dims: Sequence[int],
) -> Tuple[np.ndarray, np.ndarray]:
    """(minmax_graph_feature [2, nG], minmax_node_feature [2, nN]);
    row 0 = min, row 1 = max, over the whole dataset, per feature."""
    ng, nn = len(graph_feature_dims), len(node_feature_dims)
    mm_g = np.full((2, ng), np.inf)
    mm_n = np.full((2, nn), np.inf)
    mm_g[1] *= -1
    mm_n[1] *= -1
    for s in samples:
        start = 0
        for i, dim in enumerate(graph_feature_dims):
            if s.graph_y is not None:
                seg = s.graph_y[start : start + dim]
                mm_g[0, i] = min(mm_g[0, i], float(seg.min()))
                mm_g[1, i] = max(mm_g[1, i], float(seg.max()))
            start += dim
        start = 0
        for i, dim in enumerate(node_feature_dims):
            seg = s.x[:, start : start + dim]
            mm_n[0, i] = min(mm_n[0, i], float(seg.min()))
            mm_n[1, i] = max(mm_n[1, i], float(seg.max()))
            start += dim
    return mm_g, mm_n


def _safe_divide(num: np.ndarray, den: float) -> np.ndarray:
    # reference tensor_divide: 0 where denominator is 0
    if den == 0:
        return np.zeros_like(num)
    return num / den


def normalize_dataset(
    samples: Sequence[GraphSample],
    graph_feature_dims: Sequence[int],
    node_feature_dims: Sequence[int],
    minmax_graph: Optional[np.ndarray] = None,
    minmax_node: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Min-max normalize every feature to [0, 1] in place; returns the
    (graph, node) minmax tables used (computed if not given)."""
    if minmax_graph is None or minmax_node is None:
        minmax_graph, minmax_node = compute_minmax(
            samples, graph_feature_dims, node_feature_dims
        )
    for s in samples:
        start = 0
        for i, dim in enumerate(graph_feature_dims):
            if s.graph_y is not None:
                s.graph_y[start : start + dim] = _safe_divide(
                    s.graph_y[start : start + dim] - minmax_graph[0, i],
                    float(minmax_graph[1, i] - minmax_graph[0, i]),
                )
            start += dim
        start = 0
        for i, dim in enumerate(node_feature_dims):
            s.x[:, start : start + dim] = _safe_divide(
                s.x[:, start : start + dim] - minmax_node[0, i],
                float(minmax_node[1, i] - minmax_node[0, i]),
            )
            start += dim
    return minmax_graph, minmax_node


def update_predicted_values(
    samples: Sequence[GraphSample],
    output_type: Sequence[str],
    output_index: Sequence[int],
    output_names: Sequence[str],
    graph_feature_dims: Sequence[int],
    node_feature_dims: Sequence[int],
) -> None:
    """Populate graph_targets/node_targets dicts from the packed raw
    features — the dict-of-heads replacement for the reference's ragged
    ``y``/``y_loc`` packing (reference:
    hydragnn/preprocess/serialized_dataset_loader.py:262-303)."""
    g_starts = np.concatenate([[0], np.cumsum(graph_feature_dims)]).astype(int)
    n_starts = np.concatenate([[0], np.cumsum(node_feature_dims)]).astype(int)
    for s in samples:
        s.graph_targets = {}
        s.node_targets = {}
        for typ, idx, name in zip(output_type, output_index, output_names):
            if typ == "graph":
                lo, hi = g_starts[idx], g_starts[idx + 1]
                s.graph_targets[name] = np.asarray(s.graph_y[lo:hi], dtype=np.float32)
            elif typ == "node":
                lo, hi = n_starts[idx], n_starts[idx + 1]
                s.node_targets[name] = np.asarray(s.x[:, lo:hi], dtype=np.float32)
            else:
                raise ValueError(f"Unknown output type {typ}")


def select_input_features(
    samples: Sequence[GraphSample],
    input_node_features: Sequence[int],
    node_feature_dims: Sequence[int],
) -> None:
    """Keep only the selected input features in ``x``, in place
    (reference: serialized_dataset_loader.py __update_node_features)."""
    starts = np.concatenate([[0], np.cumsum(node_feature_dims)]).astype(int)
    cols: List[int] = []
    for idx in input_node_features:
        cols.extend(range(starts[idx], starts[idx + 1]))
    for s in samples:
        s.x = np.ascontiguousarray(s.x[:, cols], dtype=np.float32)


def samples_to_graph_dicts(samples: Sequence[GraphSample]) -> List[Dict[str, Any]]:
    """Convert to the dict format ``batch_graphs`` consumes."""
    out = []
    for s in samples:
        g: Dict[str, Any] = {
            "x": s.x,
            "senders": s.edge_index[0],
            "receivers": s.edge_index[1],
            "graph_targets": s.graph_targets,
            "node_targets": s.node_targets,
        }
        if s.pos is not None:
            g["pos"] = s.pos
        if s.edge_attr is not None:
            g["edge_attr"] = s.edge_attr
        out.append(g)
    return out
