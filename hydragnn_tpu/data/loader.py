"""Host-side batching into statically-padded GraphBatches.

Replaces the reference's DistributedSampler + PyG DataLoader stack
(reference: hydragnn/preprocess/load_data.py:226-283). TPU-specific
concerns drive the design:

  - every batch in a loader has the SAME padded (nodes, edges, graphs)
    shape, so the jitted train step compiles exactly once;
  - the pad plan is computed from the dataset up front (worst-case batch
    composition), not per batch;
  - per-epoch shuffling is seeded (epoch number = reference
    ``sampler.set_epoch``, train_validate_test.py:113-115);
  - multi-host sharding = stride-sharding the sample list per process
    (DistributedSampler equivalent); multi-device-per-host sharding =
    stacking D equally-shaped sub-batches along a leading device axis.
"""

from __future__ import annotations

import math
import os
import time
from typing import Iterator, List, Optional, Sequence

import jax
import numpy as np

from hydragnn_tpu.graph.batch import GraphBatch, batch_graphs
from hydragnn_tpu.data.dataset import GraphSample, samples_to_graph_dicts
from hydragnn_tpu.utils import knobs


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def pad_plan_for(
    samples: Sequence[GraphSample],
    batch_size: int,
    node_multiple: int = 16,
    edge_multiple: int = 8,
) -> tuple:
    """Static (n_node_pad, n_edge_pad, n_graph_pad) covering any batch of
    ``batch_size`` samples drawn from ``samples``.

    Worst case is the ``batch_size`` largest graphs landing in one batch;
    bounding by that keeps every epoch's batches one compiled shape.
    """
    nodes = sorted((s.num_nodes for s in samples), reverse=True)
    edges = sorted((s.num_edges for s in samples), reverse=True)
    worst_nodes = sum(nodes[:batch_size])
    worst_edges = sum(edges[:batch_size])
    return (
        _round_up(worst_nodes + 1, node_multiple),
        max(_round_up(worst_edges + 1, edge_multiple), edge_multiple),
        batch_size + 1,
    )


class _CapSize:
    """Synthetic (num_nodes, num_edges)-only sample for pad planning."""

    __slots__ = ("num_nodes", "num_edges")

    def __init__(self, num_nodes: int, num_edges: int):
        self.num_nodes = num_nodes
        self.num_edges = num_edges


def bucket_pad_plans(
    samples: Sequence,
    batch_size: int,
    num_buckets: int = 3,
    node_multiple: int = 16,
    edge_multiple: int = 8,
) -> list:
    """Ladder of serving pad plans over the dataset's size distribution.

    Returns an ascending, plan-deduplicated list of
    ``((cap_nodes, cap_edges), (n_node_pad, n_edge_pad, n_graph_pad))``.
    Caps are per-graph quantile cut points (bucket ``i`` covers graphs up
    to the ``(i+1)/num_buckets`` quantile of nodes AND of edges; the last
    bucket's caps are the dataset maxima); each plan is
    :func:`pad_plan_for` over a synthetic worst-case batch of
    ``batch_size`` cap-sized graphs, so ANY batch of up to ``batch_size``
    graphs within the caps fits the plan — the guarantee the serving
    router (hydragnn_tpu/serve/buckets.py) relies on to never trigger a
    fresh compile in steady state.
    """
    if not samples:
        raise ValueError("bucket_pad_plans needs a non-empty sample set")
    if num_buckets < 1:
        raise ValueError(f"num_buckets must be >= 1, got {num_buckets}")
    nodes = np.sort(np.asarray([s.num_nodes for s in samples]))
    edges = np.sort(np.asarray([s.num_edges for s in samples]))
    n = len(nodes)
    plans = []
    seen = set()
    for i in range(num_buckets):
        k = min(n - 1, max(0, math.ceil((i + 1) / num_buckets * n) - 1))
        cap_n, cap_e = int(nodes[k]), int(edges[k])
        plan = pad_plan_for(
            [_CapSize(cap_n, cap_e)] * batch_size,
            batch_size,
            node_multiple,
            edge_multiple,
        )
        if plan in seen:
            continue
        seen.add(plan)
        plans.append(((cap_n, cap_e), plan))
    return plans


class GraphLoader:
    """Iterable over fixed-shape GraphBatches.

    Args:
      samples: the split's samples (edges and targets already built).
      batch_size: graphs per batch (per process, matching the reference's
        per-rank batch size under DDP).
      shuffle: reshuffle each epoch (seeded by ``set_epoch``).
      num_shards / shard_rank: multi-host data sharding (DistributedSampler
        equivalent): this loader only sees samples[shard_rank::num_shards].
      device_stack: if > 1, each yielded batch has a leading device axis of
        this size; batch_size must divide evenly by it. Edge indices stay
        local to each sub-batch (shard_map-ready: no cross-device gathers).
      cache_device_batches: build every batch once (fixed composition) and
        keep it on device; epochs then permute batch ORDER only. Removes
        per-epoch host batching + H2D transfer from the hot loop — the win
        is large when the host->device link is slow — at the cost of
        coarser shuffling (batch membership is fixed after epoch 0).
    """

    def __init__(
        self,
        samples: Sequence[GraphSample],
        batch_size: int,
        shuffle: bool = False,
        seed: int = 0,
        num_shards: int = 1,
        shard_rank: int = 0,
        device_stack: int = 1,
        node_multiple: int = 16,
        edge_multiple: int = 8,
        drop_last: bool = False,
        cache_device_batches: bool = False,
        prefetch: Optional[int] = None,
        scan_reshuffle_every: int = 0,
        dense_slots: bool | int = True,
        run_align: bool | int = True,
    ):
        if device_stack > 1 and batch_size % device_stack != 0:
            raise ValueError(
                f"batch_size {batch_size} must be divisible by device_stack {device_stack}"
            )
        self.all_samples = list(samples)
        # DistributedSampler-style equalization: every shard sees exactly
        # ceil(n / num_shards) samples (wrapping around), so every process
        # runs the same number of jitted steps — required for cross-host
        # collectives to stay in lockstep.
        n = len(self.all_samples)
        if num_shards > 1 and n > 0:
            per_shard = math.ceil(n / num_shards)
            idx = [(shard_rank + k * num_shards) % n for k in range(per_shard)]
            self.samples = [self.all_samples[i] for i in idx]
        else:
            self.samples = list(self.all_samples)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.device_stack = device_stack
        self.drop_last = drop_last
        self.cache_device_batches = cache_device_batches
        self.scan_reshuffle_every = scan_reshuffle_every
        # an explicit argument wins; HYDRAGNN_NUM_PREFETCH sets the default
        if prefetch is None:
            raw = knobs.get_str("HYDRAGNN_NUM_PREFETCH", "2")
            try:
                prefetch = int(raw)
            except ValueError:
                raise ValueError(
                    f"HYDRAGNN_NUM_PREFETCH must be an integer, got {raw!r}"
                ) from None
        self.prefetch = prefetch
        self._cached_batches: Optional[List[GraphBatch]] = None
        self._stacked: Optional[GraphBatch] = None
        self._stacked_key: Optional[int] = None
        self._sharding = None
        self._global_mesh = None
        self._global_axes = None
        self._placer = None
        self._epoch = 0
        sub = batch_size // device_stack
        # Pad plan from the FULL dataset, not the local shard: all hosts
        # must compile identical batch shapes.
        self.pad_nodes, self.pad_edges, self.pad_graphs = pad_plan_for(
            self.all_samples, sub, node_multiple, edge_multiple
        )
        # dense slot count = dataset max in-degree (static across batches
        # AND hosts — derived from the full dataset like the pad plan).
        # True = AUTO: emit the dense map only when the slot inflation
        # (pad_nodes x Dmax vs pad_edges) stays under ~1.35x — tight
        # degree distributions (molecular radius graphs: Dmax ~= mean)
        # win big from dense [N, D, H] aggregation, while wide ones
        # (lattice surfaces: Dmax ~2x mean) pay more in inflated edge
        # passes than the dense reductions save (measured on v5e:
        # flagship BCC 2.07x inflation regressed 5.2k -> 4.3k graphs/s;
        # docs/PERF.md r03). An int pins the slot count unconditionally;
        # False/0 disables the map (pure CSR aggregation).
        if dense_slots is True:
            dmax = max_in_degree(self.all_samples)
            inflation = (
                self.pad_nodes * dmax / max(self.pad_edges, 1) if dmax else None
            )
            self.dense_slots = dmax if dmax and inflation <= 1.35 else None
        elif dense_slots:
            self.dense_slots = int(dense_slots)
        else:
            self.dense_slots = None
        # Run-aligned edge layout (graph/batch.py run_align): pads each
        # node's receiver-run to a multiple of K so segment reductions
        # pre-reduce K-fold before the serial scatter. AUTO (True):
        # K = 8 whenever the dense map is off (they answer the same
        # scatter-cost problem; dense wins for tight degree
        # distributions, run-align for wide ones) and the dataset has
        # edges. The pad plan widens to the ALIGNED worst case. An int
        # pins K; False/0 disables.
        if run_align is True:
            self.run_align = 8 if self.dense_slots is None else 0
        else:
            self.run_align = int(run_align) if run_align else 0
            if self.run_align > 1 and self.dense_slots is not None:
                raise ValueError(
                    "run_align and dense_slots are mutually exclusive — pass "
                    "dense_slots=False alongside an explicit run_align"
                )
        if self.run_align > 1:
            aligned = _aligned_edge_counts(self.all_samples, self.run_align)
            if aligned is None:
                self.run_align = 0  # no edge_index anywhere — nothing to align
            else:
                sub = batch_size // device_stack
                worst = sorted(aligned, reverse=True)[:sub]
                # Align the edge pad so the Pallas kernel grids divide it
                # evenly at BOTH scales they run on — E rows (gathers /
                # local sums) and E/K rows (pre-reduced segment ops).
                # Otherwise every pallas_call input pays a whole-array
                # pad copy per layer (r05 trace: 6 x 0.63 ms + 2.5 GB of
                # re-written bf16 [E,H] arrays on the flagship, just to
                # add 120 rows). Only at scale: for small batches the
                # in-kernel pad costs microseconds while grid alignment
                # would multiply E_pad (a 176-edge CI batch would pad to
                # 4096), bloating memory and perturbing every
                # accumulation-order-sensitive equivalence test.
                from hydragnn_tpu.ops.segment_pallas import (
                    _BCAST_CE as _bcast_ce,
                    CE as _kernel_ce,
                )

                grid_mult = self.run_align * _kernel_ce
                mult = math.lcm(edge_multiple, self.run_align)
                if max(sum(worst) + 1, self.pad_edges) >= 8 * grid_mult:
                    mult = math.lcm(edge_multiple, grid_mult)
                    # The fused gather+stats kernel additionally needs
                    # E % _BCAST_CE == 0 and _BCAST_CE % K == 0
                    # (ops/segment_pallas.py:gather_presum_eligible); a
                    # hand-tuned HYDRAGNN_BCAST_CE outside the lcm would
                    # otherwise silently disable it (ADVICE r5 #1) —
                    # correct fallback, vanished perf, no signal.
                    if _bcast_ce % self.run_align == 0:
                        mult = math.lcm(mult, _bcast_ce)
                    else:
                        import warnings

                        warnings.warn(
                            f"HYDRAGNN_BCAST_CE={_bcast_ce} is not a "
                            f"multiple of run_align={self.run_align}; the "
                            "fused PNA gather+stats kernel stays DISABLED "
                            "for this loader (unfused fallback, correct "
                            "but slower)",
                            RuntimeWarning,
                            stacklevel=2,
                        )
                self.pad_edges = _round_up(
                    max(sum(worst) + 1, self.pad_edges), mult
                )
        # Local-window block target: sized to the DATASET's mean graph
        # (capped by the [B, H] VMEM accumulator), so one kernel block
        # covers whole graphs and large graphs don't re-scan their edge
        # window per 128-row block (docs/PERF.md r04). Derived from
        # all_samples — like the pad plan — so every batch (and every
        # host) emits identically-shaped windows.
        mean_nodes = int(
            sum(s.num_nodes for s in self.all_samples) / max(len(self.all_samples), 1)
        )
        # cap 512: an r05 A/B at 640 (one block per 572-node large
        # graph, no window re-scan at all) traced 87.0 vs 86.9 ms —
        # the residual re-scan is noise once the r05 pad/dtype fixes
        # landed, and larger blocks cost VMEM for nothing
        self.win_block_rows = min(512, _round_up(max(mean_nodes, 128), 128))
        self._dicts = samples_to_graph_dicts(self.samples)

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def set_sharding(self, sharding) -> None:
        """Sharding for cached device batches (e.g. NamedSharding over the
        data mesh for device_stack > 1, so cached batches live on their
        target devices instead of being resharded from device 0 each step).
        Must be set before the first iteration builds the cache."""
        if sharding is not self._sharding:
            self._cached_batches = None  # rebuild with the new placement
            self._stacked = None
        self._sharding = sharding

    def set_global_mesh(self, mesh, axes=None) -> None:
        """Multi-host mode: assemble each local [device_stack, ...] batch
        into global jax.Arrays sharded over ``mesh``'s batch axes
        (``axes``; default the data axis — the Partitioner passes its
        composed ``(data, fsdp)`` lead axes; leading axis = device_stack
        × process_count). The assembly runs in the prefetch thread so
        cross-host batch formation overlaps compute."""
        if mesh is not self._global_mesh:
            self._cached_batches = None
            self._stacked = None
        self._global_mesh = mesh
        self._global_axes = axes

    def set_placer(self, placer) -> None:
        """Arbitrary per-batch placement callable (the Partitioner's
        ``shard_batch`` for composed meshes whose per-FIELD layouts a
        single uniform sharding cannot express, e.g. the edge axis).
        Overrides ``set_sharding``; must be set before the first
        iteration builds any cache."""
        if placer is not self._placer:
            self._cached_batches = None
            self._stacked = None
        self._placer = placer

    def __len__(self) -> int:
        n = len(self.samples)
        if self.drop_last:
            return n // self.batch_size
        return math.ceil(n / self.batch_size)

    @property
    def num_samples(self) -> int:
        return len(self.samples)

    def peek_batch(self) -> GraphBatch:
        """First batch of the current epoch's order, built and placed
        exactly as ``__iter__`` would build it — WITHOUT counting as an
        epoch iteration. Telemetry consumers (the graftcheck manifest
        stamp in ``train/loop.py``) peek here so loader wrappers that
        count ``__iter__`` draws (epoch schedulers, fault-injection
        harnesses) only ever see real epochs."""
        order = self._order()
        return self._place(self._make_batch(order[: self.batch_size]))

    def _order(self) -> np.ndarray:
        n = len(self.samples)
        if not self.shuffle:
            return np.arange(n)
        rng = np.random.default_rng(self.seed + self._epoch)
        return rng.permutation(n)

    def _make_sub_batch(self, idx: Sequence[int]) -> GraphBatch:
        batch = batch_graphs(
            [self._dicts[i] for i in idx],
            n_node_pad=self.pad_nodes,
            n_edge_pad=self.pad_edges,
            n_graph_pad=self.pad_graphs,
            dense_slots=self.dense_slots,
            run_align=self.run_align,
            win_block_rows=self.win_block_rows,
        )
        # HYDRAGNN_DEBUG_BATCH=1 validates the layout contracts the jitted
        # chassis silently relies on (sorted receivers, masked-edge
        # targeting, window coverage) on every host batch — meant for
        # debugging external/custom sample producers; off by default
        # because it walks every edge array on the host per batch.
        if knobs.get_bool("HYDRAGNN_DEBUG_BATCH", False):
            batch.check_invariants()
        return batch

    def _make_batch(self, chunk: Sequence[int]) -> GraphBatch:
        sub = self.batch_size // self.device_stack
        if self.device_stack == 1:
            return self._make_sub_batch(chunk)
        subs = []
        for d in range(self.device_stack):
            part = chunk[d * sub : (d + 1) * sub]
            if len(part) == 0:
                # Partial final batch: an all-padding sub-batch keeps
                # the device axis full; masks zero it out everywhere.
                part = chunk[:1]
                empty = self._make_sub_batch(part)
                subs.append(_mask_out(empty))
            else:
                subs.append(self._make_sub_batch(part))
        return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *subs)

    def _place(self, batch: GraphBatch) -> GraphBatch:
        """Device placement for a freshly-built host batch: global-mesh
        assembly (multi-host), explicit sharding (single-host mesh), or
        pass-through (jit moves it)."""
        if self._global_mesh is not None:
            from hydragnn_tpu.parallel.mesh import DATA_AXIS, globalize_batch

            if self.device_stack == 1:
                # the sharded steps expect a leading device axis even when
                # each process contributes a single sub-batch
                batch = jax.tree_util.tree_map(
                    lambda x: np.asarray(x)[None], batch
                )
            axes = self._global_axes if self._global_axes is not None else DATA_AXIS
            return globalize_batch(self._global_mesh, batch, axes=axes)
        if self._placer is not None:
            return self._placer(batch)
        if self._sharding is not None:
            return jax.device_put(batch, self._sharding)
        return batch

    def __iter__(self) -> Iterator[GraphBatch]:
        bs = self.batch_size
        nb = len(self)
        if self.cache_device_batches:
            if self._cached_batches is None:
                base = np.arange(len(self.samples))
                self._cached_batches = [
                    self._place(self._make_batch(base[b * bs : (b + 1) * bs]))
                    for b in range(nb)
                ]
            if self.shuffle:
                rng = np.random.default_rng(self.seed + self._epoch)
                batch_order = rng.permutation(nb)
            else:
                batch_order = np.arange(nb)
            for b in batch_order:
                yield self._cached_batches[b]
            return
        # Prefetch accounting into the shared telemetry registry
        # (hydragnn_tpu/obs): build_s is host batching + H2D placement,
        # prefetch_wait_s is time the CONSUMER blocked on the queue (the
        # part the producer thread failed to hide — the loader's share
        # of the train loop's data-wait span). Null counters when
        # telemetry is off; the timing branches are skipped entirely.
        from hydragnn_tpu.obs.registry import get_registry

        _reg = get_registry()
        _obs_on = _reg.enabled
        _c_build = _reg.counter("loader.build_s")
        _c_batches = _reg.counter("loader.batches_built")
        _c_wait = _reg.counter("loader.prefetch_wait_s")
        _c_stalls = _reg.counter("loader.prefetch_stalls")

        # Deterministic stalled-producer fault injection
        # (HYDRAGNN_INJECT_STALL_LOADER, docs/RESILIENCE.md): drives the
        # hang watchdog's data-wait abort path in tests; no-op otherwise.
        from hydragnn_tpu.resilience.inject import maybe_stall_loader

        order = self._order()
        if self.prefetch <= 0:
            for b in range(nb):
                maybe_stall_loader(b)
                t0 = time.perf_counter() if _obs_on else 0.0
                batch = self._place(self._make_batch(order[b * bs : (b + 1) * bs]))
                if _obs_on:
                    _c_build.inc(time.perf_counter() - t0)
                    _c_batches.inc()
                yield batch
            return
        # Background producer thread: batch assembly + H2D transfer
        # overlap with device compute (the reference's HydraDataLoader
        # thread-pool fetcher, hydragnn/preprocess/load_data.py:94-204 —
        # affinity pinning is unnecessary here, XLA owns the host).
        import queue
        import threading

        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        sentinel = object()

        def put_stop_aware(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False  # consumer abandoned the generator

        # graftsync: thread-root
        def producer():
            try:
                for b in range(nb):
                    maybe_stall_loader(b)
                    t0 = time.perf_counter() if _obs_on else 0.0
                    batch = self._place(self._make_batch(order[b * bs : (b + 1) * bs]))
                    if _obs_on:
                        _c_build.inc(time.perf_counter() - t0)
                        _c_batches.inc()
                    if not put_stop_aware(batch):
                        return
                put_stop_aware(sentinel)
            except BaseException as exc:  # surfaced to the consumer
                put_stop_aware(exc)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                if _obs_on:
                    t0 = time.perf_counter()
                    item = q.get()
                    dt = time.perf_counter() - t0
                    _c_wait.inc(dt)
                    if dt > 1e-3:  # the producer was actually behind
                        _c_stalls.inc()
                else:
                    item = q.get()
                if item is sentinel:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    def num_graphs_total(self) -> int:
        return len(self.samples)

    def stacked_device_batches(self, epoch: int = 0) -> GraphBatch:
        """Every batch of an epoch stacked on a new leading axis [B, ...]
        and placed on device — the input for the scan-over-epoch train
        path (train.state.make_scan_epoch). By default batch membership is
        fixed (like ``cache_device_batches``) and per-epoch shuffling
        happens device-side by permuting the batch axis — a deliberate
        divergence from the reference DataLoader(shuffle=True), which
        re-forms batches every epoch. ``scan_reshuffle_every=k`` restores
        membership-level reshuffling by rebuilding the stack host-side
        every k epochs (one extra H2D transfer per rebuild)."""
        k = self.scan_reshuffle_every
        key = (epoch // k) if (self.shuffle and k > 0) else None
        if self._stacked is None or key != self._stacked_key:
            bs = self.batch_size
            if key is None:
                base = np.arange(len(self.samples))
            else:
                # sample-level permutation, seeded like the __iter__ path
                base = np.random.default_rng(self.seed + key).permutation(
                    len(self.samples)
                )
            host = [
                self._make_batch(base[b * bs : (b + 1) * bs]) for b in range(len(self))
            ]
            stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *host)
            self._stacked = jax.device_put(stacked, self._sharding)
            self._stacked_key = key
        return self._stacked


def _aligned_edge_counts(samples, k: int):
    """Per-sample edge-slot count under run-K alignment
    (sum over nodes of roundup(in_degree, k)), or None when any sample
    lacks an edge_index."""
    import numpy as _np

    out = []
    for s in samples:
        ei = getattr(s, "edge_index", None)
        if ei is None:
            return None
        r = _np.asarray(ei)[1]
        if r.size:
            deg = _np.bincount(r)
            out.append(int((((deg + k - 1) // k) * k * (deg > 0)).sum()))
        else:
            out.append(0)
    return out


def max_in_degree(samples) -> int:
    """Dataset-wide max node in-degree (the static dense-slot count).
    Returns 0 when any sample lacks an edge_index (dense map disabled)."""
    import numpy as _np

    worst = 0
    for s in samples:
        ei = getattr(s, "edge_index", None)
        if ei is None:
            return 0
        r = _np.asarray(ei)[1]
        if r.size:
            worst = max(worst, int(_np.bincount(r).max()))
    return worst


def _block_rows(batch: GraphBatch, win) -> int:
    from hydragnn_tpu.ops.segment_pallas import local_block_rows

    return local_block_rows(batch.num_nodes, win.shape[1])


def _mask_out(batch: GraphBatch) -> GraphBatch:
    """Turn a batch into pure padding (all masks False, counts zero).

    Edges are repointed at the last node slot (always a padding slot —
    ``batch_graphs`` reserves one) to keep the loader-wide invariant
    that masked edges never target a real node: the chassis degree
    shortcut (``models/convs.py:sorted_in_degree``) counts edges
    without consulting the mask."""
    import numpy as _np

    pad_slot = batch.num_nodes - 1
    dense = {}
    if batch.dense_mask is not None:
        dense["dense_mask"] = _np.zeros_like(_np.asarray(batch.dense_mask))
        dense["dense_senders"] = _np.full_like(
            _np.asarray(batch.dense_senders), pad_slot
        )
        if batch.dense_sender_perm is not None:
            # all-equal senders: stable argsort is the identity
            dense["dense_sender_perm"] = _np.arange(
                batch.dense_senders.size, dtype=_np.int32
            )
        if batch.dense_sender_win is not None:
            w = _np.zeros_like(_np.asarray(batch.dense_sender_win))
            w[1, pad_slot // _block_rows(batch, w)] = batch.dense_senders.size
            dense["dense_sender_win"] = w
    derived = {}
    if batch.edge_occupancy is not None:
        # ZERO occupancy: the fused conv kernel's chunk loop clamps at
        # ceil(edge_occupancy / CE), so a filler batch costs no DMAs and
        # no MXU work at all on its device slot (ISSUE 10 satellite)
        derived["edge_occupancy"] = _np.int32(0)
    if batch.n_real_nodes is not None:
        derived["n_real_nodes"] = _np.int32(0)
    if batch.sender_perm is not None:
        derived["sender_perm"] = _np.arange(batch.num_edges, dtype=_np.int32)
    if batch.in_degree is not None:
        # in_degree counts real edges only; a fully-masked batch has none
        derived["in_degree"] = _np.zeros(batch.num_nodes, dtype=_np.float32)
    if batch.sender_win is not None:
        w = _np.zeros_like(_np.asarray(batch.sender_win))
        w[1, pad_slot // _block_rows(batch, w)] = batch.num_edges
        derived["sender_win"] = w
    return batch.replace(
        senders=_np.full_like(_np.asarray(batch.senders), pad_slot),
        receivers=_np.full_like(_np.asarray(batch.receivers), pad_slot),
        **derived,
        node_mask=_np.zeros_like(_np.asarray(batch.node_mask)),
        edge_mask=_np.zeros_like(_np.asarray(batch.edge_mask)),
        graph_mask=_np.zeros_like(_np.asarray(batch.graph_mask)),
        n_node=_np.zeros_like(_np.asarray(batch.n_node)),
        n_edge=_np.zeros_like(_np.asarray(batch.n_edge)),
        **dense,
    )
